"""Two-party factoring of the monolithic estimators (reference layer L2½).

The paper's deployment model is vertically partitioned: the X-party and
the Y-party each hold one column and only DP releases may cross between
them. The monolithic estimators in this package compute with both
columns in one trace, so the privacy barrier exists only as prose. This
module re-factors each family into the three pieces the barrier
actually separates —

- :func:`party_release` — the DP release ONE party constructs from its
  own column alone (noisy batch means for the NI families, the
  randomized-response sign vector / per-sample local-DP values for the
  INT families);
- :func:`finish` — the finisher's combination of the peer's released
  quantities with its *own* column's contribution (its local release
  for the NI families; the receiver-side product and central draw for
  the INT families) into (ρ̂, CI);
- :func:`split_estimate` — the two composed in one process, the
  single-process reference the wire protocol (``dpcorr.protocol``) is
  tested bit-identical against.

The factoring is **bit-identical** to the monolithic estimators under
the shared-seed ``"replay"`` key layout (pinned by
tests/test_protocol.py): every draw keeps its monolithic named-stream
address, and every combination keeps the monolithic association order.
Where the wire forces a re-association (the INT-sign core when the
sender is the y-side: ``((2s−1)·sign(y))·sign(x)`` instead of
``((2s−1)·sign(x))·sign(y)``), every factor is exactly representable
(±1/±0), so the product is exact and the re-association is still
bit-equal. That is the design invariant: the barrier changes *where*
computation happens, never *what* is computed.

Key layouts (``utils.rng.party_root``): ``"replay"`` hands both parties
the same session key — monolithic stream addresses, bit-identity, the
simulation/testing mode. ``"hardened"`` roots each party in its own
disjoint ``"protocol/x"`` / ``"protocol/y"`` subtree: the draws are
statistically interchangeable but no longer bit-comparable, and —
deployed with genuinely secret per-party seeds — one party can no
longer reconstruct (and subtract) the other party's noise.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from dpcorr.models.estimators.common import (
    batch_geometry,
    batch_means,
    sample_sd,
)
from dpcorr.models.estimators.int_sign import interval_from_rho
from dpcorr.models.estimators.int_subg import grid_interval
from dpcorr.models.estimators.registry import FAMILIES
from dpcorr.ops.lambdas import lambda_int_n, lambda_n
from dpcorr.ops.noise import clip_sym, laplace
from dpcorr.ops.standardize import priv_center
from dpcorr.utils.rng import stream

#: payload-entry kinds a release message may carry, per family — the
#: closed vocabulary the transcript scanner checks against.
RELEASE_KINDS = {
    "ni_sign": {"batch_means": "noisy_sign_batch_means"},
    "ni_subg": {"batch_means": "noisy_clipped_batch_means"},
    "int_sign": {"flipped_signs": "rr_flipped_signs"},
    "int_subg": {"ldp_values": "ldp_clipped_values"},
}


def split_roles(family: str, eps1: float, eps2: float) -> tuple[str, str]:
    """(releaser, finisher) roles for one design point — static, public.

    NI families: the x-party releases, the y-party finishes (both sides
    release in principle; the finisher's own release never needs the
    wire). INT families: the larger-ε side sends, exactly the
    monolithic sender rule (ver-cor-subG.R:76-81, vert-cor.R:170-172).
    """
    if family not in FAMILIES:
        raise ValueError(f"unknown estimator family {family!r}; "
                         f"expected one of {FAMILIES}")
    if family in ("ni_sign", "ni_subg"):
        return "x", "y"
    return ("x", "y") if bool(eps1 >= eps2) else ("y", "x")


def release_schema(family: str, n: int, eps1: float,
                   eps2: float) -> dict[str, dict]:
    """Exact (kind, shape, dtype) of every array the releaser's wire
    payload may contain — derived from public parameters only, so the
    receiving party (and the offline transcript scan) can reject any
    payload shaped like raw data before touching its values."""
    kinds = RELEASE_KINDS[family]
    if family in ("ni_sign", "ni_subg"):
        m, k = batch_geometry(n, eps1, eps2)
        shape = (k,)
    else:
        shape = (n,)
    name = next(iter(kinds))
    return {name: {"kind": kinds[name], "shape": shape,
                   "dtype": "float32"}}


def _own_eps(role: str, eps1: float, eps2: float) -> float:
    return eps1 if role == "x" else eps2


def _ni_sign_release(key, role, col, eps1, eps2, normalise):
    """One side of ``ci_ni_signbatch`` (vert-cor.R:204-233): private
    centering, sign batch means, the per-batch Laplace draws — the
    exact monolithic streams ``ni_sign/{std,lap}_{x,y}``."""
    n = col.shape[0]
    m, k = batch_geometry(n, eps1, eps2)
    eps = _own_eps(role, eps1, eps2)
    if normalise:
        l_clip = jnp.sqrt(2.0 * jnp.log(float(n)))
        col = priv_center(stream(key, f"ni_sign/std_{role}"), col, eps,
                          l_clip)
    bar = batch_means(jnp.sign(col), k, m)
    return bar + laplace(stream(key, f"ni_sign/lap_{role}"), (k,),
                         2.0 / (m * eps))


def _ni_subg_release(key, role, col, eps1, eps2):
    """One side of ``correlation_ni_subg`` (grid variant, static
    geometry — the serving configuration): clip at λ_n, batch means,
    per-batch Laplace (streams ``ni_subg/lap_{x,y}``)."""
    n = col.shape[0]
    m, k = batch_geometry(n, eps1, eps2)
    eps = _own_eps(role, eps1, eps2)
    lam = lambda_n(n, 1.0)
    bar = batch_means(clip_sym(col, lam), k, m)
    return bar + laplace(stream(key, f"ni_subg/lap_{role}"), (k,),
                         2.0 * lam / (m * eps))


def _int_sign_release(key, role, col, eps1, eps2, normalise):
    """The sender half of ``correlation_int_signflip``
    (vert-cor.R:164-195): center own column, randomized-response flip
    its signs. ``(2S−1)·sign(col)`` is the per-sample ε_s-local-DP
    release; values are exactly ±1/±0, so the receiver-side product
    re-association stays bit-exact (module docstring)."""
    n = col.shape[0]
    eps = _own_eps(role, eps1, eps2)
    if normalise:
        l_clip = jnp.sqrt(2.0 * jnp.log(float(n)))
        col = priv_center(stream(key, f"int_sign/std_{role}"), col, eps,
                          l_clip)
    est = stream(key, "int_sign/est")
    e_s = math.exp(max(eps1, eps2))
    p_keep = e_s / (e_s + 1.0)
    s = jax.random.bernoulli(stream(est, "int_sign/flips"), p_keep, (n,))
    return (2.0 * s.astype(jnp.float32) - 1.0) * jnp.sign(col)


def _int_subg_release(key, role, col, eps1, eps2):
    """The sender half of ``ci_int_subg`` (grid variant,
    ver-cor-subG.R:87-90): clip at λ_s, one Laplace draw *per sample*
    (stream ``int_subg/lap_sender``) — the local-DP release."""
    n = col.shape[0]
    eps_s = max(eps1, eps2)
    lam_s, _ = lambda_int_n(n, eta_s=1.0, eta_r=1.0, eps_s=eps_s)
    sc = clip_sym(col, lam_s)
    return sc + laplace(stream(key, "int_subg/lap_sender"), (n,),
                        2.0 * lam_s / eps_s)


@functools.lru_cache(maxsize=None)
def _release_jit(family: str, role: str, eps1: float, eps2: float,
                 normalise: bool):
    """Compiled release kernel per (family, role, ε, normalise) — the
    party-side computation must go through ``jit`` like the monolithic
    serving entry does, or eager-mode op ordering drifts the last ulp
    away from the jitted reference (bit-identity is the acceptance
    bar, so the split pieces compile exactly like the whole)."""
    return jax.jit(functools.partial(_release_impl, family, role,
                                     eps1=eps1, eps2=eps2,
                                     normalise=normalise))


def _release_impl(family, role, key, col, *, eps1, eps2, normalise):
    if family == "ni_sign":
        return {"batch_means": _ni_sign_release(key, role, col, eps1,
                                                eps2, normalise)}
    if family == "ni_subg":
        return {"batch_means": _ni_subg_release(key, role, col, eps1,
                                                eps2)}
    if family == "int_sign":
        return {"flipped_signs": _int_sign_release(key, role, col, eps1,
                                                   eps2, normalise)}
    return {"ldp_values": _int_subg_release(key, role, col, eps1, eps2)}


def party_release(family: str, key: jax.Array, role: str, col: jax.Array,
                  eps1: float, eps2: float,
                  normalise: bool = True) -> dict[str, jax.Array]:
    """The DP release one party constructs from its own column alone.

    ``key`` is that party's root (``utils.rng.party_root``); ``role``
    is ``"x"`` or ``"y"``. Returns ``{}`` for the INT finisher role —
    its ε is spent inside :func:`finish` (the receiver's central draw),
    not as a wire payload. Everything raw stays inside this function:
    the returned arrays are the only values allowed to leave the party.
    """
    if role not in ("x", "y"):
        raise ValueError(f"role must be 'x' or 'y', got {role!r}")
    releaser, _ = split_roles(family, eps1, eps2)
    if family in ("int_sign", "int_subg") and role != releaser:
        return {}
    fn = _release_jit(family, role, float(eps1), float(eps2),
                      bool(normalise))
    return dict(fn(key, jnp.asarray(col, jnp.float32)))


def _ni_sign_finish(key, role, rel, col, eps1, eps2, alpha, normalise):
    n = col.shape[0]
    m, k = batch_geometry(n, eps1, eps2)
    own = _ni_sign_release(key, role, col, eps1, eps2, normalise)
    # monolithic order: tj = m·xt·yt (vert-cor.R:233) — the x-side
    # release is the left factor
    xt, yt = (own, rel) if role == "x" else (rel, own)
    tj = m * xt * yt
    eta_hat = jnp.sum(tj) / k
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    s_eta = sample_sd(tj)
    crit = ndtri(1.0 - alpha / 2.0)
    half = crit * s_eta / jnp.sqrt(float(k))
    lo = jnp.sin(jnp.pi / 2.0 * jnp.maximum(eta_hat - half, -1.0))
    hi = jnp.sin(jnp.pi / 2.0 * jnp.minimum(eta_hat + half, 1.0))
    return rho_hat, lo, hi


def _ni_subg_finish(key, role, rel, col, eps1, eps2, alpha):
    n = col.shape[0]
    m, k = batch_geometry(n, eps1, eps2)
    own = _ni_subg_release(key, role, col, eps1, eps2)
    xt, yt = (own, rel) if role == "x" else (rel, own)
    rho_hat = (m / k) * jnp.sum(xt * yt)
    tj = m * xt * yt
    se = sample_sd(tj) / jnp.sqrt(float(k))
    crit = ndtri(1.0 - alpha / 2.0)
    lo = jnp.maximum(rho_hat - crit * se, -1.0)
    hi = jnp.minimum(rho_hat + crit * se, 1.0)
    return rho_hat, lo, hi


def _int_sign_finish(key, role, rel, col, eps1, eps2, alpha, normalise):
    n = col.shape[0]
    eps = _own_eps(role, eps1, eps2)
    if normalise:
        l_clip = jnp.sqrt(2.0 * jnp.log(float(n)))
        col = priv_center(stream(key, f"int_sign/std_{role}"), col, eps,
                          l_clip)
    est = stream(key, "int_sign/est")
    eps_s, eps_r = max(eps1, eps2), min(eps1, eps2)
    e_s = math.exp(eps_s)
    # exact ±1/±0 factors: this re-association of the monolithic core
    # ((2S−1)·sign(x))·sign(y) is bit-equal (module docstring)
    core = rel * jnp.sign(col)
    scale_z = 2.0 * (e_s + 1.0) / (n * (e_s - 1.0) * eps_r)
    z = laplace(stream(est, "int_sign/lap_z"), (), scale_z)
    eta_hat = (e_s + 1.0) / (n * (e_s - 1.0)) * jnp.sum(core) + z
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    res = interval_from_rho(key, rho_hat, n, eps_s, eps_r, alpha,
                            "auto", "det")
    return res.rho_hat, res.ci_low, res.ci_high


def _int_subg_finish(key, role, rel, col, eps1, eps2, alpha):
    n = col.shape[0]
    eps_s, eps_r = max(eps1, eps2), min(eps1, eps2)
    lam_s, lam_r = lambda_int_n(n, eta_s=1.0, eta_r=1.0, eps_s=eps_s)
    # grid variant: the receiver's own variable is NOT clipped
    # (ver-cor-subG.R:92); the released factor stays on the left,
    # matching the monolithic (sc + noise)·other association
    u = rel * col
    uc = clip_sym(u, lam_r)
    central_scale = 2.0 * lam_r / (n * eps_r)
    rho_hat = jnp.mean(uc) + laplace(stream(key, "int_subg/lap_recv"), (),
                                     central_scale)
    sd_uc = sample_sd(uc)
    res = grid_interval(key, rho_hat, sd_uc, n, eps_r, central_scale,
                        alpha, "det")
    return res.rho_hat, res.ci_low, res.ci_high


@functools.lru_cache(maxsize=None)
def _finish_jit(family: str, eps1: float, eps2: float, alpha: float,
                normalise: bool):
    """Compiled finisher per design point (same jit rationale as
    :func:`_release_jit`: the reference is jitted, so both halves of
    the split must be too for the bit-identity contract)."""
    return jax.jit(functools.partial(_finish_impl, family, eps1=eps1,
                                     eps2=eps2, alpha=alpha,
                                     normalise=normalise))


def _finish_impl(family, key, rel, col, *, eps1, eps2, alpha, normalise):
    _, finisher = split_roles(family, eps1, eps2)
    if family == "ni_sign":
        return _ni_sign_finish(key, finisher, rel, col, eps1, eps2,
                               alpha, normalise)
    if family == "ni_subg":
        return _ni_subg_finish(key, finisher, rel, col, eps1, eps2, alpha)
    if family == "int_sign":
        return _int_sign_finish(key, finisher, rel, col, eps1, eps2,
                                alpha, normalise)
    return _int_subg_finish(key, finisher, rel, col, eps1, eps2, alpha)


def finish(family: str, key: jax.Array, peer_release: dict, col: jax.Array,
           eps1: float, eps2: float, alpha: float = 0.05,
           normalise: bool = True) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The finisher's combination: peer's released quantities + its own
    column's contribution → (ρ̂, ci_low, ci_high).

    ``key`` is the *finisher's* root; ``col`` its raw column —
    consumed only inside the same DP constructions the monolithic
    estimator applies (its own release for NI; the receiver-side
    product, clip and central draw for INT). ``peer_release`` is the
    decoded wire payload, keyed as :func:`release_schema` names it.
    """
    name = next(iter(RELEASE_KINDS[family]))
    if set(peer_release) != {name}:
        raise ValueError(f"{family}: expected release payload {{{name!r}}}, "
                         f"got {sorted(peer_release)}")
    rel = jnp.asarray(peer_release[name], jnp.float32)
    fn = _finish_jit(family, float(eps1), float(eps2), float(alpha),
                     bool(normalise))
    return fn(key, rel, jnp.asarray(col, jnp.float32))


@functools.lru_cache(maxsize=None)
def _finish_batch_jit(family: str, eps1: float, eps2: float, alpha: float,
                      normalise: bool, engine: str):
    """Compiled batched finisher per design point. ``"exact"`` rolls
    ``jax.lax.map`` over the single-cell finisher — the serve batch
    engines' bit-reproducibility contract (serve.kernels: lax.map of
    the jitted single program is bit-identical to per-item calls for
    every family, measured in PR 1 and pinned again by
    tests/test_federation.py). ``"vector"`` is ``vmap`` — faster, but
    only ρ-exact/CI≤1ulp, so it is opt-in and never used where the
    federation's bit-identity acceptance applies."""
    single = functools.partial(_finish_impl, family, eps1=eps1, eps2=eps2,
                               alpha=alpha, normalise=normalise)
    if engine == "vector":
        return jax.jit(jax.vmap(single))
    if engine != "exact":
        raise ValueError(f"unknown finish engine {engine!r}; "
                         "expected 'exact' or 'vector'")
    return jax.jit(lambda keys, rels, cols: jax.lax.map(
        lambda args: single(*args), (keys, rels, cols)))


_PLAN: "object | None" = None


def _plan_executor():
    """Module-level plan executor for federation finishes — the third
    dispatch site ported onto the shared plan layer (dpcorr.plan).
    Local placement: a federation round is host-side RPC aggregation
    dispatching one batched kernel. Units are AOT-compiled at the exact
    stacked round shapes and cached per signature, which closes the old
    lazy-jit hole where every first round of a new (B, n) shape paid
    its compile on the session's critical path."""
    global _PLAN
    if _PLAN is None:
        from dpcorr import plan as plan_mod

        _PLAN = plan_mod.Executor("local")
    return _PLAN


def finish_batch(family: str, keys, peer_releases, cols,
                 eps1: float, eps2: float, alpha: float = 0.05,
                 normalise: bool = True, engine: str = "exact",
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One finish kernel over a whole federation round: B cells of the
    same design point, each with its own finisher key, peer release and
    finisher column. ``keys`` is a sequence of per-cell finisher roots,
    ``peer_releases`` a sequence of decoded release payloads (validated
    like :func:`finish`), ``cols`` a sequence of finisher columns.
    Returns (ρ̂, ci_low, ci_high) arrays of shape (B,).

    This is what makes session multiplexing pay: a pair link's round
    lands as one envelope and finishes as one compiled program instead
    of B dispatches — while the ``"exact"`` engine keeps every cell
    bit-identical to the independent two-party run it replaces."""
    name = next(iter(RELEASE_KINDS[family]))
    rels = []
    for rel in peer_releases:
        if set(rel) != {name}:
            raise ValueError(
                f"{family}: expected release payload {{{name!r}}}, "
                f"got {sorted(rel)}")
        rels.append(jnp.asarray(rel[name], jnp.float32))
    if not (len(keys) == len(rels) == len(cols)):
        raise ValueError(
            f"batch length mismatch: {len(keys)} keys, {len(rels)} "
            f"releases, {len(cols)} columns")
    fn = _finish_batch_jit(family, float(eps1), float(eps2), float(alpha),
                           bool(normalise), engine)
    keys_arr = jnp.stack(list(keys))
    rels_arr = jnp.stack(rels)
    cols_arr = jnp.stack([jnp.asarray(c, jnp.float32) for c in cols])
    ex = _plan_executor()
    unit = ex.prepare(
        ("finish_batch", family, float(eps1), float(eps2), float(alpha),
         bool(normalise), engine,
         tuple((a.shape, str(a.dtype))
               for a in (keys_arr, rels_arr, cols_arr))),
        fn,
        tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
              for a in (keys_arr, rels_arr, cols_arr)),
        signature={"kernel": "finish_batch", "family": family,
                   "engine": engine, "b": int(keys_arr.shape[0]),
                   "n": int(cols_arr.shape[-1])})
    # dispatch stays asynchronous — the protocol runtime fetches when
    # it serializes the round's results
    return ex.dispatch(unit, (keys_arr, rels_arr, cols_arr))


def split_estimate(family: str, key_x: jax.Array, key_y: jax.Array,
                   x: jax.Array, y: jax.Array, eps1: float, eps2: float,
                   alpha: float = 0.05, normalise: bool = True,
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The factored estimator composed in one process — the
    single-process reference the protocol runtime is pinned against.
    ``key_x``/``key_y`` are the per-party roots; pass the same key
    twice for the ``"replay"`` layout (bit-identical to the monolithic
    ``serving_entry`` closure on that key)."""
    releaser, finisher = split_roles(family, eps1, eps2)
    rel_key, fin_key = ((key_x, key_y) if releaser == "x"
                        else (key_y, key_x))
    rel_col, fin_col = (x, y) if releaser == "x" else (y, x)
    rel = party_release(family, rel_key, releaser, rel_col, eps1, eps2,
                        normalise)
    return finish(family, fin_key, rel, fin_col, eps1, eps2, alpha,
                  normalise)
