"""D. Interactive clipped estimator + CI (sub-Gaussian).

Reference: ``ci_INT_subG`` — grid variant ver-cor-subG.R:67-108, real-data
variant real-data-sims.R:176-252. Math (SURVEY.md §2.2-D):

Sender clips at λ_s and releases ``clip(X) + Lap(2λ_s/ε_s)`` *per sample*
(local DP); the receiver multiplies by its own variable, clips the product
at λ_r, then takes the mean plus one central-DP Laplace draw
``Lap(2λ_r/(n·ε_r))``.

The variants differ in documented ways (SURVEY.md Appendix A #3), selected
via ``variant``:

- ``"grid"`` (v1): λ pair from ``lambda_INT_n``; the receiver's own variable
  is **not** clipped before the product; CI se includes the Laplace noise
  term ``√(sd(Uc)² + 2(2λ_r/(nε_r))²)``; c* = 2/(√n·sd(Uc)·ε_r).
- ``"real"`` (v2): λ_sender/λ_other/λ_receiver overrides with
  ``lambda_receiver_from_noise`` default and per-sample tail δ (default 1/n);
  the other variable **is** clipped to ±λ_other; sampling-only se =
  sd(Uc)/√n; c* = 2λ_r/(√n·sd(Uc)·ε_r); degenerate sd(Uc)=0 branch
  (real-data-sims.R:237-238) handled branch-free with ``where``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from dpcorr.models.estimators.common import CorrResult, sample_sd
from dpcorr.ops.lambdas import lambda_int_n, lambda_n, lambda_receiver_from_noise
from dpcorr.ops.mixquant import mixquant, mixquant_mc
from dpcorr.ops.noise import clip_sym, laplace
from dpcorr.utils.rng import stream

_CSTAR_MAX = 1e6  # sd(Uc)→0 sends c*→∞; a huge finite c* yields width → ±1 CI


def grid_interval(key: jax.Array, rho_hat: jax.Array, sd_uc: jax.Array,
                  n: int, eps_r: float, central_scale, alpha: float,
                  mixquant_mode: str, mixquant_nsim: int = 1000) -> CorrResult:
    """Grid-variant (v1) CI given ρ̂ and sd(Uc) (ver-cor-subG.R:99-104),
    shared by the materialized and streaming estimators: se includes the
    central-noise variance term; ρ-space clamp."""
    sd_safe = jnp.maximum(sd_uc, 1e-30)
    p = 1.0 - alpha / 2.0
    se_norm = jnp.sqrt(sd_uc**2 + 2.0 * central_scale**2)
    cstar = jnp.minimum(2.0 / (jnp.sqrt(float(n)) * sd_safe * eps_r), _CSTAR_MAX)
    q = (mixquant_mc(stream(key, "int_subg/mixquant"), cstar, p,
                     nsim=mixquant_nsim) if mixquant_mode == "mc"
         else mixquant(cstar, p))
    width = q * se_norm / jnp.sqrt(float(n))
    lo = jnp.maximum(rho_hat - width, -1.0)
    hi = jnp.minimum(rho_hat + width, 1.0)
    return CorrResult(rho_hat, lo, hi)


def ci_int_subg(key: jax.Array, x: jax.Array, y: jax.Array,
                eps1: float, eps2: float,
                eta1: float = 1.0, eta2: float = 1.0,
                alpha: float = 0.05,
                variant: str = "grid",
                lambda_sender=None, lambda_other=None, lambda_receiver=None,
                delta_clip: float | None = None,
                mixquant_mode: str = "det",
                mixquant_nsim: int | None = None,
                sender: str | None = None) -> CorrResult:
    """One-round interactive clipped DP correlation estimate + mixture CI.

    ``mixquant_nsim`` sets the MC draw count when ``mixquant_mode="mc"``;
    the default follows the reference per variant — 1000 for the grid
    script's mixquant (ver-cor-subG.R:10) and **2000** for the real-data
    script's (real-data-sims.R:161-164).

    ``sender`` fixes the protocol direction explicitly: ``"x"`` or
    ``"y"``; ``None`` keeps the larger-ε rule (ver-cor-subG.R:76-81).
    The real-data script names its direction outright (AGE→BMI,
    real-data-sims.R:305) rather than relying on the ε tie-break, and an
    explicit direction is also what lets the ε values be JAX tracers
    (the larger-ε rule is a Python-level branch on concrete floats) —
    which is how the HRS sweep serves every ε from one compiled kernel.
    """
    if variant not in ("grid", "real"):
        raise ValueError(f"variant must be 'grid' or 'real', got {variant!r}")
    if sender not in (None, "x", "y"):
        raise ValueError(f"sender must be None, 'x' or 'y', got {sender!r}")
    if mixquant_nsim is None:
        mixquant_nsim = 2000 if variant == "real" else 1000
    n = x.shape[0]

    # Roles: larger ε sends (ver-cor-subG.R:76-81) — static — unless the
    # caller names the direction (see docstring).
    sender_is_x = (sender == "x") if sender else bool(eps1 >= eps2)
    eps_s, eps_r = (eps1, eps2) if sender_is_x else (eps2, eps1)
    eta_s, eta_r = (eta1, eta2) if sender_is_x else (eta2, eta1)
    xs, xo = (x, y) if sender_is_x else (y, x)  # sender var, other var

    if variant == "grid":
        lam_s, lam_r = lambda_int_n(n, eta_s=eta_s, eta_r=eta_r, eps_s=eps_s)
        if lambda_sender is not None:
            lam_s = lambda_sender
        if lambda_receiver is not None:
            lam_r = lambda_receiver
        other = xo  # v1 does NOT clip the receiver's own variable
    else:
        if delta_clip is None:
            delta_clip = 1.0 / n  # real-data-sims.R:199
        lam_s = lambda_sender
        lam_o = lambda_other
        if lam_s is None or lam_o is None:
            lam_pair = lambda_int_n(n, eta_s=eta_s, eta_r=eta_r, eps_s=eps_s)
            if lam_s is None:
                lam_s = lam_pair[0]
            if lam_o is None:
                lam_o = lambda_n(n, eta2 if sender_is_x else eta1)
        lam_r = lambda_receiver
        if lam_r is None:
            lam_r = lambda_receiver_from_noise(lam_s, lam_o, eps_s, delta_clip)
        other = clip_sym(xo, lam_o)

    # Sender local-DP release, receiver product + clip + one central draw
    # (ver-cor-subG.R:87-97 / real-data-sims.R:221-233).
    sc = clip_sym(xs, lam_s)
    u = (sc + laplace(stream(key, "int_subg/lap_sender"), (n,), 2.0 * lam_s / eps_s)) * other
    uc = clip_sym(u, lam_r)
    central_scale = 2.0 * lam_r / (n * eps_r)
    rho_hat = jnp.mean(uc) + laplace(stream(key, "int_subg/lap_recv"), (), central_scale)

    sd_uc = sample_sd(uc)
    # the real-data variant's richer return (real-data-sims.R:244-252);
    # the grid variant has no λ_other/δ concepts
    aux = {"lambda_sender": lam_s, "lambda_receiver": lam_r,
           "eps_sender": eps_s, "eps_receiver": eps_r}
    if variant == "real":
        aux["lambda_other"] = lam_o
        aux["delta_clip"] = delta_clip
    if variant == "grid":
        return grid_interval(key, rho_hat, sd_uc, n, eps_r, central_scale,
                             alpha, mixquant_mode,
                             mixquant_nsim=mixquant_nsim)._replace(aux=aux)
    else:
        # sampling-only se + explicit sd==0 degenerate branch
        # (real-data-sims.R:237-242)
        sd_safe = jnp.maximum(sd_uc, 1e-30)
        p = 1.0 - alpha / 2.0
        cstar = jnp.minimum(2.0 * lam_r / (jnp.sqrt(float(n)) * sd_safe * eps_r),
                            _CSTAR_MAX)
        q = (mixquant_mc(stream(key, "int_subg/mixquant"), cstar, p,
                         nsim=mixquant_nsim) if mixquant_mode == "mc"
             else mixquant(cstar, p))
        width_mix = q * sd_uc / jnp.sqrt(float(n))
        width_deg = ndtri(p) * jnp.sqrt(2.0) * central_scale
        width = jnp.where(sd_uc == 0.0, width_deg, width_mix)

    lo = jnp.maximum(rho_hat - width, -1.0)  # ρ-space clamp
    hi = jnp.minimum(rho_hat + width, 1.0)
    return CorrResult(rho_hat, lo, hi, aux)
