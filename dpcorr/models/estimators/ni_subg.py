"""C. Non-interactive clipped-batch estimator + CI (sub-Gaussian).

Reference: ``correlation_NI_subG`` — grid variant ver-cor-subG.R:25-62,
real-data variant real-data-sims.R:115-147. Math (SURVEY.md §2.2-C):

Clip X at ±λ₁ = λ_n(n, η₁), Y at ±λ₂; same (m, k) batch design as the
sign estimator; Laplace scale 2λ/(m·ε) per batch mean; ρ̂ = η̂ =
(m/k)·Σ X̃Ỹ — **no sine link**; normal CI from sd(T_j)/√k clamped in
ρ-space to [−1, 1].

The two reference variants are one function here, parameterized (SURVEY.md
Appendix A #2):

- grid (v1): sequential batches, λ from :func:`~dpcorr.ops.lambdas.lambda_n`.
- real-data (v2): ``lambda_x``/``lambda_y`` overrides, ``randomize_batches``
  (``sample.int`` randomized assignment, real-data-sims.R:132),
  ``enforce_min_k`` (k≥2 fallback, real-data-sims.R:130). NA-pair removal is
  host-side, before the kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from dpcorr.models.estimators.common import (
    CorrResult,
    batch_geometry,
    batch_geometry_dyn,
    batch_means,
    batch_means_dyn,
    sample_sd,
)
from dpcorr.ops.lambdas import lambda_n
from dpcorr.ops.noise import clip_sym, laplace
from dpcorr.utils.rng import stream


def correlation_ni_subg(key: jax.Array, x: jax.Array, y: jax.Array,
                        eps1: float, eps2: float,
                        eta1: float = 1.0, eta2: float = 1.0,
                        alpha: float = 0.05,
                        lambda_x=None, lambda_y=None,
                        randomize_batches: bool = False,
                        enforce_min_k: bool = False,
                        dynamic_geometry: bool = False,
                        k_pad: int | None = None) -> CorrResult:
    """Clipped-batch DP correlation estimate + normal CI.

    ``dynamic_geometry=True`` accepts *traced* ε values: (m, k) become
    in-kernel data (masked segment sums padded to n) so one compiled
    kernel serves every ε of a sweep — the TPU-first answer to the
    reference's 23 serial per-ε runs (real-data-sims.R:345-448). The
    batch assignment is identical to the static path (same permutation
    stream, same consecutive-element grouping); the per-batch Laplace
    draws come from a padded (n,)-shaped call, so the two paths are the
    same estimator on *different PRNG stream layouts* — statistically
    interchangeable, not bit-equal (pinned by
    tests/test_estimators.py::test_ni_subg_dynamic_geometry_*).
    """
    n = x.shape[0]
    lam1 = lambda_n(n, eta1) if lambda_x is None else lambda_x
    lam2 = lambda_n(n, eta2) if lambda_y is None else lambda_y

    xc = clip_sym(x, lam1)  # ver-cor-subG.R:33-34
    yc = clip_sym(y, lam2)

    if dynamic_geometry:
        # k_pad: static bound on k from the caller's known ε set
        # (common.k_pad_for) — shrinks every padded per-batch vector;
        # None = the always-safe bound n
        return _ni_subg_dyn(key, xc, yc, n, eps1, eps2, lam1, lam2,
                            alpha, randomize_batches, enforce_min_k,
                            n if k_pad is None else k_pad)

    m, k = batch_geometry(n, eps1, eps2, enforce_min_k=enforce_min_k)
    if randomize_batches:
        # sample.int(n, k*m): k·m draws without replacement
        # (real-data-sims.R:132)
        idx = jax.random.permutation(stream(key, "ni_subg/perm"), n)[: k * m]
        xc, yc = xc[idx], yc[idx]

    xbar = batch_means(xc, k, m)
    ybar = batch_means(yc, k, m)
    xt = xbar + laplace(stream(key, "ni_subg/lap_x"), (k,), 2.0 * lam1 / (m * eps1))
    yt = ybar + laplace(stream(key, "ni_subg/lap_y"), (k,), 2.0 * lam2 / (m * eps2))

    rho_hat = (m / k) * jnp.sum(xt * yt)  # η̂ = ρ̂, no sine link (:51-52)

    tj = m * xt * yt
    se = sample_sd(tj) / jnp.sqrt(float(k))
    crit = ndtri(1.0 - alpha / 2.0)
    lo = jnp.maximum(rho_hat - crit * se, -1.0)  # ρ-space clamp (:58-59)
    hi = jnp.minimum(rho_hat + crit * se, 1.0)
    # the real-data variant's richer return (real-data-sims.R:141-147)
    aux = {"k": k, "m": m, "lambda_x": lam1, "lambda_y": lam2}
    return CorrResult(rho_hat, lo, hi, aux)


def _ni_subg_dyn(key, xc, yc, n: int, eps1, eps2, lam1, lam2,
                 alpha: float, randomize_batches: bool,
                 enforce_min_k: bool, k_pad: int) -> CorrResult:
    """Masked-geometry body: same math as the static path with (m, k) as
    traced scalars and every per-batch vector padded to ``k_pad``."""
    m, k = batch_geometry_dyn(n, eps1, eps2, enforce_min_k=enforce_min_k)
    if randomize_batches:
        # full permutation; positions ≥ k·m never reach a live batch
        # (batch_means_dyn only gathers boundary prefix sums below k·m),
        # so the first k·m elements — the ones the static path gathers —
        # form the same randomized batches
        perm = jax.random.permutation(stream(key, "ni_subg/perm"), n)
        xc, yc = xc[perm], yc[perm]

    mf = m.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    xbar = batch_means_dyn(xc, m, k, k_pad)
    ybar = batch_means_dyn(yc, m, k, k_pad)
    xt = xbar + laplace(stream(key, "ni_subg/lap_x"), (k_pad,),
                        2.0 * lam1 / (mf * eps1))
    yt = ybar + laplace(stream(key, "ni_subg/lap_y"), (k_pad,),
                        2.0 * lam2 / (mf * eps2))

    valid = jnp.arange(k_pad) < k
    prod = jnp.where(valid, xt * yt, 0.0)
    rho_hat = (mf / kf) * jnp.sum(prod)
    # pad-bound tripwire: if the traced k ever exceeds the static pad
    # (a caller passed a k_pad not derived from its real ε set), live
    # batches would silently be dropped and the estimate biased — a
    # traced condition can't raise, so poison the result instead; NaNs
    # fail the aggregation/tests loudly
    rho_hat = jnp.where(k > k_pad, jnp.nan, rho_hat)

    tj = mf * xt * yt
    mean_tj = jnp.sum(jnp.where(valid, tj, 0.0)) / kf
    var_tj = jnp.sum(jnp.where(valid, (tj - mean_tj) ** 2, 0.0)) / (kf - 1.0)
    se = jnp.sqrt(var_tj) / jnp.sqrt(kf)
    crit = ndtri(1.0 - alpha / 2.0)
    lo = jnp.maximum(rho_hat - crit * se, -1.0)
    hi = jnp.minimum(rho_hat + crit * se, 1.0)
    aux = {"k": k, "m": m, "lambda_x": lam1, "lambda_y": lam2}
    return CorrResult(rho_hat, lo, hi, aux)
