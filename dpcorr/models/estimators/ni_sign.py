"""A. Non-interactive sign-batch estimator + CI (Gaussian).

Reference: ``correlation_NI_signbatch`` (vert-cor.R:118-156) and
``ci_NI_signbatch`` (vert-cor.R:204-255). Math (SURVEY.md §2.2-A):

1. m = ⌈8/(ε₁ε₂)⌉ capped at n; k = ⌊n/m⌋ batches.
2. Per batch j: means of signs X̄_j, Ȳ_j over m consecutive points.
3. X̃_j = X̄_j + Lap(2/(m·ε₁)) — the sensitivity of a sign-mean is 2/m.
4. η̂ = (m/k)·Σ_j X̃_j Ỹ_j; ρ̂ = sin(π·η̂/2) (Grothendieck/arcsine identity).
5. CI built in η-space from T_j = m·X̃_j Ỹ_j: η̂ ± z·sd(T_j)/√k, **clamped
   in η-space to [−1,1] and then sine-mapped** — the clamp order matters
   for coverage (vert-cor.R:249-254, SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from dpcorr.models.estimators.common import (
    CorrResult,
    batch_geometry,
    batch_means,
    sample_sd,
)
from dpcorr.ops.noise import laplace
from dpcorr.ops.standardize import priv_center
from dpcorr.utils.rng import stream


def _noisy_batch_products(key, x, y, eps1, eps2, m, k):
    """Steps 2-3: sign batch means + Laplace, returning X̃, Ỹ."""
    xbar = batch_means(jnp.sign(x), k, m)
    ybar = batch_means(jnp.sign(y), k, m)
    xt = xbar + laplace(stream(key, "ni_sign/lap_x"), (k,), 2.0 / (m * eps1))
    yt = ybar + laplace(stream(key, "ni_sign/lap_y"), (k,), 2.0 / (m * eps2))
    return xt, yt


def correlation_ni_signbatch(key: jax.Array, x: jax.Array, y: jax.Array,
                             eps1: float, eps2: float) -> jax.Array:
    """Point estimator ρ̂ (vert-cor.R:118-156). Inputs pre-standardized."""
    n = x.shape[0]
    m, k = batch_geometry(n, eps1, eps2)
    xt, yt = _noisy_batch_products(key, x, y, eps1, eps2, m, k)
    eta_hat = (m / k) * jnp.sum(xt * yt)
    return jnp.sin(jnp.pi * eta_hat / 2.0)


def ci_ni_signbatch(key: jax.Array, x: jax.Array, y: jax.Array,
                    eps1: float, eps2: float, alpha: float = 0.05,
                    normalise: bool = True) -> CorrResult:
    """Estimate + CI (vert-cor.R:204-255).

    With ``normalise``, the *raw* values (not the signs) are privately
    centered first with clip L = √(2·log n), spending ε₁/ε₂ again exactly
    as the reference's full standardization does (vert-cor.R:211-215) —
    the σ division is dropped because this estimator consumes only signs
    and sign((x−μ)/σ) ≡ sign(x−μ); see :func:`priv_center`.
    """
    n = x.shape[0]
    m, k = batch_geometry(n, eps1, eps2)
    if normalise:
        l_clip = jnp.sqrt(2.0 * jnp.log(float(n)))
        # center-only: this estimator consumes signs, and
        # sign((x−μ)/σ) ≡ sign(x−μ) — see priv_center
        x = priv_center(stream(key, "ni_sign/std_x"), x, eps1, l_clip)
        y = priv_center(stream(key, "ni_sign/std_y"), y, eps2, l_clip)

    xt, yt = _noisy_batch_products(key, x, y, eps1, eps2, m, k)
    tj = m * xt * yt  # Sec 3.1 eq. (2) components (vert-cor.R:233)
    eta_hat = jnp.sum(tj) / k
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)

    s_eta = sample_sd(tj)
    crit = ndtri(1.0 - alpha / 2.0)
    half = crit * s_eta / jnp.sqrt(float(k))
    # η-space clamp THEN sine map (vert-cor.R:249-254).
    lo = jnp.sin(jnp.pi / 2.0 * jnp.maximum(eta_hat - half, -1.0))
    hi = jnp.sin(jnp.pi / 2.0 * jnp.minimum(eta_hat + half, 1.0))
    return CorrResult(rho_hat, lo, hi)
