"""The estimator-family name universe, jax-free.

:data:`FAMILIES` is the single source of truth for which estimator
families the serving layer accepts. It lives here — not in
:mod:`dpcorr.models.estimators.registry` — because the registry
imports the estimator implementations (and therefore jax), while
request validation, the fleet front end, and the jax-free benchmark
drivers only need the *names*. The registry re-exports it, so
``from dpcorr.models.estimators.registry import FAMILIES`` keeps
working for jax-loaded callers.
"""

from __future__ import annotations

#: Families the serving layer accepts, in SURVEY.md §2.2 order.
FAMILIES: tuple[str, ...] = ("ni_sign", "int_sign", "ni_subg", "int_subg")
