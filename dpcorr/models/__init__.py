"""Models: data-generating processes and DP correlation estimators (layers
L0 and L2 of the reference — SURVEY.md §1).

Submodules resolve lazily (PEP 562): :mod:`dpcorr.models.estimators.families`
is jax-free and is imported by the serve request validator and the fleet
front end, so this package init must not eagerly pull :mod:`dgp` (jax).
``dpcorr.models.dgp`` and ``from dpcorr.models import dgp`` still work —
the submodule loads on first attribute access.
"""

import importlib

_SUBMODULES = ("dgp", "estimators")


def __getattr__(name):
    if name in _SUBMODULES:
        mod = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_SUBMODULES))
