"""Models: data-generating processes and DP correlation estimators (layers
L0 and L2 of the reference — SURVEY.md §1)."""

from dpcorr.models import dgp  # noqa: F401
