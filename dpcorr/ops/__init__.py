"""DP primitives (reference layer L1 — SURVEY.md §1).

Laplace noise, clipping, clipping-threshold (λ) rules, mixture quantiles and
DP standardization, each as pure vmap-able JAX functions.
"""

from dpcorr.ops.lambdas import (  # noqa: F401
    lambda_from_priv,
    lambda_int_n,
    lambda_n,
    lambda_receiver_from_noise,
)
from dpcorr.ops.mixquant import mixquant, mixquant_mc  # noqa: F401
from dpcorr.ops.noise import clip, clip_sym, laplace  # noqa: F401
from dpcorr.ops.standardize import (  # noqa: F401
    dp_mean,
    dp_sd,
    dp_second_moment,
    priv_center,
    priv_mean_from_sum,
    priv_standardize,
    standardize_dp,
)
