"""DP primitives (reference layer L1 — SURVEY.md §1).

Laplace noise, clipping, clipping-threshold (λ) rules, mixture quantiles and
DP standardization, each as pure vmap-able JAX functions.
"""

from dpcorr.ops.noise import laplace, clip, clip_sym  # noqa: F401
from dpcorr.ops.lambdas import (  # noqa: F401
    lambda_n,
    lambda_int_n,
    lambda_from_priv,
    lambda_receiver_from_noise,
)
from dpcorr.ops.mixquant import mixquant, mixquant_mc  # noqa: F401
from dpcorr.ops.standardize import (  # noqa: F401
    priv_standardize,
    priv_center,
    priv_mean_from_sum,
    dp_mean,
    dp_second_moment,
    dp_sd,
    standardize_dp,
)
