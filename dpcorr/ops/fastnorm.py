"""Box–Muller Gaussian sampler with polynomial sincos (CPU fast path).

Why this exists (r08 profile of the bench headline on the 1-core CPU
box): the per-rep cost is ~75% ``jax.random.normal``, and inside it XLA
CPU *scalarizes* ``log1p`` — the erf⁻¹ rational approximation calls
libm per element (~200 µs of the 310 µs normal-draw cost at n=2·10⁴)
while ``log``/``exp``/``sqrt`` vectorize. Box–Muller avoids erf⁻¹
entirely, but the naive form loses the win to ``sin``/``cos`` — also
scalar libm calls on CPU (~120 µs each). The sampler here spends its
transcendental budget only on the vectorized ops:

- radius: ``sqrt(-2·log(u1))`` — both vectorized;
- angle: ``sincos_2pi(u2)`` evaluates sin/cos of ``θ = 2π·u2`` with
  degree-7/8 minimax polynomials (Cephes f32 coefficients) after an
  *exact* range reduction: ``t = 4·u2`` is exact in f32 (a power-of-two
  scale of a [0,1) value), the quarter-turn index ``k = round(t)`` and
  remainder ``r = (t−k)·π/2`` then select the quadrant — no Payne–Hanek
  machinery needed because the argument is constructed, not arbitrary.

Accuracy: max |error| vs f64 sin/cos is ~4.2e-7 (≈4 ulp at 1.0) across
[0,1) — far below the sampler's own f32 rounding noise downstream.
Distributionally this is an *exact* Gaussian sampler (Box–Muller is
exact; the polynomial error perturbs each draw by ≲1e-6 relative),
but it is NOT bit-identical to ``jax.random.normal``'s inverse-CDF
draws — same stream-independence contract as the TPU ``rbg`` impl and
the Pallas hardware-PRNG path: acceptance is statistical (the bench
``_sane`` gate; SURVEY.md §5 RNG), and results are stamped as their
own path (``xla_bm``), never mixed with threefry+erf⁻¹ numbers.

Measured (r08, this box, n=10⁴ bench rep): 194 µs/rep vs 411 µs on the
inverse-CDF path — the whole-bench win that recovers the ≥1.0×-baseline
headline on CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["sincos_2pi", "normal_pairs", "gen_gaussian_bm"]

#: Cephes single-precision minimax coefficients on [-π/4, π/4].
_SIN_C = (-1.6666654611e-1, 8.3321608736e-3, -1.9515295891e-4)
_COS_C = (4.166664568298827e-2, -1.388731625493765e-3,
          2.443315711809948e-5)


def sincos_2pi(u: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``(sin(2πu), cos(2πu))`` for ``u ∈ [0, 1)``, f32, vectorized.

    Range reduction is exact: ``t = 4u`` only scales the exponent, so
    the quarter-turn remainder ``r = (t − round(t))·π/2 ∈ [−π/4, π/4]``
    carries no cancellation error beyond the one rounding in the final
    multiply. Quadrant selection rotates (sin, cos) by k·90°.
    """
    u = jnp.asarray(u, jnp.float32)
    t4 = 4.0 * u
    k = jnp.round(t4)
    r = (t4 - k) * jnp.float32(np.pi / 2)
    r2 = r * r
    s = r * (1.0 + r2 * (_SIN_C[0] + r2 * (_SIN_C[1] + r2 * _SIN_C[2])))
    c = 1.0 + r2 * (-0.5 + r2 * (_COS_C[0]
                                 + r2 * (_COS_C[1] + r2 * _COS_C[2])))
    km = k.astype(jnp.int32) & 3
    sin = jnp.where(km == 0, s,
                    jnp.where(km == 1, c, jnp.where(km == 2, -s, -c)))
    cos = jnp.where(km == 0, c,
                    jnp.where(km == 1, -s, jnp.where(km == 2, -c, s)))
    return sin, cos


def normal_pairs(key: jax.Array, n: int) -> tuple[jax.Array, jax.Array]:
    """Two independent N(0,1) f32 vectors of length ``n`` from one key
    (Box–Muller: each uniform pair yields a full Gaussian pair — half
    the random bits of two inverse-CDF draws, zero erf⁻¹ calls)."""
    u = jax.random.uniform(key, (n, 2), jnp.float32)
    # u1 = 0 would send the radius to +inf; clamp to the smallest
    # positive normal (probability 2⁻³² per draw, same guard the
    # textbook form uses)
    u1 = jnp.maximum(u[:, 0], jnp.finfo(jnp.float32).tiny)
    rad = jnp.sqrt(-2.0 * jnp.log(u1))
    s, c = sincos_2pi(u[:, 1])
    return rad * c, rad * s


def gen_gaussian_bm(key: jax.Array, n: int, rho, mu: float = 0.0,
                    sigma: float = 1.0) -> jax.Array:
    """Drop-in for ``dpcorr.models.dgp.gen_gaussian`` on the Box–Muller
    sampler: (n, 2) correlated Gaussians via the same 2×2 Cholesky
    ``y = ρ·z₁ + √(1−ρ²)·z₂``. Statistically identical law, different
    stream — bench ``xla_bm`` path only; the simulator's replay
    contract stays on ``gen_gaussian``."""
    rho = jnp.asarray(rho, jnp.float32)
    z1, z2 = normal_pairs(key, n)
    x = z1
    y = rho * z1 + jnp.sqrt(jnp.maximum(1.0 - rho * rho, 0.0)) * z2
    return mu + sigma * jnp.stack([x, y], axis=1)
