"""DP standardization primitives (reference layer L1).

Two families in the reference:

- the simulation-side ``priv_standardize`` with a symmetric clip and an ε
  split in half between DP mean and DP second moment (vert-cor.R:322-348);
- the real-data building blocks ``dp_mean`` / ``dp_sd`` /
  ``standardize_dp`` with asymmetric [lo, hi] bounds
  (real-data-sims.R:64-100).

All are pure functions of (key, data, bounds, ε); NA handling is done
host-side before entering kernels (the reference's ``x[!is.na(x)]`` /
pairwise-complete filters, real-data-sims.R:65, 119-120, 187-188).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dpcorr.ops.noise import clip, clip_sym, laplace
from dpcorr.utils.rng import stream


def priv_moments_from_sums(key: jax.Array, s1, s2, n: int, eps_norm, l_raw,
                           var_floor=1e-12):
    """(μ_priv, var_priv) from Σ clip(x) and Σ clip(x)² — the noise half of
    ``priv_standardize`` (vert-cor.R:337-343): split ε in half; DP mean
    (sensitivity 2L/n) and DP second moment (sensitivity 2L²/n) via one
    Laplace draw each; variance floored at ``var_floor``.

    Shared by the materialized and streaming standardization paths so noise
    scales and key addresses can never diverge between them.
    """
    eps_half = eps_norm / 2.0
    mu_priv = priv_mean_from_sum(key, s1, n, eps_norm, l_raw)
    m2_priv = s2 / n + laplace(stream(key, "priv_standardize/m2"), (),
                               2.0 * l_raw * l_raw / (n * eps_half))
    return mu_priv, jnp.maximum(m2_priv - mu_priv * mu_priv, var_floor)


def priv_mean_from_sum(key: jax.Array, s1, n: int, eps_norm, l_raw):
    """The DP-mean half of ``priv_moments_from_sums`` alone: ε/2 of the
    standardization budget, sensitivity 2L/n (vert-cor.R:337-339).

    Streams are namespaced per primitive so two different primitives
    handed the same key never draw correlated noise; the ``mu`` address is
    shared with ``priv_moments_from_sums``, so a center-only consumer sees
    the *bit-identical* μ_priv the full standardizer would compute.
    """
    eps_half = eps_norm / 2.0
    return s1 / n + laplace(stream(key, "priv_standardize/mu"), (),
                            2.0 * l_raw / (n * eps_half))


def priv_standardize(key: jax.Array, vec: jax.Array, eps_norm, l_raw=6.0,
                     var_floor=1e-12) -> jax.Array:
    """DP center–scale with a single pre-clip (vert-cor.R:322-348):
    clip at ±l_raw, private moments, standardize without further clipping."""
    n = vec.shape[0]
    x = clip_sym(vec, l_raw)
    mu_priv, var_priv = priv_moments_from_sums(
        key, jnp.sum(x), jnp.sum(x * x), n, eps_norm, l_raw, var_floor)
    return (x - mu_priv) / jnp.sqrt(var_priv)


def priv_center(key: jax.Array, vec: jax.Array, eps_norm,
                l_raw=6.0) -> jax.Array:
    """Center-only ``priv_standardize`` for sign-only consumers: since
    σ_priv > 0, sign((x−μ_priv)/σ_priv) ≡ sign(x−μ_priv), so the second
    moment — whose ε/2 the construction's budget accounting still spends
    (vert-cor.R:340-343) — never needs materializing. Saves the Σx²
    reduction and the n-length divide per call; μ_priv is bit-identical to
    the full standardizer's (same ``mu`` stream address). The fused Pallas
    kernel applies the same identity on-chip (pallas_ni.py)."""
    n = vec.shape[0]
    x = clip_sym(vec, l_raw)
    return x - priv_mean_from_sum(key, jnp.sum(x), n, eps_norm, l_raw)


def dp_mean(key: jax.Array, x: jax.Array, lo, hi, eps) -> jax.Array:
    """Clipped DP mean, sensitivity (hi−lo)/n (real-data-sims.R:64-70)."""
    n = x.shape[0]
    return jnp.mean(clip(x, lo, hi)) + laplace(key, (), (hi - lo) / (n * eps))


def dp_second_moment(key: jax.Array, x: jax.Array, lo, hi, eps) -> jax.Array:
    """Clipped DP E[x²].

    The reference uses sensitivity (hi²−lo²)/n (real-data-sims.R:80), valid
    for its use sites where 0 ≤ lo < hi (age [45,90], BMI [15,35]). As a
    generic primitive that formula degenerates to zero noise for symmetric
    bounds, so we use the correct range of x² over [lo, hi]: when the bounds
    straddle 0, x² ∈ [0, max(lo², hi²)]; otherwise |hi²−lo²|. Reduces to the
    reference's exactly on its domain.
    """
    n = x.shape[0]
    xc = clip(x, lo, hi)
    lo2, hi2 = lo * lo, hi * hi
    straddles = (lo < 0.0) & (hi > 0.0)
    sens_range = jnp.where(straddles, jnp.maximum(lo2, hi2), jnp.abs(hi2 - lo2))
    return jnp.mean(xc * xc) + laplace(key, (), sens_range / (n * eps))


def dp_sd(key: jax.Array, x: jax.Array, lo, hi, eps1, eps2):
    """Private (mean, sd) via clipped 2nd moment (real-data-sims.R:73-84).

    sd = √max(m2 − μ², 0) — floored at exactly 0 as in the reference (:82),
    unlike :func:`priv_standardize`'s 1e-12 floor.
    """
    mu = dp_mean(stream(key, "dp_sd/mean"), x, lo, hi, eps1)
    m2 = dp_second_moment(stream(key, "dp_sd/m2"), x, lo, hi, eps2)
    sd = jnp.sqrt(jnp.maximum(m2 - mu * mu, 0.0))
    return mu, sd


def standardize_dp(x: jax.Array, priv_mean, priv_sd, lo, hi, eps=1e-8) -> jax.Array:
    """Clip to [lo, hi] then standardize by private moments with an sd floor
    (real-data-sims.R:87-100)."""
    return (clip(x, lo, hi) - priv_mean) / jnp.maximum(priv_sd, eps)
