"""Clipping-threshold (λ) rules.

One definition per rule (the reference duplicates them across files —
SURVEY.md Appendix A #6). All are cheap scalar formulas evaluated at trace
time or inside kernels; they accept Python floats or JAX scalars.
"""

from __future__ import annotations

import jax.numpy as jnp


def lambda_n(n, eta=1.0):
    """NI clip threshold ``min(2η√log n, 2√3)``.

    Reference: ver-cor-subG.R:1 (duplicate real-data-sims.R:109).
    """
    return jnp.minimum(2.0 * eta * jnp.sqrt(jnp.log(n * 1.0)), 2.0 * jnp.sqrt(3.0))


def lambda_int_n(n, eta_s=1.0, eta_r=1.0, eps_s=1.0):
    """INT clip pair ``(λ_s, λ_r)``.

    λ_s as :func:`lambda_n`; λ_r = 5·max(η_r,1)·min(log n, 6)/min(ε_s, 1).
    The reference flags λ_r as a deliberate deviation from the paper
    (ver-cor-subG.R:3-7, real-data-sims.R:154-158).
    """
    lam_s = lambda_n(n, eta_s)
    lam_r = 5.0 * jnp.maximum(eta_r, 1.0) * jnp.minimum(jnp.log(n * 1.0), 6.0) / jnp.minimum(eps_s, 1.0)
    return lam_s, lam_r


def lambda_from_priv(lo, hi, priv_mean, priv_sd, eps_sd=1e-8):
    """Symmetric bound for a standardized variable from known raw bounds and
    its private mean/sd: ``max(|lo−μ|, |hi−μ|)/max(sd, eps)``.

    Reference: real-data-sims.R:103-106.
    """
    sig = jnp.maximum(priv_sd, eps_sd)
    return jnp.maximum(jnp.abs((lo - priv_mean) / sig), jnp.abs((hi - priv_mean) / sig))


def lambda_receiver_from_noise(lambda_sender, lambda_other, eps_sender,
                               delta_per_sample):
    """Receiver product bound accounting for the sender's local-DP noise.

    If the sender releases ``clip(X, ±λ_s) + Lap(0, b_s)`` with
    ``b_s = 2λ_s/ε_s`` and the receiver multiplies by its variable clipped to
    ±λ_o, then with probability ≥ 1−δ per sample
    ``|U| ≤ (λ_s + b_s·log(1/δ))·λ_o``.

    Reference: real-data-sims.R:170-174.
    """
    b_s = 2.0 * lambda_sender / eps_sender
    return (lambda_sender + b_s * jnp.log(1.0 / delta_per_sample)) * lambda_other
