"""Laplace noise and clipping.

The reference has two Laplace samplers — ``extraDistr::rlaplace`` wrapped as
``rLap`` (vert-cor.R:106) and a hand-rolled inverse-CDF version
(real-data-sims.R:58-61: ``-scale*sign(u)*log(1-2|u|)`` for u~U(-.5,.5)).
Both are Laplace(0, scale); here there is exactly one implementation on top
of JAX's counter-based PRNG, usable under ``jit``/``vmap`` and on TPU.

Clipping is the reference's ubiquitous ``pmax(pmin(x, λ), -λ)``.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def laplace(key: jax.Array, shape: Sequence[int] | tuple = (),
            scale: jax.Array | float = 1.0,
            dtype=jnp.float32) -> jax.Array:
    """Laplace(0, scale) draws. ``scale`` may be a scalar or broadcastable.

    Equivalent in distribution to ``rLap(n, scale)`` (vert-cor.R:106,
    real-data-sims.R:58-61).
    """
    return jax.random.laplace(key, shape=tuple(shape), dtype=dtype) * scale


def clip(x: jax.Array, lo, hi) -> jax.Array:
    """``pmin(pmax(x, lo), hi)`` (e.g. real-data-sims.R:67)."""
    return jnp.clip(x, lo, hi)


def clip_sym(x: jax.Array, lam) -> jax.Array:
    """Symmetric clip to [-λ, λ] (e.g. ver-cor-subG.R:33-34)."""
    return jnp.clip(x, -lam, lam)
