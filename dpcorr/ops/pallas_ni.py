"""Pallas TPU kernel: one fully-fused NI sign-batch replication.

The bench hot loop (vert-cor.R:392-419 → ``bench.py``) is, per replication:
generate an (n, 2) Gaussian pair, privately standardize, sign-batch
estimate (SURVEY.md §2.2-A). The XLA path materializes the n-vectors
between fusion boundaries and burns most of its time in the counter-based
threefry PRNG. This kernel runs the whole replication inside VMEM on one
grid step:

- **on-chip PRNG** (``pltpu.prng_random_bits``, the TPU hardware generator)
  seeded per replication from an SMEM scalar; Gaussians via Box–Muller,
  Laplace via the reference's own inverse-CDF (real-data-sims.R:58-61);
- **DP standardization** (vert-cor.R:322-348) from masked in-register
  moment sums;
- **sign batch sums as an MXU matmul** against a static 0/1 block-
  aggregation matrix G[l, c] = 1{l//m' == c} — the (k, m)-reshape-mean
  (vert-cor.R:131-140) becomes ``signs(R,128) @ G(128,128)`` — G's
  columns beyond 128//m' are identically zero, keeping full-lane tiles;
- per-batch Laplace noise, Σ T_j / Σ T_j² reduction; only the two scalars
  (η̂, sd T) leave the chip per replication;
- optionally (``compute_int``) the INT sign-flip estimator
  (vert-cor.R:164-195) on the *same* in-kernel draw with its own fresh DP
  centering noise — the grid's hot-loop body computes both estimators per
  dataset (vert-cor.R:392-419) — adding one more scalar (η̂_INT) to the
  output; :func:`sim_detail_pallas` turns the three scalars into the full
  12-column detail row and is the bucketed grid backend's fused path.

**Batch layout (any m ≤ 128).** Lanes are grouped into k groups of
m' = next power of two ≥ m (so m' | 128 and groups never straddle a
register row); each group's first m lanes hold one batch's data and the
remaining m'−m lanes are padding, masked out of both the moment sums and
the sign matmul. The n − k·m leftover observations (which the estimator
ignores but ``priv_standardize`` *does* consume, vert-cor.R:126 vs 322-348)
are appended after the k groups so the DP moments see exactly n elements.
Because every element is an iid draw generated in-kernel, assigning
positions to batches this way is distribution-identical to the reference's
consecutive-index batching. When m | 128 the layout degenerates to the
dense one (m' = m, no padding).

Applicability: the Gaussian DGP with m ≤ 128 and k ≥ 2 — this covers the
whole reference ε-grid, including the (1.5, 0.5) pair's m = 11 → m' = 16
(vert-cor.R:488-494). Other shapes fall back to the XLA path
(``use_ni_sign_pallas`` reports which). Estimates are
distribution-identical to :func:`~dpcorr.models.estimators.ci_ni_signbatch`
but draw from a different PRNG, so acceptance is statistical (SURVEY.md §5
RNG), validated in ``tests/test_pallas_ni.py``.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.scipy.special import ndtri

from dpcorr.models.estimators.common import CorrResult, batch_geometry

LANES = 128
_TWO_PI = 2.0 * math.pi


def _pad_m(m: int) -> int:
    """Smallest power of two ≥ m (the lane-group width m' | 128)."""
    return 1 << (m - 1).bit_length()


def _layout(n: int, eps1: float, eps2: float):
    """(m, m', k, leftover, rows) for the padded lane-group layout.

    ``rows`` is rounded up to a multiple of 8 so every kernel
    intermediate is a full (8·r, 128) TPU tile — Mosaic handles aligned
    shapes best (and the position masks make padding rows inert)."""
    m, k = batch_geometry(n, eps1, eps2)
    m_pad = _pad_m(m)
    leftover = n - k * m
    rows = -(-(k * m_pad + leftover) // LANES)
    rows = -(-rows // 8) * 8
    return m, m_pad, k, leftover, rows


def use_ni_sign_pallas(n: int, eps1: float, eps2: float) -> bool:
    """True iff the fused kernel covers this configuration
    (m ≤ 128 so one lane group holds a batch, and k ≥ 2 so sd(T_j) exists).
    """
    m, k = batch_geometry(n, eps1, eps2)
    return m <= LANES and k >= 2


def _uniform(bits):
    """random bits → strictly-interior (0, 1) float32 uniforms.

    ``pltpu.prng_random_bits`` yields *int32* on TPU; a bare right-shift
    would sign-extend and make half the draws negative (NaN under log), so
    mask the shift result. 23 bits (not 24): with 24, the top value
    (2²⁴−1)+0.5 rounds to 2²⁴ in float32 and the uniform becomes exactly
    1.0 — −inf through the Laplace ``log1p``. Every 23-bit value ±0.5 is
    exactly representable, so u ∈ [2⁻²⁴, 1−2⁻²⁴].
    """
    b23 = jnp.bitwise_and(jnp.right_shift(bits, 9), 0x7FFFFF)
    return (b23.astype(jnp.float32) + 0.5) * (2.0**-23)


def _rand_uniform(shape):
    return _uniform(pltpu.prng_random_bits(shape))


# ---- scaffolding shared by every replication kernel in this module:
# seed words, uniform source, layout masks, aggregation matrix, and the
# pallas_call shell. One copy — the lane-group mask and BlockSpec rules
# are the easiest places for kernels to drift apart. (The fused subG
# kernel, pallas_subg.py, consumed this scaffolding until its r05
# retirement — GridConfig.fused has the decision record.)


def _seed_words(seeds) -> jax.Array:
    """(B,) or (B, 2) int32 → (B, 2) seed words. Two 32-bit words give the
    on-chip PRNG a 2⁶⁴ seed space — a (B,) input is zero-extended (kept for
    the bench's block-indexed seeds, which are collision-free by
    construction; key-tree-derived seeds use both words, rng.pallas_seeds)."""
    seeds = jnp.asarray(seeds, jnp.int32)
    if seeds.ndim == 1:
        seeds = jnp.stack([seeds, jnp.zeros_like(seeds)], axis=-1)
    return seeds


def _taker(external: bool, u_ref, seed_ref):
    """The kernel's uniform source: external-mode cursor reads from the
    HBM uniform block (CPU-testable path), on-chip mode seeds the hardware
    PRNG from the two SMEM seed words and draws fresh bits per take()."""
    if external:
        cursor = [0]

        def take(shape):
            r0 = cursor[0]
            cursor[0] += shape[0]
            return u_ref[0, pl.ds(r0, shape[0]), :]
    else:
        pltpu.prng_seed(seed_ref[0, 0, 0], seed_ref[0, 0, 1])

        def take(shape):
            return _rand_uniform(shape)

    return take


def _position_masks(rows: int, m: int, m_pad: int, k: int, leftover: int):
    """(batch_elem, w) over the padded lane-group layout: position p holds
    batch element (group p//m', offset p%m' < m), a leftover observation
    (k·m' ≤ p < k·m'+leftover), or pure padding. ``w`` masks exactly the n
    real observations (float), ``batch_elem`` the k·m estimator inputs
    (bool)."""
    pos = (jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0) * LANES
           + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1))
    batch_elem = (pos % m_pad < m) & (pos // m_pad < k)
    in_leftover = (pos >= k * m_pad) & (pos < k * m_pad + leftover)
    return batch_elem, (batch_elem | in_leftover).astype(jnp.float32)


def _gmat(m_pad: int) -> jax.Array:
    """Static 0/1 aggregation matrix: lane l feeds batch column l // m'
    (columns ≥ 128//m' are identically zero — full-lane tiles)."""
    return jnp.asarray(
        (np.arange(LANES)[:, None] // m_pad) == np.arange(LANES)[None, :],
        jnp.float32)


def _replication_call(kernel, b: int, seeds2: jax.Array, rho_b: jax.Array,
                      gmat: jax.Array, u_rows: int | None,
                      uniforms: jax.Array | None, interpret: bool):
    """One-replication-per-grid-step pallas_call shell. Mosaic requires
    every block's trailing two dims to be divisible by (8, 128) or equal
    to the array's — so the grid axis is a *leading* third dim everywhere
    and each block's last two dims equal the array's. Out layout:
    (b, 1, LANES) with the kernel's scalars in the leading lanes."""
    in_specs = [
        pl.BlockSpec((1, 1, 2), lambda i: (i, 0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((LANES, LANES), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    inputs = [seeds2.reshape(b, 1, 2), rho_b.reshape(b, 1, 1), gmat]
    if uniforms is not None:
        in_specs.append(pl.BlockSpec((1, u_rows, LANES),
                                     lambda i: (i, 0, 0),
                                     memory_space=pltpu.VMEM))
        inputs.append(uniforms.reshape(b, u_rows, LANES))
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 1, LANES), jnp.float32),
        # TPU interpret mode runs the kernel on CPU (pltpu.prng_* stubs
        # return zeros there — external uniforms cover testing)
        interpret=pltpu.InterpretParams() if interpret else False,
    )(*inputs)


def _ndtri_inline(p):
    """Inverse standard-normal CDF as an in-kernel rational polynomial
    (Acklam's algorithm; ~1.15e-9 relative in f64, but the kernel runs
    f32 where cancellation near the central/tail seam |z|≈1.97 brings
    the max abs error to ~3e-4 — same order as Box–Muller's own f32
    rounding, and the draws only feed sign/clip estimators whose 1e-3
    coverage criterion is insensitive at that scale).
    ``jax.scipy.special.ndtri`` lowers with captured f32 coefficient
    *tables*, which a pallas kernel cannot close over — these scalar
    literals fold into the kernel. Central branch costs two
    ~5-term polynomial chains; the tail branch's log+sqrt run on all lanes
    (SIMD ``where`` evaluates both sides), so the saving vs Box–Muller is
    cos+sin, not the log."""
    q = p - 0.5
    r = q * q
    central = (q * (((((-3.969683028665376e+01 * r
                        + 2.209460984245205e+02) * r
                       - 2.759285104469687e+02) * r
                      + 1.383577518672690e+02) * r
                     - 3.066479806614716e+01) * r
                    + 2.506628277459239e+00)
               / (((((-5.447609879822406e+01 * r
                      + 1.615858368580409e+02) * r
                     - 1.556989798598866e+02) * r
                    + 6.680131188771972e+01) * r
                   - 1.328068155288572e+01) * r + 1.0))
    # lower tail on min(p, 1-p), mirrored by sign
    pt = jnp.minimum(p, 1.0 - p)
    s = jnp.sqrt(-2.0 * jnp.log(pt))
    tail = ((((((-7.784894002430293e-03 * s
                 - 3.223964580411365e-01) * s
                - 2.400758277161838e+00) * s
               - 2.549732539343734e+00) * s
              + 4.374664141464968e+00) * s
             + 2.938163982698783e+00)
            / ((((7.784695709041462e-03 * s
                  + 3.224671290700398e-01) * s
                 + 2.445134137142996e+00) * s
                + 3.754408661907416e+00) * s + 1.0))
    tail = jnp.where(q < 0.0, tail, -tail)
    return jnp.where(jnp.abs(q) <= 0.5 - 0.02425, central, tail)


def _laplace_from_uniform(u, scale):
    """Inverse-CDF Laplace(0, scale) — the reference's own sampler
    (real-data-sims.R:58-61) on centered u−½ ∈ (−½, ½)."""
    c = u - 0.5
    return -scale * jnp.sign(c) * jnp.log1p(-2.0 * jnp.abs(c))


def n_uniform_rows(n: int, eps1: float = 1.0, eps2: float = 1.0,
                   compute_int: bool = False) -> int:
    """Rows of (·, 128) uniforms one replication consumes (external mode):
    u1 + u2 (rows each) + 8 standardization rows + 2·rows batch noise,
    plus (``compute_int``) 8 INT-standardization/Z rows + rows flip draws.
    ``rows`` depends on the ε-pair through the padded lane-group layout."""
    *_, rows = _layout(n, eps1, eps2)
    return 4 * rows + 8 + (rows + 8 if compute_int else 0)


def _make_kernel(n: int, m: int, m_pad: int, k: int, leftover: int,
                 rows: int, eps1: float, eps2: float,
                 mu, sigma, normalise: bool, external_uniforms: bool,
                 compute_int: bool = False, gauss: str = "boxmuller"):
    g_cols = LANES // m_pad
    l_clip = math.sqrt(2.0 * math.log(n))
    scale_x = 2.0 / (m * eps1)
    scale_y = 2.0 / (m * eps2)
    # INT sign-flip constants (vert-cor.R:170-191): sender = larger ε
    eps_s, eps_r = max(eps1, eps2), min(eps1, eps2)
    e_s = math.exp(eps_s)
    p_keep = e_s / (e_s + 1.0)
    c_eta = (e_s + 1.0) / (n * (e_s - 1.0))
    scale_z = 2.0 * (e_s + 1.0) / (n * (e_s - 1.0) * eps_r)

    def kernel(seed_ref, rho_ref, gmat_ref, *rest):
        if external_uniforms:
            # test mode: the interpreter stubs pltpu.prng_random_bits to
            # zeros, so uniforms come from HBM and only the on-chip PRNG
            # is untested off-TPU
            u_ref, out_ref = rest
        else:
            u_ref, (out_ref,) = None, rest
        take = _taker(external_uniforms, u_ref, seed_ref)

        rho = rho_ref[0, 0, 0]

        # ---- generate: standard-normal planes → 2×2 Cholesky
        # (dgp.py:_bvn). Two exact samplers, selectable because the
        # kernel is VPU-bound on this step: "boxmuller" costs
        # log+sqrt+cos+sin per pair, "ndtri" one inverse-CDF per normal
        # (same uniform consumption, so external-mode tests cover both).
        u1 = take((rows, LANES))
        u2 = take((rows, LANES))
        if gauss == "ndtri":
            z1 = _ndtri_inline(u1)
            z2 = _ndtri_inline(u2)
        else:
            r = jnp.sqrt(-2.0 * jnp.log(u1))
            z1 = r * jnp.cos(_TWO_PI * u2)
            z2 = r * jnp.sin(_TWO_PI * u2)
        x = mu[0] + sigma[0] * z1
        y = mu[1] + sigma[1] * (rho * z1 + jnp.sqrt(1.0 - rho * rho) * z2)

        # moment mask w: exactly the n real observations (vert-cor.R:322-348
        # standardizes over all n, estimator uses the first k·m)
        batch_elem, w = _position_masks(rows, m, m_pad, k, leftover)

        def center(v, eps, mu_noise):
            # priv_standardize (vert-cor.R:322-348): clip, DP mean + DP
            # 2nd moment (ε/2 each), standardize. Signs only need
            # x − μ (σ_priv > 0), so the division is dropped and the DP
            # 2nd moment (which the budget still pays for, ε/2) never
            # needs to be materialized here.
            vc = jnp.clip(v, -l_clip, l_clip)
            eps_half = eps / 2.0
            mu_p = (jnp.sum(vc * w) / n
                    + mu_noise * 2.0 * l_clip / (n * eps_half))
            return vc - mu_p

        if normalise:
            lap4 = _laplace_from_uniform(take((8, LANES)), 1.0)
            x_c = center(x, eps1, lap4[0, 0])
            y_c = center(y, eps2, lap4[1, 0])
        else:
            x_c, y_c = x, y

        # ---- sign batch sums on the MXU: (rows,128) @ G(128,128) ----
        # padding lanes inside a group must not leak into the batch sum;
        # G's columns beyond g_cols are identically zero (l // m' never
        # reaches them), so intermediates stay full 128-lane tiles
        bmask = batch_elem.astype(jnp.float32)
        sx = jnp.sign(x_c) * bmask
        sy = jnp.sign(y_c) * bmask
        g = gmat_ref[...]
        xb = jnp.dot(sx, g, preferred_element_type=jnp.float32) / m
        yb = jnp.dot(sy, g, preferred_element_type=jnp.float32) / m

        # ---- per-batch Laplace noise (sens 2/m, vert-cor.R:143-146) ----
        # full-width draws; the same uniforms land on the same live
        # (row, col < g_cols) positions, dead columns are masked below
        lap_xy = _laplace_from_uniform(take((2 * rows, LANES)), 1.0)
        xt = xb + lap_xy[:rows, :] * scale_x
        yt = yb + lap_xy[rows:, :] * scale_y

        # ---- T_j = m·X̃_j·Ỹ_j over the k real batches ----
        rr = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
        cc = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
        live = (cc < g_cols) & (rr * g_cols + cc < k)
        t = jnp.where(live, m * xt * yt, 0.0)
        st = jnp.sum(t)
        st2 = jnp.sum(t * t)

        if compute_int:
            # ---- INT sign-flip on the same draw (vert-cor.R:164-195):
            # the grid computes BOTH estimators per replication from one
            # dataset (vert-cor.R:392-419), each with its own fresh DP
            # centering noise (ci_NI/ci_INT both call priv_standardize,
            # vert-cor.R:211-215, 268-273) ----
            lap_i = _laplace_from_uniform(take((8, LANES)), 1.0)
            if normalise:
                x_i = center(x, eps1, lap_i[0, 0])
                y_i = center(y, eps2, lap_i[1, 0])
            else:
                x_i, y_i = x, y
            # randomized response: keep w.p. e^εs/(e^εs+1) (vert-cor.R:174)
            flips = jnp.where(take((rows, LANES)) < p_keep, 1.0, -1.0)
            core = flips * jnp.sign(x_i) * jnp.sign(y_i) * w
            # debias + one receiver Laplace draw (vert-cor.R:186-191)
            eta_int = c_eta * jnp.sum(core) + lap_i[2, 0] * scale_z
        else:
            eta_int = jnp.float32(0.0)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        out_ref[0, 0, :] = jnp.where(
            lane == 0, st,
            jnp.where(lane == 1, st2,
                      jnp.where(lane == 2, eta_int, 0.0)))[0, :]

    return kernel


@partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
def _ni_sign_pallas_sums(seeds: jax.Array, rho: jax.Array, n: int,
                         eps1: float, eps2: float, mu, sigma,
                         normalise: bool, interpret: bool,
                         compute_int: bool = False,
                         gauss: str = "boxmuller",
                         uniforms: jax.Array | None = None):
    seeds = _seed_words(seeds)
    b = seeds.shape[0]
    m, m_pad, k, leftover, rows = _layout(n, eps1, eps2)
    external = uniforms is not None
    kernel = _make_kernel(n, m, m_pad, k, leftover, rows, eps1, eps2,
                          tuple(mu), tuple(sigma), normalise, external,
                          compute_int, gauss)
    # ρ rides a per-replication SMEM scalar like the seed, so one compiled
    # kernel serves a whole shape bucket's ρ-sweep (the bucketed grid
    # flattens (point × rep) pairs; scalar ρ callers broadcast).
    rho = jnp.broadcast_to(jnp.asarray(rho, jnp.float32), (b,))
    u_rows = (n_uniform_rows(n, eps1, eps2, compute_int) if external
              else None)
    out = _replication_call(kernel, b, seeds, rho, _gmat(m_pad), u_rows,
                            uniforms, interpret)
    return out[:, 0, 0], out[:, 0, 1], out[:, 0, 2]


def ni_sign_pallas(seeds: jax.Array, rho, n: int, eps1: float, eps2: float,
                   mu=(0.0, 0.0), sigma=(1.0, 1.0), alpha: float = 0.05,
                   normalise: bool = True,
                   interpret: bool | None = None,
                   gauss: str = "boxmuller",
                   uniforms: jax.Array | None = None) -> CorrResult:
    """Fused generate+estimate for a whole replication batch.

    ``seeds``: (B,) int32 per-replication PRNG seeds. Returns the batched
    :class:`CorrResult` with the same CI construction as
    ``ci_ni_signbatch`` (η-space clamp then sine map, vert-cor.R:249-254).

    ``uniforms``: optional (B, n_uniform_rows(n, eps1, eps2), 128) external
    uniforms in (0, 1) replacing the on-chip PRNG — the CPU-testable path.
    """
    m, k = batch_geometry(n, eps1, eps2)
    if not use_ni_sign_pallas(n, eps1, eps2):
        raise ValueError(
            f"fused kernel needs m <= {LANES} and k >= 2, got m={m}, k={k}; "
            f"use the XLA path (see use_ni_sign_pallas)")
    if gauss not in ("boxmuller", "ndtri"):
        # a typo must not silently select the wrong sampler
        raise ValueError(f"gauss must be 'boxmuller' or 'ndtri', "
                         f"got {gauss!r}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if interpret and uniforms is None:
        raise ValueError(
            "on-chip PRNG is only live on real TPU (the interpreter stubs "
            "pltpu.prng_random_bits to zeros) — pass `uniforms` with shape "
            f"(B, {n_uniform_rows(n, eps1, eps2)}, {LANES}) off-TPU")
    st, st2, _ = _ni_sign_pallas_sums(
        jnp.asarray(seeds, jnp.int32), jnp.float32(rho), n, eps1, eps2,
        tuple(mu), tuple(sigma), normalise, interpret, False, gauss,
        uniforms=uniforms)
    # jitted tail: eagerly dispatching the ~50 ops inside ndtri after an
    # interpret-mode pallas_call contends with the interpreter's
    # io_callback machinery and can stall for minutes (observed in-suite)
    return CorrResult(*_ni_result_jit(st, st2, k, float(alpha)))


@partial(jax.jit, static_argnums=(2, 3))
def _ni_result_jit(st, st2, k: int, alpha: float):
    r = _ni_result(st, st2, k, alpha)
    return r.rho_hat, r.ci_low, r.ci_high


def _ni_result(st: jax.Array, st2: jax.Array, k: int,
               alpha: float) -> CorrResult:
    """NI estimate + CI from the kernel's (ΣT_j, ΣT_j²) scalars — the same
    η-space clamp-then-sine construction as ``ci_ni_signbatch``
    (vert-cor.R:249-254)."""
    eta_hat = st / k
    var_t = jnp.maximum((st2 - k * eta_hat * eta_hat) / (k - 1), 0.0)
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    half = ndtri(1.0 - alpha / 2.0) * jnp.sqrt(var_t) / math.sqrt(k)
    lo = jnp.sin(jnp.pi / 2.0 * jnp.maximum(eta_hat - half, -1.0))
    hi = jnp.sin(jnp.pi / 2.0 * jnp.minimum(eta_hat + half, 1.0))
    return CorrResult(rho_hat, lo, hi)


@partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10, 11))
def _sim_detail_jit(seeds, rhos, n: int, eps1: float, eps2: float,
                    mu, sigma, alpha: float, ci_mode: str,
                    normalise: bool, interpret: bool,
                    gauss: str = "boxmuller", uniforms=None):
    from dpcorr.models.estimators.int_sign import interval_from_rho
    from dpcorr.sim import _metrics_row

    _, k = batch_geometry(n, eps1, eps2)
    st, st2, eta_int = _ni_sign_pallas_sums(
        seeds, rhos, n, eps1, eps2, mu, sigma, normalise, interpret,
        True, gauss, uniforms=uniforms)
    ni = _ni_result(st, st2, k, alpha)
    rho_hat_int = jnp.sin(jnp.pi * eta_int / 2.0)
    eps_s, eps_r = max(eps1, eps2), min(eps1, eps2)
    # det mixquant only: the closed-form quantile needs no key (the grid's
    # fused path is gated on mixquant_mode="det")
    it = interval_from_rho(None, rho_hat_int, n, eps_s, eps_r, alpha,
                           ci_mode, "det")
    return _metrics_row(ni, it, rhos)


def sim_detail_pallas(seeds: jax.Array, rhos, n: int, eps1: float,
                      eps2: float, mu=(0.0, 0.0), sigma=(1.0, 1.0),
                      alpha: float = 0.05, ci_mode: str = "auto",
                      normalise: bool = True,
                      interpret: bool | None = None,
                      gauss: str = "boxmuller",
                      uniforms: jax.Array | None = None) -> tuple:
    """Whole-replication fused simulation: one kernel pass generates the
    data on-chip and computes BOTH the NI sign-batch sums and the INT
    sign-flip η̂ from it (the reference's hot-loop body computes both
    estimators per dataset, vert-cor.R:392-419), then the CI constructions
    run as scalar XLA ops. Returns the 12-tuple in
    :data:`dpcorr.sim.DETAIL_FIELDS` order — drop-in for
    ``sim._run_detail_flat`` where :func:`use_ni_sign_pallas` allows
    (Gaussian DGP, det mixquant; the bucketed grid backend's ``fused``
    mode is the consumer).

    ``rhos``: scalar or (B,) per-replication ρ (the bucketed grid flattens
    design points × replications).
    """
    m, k = batch_geometry(n, eps1, eps2)
    if not use_ni_sign_pallas(n, eps1, eps2):
        raise ValueError(
            f"fused kernel needs m <= {LANES} and k >= 2, got m={m}, k={k}; "
            f"use the XLA path (see use_ni_sign_pallas)")
    if gauss not in ("boxmuller", "ndtri"):
        raise ValueError(f"gauss must be 'boxmuller' or 'ndtri', "
                         f"got {gauss!r}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if interpret and uniforms is None:
        raise ValueError(
            "on-chip PRNG is only live on real TPU — pass `uniforms` with "
            f"shape (B, {n_uniform_rows(n, eps1, eps2, True)}, {LANES}) "
            "off-TPU")
    return _sim_detail_jit(jnp.asarray(seeds, jnp.int32),
                           jnp.asarray(rhos, jnp.float32), n, eps1, eps2,
                           tuple(mu), tuple(sigma), float(alpha), ci_mode,
                           normalise, interpret, gauss, uniforms=uniforms)
