"""Pallas TPU kernel: one fully-fused NI sign-batch replication.

The bench hot loop (vert-cor.R:392-419 → ``bench.py``) is, per replication:
generate an (n, 2) Gaussian pair, privately standardize, sign-batch
estimate (SURVEY.md §2.2-A). The XLA path materializes the n-vectors
between fusion boundaries and burns most of its time in the counter-based
threefry PRNG. This kernel runs the whole replication inside VMEM on one
grid step:

- **on-chip PRNG** (``pltpu.prng_random_bits``, the TPU hardware generator)
  seeded per replication from an SMEM scalar; Gaussians via Box–Muller,
  Laplace via the reference's own inverse-CDF (real-data-sims.R:58-61);
- **DP standardization** (vert-cor.R:322-348) from masked in-register
  moment sums;
- **sign batch sums as an MXU matmul** against a static 0/1 block-
  aggregation matrix G[l, c] = 1{l//m' == c} — the (k, m)-reshape-mean
  (vert-cor.R:131-140) becomes ``signs(R,128) @ G(128,128)`` — G's
  columns beyond 128//m' are identically zero, keeping full-lane tiles;
- per-batch Laplace noise, Σ T_j / Σ T_j² reduction; only the two scalars
  (η̂, sd T) leave the chip per replication.

**Batch layout (any m ≤ 128).** Lanes are grouped into k groups of
m' = next power of two ≥ m (so m' | 128 and groups never straddle a
register row); each group's first m lanes hold one batch's data and the
remaining m'−m lanes are padding, masked out of both the moment sums and
the sign matmul. The n − k·m leftover observations (which the estimator
ignores but ``priv_standardize`` *does* consume, vert-cor.R:126 vs 322-348)
are appended after the k groups so the DP moments see exactly n elements.
Because every element is an iid draw generated in-kernel, assigning
positions to batches this way is distribution-identical to the reference's
consecutive-index batching. When m | 128 the layout degenerates to the
dense one (m' = m, no padding).

Applicability: the Gaussian DGP with m ≤ 128 and k ≥ 2 — this covers the
whole reference ε-grid, including the (1.5, 0.5) pair's m = 11 → m' = 16
(vert-cor.R:488-494). Other shapes fall back to the XLA path
(``use_ni_sign_pallas`` reports which). Estimates are
distribution-identical to :func:`~dpcorr.models.estimators.ci_ni_signbatch`
but draw from a different PRNG, so acceptance is statistical (SURVEY.md §5
RNG), validated in ``tests/test_pallas_ni.py``.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.scipy.special import ndtri

from dpcorr.models.estimators.common import CorrResult, batch_geometry

LANES = 128
_TWO_PI = 2.0 * math.pi


def _pad_m(m: int) -> int:
    """Smallest power of two ≥ m (the lane-group width m' | 128)."""
    return 1 << (m - 1).bit_length()


def _layout(n: int, eps1: float, eps2: float):
    """(m, m', k, leftover, rows) for the padded lane-group layout.

    ``rows`` is rounded up to a multiple of 8 so every kernel
    intermediate is a full (8·r, 128) TPU tile — Mosaic handles aligned
    shapes best (and the position masks make padding rows inert)."""
    m, k = batch_geometry(n, eps1, eps2)
    m_pad = _pad_m(m)
    leftover = n - k * m
    rows = -(-(k * m_pad + leftover) // LANES)
    rows = -(-rows // 8) * 8
    return m, m_pad, k, leftover, rows


def use_ni_sign_pallas(n: int, eps1: float, eps2: float) -> bool:
    """True iff the fused kernel covers this configuration
    (m ≤ 128 so one lane group holds a batch, and k ≥ 2 so sd(T_j) exists).
    """
    m, k = batch_geometry(n, eps1, eps2)
    return m <= LANES and k >= 2


def _uniform(bits):
    """random bits → strictly-interior (0, 1) float32 uniforms.

    ``pltpu.prng_random_bits`` yields *int32* on TPU; a bare right-shift
    would sign-extend and make half the draws negative (NaN under log), so
    mask the shift result. 23 bits (not 24): with 24, the top value
    (2²⁴−1)+0.5 rounds to 2²⁴ in float32 and the uniform becomes exactly
    1.0 — −inf through the Laplace ``log1p``. Every 23-bit value ±0.5 is
    exactly representable, so u ∈ [2⁻²⁴, 1−2⁻²⁴].
    """
    b23 = jnp.bitwise_and(jnp.right_shift(bits, 9), 0x7FFFFF)
    return (b23.astype(jnp.float32) + 0.5) * (2.0**-23)


def _rand_uniform(shape):
    return _uniform(pltpu.prng_random_bits(shape))


def _laplace_from_uniform(u, scale):
    """Inverse-CDF Laplace(0, scale) — the reference's own sampler
    (real-data-sims.R:58-61) on centered u−½ ∈ (−½, ½)."""
    c = u - 0.5
    return -scale * jnp.sign(c) * jnp.log1p(-2.0 * jnp.abs(c))


def n_uniform_rows(n: int, eps1: float = 1.0, eps2: float = 1.0) -> int:
    """Rows of (·, 128) uniforms one replication consumes (external mode):
    u1 + u2 (rows each) + 8 standardization rows + 2·rows batch noise.
    ``rows`` depends on the ε-pair through the padded lane-group layout."""
    *_, rows = _layout(n, eps1, eps2)
    return 4 * rows + 8


def _make_kernel(n: int, m: int, m_pad: int, k: int, leftover: int,
                 rows: int, eps1: float, eps2: float,
                 mu, sigma, normalise: bool, external_uniforms: bool):
    g_cols = LANES // m_pad
    l_clip = math.sqrt(2.0 * math.log(n))
    scale_x = 2.0 / (m * eps1)
    scale_y = 2.0 / (m * eps2)

    def kernel(seed_ref, rho_ref, gmat_ref, *rest):
        if external_uniforms:
            # test mode: the interpreter stubs pltpu.prng_random_bits to
            # zeros, so uniforms come from HBM and only the on-chip PRNG
            # is untested off-TPU
            u_ref, out_ref = rest
            cursor = [0]

            def take(shape):
                r0 = cursor[0]
                cursor[0] += shape[0]
                return u_ref[0, pl.ds(r0, shape[0]), :]
        else:
            (out_ref,) = rest
            pltpu.prng_seed(seed_ref[0, 0, 0])

            def take(shape):
                return _rand_uniform(shape)

        rho = rho_ref[0, 0]

        # ---- generate: Box–Muller pair → 2×2 Cholesky (dgp.py:_bvn) ----
        u1 = take((rows, LANES))
        u2 = take((rows, LANES))
        r = jnp.sqrt(-2.0 * jnp.log(u1))
        z1 = r * jnp.cos(_TWO_PI * u2)
        z2 = r * jnp.sin(_TWO_PI * u2)
        x = mu[0] + sigma[0] * z1
        y = mu[1] + sigma[1] * (rho * z1 + jnp.sqrt(1.0 - rho * rho) * z2)

        # position masks over the padded lane-group layout: position p holds
        # batch element (group p//m', offset p%m' < m), a leftover
        # observation (k·m' ≤ p < k·m'+leftover), or pure padding
        pos = (jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0) * LANES
               + jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1))
        batch_elem = ((pos % m_pad < m) & (pos // m_pad < k))
        in_leftover = (pos >= k * m_pad) & (pos < k * m_pad + leftover)
        # moment mask: exactly the n real observations (vert-cor.R:322-348
        # standardizes over all n, estimator uses the first k·m)
        w = (batch_elem | in_leftover).astype(jnp.float32)

        if normalise:
            # priv_standardize both sides (vert-cor.R:322-348): clip, DP
            # mean + DP 2nd moment (ε/2 each), standardize. Signs only
            # need x − μ (σ > 0), so the division is dropped.
            lap4 = _laplace_from_uniform(take((8, LANES)), 1.0)

            def center(v, eps, mu_noise):
                # sign((clip(v) − μ_priv)/σ_priv) = sign(clip(v) − μ_priv)
                # since σ_priv > 0, so the DP 2nd moment (which the budget
                # still pays for, ε/2) never needs to be materialized here
                vc = jnp.clip(v, -l_clip, l_clip)
                eps_half = eps / 2.0
                mu_p = (jnp.sum(vc * w) / n
                        + mu_noise * 2.0 * l_clip / (n * eps_half))
                return vc - mu_p

            x_c = center(x, eps1, lap4[0, 0])
            y_c = center(y, eps2, lap4[1, 0])
        else:
            x_c, y_c = x, y

        # ---- sign batch sums on the MXU: (rows,128) @ G(128,128) ----
        # padding lanes inside a group must not leak into the batch sum;
        # G's columns beyond g_cols are identically zero (l // m' never
        # reaches them), so intermediates stay full 128-lane tiles
        bmask = batch_elem.astype(jnp.float32)
        sx = jnp.sign(x_c) * bmask
        sy = jnp.sign(y_c) * bmask
        g = gmat_ref[...]
        xb = jnp.dot(sx, g, preferred_element_type=jnp.float32) / m
        yb = jnp.dot(sy, g, preferred_element_type=jnp.float32) / m

        # ---- per-batch Laplace noise (sens 2/m, vert-cor.R:143-146) ----
        # full-width draws; the same uniforms land on the same live
        # (row, col < g_cols) positions, dead columns are masked below
        lap_xy = _laplace_from_uniform(take((2 * rows, LANES)), 1.0)
        xt = xb + lap_xy[:rows, :] * scale_x
        yt = yb + lap_xy[rows:, :] * scale_y

        # ---- T_j = m·X̃_j·Ỹ_j over the k real batches ----
        rr = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
        cc = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
        live = (cc < g_cols) & (rr * g_cols + cc < k)
        t = jnp.where(live, m * xt * yt, 0.0)
        st = jnp.sum(t)
        st2 = jnp.sum(t * t)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        out_ref[0, 0, :] = jnp.where(lane == 0, st,
                                     jnp.where(lane == 1, st2, 0.0))[0, :]

    return kernel


@partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8))
def _ni_sign_pallas_sums(seeds: jax.Array, rho: jax.Array, n: int,
                         eps1: float, eps2: float, mu, sigma,
                         normalise: bool, interpret: bool,
                         uniforms: jax.Array | None = None):
    b = seeds.shape[0]
    m, m_pad, k, leftover, rows = _layout(n, eps1, eps2)
    external = uniforms is not None
    kernel = _make_kernel(n, m, m_pad, k, leftover, rows, eps1, eps2,
                          tuple(mu), tuple(sigma), normalise, external)
    # static 0/1 aggregation matrix: lane l feeds batch column l // m'
    gmat = jnp.asarray(
        (np.arange(LANES)[:, None] // m_pad) == np.arange(LANES)[None, :],
        jnp.float32)  # (128, 128); columns >= 128//m' are all zero

    # Mosaic requires every block's trailing two dims to be divisible by
    # (8, 128) or equal to the array's — so the grid axis is a *leading*
    # third dim everywhere and each block's last two dims equal the array's.
    in_specs = [
        pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((LANES, LANES), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    inputs = [seeds.reshape(b, 1, 1), rho.reshape(1, 1), gmat]
    if external:
        u_rows = n_uniform_rows(n, eps1, eps2)
        in_specs.append(pl.BlockSpec((1, u_rows, LANES),
                                     lambda i: (i, 0, 0),
                                     memory_space=pltpu.VMEM))
        inputs.append(uniforms.reshape(b, u_rows, LANES))

    out = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 1, LANES), jnp.float32),
        # TPU interpret mode runs the kernel on CPU (pltpu.prng_* stubs
        # return zeros there — external uniforms cover testing)
        interpret=pltpu.InterpretParams() if interpret else False,
    )(*inputs)
    return out[:, 0, 0], out[:, 0, 1]


def ni_sign_pallas(seeds: jax.Array, rho, n: int, eps1: float, eps2: float,
                   mu=(0.0, 0.0), sigma=(1.0, 1.0), alpha: float = 0.05,
                   normalise: bool = True,
                   interpret: bool | None = None,
                   uniforms: jax.Array | None = None) -> CorrResult:
    """Fused generate+estimate for a whole replication batch.

    ``seeds``: (B,) int32 per-replication PRNG seeds. Returns the batched
    :class:`CorrResult` with the same CI construction as
    ``ci_ni_signbatch`` (η-space clamp then sine map, vert-cor.R:249-254).

    ``uniforms``: optional (B, n_uniform_rows(n, eps1, eps2), 128) external
    uniforms in (0, 1) replacing the on-chip PRNG — the CPU-testable path.
    """
    m, k = batch_geometry(n, eps1, eps2)
    if not use_ni_sign_pallas(n, eps1, eps2):
        raise ValueError(
            f"fused kernel needs m <= {LANES} and k >= 2, got m={m}, k={k}; "
            f"use the XLA path (see use_ni_sign_pallas)")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if interpret and uniforms is None:
        raise ValueError(
            "on-chip PRNG is only live on real TPU (the interpreter stubs "
            "pltpu.prng_random_bits to zeros) — pass `uniforms` with shape "
            f"(B, {n_uniform_rows(n, eps1, eps2)}, {LANES}) off-TPU")
    st, st2 = _ni_sign_pallas_sums(
        jnp.asarray(seeds, jnp.int32), jnp.float32(rho), n, eps1, eps2,
        tuple(mu), tuple(sigma), normalise, interpret, uniforms=uniforms)

    eta_hat = st / k
    var_t = jnp.maximum((st2 - k * eta_hat * eta_hat) / (k - 1), 0.0)
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    half = ndtri(1.0 - alpha / 2.0) * jnp.sqrt(var_t) / math.sqrt(k)
    lo = jnp.sin(jnp.pi / 2.0 * jnp.maximum(eta_hat - half, -1.0))
    hi = jnp.sin(jnp.pi / 2.0 * jnp.minimum(eta_hat + half, 1.0))
    return CorrResult(rho_hat, lo, hi)
