"""Pallas TPU kernel: one fully-fused sub-Gaussian replication (v2 grid).

The subG grid's hot-loop body (ver-cor-subG.R:174-198) per replication:
generate a ``bounded_factor`` pair (ver-cor-subG.R:141-154), run the NI
clipped-batch estimator (ver-cor-subG.R:25-62) and the INT clipped
estimator (ver-cor-subG.R:67-108) on it. This kernel runs all of it inside
VMEM on one grid step, mirroring the sign-estimator kernel
(:mod:`dpcorr.ops.pallas_ni`, whose layout/PRNG helpers it shares):

- **bounded-factor DGP on-chip**: X = U+E₁, Y = U+E₂ from three uniform
  planes scaled by √(3ρ) / √(3(1−ρ)) — ρ rides a per-replication SMEM
  scalar, so one compiled kernel serves a bucket's whole ρ-sweep;
- **NI clipped-batch**: clip at ±λᵢ = λ_n(n, ηᵢ), batch means as the same
  MXU matmul against the 0/1 aggregation matrix, per-batch Laplace
  (scale 2λ/(m·ε)), Σ T_j / Σ T_j²  (ver-cor-subG.R:33-52);
- **INT clipped**: sender clips at λ_s and releases per-sample
  ``clip(X)+Lap(2λ_s/ε_s)`` (local DP), receiver multiplies by its own
  *unclipped* variable (grid-variant semantics), clips the product at λ_r,
  and adds one central draw (ver-cor-subG.R:87-97); the kernel emits
  Σ Uc / Σ Uc² and ρ̂_INT.

Five scalars leave the chip per replication; the CI constructions (normal
for NI, det-mixquant ``grid_interval`` for INT) run as scalar XLA ops in
:func:`sim_detail_subg_pallas`, which returns the full 12-column detail
row — the bucketed grid backend's fused path for ``use_subg`` buckets
(``subg_variant="grid"`` only: the real-data variant's randomized batch
permutation has no in-kernel equivalent).

Like the sign kernel, estimates are distribution-identical to the XLA
estimators but draw from the on-chip PRNG — acceptance is statistical
(SURVEY.md §5 RNG), validated in ``tests/test_pallas_subg.py``.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

from dpcorr.models.estimators.common import CorrResult, batch_geometry
from dpcorr.ops.lambdas import lambda_int_n, lambda_n
from dpcorr.ops.pallas_ni import (
    LANES,
    _gmat,
    _laplace_from_uniform,
    _layout,
    _position_masks,
    _replication_call,
    _seed_words,
    _taker,
    use_ni_sign_pallas,
)


def use_subg_pallas(n: int, eps1: float, eps2: float) -> bool:
    """Same geometry envelope as the sign kernel: m ≤ 128 lanes, k ≥ 2."""
    return use_ni_sign_pallas(n, eps1, eps2)


def n_uniform_rows_subg(n: int, eps1: float = 1.0, eps2: float = 1.0) -> int:
    """(·, 128) uniform rows per replication in external mode: 3·rows DGP
    planes + 2·rows NI batch noise + rows INT sender noise + 8 central."""
    *_, rows = _layout(n, eps1, eps2)
    return 6 * rows + 8


def _lambdas(n: int, eps1: float, eps2: float, eta1: float, eta2: float):
    """All four static clip thresholds as Python floats, evaluated OUTSIDE
    any jit trace (the λ rules are jnp formulas — lambdas.py — and inside a
    trace even scalar constants stage into tracers)."""
    lam1 = float(lambda_n(n, eta1))  # ver-cor-subG.R:33-34
    lam2 = float(lambda_n(n, eta2))
    sender_is_x = eps1 >= eps2       # ver-cor-subG.R:76-81
    eta_s, eta_r = (eta1, eta2) if sender_is_x else (eta2, eta1)
    lam_s, lam_r = (float(v) for v in
                    lambda_int_n(n, eta_s=eta_s, eta_r=eta_r,
                                 eps_s=max(eps1, eps2)))
    return lam1, lam2, lam_s, lam_r


def _make_kernel(n: int, m: int, m_pad: int, k: int, leftover: int,
                 rows: int, eps1: float, eps2: float,
                 lams: tuple, external_uniforms: bool):
    g_cols = LANES // m_pad
    lam1, lam2, lam_s, lam_r = lams
    scale_x = 2.0 * lam1 / (m * eps1)
    scale_y = 2.0 * lam2 / (m * eps2)
    # INT roles: larger ε sends (ver-cor-subG.R:76-81) — static
    sender_is_x = eps1 >= eps2
    eps_s, eps_r = (eps1, eps2) if sender_is_x else (eps2, eps1)
    sender_scale = 2.0 * lam_s / eps_s
    central_scale = 2.0 * lam_r / (n * eps_r)

    def kernel(seed_ref, rho_ref, gmat_ref, *rest):
        if external_uniforms:
            u_ref, out_ref = rest
        else:
            u_ref, (out_ref,) = None, rest
        take = _taker(external_uniforms, u_ref, seed_ref)

        rho = rho_ref[0, 0, 0]

        # ---- bounded-factor DGP (ver-cor-subG.R:141-154) ----
        c_u = jnp.sqrt(3.0 * rho)
        c_e = jnp.sqrt(3.0 * (1.0 - rho))
        uu = (2.0 * take((rows, LANES)) - 1.0) * c_u
        e1 = (2.0 * take((rows, LANES)) - 1.0) * c_e
        e2 = (2.0 * take((rows, LANES)) - 1.0) * c_e
        x = uu + e1
        y = uu + e2

        batch_elem, w = _position_masks(rows, m, m_pad, k, leftover)
        bmask = batch_elem.astype(jnp.float32)

        # ---- NI clipped-batch sums on the MXU (ver-cor-subG.R:33-52) ----
        xc = jnp.clip(x, -lam1, lam1) * bmask
        yc = jnp.clip(y, -lam2, lam2) * bmask
        g = gmat_ref[...]
        xb = jnp.dot(xc, g, preferred_element_type=jnp.float32) / m
        yb = jnp.dot(yc, g, preferred_element_type=jnp.float32) / m
        lap_xy = _laplace_from_uniform(take((2 * rows, LANES)), 1.0)
        xt = xb + lap_xy[:rows, :] * scale_x
        yt = yb + lap_xy[rows:, :] * scale_y
        rr = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
        cc = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
        live = (cc < g_cols) & (rr * g_cols + cc < k)
        t = jnp.where(live, m * xt * yt, 0.0)
        st = jnp.sum(t)
        st2 = jnp.sum(t * t)

        # ---- INT clipped (grid variant, ver-cor-subG.R:87-97): sender
        # local-DP release × receiver's *unclipped* variable ----
        xs, xo = (x, y) if sender_is_x else (y, x)
        sc = jnp.clip(xs, -lam_s, lam_s)
        lap_send = _laplace_from_uniform(take((rows, LANES)), 1.0)
        u_prod = (sc + lap_send * sender_scale) * xo
        uc = jnp.clip(u_prod, -lam_r, lam_r) * w  # all n real obs
        sum_uc = jnp.sum(uc)
        sumsq_uc = jnp.sum(uc * uc)
        lap8 = _laplace_from_uniform(take((8, LANES)), 1.0)
        rho_int = sum_uc / n + lap8[0, 0] * central_scale

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        out = jnp.where(lane == 0, st,
                        jnp.where(lane == 1, st2,
                                  jnp.where(lane == 2, sum_uc,
                                            jnp.where(lane == 3, sumsq_uc,
                                                      jnp.where(lane == 4,
                                                                rho_int,
                                                                0.0)))))
        out_ref[0, 0, :] = out[0, :]

    return kernel


@partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def _subg_pallas_sums(seeds: jax.Array, rho: jax.Array, n: int,
                      eps1: float, eps2: float, lams: tuple,
                      interpret: bool, uniforms: jax.Array | None = None):
    seeds = _seed_words(seeds)
    b = seeds.shape[0]
    m, m_pad, k, leftover, rows = _layout(n, eps1, eps2)
    external = uniforms is not None
    kernel = _make_kernel(n, m, m_pad, k, leftover, rows, eps1, eps2,
                          lams, external)
    rho = jnp.broadcast_to(jnp.asarray(rho, jnp.float32), (b,))
    u_rows = n_uniform_rows_subg(n, eps1, eps2) if external else None
    out = _replication_call(kernel, b, seeds, rho, _gmat(m_pad), u_rows,
                            uniforms, interpret)
    return tuple(out[:, 0, j] for j in range(5))


@partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7))
def _sim_detail_subg_jit(seeds, rhos, n: int, eps1: float, eps2: float,
                         lams: tuple, alpha: float,
                         interpret: bool, uniforms=None):
    from dpcorr.models.estimators.int_subg import grid_interval
    from dpcorr.sim import _metrics_row

    m, k = batch_geometry(n, eps1, eps2)
    st, st2, sum_uc, sumsq_uc, rho_int = _subg_pallas_sums(
        seeds, rhos, n, eps1, eps2, lams, interpret,
        uniforms=uniforms)

    # NI: ρ̂ = η̂ (no sine link), normal CI, ρ-space clamp
    # (ver-cor-subG.R:51-59)
    rho_ni = st / k
    var_t = jnp.maximum((st2 - k * rho_ni * rho_ni) / (k - 1), 0.0)
    se = jnp.sqrt(var_t) / math.sqrt(k)
    crit = ndtri(1.0 - alpha / 2.0)
    ni = CorrResult(rho_ni, jnp.maximum(rho_ni - crit * se, -1.0),
                    jnp.minimum(rho_ni + crit * se, 1.0))

    # INT: det-mixquant grid interval from (ρ̂, sd(Uc))
    # (ver-cor-subG.R:99-104)
    mean_uc = sum_uc / n
    sd_uc = jnp.sqrt(jnp.maximum(
        (sumsq_uc - n * mean_uc * mean_uc) / (n - 1), 0.0))
    eps_r = min(eps1, eps2)
    lam_r = lams[3]
    central_scale = 2.0 * lam_r / (n * eps_r)
    it = grid_interval(None, rho_int, sd_uc, n, eps_r, central_scale,
                       alpha, "det")
    return _metrics_row(ni, it, rhos)


def sim_detail_subg_pallas(seeds: jax.Array, rhos, n: int, eps1: float,
                           eps2: float, eta1: float = 1.0,
                           eta2: float = 1.0, alpha: float = 0.05,
                           interpret: bool | None = None,
                           uniforms: jax.Array | None = None) -> tuple:
    """Fused subG replication batch → 12-tuple in
    :data:`dpcorr.sim.DETAIL_FIELDS` order (drop-in for
    ``sim._run_detail_flat`` on ``use_subg`` grid-variant buckets with the
    ``bounded_factor`` DGP and det mixquant).

    ``rhos``: scalar or (B,) per-replication ρ.
    """
    m, k = batch_geometry(n, eps1, eps2)
    if not use_subg_pallas(n, eps1, eps2):
        raise ValueError(
            f"fused kernel needs m <= {LANES} and k >= 2, got m={m}, k={k}; "
            f"use the XLA path (see use_subg_pallas)")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if interpret and uniforms is None:
        raise ValueError(
            "on-chip PRNG is only live on real TPU — pass `uniforms` with "
            f"shape (B, {n_uniform_rows_subg(n, eps1, eps2)}, {LANES}) "
            "off-TPU")
    lams = _lambdas(n, eps1, eps2, float(eta1), float(eta2))
    return _sim_detail_subg_jit(jnp.asarray(seeds, jnp.int32),
                                jnp.asarray(rhos, jnp.float32), n,
                                eps1, eps2, lams,
                                float(alpha), interpret, uniforms=uniforms)
