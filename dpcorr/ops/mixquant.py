"""Quantiles of the Gaussian + scaled-Laplace mixture  X = Z + c·L.

Z ~ N(0,1), L ~ standard (symmetric) Laplace. Used by the INT CI
constructors: the reference draws a *fresh 1000-sample Monte-Carlo per CI*
and takes an order statistic (``mixquant``, vert-cor.R:44-56,
ver-cor-subG.R:8-20; nsim=2000 in real-data-sims.R:161-164) — noisy by
design (SURVEY.md Appendix A #4). Under ``vmap`` over 10^6 replications that
would be 10^9 wasted draws per CI batch, so the default here is a
**deterministic closed-form inversion** (the reference itself sketches a
deterministic numerical variant in comments, vert-cor.R:50-55):

The CDF of X = Z + c·L has the closed form (derived by conditioning on L and
integrating by parts; b ≡ c):

    F(x) = Φ(x) + ½·[ e^{1/(2b²) + x/b}·Φ(−x − 1/b)
                    − e^{1/(2b²) − x/b}·Φ( x − 1/b) ]

which we evaluate in log-space via ``log_ndtr`` for stability at small b
(where 1/(2b²) alone overflows) and invert by bisection — branch-free,
fixed trip count, fully ``vmap``/TPU friendly.

``mixquant_mc`` reproduces the reference's MC order-statistic estimator
exactly in distribution, for fidelity tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import log_ndtr, ndtri


def mix_cdf(x, c):
    """P(Z + c·L ≤ x), elementwise; c ≥ 0.

    Below c=0.01 the exponent ``1/(2b²) ± x/b + logΦ(...)`` cancels
    catastrophically in float32, and the Laplace component (var 2c² ≤ 2e-4)
    is negligible anyway, so we fall back to Φ(x) there.
    """
    x = jnp.asarray(x, jnp.float32)
    c = jnp.abs(jnp.asarray(c, jnp.float32))  # Z + cL ≡ Z + |c|L
    b = jnp.maximum(c, 0.01)
    inv_b = 1.0 / b
    base = 0.5 * inv_b * inv_b  # 1/(2b²)
    # log-space terms: exp(base ± x/b + logΦ(∓x − 1/b))
    t_plus = jnp.exp(base + x * inv_b + log_ndtr(-x - inv_b))
    t_minus = jnp.exp(base - x * inv_b + log_ndtr(x - inv_b))
    mix = jax.scipy.stats.norm.cdf(x) + 0.5 * (t_plus - t_minus)
    cdf = jnp.where(c < 0.01, jax.scipy.stats.norm.cdf(x), mix)
    return jnp.clip(cdf, 0.0, 1.0)


def mixquant(c, p, n_iter: int = 32):
    """Deterministic p-quantile of Z + c·L by bisection on :func:`mix_cdf`.

    Drop-in for the reference's ``mixquant(c, p)`` modulo its Monte-Carlo
    noise (vert-cor.R:44-56). Broadcasts over ``c`` and ``p``.
    """
    c = jnp.abs(jnp.asarray(c, jnp.float32))
    p = jnp.asarray(p, jnp.float32)
    c, p = jnp.broadcast_arrays(c, p)
    # Bracket: |quantile| ≤ |z_p| + c·|Laplace quantile_p| + slack.
    zq = jnp.abs(ndtri(jnp.clip(p, 1e-7, 1.0 - 1e-7)))
    lapq = 16.2  # |Laplace(1) quantile| at p = 1e-7
    hi0 = zq + jnp.maximum(c, 0.0) * lapq + 1.0
    lo0 = -hi0

    def body(_, state):
        lo, hi = state
        mid = 0.5 * (lo + hi)
        below = mix_cdf(mid, c) < p
        return jnp.where(below, mid, lo), jnp.where(below, hi, mid)

    lo, hi = jax.lax.fori_loop(0, n_iter, body, (lo0, hi0))
    return 0.5 * (lo + hi)


def mixquant_mc(key: jax.Array, c, p, nsim: int = 1000):
    """The reference's MC order-statistic estimator, faithfully.

    ``sort(Z + c·E·S)[ceil(p·nsim)]`` with Z~N(0,1), E~Exp(1), S~±1
    (vert-cor.R:45-48; nsim=2000 variant real-data-sims.R:161-164).

    ``p`` must be a concrete Python float (it always is — 1−α/2 with a
    static α): the order-statistic index is computed host-side in float64,
    matching R's arithmetic; float32 ``ceil(p·nsim)`` picks the wrong order
    statistic for ~1% of p values.
    """
    import math

    kz, ke, ks = jax.random.split(key, 3)
    z = jax.random.normal(kz, (nsim,), jnp.float32)
    e = jax.random.exponential(ke, (nsim,), jnp.float32)
    s = 2.0 * jax.random.bernoulli(ks, 0.5, (nsim,)).astype(jnp.float32) - 1.0
    x = z + jnp.asarray(c, jnp.float32) * e * s
    idx = min(max(math.ceil(float(p) * nsim) - 1, 0), nsim - 1)  # R 1-indexed
    return jnp.sort(x)[idx]
