"""Acceptance campaign: B≥10⁶ CI-coverage at the BASELINE 1e-3 criterion.

The reference validates itself statistically — empirical coverage against
the 0.95 nominal line (vert-cor.R:687, ver-cor-subG.R:404) — but only at
B=250 per design point (±2.8 pp of MC noise). BASELINE.json sets the
acceptance bar at 1e-3, which needs B ≥ 10⁶ (MC SE of a 0.95 proportion at
B=10⁶ is 2.2e-4). This module runs that campaign:

- all four estimator families (NI/INT sign — SURVEY.md §2.2-A/B; NI/INT
  sub-Gaussian — §2.2-C/D) at design points chosen to cross every CI
  regime: the INT sign normal-vs-Laplace switch at √n·ε_r = 0.5
  (vert-cor.R:294-296), the λ_r log-n cap branches (ver-cor-subG.R:3-7),
  and both mixquant modes;
- **det-vs-MC mixquant agreement**: the deterministic closed-form mixture
  quantile replaces the reference's fresh 1000-draw MC per CI
  (vert-cor.R:302, 44-56) — the one deliberate behavioral deviation
  (SURVEY.md §7 hard parts). Both modes run on the SAME replication keys
  (common random numbers), so their coverage difference isolates the CI
  construction itself; the campaign asserts |cov_det − cov_mc| ≤ 1e-3.

Summary sums are accumulated block-by-block on device (nothing bigger than
one block of detail rows is ever resident), so B=10⁶ at n≤4000 fits any
chip. Results persist as a JSON table (``benchmarks/results/``) consumed by
``tests/test_acceptance.py``.
"""

from __future__ import annotations

import dataclasses
import json
import time
from functools import partial
from pathlib import Path
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp

from dpcorr import sim as sim_mod
from dpcorr.sim import SimConfig
from dpcorr.utils import rng

#: fields summed per block; coverage is the acceptance-critical one
_SUM_FIELDS = ("ni_cover", "int_cover", "ni_se2", "int_se2",
               "ni_ci_len", "int_ci_len")


def dumps(obj) -> str:
    """RFC-compliant JSON for campaign artifacts: NaN/±inf → null
    (degenerate points — e.g. a k=1 NI CI — produce NaN metrics, and bare
    ``NaN`` tokens break every non-Python JSON consumer)."""
    def clean(v):
        if isinstance(v, float) and (v != v or v in (float("inf"),
                                                     float("-inf"))):
            return None
        if isinstance(v, dict):
            return {k: clean(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [clean(x) for x in v]
        return v

    return json.dumps(clean(obj), indent=1, allow_nan=False)


@dataclasses.dataclass(frozen=True)
class AccPoint:
    """One acceptance design point; ``both_mixquant`` adds the MC-mode twin
    run on identical rep keys. ``coverage_exempt`` maps method → reason for
    points that exist to *cross a CI regime branch* whose construction is
    not 0.95-calibrated there (the recorded coverage documents the actual
    behavior; the nominal criterion is waived with the reason)."""

    name: str
    regime: str
    kwargs: Mapping[str, Any]
    both_mixquant: bool = False
    coverage_exempt: Mapping[str, str] = dataclasses.field(
        default_factory=dict)
    #: widened |coverage − nominal| tolerance for this point (with the
    #: documented reason) — for constructions whose finite-n coverage is
    #: intrinsically off nominal, reproduced faithfully
    coverage_tol: float = 0.0
    tol_reason: str = ""


#: The campaign grid. n kept ≤ 4000 so the whole campaign is minutes, not
#: hours; every CI regime the estimators can enter is crossed at least once.
POINTS: tuple[AccPoint, ...] = (
    AccPoint("sign_normal", "INT normal regime (√n·ε_r = 44.7 > 0.5), "
             "mixquant width", {"n": 2000, "rho": 0.3, "eps1": 1.0,
                                "eps2": 1.0}, both_mixquant=True),
    AccPoint("sign_low_eps", "reference ε-pair (0.5, 0.5) ⇒ m=32 batches",
             {"n": 2000, "rho": 0.0, "eps1": 0.5, "eps2": 0.5}),
    AccPoint("sign_laplace", "INT Laplace regime (√400·0.02 = 0.4 < 0.5, "
             "vert-cor.R:304-308)", {"n": 400, "rho": 0.3, "eps1": 1.0,
                                     "eps2": 0.02},
             coverage_exempt={"INT": "Laplace-regime width "
                              "(2/(nε_r))·log(1/α) exceeds the ρ range at "
                              "ε_r=0.02 — the CI clamps to [-1,1] and "
                              "coverage saturates near 1, the "
                              "construction's intended behavior at tiny ε "
                              "(vert-cor.R:304-313)",
                              "NI": "m=⌈8/(ε₁ε₂)⌉=400=n ⇒ k=1 batch: "
                              "sd(T_j) of one value is undefined (R's sd "
                              "returns NA, vert-cor.R:237) — the NI CI is "
                              "degenerate by construction at this ε-pair "
                              "and covers nothing; measured coverage 0 "
                              "reproduces the reference exactly"}),
    AccPoint("subg_factor", "subG families on bounded-factor DGP "
             "(ver-cor-subG.R:283)", {"n": 4000, "rho": 0.5, "eps1": 1.0,
                                      "eps2": 1.0, "dgp": "bounded_factor",
                                      "use_subg": True}, both_mixquant=True,
             coverage_tol=0.011,
             tol_reason="the INT subG grid construction (se with Laplace "
             "term + mixquant width, ver-cor-subG.R:99-101) has ~0.9pp "
             "intrinsic under-coverage at n=4000 — the faithful MC mode "
             "measures 0.9397 at B=10⁶, so this is the reference's own "
             "finite-n behavior, reproduced (det is closer to nominal)"),
    AccPoint("subg_real", "real-data (v2) estimator pair: randomized "
             "batches + k≥2 fallback, receiver-λ from noise, sampling-only "
             "se, δ_clip=1/n (real-data-sims.R:115-252)",
             {"n": 4000, "rho": 0.5, "eps1": 1.0, "eps2": 1.0,
              "dgp": "bounded_factor", "use_subg": True,
              "subg_variant": "real"},
             both_mixquant=True,
             ),  # measured exactly calibrated at B=1e6: NI 0.95046,
                 # INT 0.95016 (r02 campaign) — no tolerance needed.
                 # The MC twin here runs at the real-data script's
                 # nsim=2000 (real-data-sims.R:161-164), not the grid
                 # scripts' 1000 — ci_int_subg's variant-aware default.
    AccPoint("subg_small_n", "λ_r log-n branch: log 300 < 6 "
             "(ver-cor-subG.R:5)", {"n": 300, "rho": 0.4, "eps1": 2.0,
                                    "eps2": 0.5, "dgp": "bounded_factor",
                                    "use_subg": True},
             coverage_exempt={"NI": "n=300 is 8× below the reference's "
                              "own smallest subG grid point (n=2500, "
                              "ver-cor-subG.R:245); the normal CI is not "
                              "0.95-calibrated there — the point exists "
                              "to cross the λ_r log-n branch",
                              "INT": "same small-n regime; recorded "
                              "coverage documents the construction's "
                              "actual behavior"}),
)


@partial(jax.jit, static_argnums=(0,))
def _block_sums(cfg_norho: SimConfig, keys: jax.Array, rho: jax.Array):
    raw = sim_mod.chunked_vmap(
        lambda k: sim_mod._one_rep(k, rho, cfg_norho), keys,
        cfg_norho.chunk_size)
    named = dict(zip(sim_mod.DETAIL_FIELDS, raw, strict=True))
    return [jnp.sum(named[f], dtype=jnp.float64
                    if jax.config.jax_enable_x64 else jnp.float32)
            for f in _SUM_FIELDS]


def _coverage_run(cfg: SimConfig, b: int, block: int) -> dict:
    """Accumulate summary sums over ⌈b/block⌉ equal blocks of reps."""
    n_blocks = -(-b // block)
    b_run = n_blocks * block  # run whole blocks; record the exact count
    master = rng.master_key(cfg.seed)
    cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
    totals = [0.0] * len(_SUM_FIELDS)
    t0 = time.perf_counter()
    for j in range(n_blocks):
        keys = rng.rep_keys(rng.design_key(master, j), block)
        sums = _block_sums(cfg_norho, keys, jnp.float32(cfg.rho))
        for i, s in enumerate(sums):
            totals[i] += float(s)
    dt = time.perf_counter() - t0
    out = {f: totals[i] / b_run for i, f in enumerate(_SUM_FIELDS)}
    return {
        "b": b_run,
        "seconds": round(dt, 1),
        "reps_per_sec": round(b_run / dt, 1),
        "NI": {"coverage": out["ni_cover"], "mse": out["ni_se2"],
               "ci_length": out["ni_ci_len"]},
        "INT": {"coverage": out["int_cover"], "mse": out["int_se2"],
                "ci_length": out["int_ci_len"]},
    }


def run_campaign(b: int = 1_000_000, block: int = 65_536,
                 points: Sequence[AccPoint] = POINTS,
                 chunk_size: int = 4096,
                 out: str | Path | None = None) -> dict:
    """Run the acceptance campaign; returns (and optionally writes) the
    table with per-point coverage, MC standard errors, and the det-vs-MC
    criterion evaluation."""
    alpha = 0.05
    block = min(block, b)
    rows = []
    for pt in points:
        cfg = SimConfig(**pt.kwargs, alpha=alpha, chunk_size=chunk_size,
                        mixquant_mode="det")
        res_det = _coverage_run(cfg, b, block)
        row = {"point": pt.name, "regime": pt.regime,
               "config": dict(pt.kwargs), "det": res_det}
        if pt.coverage_exempt:
            row["coverage_exempt"] = dict(pt.coverage_exempt)
        if pt.coverage_tol:
            row["coverage_tol"] = pt.coverage_tol
            row["tol_reason"] = pt.tol_reason
        if pt.both_mixquant:
            cfg_mc = dataclasses.replace(cfg, mixquant_mode="mc")
            row["mc"] = _coverage_run(cfg_mc, b, block)
            # mixquant enters only the INT CI widths (vert-cor.R:302,
            # ver-cor-subG.R:99-101) — NI must agree exactly, INT at 1e-3
            row["int_det_mc_diff"] = abs(row["det"]["INT"]["coverage"]
                                         - row["mc"]["INT"]["coverage"])
            row["ni_det_mc_diff"] = abs(row["det"]["NI"]["coverage"]
                                        - row["mc"]["NI"]["coverage"])
        rows.append(row)
        if out:  # incremental: a killed campaign keeps finished points
            # (.tmp so it can never match the test suite's *.json glob)
            Path(out).parent.mkdir(parents=True, exist_ok=True)
            Path(out).with_suffix(".partial.tmp").write_text(
                dumps({"points": rows}))

    table = build_table(rows, alpha=alpha, device=str(jax.devices()[0]))
    if out:
        out = Path(out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(dumps(table))
        out.with_suffix(".partial.tmp").unlink(missing_ok=True)
    return table


def build_table(rows: list[dict], alpha: float = 0.05,
                device: str = "?") -> dict:
    """Criteria evaluation over campaign rows (separated so a finished
    campaign's rows can be re-evaluated without recomputation).

    The det-vs-MC criterion is two-pronged. ``mixquant_mode="mc"`` is the
    construction-faithful mode (the reference's nsim-draw order statistic,
    vert-cor.R:44-56), so its coverage IS the reference's up to MC SE.
    The default det mode is the exact quantile; where the two differ
    beyond 1e-3 under common random numbers, the difference is the bias of
    the reference's own 1000-draw quantile estimator — attributed as such
    only if det is closer to nominal than mc at every compared point
    (exactness evidence), else it's a det-mode regression and the
    criterion fails.
    """
    b_eff = rows[0]["det"]["b"]
    nominal = 1 - alpha
    mc_se = (nominal * alpha / b_eff) ** 0.5
    # NI diffs included: mixquant must not touch the NI CI at all, so any
    # NI diff is a regression the criterion must catch
    det_mc_max = max((max(r.get("int_det_mc_diff", 0.0),
                          r.get("ni_det_mc_diff", 0.0))
                      for r in rows), default=0.0)
    compared = [r for r in rows if "mc" in r]
    # the attribution escape hatch is for the INT-only quantile-bias gap;
    # it must never excuse an NI diff (mixquant is not in the NI CI)
    det_closer = all(
        r.get("ni_det_mc_diff", 0.0) <= 1e-3
        and abs(r["det"]["INT"]["coverage"] - nominal)
        <= abs(r["mc"]["INT"]["coverage"] - nominal) + mc_se
        for r in compared)
    table = {
        "criterion": "BASELINE.json: CI-coverage error vs the reference "
                     "construction <= 1e-3; mixquant_mode='mc' is the "
                     "construction-faithful mode",
        "b_per_run": b_eff,
        "coverage_mc_se": mc_se,
        "nominal": nominal,
        "device": device,
        "points": rows,
        "det_mc_max_diff": det_mc_max,
        "det_mc_within_1e3": bool(det_mc_max <= 1e-3),
        "det_closer_to_nominal_everywhere": bool(det_closer),
    }
    table["det_mc_pass"] = bool(table["det_mc_within_1e3"] or det_closer)
    if not table["det_mc_within_1e3"] and det_closer:
        table["det_mc_attribution"] = (
            "det (exact quantile) sits within MC SE of nominal where the "
            "construction is calibrated, while the faithful mc mode is "
            "consistently lower — the gap is the reference mixquant's "
            "order-statistic index choice sort(x)[ceiling(p*nsim)] "
            "(vert-cor.R:44-48, real-data-sims.R:161-164): the classical "
            "identity E[F(X_(k:n))] = k/(n+1) makes the effective "
            "two-sided level 2*ceil(p*nsim)/(nsim+1) - 1, predicting the "
            "gap in closed form — 1.948e-3 at the grid scripts' "
            "nsim=1000, 0.974e-3 at the real-data script's nsim=2000 — "
            "which the measured campaign group means match within MC "
            "error (test_det_mc_gap_matches_order_statistic_theory). "
            "The reference's own MC bias, not a det-mode error; set "
            "mixquant_mode='mc' for strict construction fidelity")
    return table
