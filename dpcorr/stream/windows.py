"""Event-time windowing with bounded late-data admission. jax-free.

Tumbling (``slide_s=None``) and sliding windows keyed by **event
time** — the producer's timestamp, not arrival time — with the
standard watermark discipline (Akidau et al., "The Dataflow Model",
VLDB 2015): the watermark trails the maximum event time seen by the
lateness bound ``late_s``. A record older than the watermark is
refused (``too_late``), a record between watermark and max-seen is
*late but admissible* and still lands in its (still-open) windows, and
a window closes exactly when the watermark passes its end — so every
admitted row is in the window state before any release can run.

Window identity is a pure function of the spec and the epoch
(``<start_ms>-<end_ms>``): two processes — or one process before and
after a kill — derive the same id for the same span, which is what
lets the per-window noise subtree and the idempotent per-window
charge id be stable across recovery.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

__all__ = ["LateRecordError", "Window", "WindowManager", "WindowSpec"]


class LateRecordError(ValueError):
    """The record's event time is older than the watermark: admitting
    it could touch an already-released window, so it is refused at the
    door (counted, never silently dropped)."""

    def __init__(self, ts: float, watermark: float):
        self.ts = ts
        self.watermark = watermark
        super().__init__(
            f"event time {ts:.3f} is older than the watermark "
            f"{watermark:.3f} (lateness bound exhausted)")


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """``size_s`` window length; ``slide_s`` hop (None = tumbling);
    ``late_s`` bounded lateness (0 = in-order streams only)."""

    size_s: float
    slide_s: float | None = None
    late_s: float = 0.0

    def __post_init__(self):
        if self.size_s <= 0.0:
            raise ValueError(f"size_s must be positive, got "
                             f"{self.size_s}")
        if self.slide_s is not None:
            if self.slide_s <= 0.0 or self.slide_s > self.size_s:
                raise ValueError(
                    f"slide_s must be in (0, size_s], got "
                    f"{self.slide_s}")
        if self.late_s < 0.0:
            raise ValueError(f"late_s must be >= 0, got {self.late_s}")

    @property
    def hop_s(self) -> float:
        return self.slide_s if self.slide_s is not None else self.size_s

    def spans_for(self, ts: float) -> list[tuple[float, float]]:
        """Every (start, end) span containing event time ``ts``
        (half-open [start, end)); one for tumbling, size/slide for
        sliding. Starts are multiples of the hop, so the span set is a
        pure function of the spec — every process agrees."""
        if ts < 0.0:
            raise ValueError(f"event time must be >= 0, got {ts}")
        hop = self.hop_s
        start = int(ts // hop) * hop
        spans = []
        while start > ts - self.size_s and start >= 0.0:
            spans.append((start, start + self.size_s))
            start -= hop
        spans.sort()
        return spans

    @staticmethod
    def window_id(span: tuple[float, float]) -> str:
        return f"{int(round(span[0] * 1000))}-{int(round(span[1] * 1000))}"


class Window:
    """One open window's accumulating state."""

    __slots__ = ("id", "start", "end", "rows")

    def __init__(self, span: tuple[float, float]):
        self.start, self.end = span
        self.id = WindowSpec.window_id(span)
        self.rows: list[tuple[float, float]] = []

    def __len__(self) -> int:
        return len(self.rows)


class WindowManager:
    """Open-window table + watermark. Single-threaded by design — the
    service serializes ingest under its own lock; this class holds the
    pure windowing logic so it is testable with a scripted sequence."""

    def __init__(self, spec: WindowSpec):
        self.spec = spec
        self.windows: dict[str, Window] = {}
        self.max_event_ts = float("-inf")
        self.closed: set[str] = set()
        self.late_refused = 0
        self.reclosed_skips = 0

    @property
    def watermark(self) -> float:
        return self.max_event_ts - self.spec.late_s

    def admit(self, ts: float, rows: Iterable[tuple[float, float]]
              ) -> list[str]:
        """Admit one batch at event time ``ts``; returns the window ids
        it landed in. Raises :class:`LateRecordError` past the
        lateness bound; an empty ``rows`` only advances the watermark
        (the heartbeat/flush form)."""
        ts = float(ts)
        rows = [(float(x), float(y)) for x, y in rows]
        if rows and self.max_event_ts != float("-inf") \
                and ts < self.watermark:
            self.late_refused += 1
            raise LateRecordError(ts, self.watermark)
        hit = []
        if rows:
            for span in self.spec.spans_for(ts):
                wid = WindowSpec.window_id(span)
                if wid in self.closed:
                    # recovery replay: the batch already contributed to
                    # this (journaled) window's release — skip the span,
                    # never reopen it, but still land the rows in any
                    # sibling span that is still open. Genuine late data
                    # can't reach here: closure implies watermark >= end
                    # > ts, which the watermark check above refuses.
                    self.reclosed_skips += 1
                    continue
                w = self.windows.get(wid)
                if w is None:
                    w = self.windows[wid] = Window(span)
                w.rows.extend(rows)
                hit.append(wid)
        self.max_event_ts = max(self.max_event_ts, ts)
        return hit

    def closable(self) -> list[Window]:
        """Windows the watermark has passed, oldest first — ready for
        release (no admissible record can reach them anymore)."""
        ready = [w for w in self.windows.values()
                 if w.end <= self.watermark]
        ready.sort(key=lambda w: (w.start, w.end))
        return ready

    def close(self, window_id: str) -> None:
        """Drop a released (or refused) window's state and remember the
        id so recovery re-admission can never resurrect it."""
        self.windows.pop(window_id, None)
        self.closed.add(window_id)

    def pending(self) -> list[Window]:
        return sorted(self.windows.values(),
                      key=lambda w: (w.start, w.end))
