"""HTTP front end for one :class:`~dpcorr.stream.service.StreamService`.

Same conventions as the serve stack's front end (serve/server.py):
JSON bodies, typed refusals with distinct status codes (400 invalid /
late, 429 overload with ``Retry-After``, 500 with the exception type),
Prometheus ``/metrics`` off the same registry as ``/stats``, and the
fleet's ``POST /obs/trigger`` hook validated against the recorder's
append-only reason registry.

Routes:

- ``POST /ingest`` — ``{"batch_id", "ts", "rows": [[x, y], ...]}``;
  the 200 ack carries the WAL seq and any windows this batch's
  watermark advance released. Empty ``rows`` is the watermark
  heartbeat / flush form.
- ``GET /releases?since=N`` — journal entries with
  ``release_seq > N`` (the polling subscribe feed).
- ``GET /stats``, ``GET /metrics``, ``GET /healthz``.
- ``POST /obs/trigger`` — arm/dump the flight recorder remotely.
"""

from __future__ import annotations

import json
from urllib.parse import parse_qs, urlparse

from dpcorr.obs import recorder as obs_recorder
from dpcorr.obs.metrics import CONTENT_TYPE as _PROM_CONTENT_TYPE
from dpcorr.stream.service import StreamOverloadedError, StreamService
from dpcorr.stream.windows import LateRecordError

__all__ = ["make_stream_http_server"]


def make_stream_http_server(service: StreamService,
                            host: str = "127.0.0.1", port: int = 8324):
    """Build (not start) the threaded HTTP front end; the caller owns
    ``serve_forever`` / ``shutdown`` so tests can run it on a thread."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict,
                  headers: tuple = ()) -> None:
            blob = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(blob)

        def _send_text(self, code: int, text: str,
                       content_type: str) -> None:
            blob = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        @staticmethod
        def _retry_after(e) -> tuple:
            ra = getattr(e, "retry_after_s", None)
            if ra is None:
                return ()
            secs = max(1, int(ra) + (1 if ra % 1 else 0))
            return (("Retry-After", str(secs)),)

        def do_GET(self):  # noqa: N802 (stdlib handler casing)
            url = urlparse(self.path)
            if url.path == "/stats":
                self._send(200, service.stats())
            elif url.path == "/metrics":
                self._send_text(200, service.render_metrics(),
                                _PROM_CONTENT_TYPE)
            elif url.path == "/healthz":
                self._send(200, {"ok": True})
            elif url.path == "/releases":
                try:
                    since = int(parse_qs(url.query).get(
                        "since", ["0"])[0])
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                    return
                self._send(200, {"releases": service.releases(since)})
            else:
                self._send(404, {"error": f"no route {url.path}"})

        def do_POST(self):  # noqa: N802
            if self.path == "/obs/trigger":
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length))
                    reason = body.get("reason")
                    detail = body.get("detail") or {}
                    if reason not in obs_recorder.TRIGGER_REASONS:
                        raise ValueError(
                            f"unknown trigger reason {reason!r}")
                    if not isinstance(detail, dict):
                        raise ValueError("detail must be an object")
                except (ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                path = obs_recorder.trigger(
                    reason, **{str(k): v for k, v in detail.items()})
                self._send(200, {"dumped": path,
                                 "armed": obs_recorder.active()
                                 is not None})
                return
            if self.path != "/ingest":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length))
                batch_id = str(body["batch_id"])
                ts = float(body["ts"])
                rows = body.get("rows") or []
            except (KeyError, TypeError, ValueError,
                    json.JSONDecodeError) as e:
                self._send(400, {"error": f"invalid ingest body: {e}"})
                return
            try:
                ack = service.ingest(batch_id, ts, rows)
            except StreamOverloadedError as e:
                self._send(429, {"error": str(e), "refused": "overload"},
                           headers=self._retry_after(e))
            except LateRecordError as e:
                self._send(400, {"error": str(e), "refused": "late",
                                 "watermark": e.watermark})
            except (TypeError, ValueError) as e:
                self._send(400, {"error": str(e)})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
            else:
                self._send(200, ack)

        def log_message(self, *args):  # quiet by default
            pass

    return ThreadingHTTPServer((host, port), Handler)
