"""Mergeable window sketches: the streaming accumulators made explicit.

``models/estimators/streaming.py`` already factors all four families
into per-chunk sufficient statistics — but the chunk loop lives inside
one ``lax.map``, so the partial sums never exist as values a second
process could hold. This module reifies them: a :class:`SketchState`
maps **chunk index → that chunk's stat tuple**, computed by one shared
jitted kernel per family. Merging two sketches is a *disjoint dict
union* — associative and commutative by construction, with no float
reassociation anywhere — and :meth:`SketchState.merge` is therefore
bit-deterministic under any shard split or tree-reduce order. The fold
back to totals happens once, at finalize, in a fixed ascending-chunk
left fold, so

    finalize(merge(shard_a, shard_b)) == finalize(monolithic)

holds **bitwise** for every partition of the chunk set (pinned by
``tests/test_stream.py`` and gated in ``benchmarks/stream_load.py``).

Noise addressing: every draw hangs off the per-window root
``stream(master, "stream/<window_id>")`` using the *same substream
names* as the monolithic streaming estimators (``ni_sign/lap_x``,
``int_sign/est`` → ``int_sign/flips``, …), so a replayed window is a
pure function of (master seed, window id, admitted rows) — byte-
identical wherever and whenever it is recomputed. That is the whole
crash-recovery contract of :mod:`dpcorr.stream.service`.

Kernel builds go through the serve stack's compile layer
(:class:`dpcorr.utils.compile.SingleFlight` dedup +
:func:`dpcorr.utils.compile.aot_compile` under an optional
:class:`~dpcorr.utils.compile.CompileObserver`), so a stream service's
chunk kernels show up in the same ``dpcorr_compile_*`` series as the
serve kernels.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dpcorr.models.estimators import int_sign
from dpcorr.models.estimators.common import batch_geometry
from dpcorr.models.estimators.streaming import (
    _int_subg_chunk_stats,
    _int_subg_interval,
    _int_subg_roles,
    _ni_batch_noise,
    _ni_chunk_stats,
    _ni_from_sums,
    _ni_subg_interval,
    choose_n_chunk,
)
from dpcorr.models.estimators.registry import FAMILIES
from dpcorr.ops.lambdas import lambda_n
from dpcorr.ops.noise import clip_sym, laplace
from dpcorr.ops.standardize import priv_moments_from_sums
from dpcorr.utils import compile as dpc_compile
from dpcorr.utils.rng import chunk_key, stream

__all__ = [
    "ChunkGrid", "ReleaseParams", "SketchState", "grid_for",
    "moments_for_window", "placement_shards", "release_from_sketch", "release_window",
    "set_compile_observer", "sketch_window", "tree_merge", "window_key",
]


def window_key(master: jax.Array, window_id: str) -> jax.Array:
    """Per-window noise root: the ``stream/<window_id>`` subtree of the
    party root. Every family substream below it keeps its monolithic
    name, so a window's noise is addressed by (master, window id) alone
    — the replay/crash-exactness contract."""
    if not window_id:
        raise ValueError("window_id must be non-empty")
    return stream(master, f"stream/{window_id}")


@dataclasses.dataclass(frozen=True)
class ReleaseParams:
    """Everything that decides a window release besides the data and
    the window key. Hashable so kernels cache on it."""

    family: str
    eps1: float
    eps2: float
    normalise: bool = True
    alpha: float = 0.05
    eta1: float = 1.0
    eta2: float = 1.0
    target_chunk: int = 65536

    def __post_init__(self):
        if self.family not in FAMILIES:
            raise ValueError(f"unknown family {self.family!r}; "
                             f"expected one of {FAMILIES}")
        if self.eps1 <= 0.0 or self.eps2 <= 0.0:
            raise ValueError(
                f"eps must be positive, got ({self.eps1}, {self.eps2})")

    @property
    def needs_moments(self) -> bool:
        """Sign families under ``normalise`` standardize privately
        first — a second pass whose moments every shard must agree on
        before any estimate chunk can be computed."""
        return self.normalise and self.family in ("ni_sign", "int_sign")


@dataclasses.dataclass(frozen=True)
class ChunkGrid:
    """The chunk geometry of one window: fixed by (family, n, ε) alone,
    so every shard derives the identical grid independently."""

    family: str
    n: int
    n_chunk: int
    n_chunks: int
    m: int
    k: int

    @property
    def kc(self) -> int:
        return self.n_chunk // self.m


def grid_for(params: ReleaseParams, n: int) -> ChunkGrid:
    """Chunk grid for an n-row window. NI families align ``n_chunk`` to
    the batch size m (:func:`choose_n_chunk`) so batches never straddle
    chunks; INT families stream per-sample (m = 1)."""
    if params.family in ("ni_sign", "ni_subg"):
        m, k = batch_geometry(n, params.eps1, params.eps2)
    else:
        m, k = 1, n
    n_chunk = choose_n_chunk(n, m, params.target_chunk)
    return ChunkGrid(params.family, n, n_chunk, -(-n // n_chunk), m, k)


# --------------------------------------------------------- sketches ----
class SketchState:
    """Per-chunk sufficient statistics of one window pass.

    ``meta`` pins what the stats are a function of (family, pass, n,
    grid, params digest, moments); ``chunks`` maps chunk index → a
    tuple-of-tuples of floats (JSON-safe, exact for float32 values).
    Two sketches merge only when their meta agrees; overlapping chunk
    indices must carry identical stats (the same chunk computed twice
    is fine, a *conflicting* recomputation is corruption)."""

    __slots__ = ("meta", "chunks")

    def __init__(self, meta: Mapping,
                 chunks: Mapping[int, tuple] | None = None):
        self.meta = dict(meta)
        self.chunks: dict[int, tuple] = {
            int(c): _freeze_stats(st) for c, st in (chunks or {}).items()}

    def merge(self, other: "SketchState") -> "SketchState":
        """Disjoint-union merge — associative, commutative and
        bit-deterministic: no arithmetic happens here at all."""
        if self.meta != other.meta:
            raise ValueError(
                f"cannot merge sketches of different windows/passes: "
                f"{self.meta} != {other.meta}")
        for c, st in other.chunks.items():
            if c in self.chunks and self.chunks[c] != st:
                raise ValueError(
                    f"chunk {c} carries conflicting stats in the two "
                    f"sketches — same window recomputed differently")
        merged = dict(self.chunks)
        merged.update(other.chunks)
        return SketchState(self.meta, merged)

    def missing(self, grid: ChunkGrid) -> list[int]:
        return [c for c in range(grid.n_chunks) if c not in self.chunks]

    def to_dict(self) -> dict:
        """Wire/journal form (strict JSON; chunk keys as strings)."""
        return {"meta": dict(self.meta),
                "chunks": {str(c): [list(s) for s in st]
                           for c, st in sorted(self.chunks.items())}}

    @classmethod
    def from_dict(cls, d: Mapping) -> "SketchState":
        return cls(d["meta"], {int(c): tuple(tuple(float(v) for v in s)
                                             for s in st)
                               for c, st in d["chunks"].items()})


def _freeze_stats(st) -> tuple:
    return tuple(tuple(float(v) for v in s) for s in st)


def tree_merge(sketches: Sequence[SketchState]) -> SketchState:
    """Pairwise binary tree reduction of shard sketches — the merge
    shape a mesh of N workers produces (log₂N rounds of neighbor
    merges) rather than the sequential left fold of
    :func:`release_window`. Because :meth:`SketchState.merge` is a
    disjoint dict union with no arithmetic, the result is **bitwise
    identical** to any other merge order — this function exists so the
    tree shape is exercised and pinned by tests, not assumed."""
    level = list(sketches)
    if not level:
        raise ValueError("tree_merge needs at least one sketch")
    while len(level) > 1:
        nxt = [level[i].merge(level[i + 1])
               for i in range(0, len(level) - 1, 2)]
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def _fold(sketch: SketchState, grid: ChunkGrid) -> list[list[float]]:
    """Canonical reduction: ascending-chunk left fold in float64. The
    ONE place partial sums are combined, so the result cannot depend on
    which shard held which chunk."""
    miss = sketch.missing(grid)
    if miss:
        raise ValueError(f"sketch incomplete: missing chunks {miss[:8]}"
                         f"{'…' if len(miss) > 8 else ''} of "
                         f"{grid.n_chunks}")
    totals: list[list[float]] | None = None
    for c in range(grid.n_chunks):
        st = sketch.chunks[c]
        if totals is None:
            totals = [list(s) for s in st]
        else:
            for t, s in zip(totals, st):
                for i, v in enumerate(s):
                    t[i] += v
    return totals


# ---------------------------------------------------- chunk kernels ----
# Kernel builds share the serve compile layer: SingleFlight dedups
# concurrent first-builds and aot_compile records each compile into the
# (optionally service-owned) CompileObserver, so stream kernels appear
# in the same dpcorr_compile_* series as serve kernels.
_KERNELS: dict = {}
_FLIGHT = dpc_compile.SingleFlight()
_OBSERVER: dpc_compile.CompileObserver | None = None


def set_compile_observer(obs) -> None:
    """Route subsequent kernel compiles through a service's observer
    (its /metrics registry). Process-wide, like the kernel cache."""
    global _OBSERVER
    _OBSERVER = obs


def _get_kernel(kind: str, statics: tuple, build_jitted, example_args):
    """Compile-once per (kind, statics) through SingleFlight +
    aot_compile; falls back to the lazily-jitted callable when AOT
    lowering is unavailable for the arg mix."""
    key = (kind,) + statics
    fn = _KERNELS.get(key)
    if fn is not None:
        return fn

    def build():
        jitted = build_jitted()
        compiled, _ok = dpc_compile.aot_compile(
            jitted, example_args,
            signature={"kernel": f"stream.{kind}",
                       "statics": repr(statics)},
            observer=_OBSERVER)
        _KERNELS[key] = compiled
        return compiled

    fn, _leader = _FLIGHT.do(key, build)
    return fn


def _row_mask(c, n, n_chunk: int, dtype):
    return ((c * n_chunk + jnp.arange(n_chunk)) < n).astype(dtype)


def _pass_a_kernel(n_chunk: int, example_args):
    def fn(xy, c, n, l_raw):
        xyc = clip_sym(xy, l_raw)
        w = _row_mask(c, n, n_chunk, xyc.dtype)[:, None]
        return jnp.sum(xyc * w, axis=0), jnp.sum(xyc * xyc * w, axis=0)

    return _get_kernel("pass_a", (n_chunk,), lambda: jax.jit(fn),
                       example_args)


def _ni_kernel(mode: str, n_chunk: int, m: int, example_args):
    kc = n_chunk // m

    def fn(xy, c, k, lap_x, lap_y, mu_x, inv_x, mu_y, inv_y, l_clip,
           lam1, lam2):
        if mode == "sign_norm":
            tx = lambda v: jnp.sign((clip_sym(v, l_clip) - mu_x) * inv_x)
            ty = lambda v: jnp.sign((clip_sym(v, l_clip) - mu_y) * inv_y)
        elif mode == "sign_raw":
            tx = ty = jnp.sign
        else:  # "clip": NI subG transforms
            tx = lambda v: clip_sym(v, lam1)
            ty = lambda v: clip_sym(v, lam2)
        return _ni_chunk_stats(xy, c, tx, ty, m, kc, k, lap_x, lap_y)

    return _get_kernel(f"ni.{mode}", (n_chunk, m), lambda: jax.jit(fn),
                       example_args)


def _int_sign_kernel(mode: str, n_chunk: int, example_args):
    def fn(xy, c, n, flip_base, p_keep, mu_x, inv_x, mu_y, inv_y,
           l_clip):
        if mode == "sign_norm":
            sx = lambda v: (clip_sym(v, l_clip) - mu_x) * inv_x
            sy = lambda v: (clip_sym(v, l_clip) - mu_y) * inv_y
        else:
            sx = sy = lambda v: v
        s = jax.random.bernoulli(chunk_key(flip_base, c), p_keep,
                                 (n_chunk,))
        core = ((2.0 * s.astype(jnp.float32) - 1.0)
                * jnp.sign(sx(xy[:, 0])) * jnp.sign(sy(xy[:, 1])))
        w = (c * n_chunk + jnp.arange(n_chunk)) < n
        return (jnp.sum(jnp.where(w, core, 0.0)),)

    return _get_kernel(f"int_sign.{mode}", (n_chunk,),
                       lambda: jax.jit(fn), example_args)


def _int_subg_kernel(sender_is_x: bool, n_chunk: int, example_args):
    def fn(xy, c, n, noise_base, lam_s, lam_r, eps_s):
        return _int_subg_chunk_stats(xy, c, noise_base, sender_is_x,
                                     lam_s, lam_r, eps_s, n, n_chunk)

    return _get_kernel("int_subg", (sender_is_x, n_chunk),
                       lambda: jax.jit(fn), example_args)


# -------------------------------------------------- window pipeline ----
def _padded(xy: np.ndarray, grid: ChunkGrid) -> np.ndarray:
    pad = grid.n_chunks * grid.n_chunk - grid.n
    if pad:
        xy = np.concatenate(
            [xy, np.zeros((pad, 2), dtype=xy.dtype)], axis=0)
    return xy


def _chunk(xy_pad: np.ndarray, c: int, grid: ChunkGrid) -> jnp.ndarray:
    return jnp.asarray(xy_pad[c * grid.n_chunk:(c + 1) * grid.n_chunk])


def _f32(v) -> jnp.ndarray:
    return jnp.asarray(v, jnp.float32)


def _i32(v) -> jnp.ndarray:
    return jnp.asarray(v, jnp.int32)


def _meta(params: ReleaseParams, grid: ChunkGrid, pass_name: str,
          moments: Mapping | None) -> dict:
    meta = {"family": params.family, "pass": pass_name, "n": grid.n,
            "n_chunk": grid.n_chunk, "m": grid.m, "k": grid.k,
            "eps1": params.eps1, "eps2": params.eps2,
            "normalise": params.normalise, "alpha": params.alpha}
    if moments is not None:
        meta["moments"] = {k: float(v) for k, v in sorted(moments.items())}
    return meta


def moments_for_window(pass_a: SketchState, params: ReleaseParams,
                       grid: ChunkGrid, wkey: jax.Array) -> dict:
    """DP standardization moments from a complete pass-A sketch: the
    window's private (μ, 1/σ) per column, drawn from the window key at
    the family's monolithic substream addresses
    (``<ns>/std_x`` / ``<ns>/std_y``). Every shard computing pass B
    must be handed these exact values (they ride the pass-B meta)."""
    totals = _fold(pass_a, grid)
    s1, s2 = totals
    l_clip = math.sqrt(2.0 * math.log(grid.n))
    ns = params.family
    out = {}
    for col, (eps, name) in enumerate(
            ((params.eps1, "std_x"), (params.eps2, "std_y"))):
        mu, var = priv_moments_from_sums(
            stream(wkey, f"{ns}/{name}"), _f32(s1[col]), _f32(s2[col]),
            grid.n, eps, l_clip)
        suffix = "x" if col == 0 else "y"
        out[f"mu_{suffix}"] = float(mu)
        out[f"inv_{suffix}"] = float(1.0 / jnp.sqrt(var))
    out["l_clip"] = l_clip
    return out


def sketch_window(xy, params: ReleaseParams, wkey: jax.Array,
                  pass_name: str = "estimate",
                  chunk_ids: Sequence[int] | None = None,
                  moments: Mapping | None = None) -> SketchState:
    """Sketch one pass over (a shard of) a window.

    ``xy`` is the full (n, 2) admitted-row array — the shard split is
    over *chunk indices* (``chunk_ids``; None = all), which is what
    makes shard sketches mergeable: chunk c's stats are a pure function
    of (rows of chunk c, window key, params), identical whichever shard
    computes them. ``pass_name`` is ``"pass_a"`` (clipped moment sums,
    normalise families) or ``"estimate"``; the estimate pass of a
    normalise family requires ``moments`` from
    :func:`moments_for_window`."""
    xy = np.ascontiguousarray(np.asarray(xy, dtype=np.float32))
    if xy.ndim != 2 or xy.shape[1] != 2:
        raise ValueError(f"xy must be (n, 2), got {xy.shape}")
    grid = grid_for(params, xy.shape[0])
    if pass_name not in ("pass_a", "estimate"):
        raise ValueError(f"unknown pass {pass_name!r}")
    if pass_name == "pass_a" and not params.needs_moments:
        raise ValueError(
            f"family {params.family!r} (normalise={params.normalise}) "
            f"has no standardization pass")
    if pass_name == "estimate" and params.needs_moments \
            and moments is None:
        raise ValueError("estimate pass of a normalise family needs "
                         "moments= from moments_for_window()")
    ids = range(grid.n_chunks) if chunk_ids is None \
        else sorted({int(c) for c in chunk_ids})
    for c in ids:
        if not 0 <= c < grid.n_chunks:
            raise ValueError(f"chunk id {c} outside grid "
                             f"[0, {grid.n_chunks})")
    xy_pad = _padded(xy, grid)
    if pass_name == "pass_a":
        stats = _pass_a_stats(xy_pad, grid, ids)
    else:
        stats = _estimate_stats(xy_pad, params, grid, wkey, ids, moments)
    return SketchState(
        _meta(params, grid, pass_name,
              moments if pass_name == "estimate" else None), stats)


def _pass_a_stats(xy_pad, grid: ChunkGrid, ids) -> dict[int, tuple]:
    l_raw = _f32(math.sqrt(2.0 * math.log(grid.n)))
    n = _i32(grid.n)
    out = {}
    kern = None
    for c in ids:
        args = (_chunk(xy_pad, c, grid), _i32(c), n, l_raw)
        if kern is None:
            kern = _pass_a_kernel(grid.n_chunk, args)
        s1, s2 = kern(*args)
        out[c] = (tuple(np.asarray(s1, np.float64)),
                  tuple(np.asarray(s2, np.float64)))
    return out


def _estimate_stats(xy_pad, params: ReleaseParams, grid: ChunkGrid,
                    wkey, ids, moments) -> dict[int, tuple]:
    fam = params.family
    if fam in ("ni_sign", "ni_subg"):
        return _ni_stats(xy_pad, params, grid, wkey, ids, moments)
    if fam == "int_sign":
        return _int_sign_stats(xy_pad, params, grid, wkey, ids, moments)
    return _int_subg_stats(xy_pad, params, grid, wkey, ids)


def _zero_moments() -> dict:
    return {"mu_x": 0.0, "inv_x": 1.0, "mu_y": 0.0, "inv_y": 1.0,
            "l_clip": 1.0}


def _ni_stats(xy_pad, params, grid, wkey, ids, moments):
    ns = "ni_sign" if params.family == "ni_sign" else "ni_subg"
    if params.family == "ni_sign":
        mode = "sign_norm" if params.normalise else "sign_raw"
        scale_x = 2.0 / (grid.m * params.eps1)
        scale_y = 2.0 / (grid.m * params.eps2)
        lam1 = lam2 = 1.0
    else:
        mode = "clip"
        lam1 = lambda_n(grid.n, params.eta1)
        lam2 = lambda_n(grid.n, params.eta2)
        scale_x = 2.0 * lam1 / (grid.m * params.eps1)
        scale_y = 2.0 * lam2 / (grid.m * params.eps2)
    # the (k,) batch-noise draws at the monolithic addresses, padded to
    # the data-chunk grid (n_chunks*kc >= k) — every shard re-derives
    # the identical vectors from the window key
    lap_x, lap_y = _ni_batch_noise(
        stream(wkey, f"{ns}/lap_x"), stream(wkey, f"{ns}/lap_y"),
        grid.k, _f32(scale_x), _f32(scale_y), grid.n_chunks * grid.kc)
    mo = dict(moments) if moments is not None else _zero_moments()
    k = _i32(grid.k)
    out = {}
    kern = None
    for c in ids:
        args = (_chunk(xy_pad, c, grid), _i32(c), k, lap_x, lap_y,
                _f32(mo["mu_x"]), _f32(mo["inv_x"]), _f32(mo["mu_y"]),
                _f32(mo["inv_y"]), _f32(mo["l_clip"]), _f32(lam1),
                _f32(lam2))
        if kern is None:
            kern = _ni_kernel(mode, grid.n_chunk, grid.m, args)
        st, st2 = kern(*args)
        out[c] = ((float(np.asarray(st, np.float64)),),
                  (float(np.asarray(st2, np.float64)),))
    return out


def _int_sign_stats(xy_pad, params, grid, wkey, ids, moments):
    mode = "sign_norm" if params.normalise else "sign_raw"
    eps_s = max(params.eps1, params.eps2)
    e_s = math.exp(eps_s)
    p_keep = e_s / (e_s + 1.0)
    flip_base = stream(stream(wkey, "int_sign/est"), "int_sign/flips")
    mo = dict(moments) if moments is not None else _zero_moments()
    n = _i32(grid.n)
    out = {}
    kern = None
    for c in ids:
        args = (_chunk(xy_pad, c, grid), _i32(c), n, flip_base,
                _f32(p_keep), _f32(mo["mu_x"]), _f32(mo["inv_x"]),
                _f32(mo["mu_y"]), _f32(mo["inv_y"]), _f32(mo["l_clip"]))
        if kern is None:
            kern = _int_sign_kernel(mode, grid.n_chunk, args)
        (sum_core,) = kern(*args)
        out[c] = ((float(np.asarray(sum_core, np.float64)),),)
    return out


def _int_subg_stats(xy_pad, params, grid, wkey, ids):
    sender_is_x, eps_s, _eps_r, lam_s, lam_r = _int_subg_roles(
        grid.n, params.eps1, params.eps2, params.eta1, params.eta2)
    noise_base = stream(wkey, "int_subg/lap_sender")
    n = _i32(grid.n)
    out = {}
    kern = None
    for c in ids:
        args = (_chunk(xy_pad, c, grid), _i32(c), n, noise_base,
                _f32(lam_s), _f32(lam_r), _f32(eps_s))
        if kern is None:
            kern = _int_subg_kernel(bool(sender_is_x), grid.n_chunk, args)
        s1, s2 = kern(*args)
        out[c] = ((float(np.asarray(s1, np.float64)),),
                  (float(np.asarray(s2, np.float64)),))
    return out


# ---------------------------------------------------------- release ----
def release_from_sketch(sketch: SketchState, params: ReleaseParams,
                        wkey: jax.Array) -> dict:
    """Fold a complete estimate sketch and finish the release: the
    window-level noise draws (central Laplace, CI construction) at
    their monolithic substream addresses under the window key. Returns
    the strict-JSON release record; ``json.dumps(..., sort_keys=True)``
    of it is the byte-identity surface the crash gates compare."""
    grid = ChunkGrid(params.family, int(sketch.meta["n"]),
                     int(sketch.meta["n_chunk"]), -1,
                     int(sketch.meta["m"]), int(sketch.meta["k"]))
    grid = dataclasses.replace(
        grid, n_chunks=-(-grid.n // grid.n_chunk))
    totals = _fold(sketch, grid)
    fam = params.family
    if fam == "ni_sign":
        res = _finish_ni_sign(totals, params, grid)
    elif fam == "ni_subg":
        res = _finish_ni_subg(totals, params, grid)
    elif fam == "int_sign":
        res = _finish_int_sign(totals, params, grid, wkey)
    else:
        res = _finish_int_subg(totals, params, grid, wkey)
    rho, lo, hi = res
    return {"family": fam, "n": grid.n, "m": grid.m, "k": grid.k,
            "eps1": params.eps1, "eps2": params.eps2,
            "normalise": params.normalise, "alpha": params.alpha,
            "rho": float(rho), "lo": float(lo), "hi": float(hi)}


def _finish_ni_sign(totals, params, grid):
    from jax.scipy.special import ndtri

    (st,), (st2,) = totals
    eta_hat, s_eta = _ni_from_sums(_f32(st), _f32(st2), grid.k)
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    half = (float(ndtri(1.0 - params.alpha / 2.0)) * s_eta
            / jnp.sqrt(float(grid.k)))
    lo = jnp.sin(jnp.pi / 2.0 * jnp.maximum(eta_hat - half, -1.0))
    hi = jnp.sin(jnp.pi / 2.0 * jnp.minimum(eta_hat + half, 1.0))
    return rho_hat, lo, hi


def _finish_ni_subg(totals, params, grid):
    (st,), (st2,) = totals
    eta_hat, s_t = _ni_from_sums(_f32(st), _f32(st2), grid.k)
    lam1 = lambda_n(grid.n, params.eta1)
    lam2 = lambda_n(grid.n, params.eta2)
    res = _ni_subg_interval(eta_hat, s_t, grid.k, grid.m, lam1, lam2,
                            params.alpha)
    return res.rho_hat, res.ci_low, res.ci_high


def _finish_int_sign(totals, params, grid, wkey):
    ((sum_core,),) = totals
    eps_s = max(params.eps1, params.eps2)
    eps_r = min(params.eps1, params.eps2)
    e_s = math.exp(eps_s)
    est_key = stream(wkey, "int_sign/est")
    scale_z = 2.0 * (e_s + 1.0) / (grid.n * (e_s - 1.0) * eps_r)
    z = laplace(stream(est_key, "int_sign/lap_z"), (), scale_z)
    eta_hat = (e_s + 1.0) / (grid.n * (e_s - 1.0)) * _f32(sum_core) + z
    rho_hat = jnp.sin(jnp.pi * eta_hat / 2.0)
    res = int_sign.interval_from_rho(wkey, rho_hat, grid.n, eps_s,
                                     eps_r, params.alpha, "auto", "det")
    return res.rho_hat, res.ci_low, res.ci_high


def _finish_int_subg(totals, params, grid, wkey):
    (s1,), (s2,) = totals
    _sx, eps_s, eps_r, lam_s, lam_r = _int_subg_roles(
        grid.n, params.eps1, params.eps2, params.eta1, params.eta2)
    res = _int_subg_interval(wkey, _f32(s1), _f32(s2), grid.n, eps_s,
                             eps_r, lam_s, lam_r, params.alpha, "det")
    return res.rho_hat, res.ci_low, res.ci_high


def placement_shards(placement, n_chunks: int) -> list[list[int]]:
    """The chunk partition a plan placement induces: one shard per
    device, chunks dealt round-robin (shard ``d`` gets every chunk
    ``c`` with ``c % D == d``). A :class:`~dpcorr.plan.placement.
    LocalPlacement` (one device) degenerates to the monolithic single
    shard; a ``MeshPlacement`` over D devices yields the D-way split
    whose :func:`tree_merge` is pinned bitwise-equal to the monolith.
    Duck-typed on the ``device_count`` property so this module never
    imports :mod:`dpcorr.plan`."""
    d = max(1, int(placement.device_count))
    shards = [[c for c in range(n_chunks) if c % d == i]
              for i in range(d)]
    return [s for s in shards if s]


def release_window(xy, params: ReleaseParams, wkey: jax.Array,
                   shards: Sequence[Sequence[int]] | None = None,
                   *, placement=None) -> dict:
    """Full window pipeline: (pass A → moments →) estimate sketch →
    fold → release. ``shards`` splits every pass's chunk set (e.g.
    ``[[0, 2], [1, 3]]``) and merges the shard sketches — the release
    is bitwise identical for every partition, which is exactly what the
    associativity gate runs this function to prove. ``placement``
    (a :mod:`dpcorr.plan` placement; mutually exclusive with explicit
    ``shards``) derives the partition from the execution plan via
    :func:`placement_shards` — the mesh path the stream service routes
    finalize through."""
    xy = np.ascontiguousarray(np.asarray(xy, dtype=np.float32))
    grid = grid_for(params, xy.shape[0])
    if shards is None:
        if placement is not None:
            shards = placement_shards(placement, grid.n_chunks)
        else:
            shards = [list(range(grid.n_chunks))]
    elif placement is not None:
        raise ValueError("pass shards= or placement=, not both")
    moments = None
    if params.needs_moments:
        pass_a = _merged(xy, params, wkey, "pass_a", shards, None)
        moments = moments_for_window(pass_a, params, grid, wkey)
    est = _merged(xy, params, wkey, "estimate", shards, moments)
    return release_from_sketch(est, params, wkey)


def _merged(xy, params, wkey, pass_name, shards, moments) -> SketchState:
    # tree reduction, not a left fold: the shape a mesh of workers
    # produces. merge() is a no-arithmetic dict union, so this is
    # bitwise-identical to any other order — pinned by test_plan.
    return tree_merge([
        sketch_window(xy, params, wkey, pass_name, chunk_ids=ids,
                      moments=moments)
        for ids in shards])
