"""`dpcorr stream` — always-on windowed DP correlation (docs/STREAMING.md).

The paper's estimate is one-shot; the ROADMAP's "continuous telemetry
between two orgs" workload needs it *continuously*: per-window DP
releases over an unbounded stream — DP under continual observation
(Dwork et al., STOC 2010). This package is that service, grown from
parts the repo already has:

- :mod:`dpcorr.stream.sketch` — mergeable per-window sketch states
  factored out of the chunked accumulators in
  ``models/estimators/streaming.py``: an associative,
  bit-deterministic ``merge`` over per-chunk sufficient statistics,
  so shard sketches tree-reduce across processes and the shard split
  can never change a release byte.
- :mod:`dpcorr.stream.windows` — tumbling/sliding event-time windows
  with a bounded late-data admission (watermark = max event time seen
  minus the lateness bound). jax-free.
- :mod:`dpcorr.stream.wal` — ingest WAL (fsynced append before ack)
  and the released-window journal, the same durability discipline as
  ``SessionJournal`` / ``BudgetDirectory``. jax-free.
- :mod:`dpcorr.stream.service` — the window manager + per-window DP
  release: one atomic :class:`~dpcorr.serve.budget_dir.CompositeLedger`
  charge per window (refuse-before-release, idempotent
  ``stream:<stream>:<window>`` charge ids), pinned per-window noise
  streams (``stream/<window_id>`` subtree), crash-exact resume.
- :mod:`dpcorr.stream.http` — the ingest/subscribe HTTP front end
  with the serve stack's overload conventions (bounded ingest queue,
  429 + ``Retry-After``, ``/metrics`` + ``/stats``).
"""

from dpcorr.stream.sketch import (  # noqa: F401
    ChunkGrid,
    ReleaseParams,
    SketchState,
    grid_for,
    release_window,
    window_key,
)
from dpcorr.stream.service import (  # noqa: F401
    StreamOverloadedError,
    StreamService,
)
from dpcorr.stream.windows import WindowManager, WindowSpec  # noqa: F401

__all__ = [
    "ChunkGrid", "ReleaseParams", "SketchState", "StreamOverloadedError",
    "StreamService", "WindowManager", "WindowSpec", "grid_for",
    "release_window", "window_key",
]
