"""The stream service: window manager + per-window DP release.

One :class:`StreamService` owns the whole always-on pipeline for one
logical stream: ingest (WAL before ack, bounded pending rows, late-data
refusal), event-time windowing (:mod:`dpcorr.stream.windows`), and the
per-window release sequence whose ordering IS the crash-safety
argument:

1. ``chaos.point("stream.pre_release")`` — the window is closable,
   nothing charged yet. A kill here loses only in-memory state; the
   WAL replays it and the release runs at recovery.
2. **Charge** — one atomic
   :class:`~dpcorr.serve.budget_dir.CompositeLedger` charge for the
   whole window (every family, both parties, plus the optional
   per-user and global legs), under the idempotent charge id
   ``stream:<stream_id>:<window_id>``. Refuse-before-release: a budget
   refusal marks the window refused and draws **no** noise. A kill
   after the charge persists re-runs the same charge at recovery and
   dedups — exactly-once ε.
3. **Release** — :func:`dpcorr.stream.sketch.release_window` under the
   pinned per-window key (``stream/<window_id>`` subtree of the
   service master key). A pure function of (admitted rows, window id,
   params), so a replayed window is byte-identical. An in-process
   release failure refunds the charge and arms the flight recorder
   (``stream_release_failed``); a simulated *crash*
   (:class:`~dpcorr.chaos.SimulatedCrash`, a BaseException) sails
   through the refund handler like a real kill would.
4. **Journal** — fsynced append to the released-window journal, then
   ``chaos.point("stream.post_journal")``. A journaled window is done:
   recovery serves it from the journal and closes it without
   recomputing.

Renewal epoch == release epoch: when a per-user budget directory is
attached, its :class:`~dpcorr.serve.budget_dir.RenewalPolicy` period is
the window hop and its clock is the *event time of the window being
released* — so each release epoch charges exactly one renewal window,
never straddling two.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from dpcorr import chaos
from dpcorr.obs import recorder as obs_recorder
from dpcorr.obs.audit import AuditTrail
from dpcorr.obs.cost import CostRegistry
from dpcorr.obs.metrics import Registry
from dpcorr.serve.budget_dir import (
    BudgetDirectory,
    CompositeLedger,
    RenewalPolicy,
)
from dpcorr.serve.ledger import (
    BudgetExceededError,
    PrivacyLedger,
    release_factor,
)
from dpcorr.stream import sketch
from dpcorr.stream.wal import IngestWAL, ReleaseJournal
from dpcorr.stream.windows import (
    LateRecordError,
    Window,
    WindowManager,
    WindowSpec,
)
from dpcorr.utils import compile as dpc_compile
from dpcorr.utils.rng import master_key

__all__ = ["Releaser", "StreamOverloadedError", "StreamService",
           "window_charges"]


class StreamOverloadedError(Exception):
    """The bounded ingest queue (pending un-released rows) is full.
    The HTTP layer maps this to 429 + ``Retry-After``."""

    def __init__(self, retry_after_s: float):
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"stream ingest queue full; retry after "
            f"{self.retry_after_s:.3g}s")


def window_charges(families, eps1: float, eps2: float, normalise: bool,
                   party_x: str, party_y: str) -> dict[str, float]:
    """The per-party ε one window release spends — the same
    :func:`~dpcorr.serve.ledger.release_factor` math as the serving
    admission path, summed over the released families, so a stream
    window and the equivalent one-shot requests can never drift on
    cost."""
    charges: dict[str, float] = {}
    for family in families:
        factor = release_factor(family, normalise)
        for party, eps in ((party_x, eps1 * factor),
                           (party_y, eps2 * factor)):
            charges[party] = charges.get(party, 0.0) + float(eps)
    return charges


class Releaser:
    """The execution layer the service's admission path hands a
    charged window to: one :func:`sketch.release_window` per family
    under the window's pinned key. Kept separate from the service so
    the charge→release→refund shape is the admission function's whole
    body (the ``budget`` lint rules key on exactly this boundary)."""

    def __init__(self, seed: int, families, eps1: float, eps2: float,
                 normalise: bool, placement=None):
        self.master = master_key(seed)
        self.families = tuple(families)
        self.eps1 = float(eps1)
        self.eps2 = float(eps2)
        self.normalise = bool(normalise)
        # a dpcorr.plan placement (or None = monolithic): finalize
        # routes through sketch.placement_shards, so a MeshPlacement
        # splits each pass's chunk set across devices and tree-merges
        # the shard sketches — bitwise-equal to the monolith by the
        # no-arithmetic-merge contract (pinned in tests/test_plan.py)
        self.placement = placement

    def release(self, window: Window) -> dict:
        rows = np.asarray(window.rows, dtype=np.float32)
        wkey = sketch.window_key(self.master, window.id)
        out = {}
        for family in self.families:
            params = sketch.ReleaseParams(
                family, self.eps1, self.eps2, normalise=self.normalise)
            out[family] = sketch.release_window(
                rows, params, wkey, placement=self.placement)
        return {"start": window.start, "end": window.end,
                "rows": int(len(window.rows)), "releases": out}


class StreamService:
    """One always-on windowed DP correlation stream. Thread-safe: the
    HTTP front end calls :meth:`ingest` from handler threads; all
    mutation is serialized under one lock."""

    def __init__(self, workdir: str, spec: WindowSpec, families,
                 eps1: float, eps2: float, *, normalise: bool = True,
                 budget: float = 10.0, seed: int = 0,
                 party_x: str = "party/x", party_y: str = "party/y",
                 stream_id: str = "stream",
                 user: str | None = None,
                 user_budget: float | None = None,
                 global_budget: float | None = None,
                 max_pending_rows: int = 1 << 20,
                 fsync: bool = True,
                 registry: Registry | None = None,
                 placement=None,
                 clock=time.time):
        self.workdir = str(workdir)
        self.clock = clock
        os.makedirs(self.workdir, exist_ok=True)
        self.spec = spec
        self.families = tuple(families)
        if not self.families:
            raise ValueError("need at least one family to release")
        self.eps1 = float(eps1)
        self.eps2 = float(eps2)
        self.normalise = bool(normalise)
        self.party_x = party_x
        self.party_y = party_y
        self.stream_id = str(stream_id)
        self.max_pending_rows = int(max_pending_rows)
        self.per_window_charges = window_charges(
            self.families, self.eps1, self.eps2, self.normalise,
            party_x, party_y)

        self.registry = registry if registry is not None else Registry()
        self.audit = AuditTrail(os.path.join(self.workdir,
                                             "audit.jsonl"))
        self.costs = CostRegistry()
        self._epoch_ts = 0.0  # guarded by: _lock — release epoch
        base = PrivacyLedger(
            budget, path=os.path.join(self.workdir, "ledger.json"),
            audit=self.audit, registry=self.registry)
        directory = None
        if user is not None:
            directory = BudgetDirectory(
                os.path.join(self.workdir, "budget_dir"),
                user_budget=(user_budget if user_budget is not None
                             else budget),
                renewal=RenewalPolicy(period_s=spec.hop_s),
                clock=lambda: self._epoch_ts,
                fsync=fsync, audit=self.audit)
        self.ledger = CompositeLedger(base, directory, user=user,
                                      global_budget=global_budget)
        self.releaser = Releaser(seed, self.families, self.eps1,
                                 self.eps2, self.normalise,
                                 placement=placement)
        self._cobs = dpc_compile.CompileObserver(registry=self.registry)
        sketch.set_compile_observer(self._cobs)

        self._batches = self.registry.counter(
            "dpcorr_stream_batches_total",
            "Ingest batches by outcome", labelnames=("kind",))
        self._rows = self.registry.counter(
            "dpcorr_stream_rows_total", "Rows admitted into windows")
        self._windows = self.registry.counter(
            "dpcorr_stream_windows_total",
            "Windows finalized by outcome", labelnames=("outcome",))
        self._open_g = self.registry.gauge(
            "dpcorr_stream_open_windows", "Currently open windows")
        self._pending_g = self.registry.gauge(
            "dpcorr_stream_pending_rows",
            "Rows buffered in open windows")
        self._wm_g = self.registry.gauge(
            "dpcorr_stream_watermark_ts",
            "Event-time watermark (seconds)")
        self._wm_lag_g = self.registry.gauge(
            "dpcorr_stream_watermark_lag_seconds",
            "Ingest-clock seconds the watermark trails now "
            "(the thresholdable form of freshness)")
        self._release_h = self.registry.histogram(
            "dpcorr_stream_release_seconds",
            "Wall seconds per window release (all families)")

        self._lock = threading.Lock()
        self.manager = WindowManager(spec)   # guarded by: _lock
        self._seen: set[str] = set()         # guarded by: _lock
        self._refused: list[str] = []        # guarded by: _lock
        self.wal = IngestWAL(os.path.join(self.workdir, "wal.jsonl"),
                             fsync=fsync)
        self.journal = ReleaseJournal(
            os.path.join(self.workdir, "releases.jsonl"), fsync=fsync)
        self._recover_locked()

    # ------------------------------------------------------ recovery ----
    def _recover_locked(self) -> None:
        """Rebuild in-memory state from the durable stores: journaled
        windows are closed (never recomputed), the WAL re-admits every
        acked batch in append order (so watermark history — hence the
        admit/refuse sequence — replays exactly), then any window the
        watermark already passed is released. Idempotent charge ids
        make the re-release spend nothing it already spent.
        Runs from the constructor, before any other thread can hold
        the lock (the ``_locked`` suffix marks the same caller-owns-
        the-lock contract the release helpers follow)."""
        for entry in self.journal.entries():
            self.manager.close(str(entry["window_id"]))
        for rec in self.wal.replay():
            self._seen.add(str(rec["batch_id"]))
            try:
                self.manager.admit(float(rec["ts"]), rec["rows"])
            except LateRecordError:
                # admissible when logged; only refusable now because
                # every window it fed is already journaled
                continue
        self._close_ready_locked()
        self._publish_gauges_locked()

    # -------------------------------------------------------- ingest ----
    def ingest(self, batch_id: str, ts: float, rows) -> dict:
        """Admit one batch (``rows``: list of [x, y] pairs; empty list
        = watermark heartbeat). The ack — which includes any windows
        this batch's watermark advance released — is returned only
        after the batch is durably in the WAL. ``batch_id`` is the
        client's idempotency key: a re-send of an acked batch dedups
        (the crash-recovery contract is "re-send everything unacked,
        re-sending acked is free")."""
        batch_id = str(batch_id)
        rows = [(float(x), float(y)) for x, y in rows]
        with self._lock:
            if batch_id in self._seen:
                self._batches.inc(kind="deduped")
                return {"ok": True, "deduped": True, "seq": None,
                        "released": [], "refused": []}
            pending = sum(len(w) for w in self.manager.windows.values())
            if rows and pending + len(rows) > self.max_pending_rows:
                self._batches.inc(kind="overload")
                raise StreamOverloadedError(
                    retry_after_s=max(0.05, self.spec.hop_s / 10.0))
            try:
                self.manager.admit(ts, rows)
            except LateRecordError:
                self._batches.inc(kind="late")
                raise
            # dpcorr-lint: ignore[blocking-under-lock] — WAL-before-ack: the batch is durable before the ack forms
            seq = self.wal.append(batch_id, float(ts), rows)
            chaos.point("stream.mid_window")
            self._seen.add(batch_id)
            self._batches.inc(kind="accepted")
            if rows:
                self._rows.inc(len(rows))
            # dpcorr-lint: ignore[blocking-under-lock] — release charge+journal must serialize with admission
            released, refused = self._close_ready_locked()
            self._publish_gauges_locked()
            return {"ok": True, "deduped": False, "seq": seq,
                    "released": released, "refused": refused}

    # ------------------------------------------------------- release ----
    def _close_ready_locked(self):
        """Release every window the watermark has passed, oldest
        first. Caller holds the lock (or is the constructor)."""
        released, refused = [], []
        for window in self.manager.closable():
            entry = self._release_window_locked(window)
            if entry is None:
                refused.append(window.id)
            else:
                released.append(window.id)
        return released, refused

    def _release_window_locked(self, window: Window) -> dict | None:
        """Charge → release → journal for one closable window; the
        chaos points bracket the durability boundaries (module
        docstring). Returns the journal entry, or None on a budget
        refusal (refuse-before-release: no noise drawn, no ε spent)."""
        chaos.point("stream.pre_release")
        prior = self.journal.get(window.id)
        if prior is not None:
            # crashed after the journal append, before close: done
            self.manager.close(window.id)
            return prior
        charge_id = f"stream:{self.stream_id}:{window.id}"
        cost = self.costs.new(trace_id=charge_id)
        self._epoch_ts = window.start  # renewal epoch == release epoch
        try:
            self.ledger.charge(self.per_window_charges,
                               trace_id=charge_id, charge_id=charge_id)
        except BudgetExceededError:
            self._windows.inc(outcome="refused")
            self._refused.append(window.id)
            self.manager.close(window.id)
            cost.event("stream_window_refused")
            return None
        cost.charge(self.per_window_charges)
        t0 = time.monotonic()
        try:
            result = self.releaser.release(window)
        except Exception:
            self.ledger.refund(self.per_window_charges,
                               trace_id=charge_id, charge_id=charge_id,
                               reason="release_failed")
            cost.refund(self.per_window_charges,
                        reason="release_failed")
            obs_recorder.trigger("stream_release_failed",
                                 window=window.id,
                                 stream=self.stream_id)
            raise
        entry = dict(result)
        entry["charge_id"] = charge_id
        entry["eps_window"] = sum(self.per_window_charges.values())
        entry = self.journal.append(window.id, entry)
        chaos.point("stream.post_journal")
        self.manager.close(window.id)
        dt = time.monotonic() - t0
        self._release_h.observe(dt)
        self._windows.inc(outcome="released")
        cost.add_kernel(dt)
        cost.event("stream_window_released")
        return entry

    # --------------------------------------------------------- views ----
    def _publish_gauges_locked(self) -> None:
        self._open_g.set(float(len(self.manager.windows)))
        self._pending_g.set(float(
            sum(len(w) for w in self.manager.windows.values())))
        wm = self.manager.watermark
        if wm != float("-inf"):
            self._wm_g.set(wm)
            self._wm_lag_g.set(max(0.0, float(self.clock()) - wm))

    def releases(self, since: int = 0) -> list[dict]:
        """Journal entries with ``release_seq > since`` — the subscribe
        feed (clients poll with their highest seen seq)."""
        return [e for e in self.journal.entries()
                if int(e.get("release_seq", 0)) > int(since)]

    def stats(self) -> dict:
        with self._lock:
            wm = self.manager.watermark
            out = {
                "stream_id": self.stream_id,
                "families": list(self.families),
                "window": {"size_s": self.spec.size_s,
                           "slide_s": self.spec.slide_s,
                           "late_s": self.spec.late_s},
                "eps_per_window": dict(self.per_window_charges),
                "open_windows": len(self.manager.windows),
                "pending_rows": sum(
                    len(w) for w in self.manager.windows.values()),
                "watermark": None if wm == float("-inf") else wm,
                "watermark_lag_s": (
                    None if wm == float("-inf")
                    else max(0.0, float(self.clock()) - wm)),
                "released": len(self.journal.entries()),
                "refused": list(self._refused),
                "late_refused": self.manager.late_refused,
                "seen_batches": len(self._seen),
                "ledger": self.ledger.snapshot(),
                "cost": self.costs.aggregate(),
            }
            bd = self.ledger.directory_snapshot()
            if bd is not None:
                out["budget_dir"] = bd
            return out

    def render_metrics(self) -> str:
        return self.registry.render()

    def close(self) -> None:
        self.wal.close()
        self.journal.close()
        self.ledger.close()
        self.audit.close()
