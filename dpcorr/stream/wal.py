"""Stream durability: ingest WAL + released-window journal. jax-free.

Same discipline as the repo's other durable stores (``SessionJournal``,
``BudgetDirectory``): every mutation is an **fsynced append** of one
JSON line *before* the caller acknowledges anything, snapshots are
written tmp + fsync + rename, and recovery tolerates exactly one torn
tail line (a crash mid-append) by ignoring it — any other parse
failure quarantines the file to a ``.corrupt`` sidecar and raises,
because silently skipping a mid-file line could drop acknowledged
data.

- :class:`IngestWAL`: one line per admitted batch
  (``{"seq", "batch_id", "ts", "rows"}``). ``batch_id`` is the
  client's idempotency key — recovery rebuilds the seen-set so a
  client re-sending an acked batch after a crash dedups instead of
  double-counting.
- :class:`ReleaseJournal`: one line per released window, appended
  *after* the ledger charge and *before* the release is acknowledged
  to subscribers. A journaled window is done: recovery serves it from
  the journal and never recomputes (the charge it rode is idempotent
  under the window's charge id, so even the recompute path could not
  double-spend).
"""

from __future__ import annotations

import json
import os
from typing import Iterator

from dpcorr.obs.budget_replay import quarantine_corrupt, sweep_stale_tmp

__all__ = ["IngestWAL", "ReleaseJournal", "StreamCorruptError"]


class StreamCorruptError(ValueError):
    """A stream durability file failed to parse mid-file. The bad file
    has been quarantined to a ``.corrupt`` sidecar."""


def _append_line(fh, record: dict, fsync: bool) -> None:
    fh.write(json.dumps(record, sort_keys=True) + "\n")
    fh.flush()
    if fsync:
        os.fsync(fh.fileno())


def _read_lines(path: str) -> list[dict]:
    """All complete records; a torn final line (no trailing newline —
    the only state a kill mid-append can leave) is dropped."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    lines = text.split("\n")
    torn = lines.pop() if lines and lines[-1] != "" else None
    records = []
    for i, line in enumerate(line for line in lines if line):
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError as e:
            quarantine = quarantine_corrupt(path)
            raise StreamCorruptError(
                f"{path!r} line {i + 1} is corrupt ({e}); the file was "
                f"moved to {quarantine!r} — restore from a replica or "
                f"accept the data loss explicitly by removing the "
                f"sidecar") from e
    if torn:
        try:
            records.append(json.loads(torn))
        except json.JSONDecodeError:
            pass  # crash mid-append: the batch was never acked
    return records


class IngestWAL:
    """Append-ack ingest log. ``append`` returns the assigned sequence
    number only after the line is durably on disk — the service acks
    nothing it could forget."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        sweep_stale_tmp(path)
        self._seq = 0
        self._fh = None

    def replay(self) -> Iterator[dict]:
        """Recovery scan, in append order; leaves ``seq`` continuing
        after the highest replayed entry."""
        for rec in _read_lines(self.path):
            self._seq = max(self._seq, int(rec.get("seq", 0)))
            yield rec

    def append(self, batch_id: str, ts: float, rows: list) -> int:
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        self._seq += 1
        _append_line(self._fh, {"seq": self._seq, "batch_id": batch_id,
                                "ts": ts, "rows": rows}, self.fsync)
        return self._seq

    def compact(self, keep) -> None:
        """Rewrite the WAL keeping only entries ``keep(rec)`` selects
        (rows whose every window is already journaled can go):
        tmp + fsync + rename, so a kill mid-compaction leaves the full
        old WAL."""
        records = [r for r in _read_lines(self.path) if keep(r)]
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            for rec in records:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        self.close()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class ReleaseJournal:
    """Append-only record of released windows, keyed by window id.
    Idempotent: re-appending an already-journaled window is a no-op
    (recovery re-runs the release sequence; the journal, like the
    ledger, must absorb the repeat)."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        sweep_stale_tmp(path)
        self._fh = None
        self._entries: dict[str, dict] = {}
        for rec in _read_lines(path):
            self._entries[str(rec["window_id"])] = rec

    def __contains__(self, window_id: str) -> bool:
        return window_id in self._entries

    def get(self, window_id: str) -> dict | None:
        return self._entries.get(window_id)

    def entries(self) -> list[dict]:
        """Journal order (= release order): the subscribe feed."""
        return sorted(self._entries.values(),
                      key=lambda r: int(r.get("release_seq", 0)))

    def append(self, window_id: str, record: dict) -> dict:
        if window_id in self._entries:
            return self._entries[window_id]
        rec = dict(record)
        rec["window_id"] = window_id
        rec["release_seq"] = len(self._entries) + 1
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        _append_line(self._fh, rec, self.fsync)
        self._entries[window_id] = rec
        return rec

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
