"""Compiled-kernel cache + batched execution for the serving layer.

Steady-state traffic must never recompile: the cache is keyed on the
**kernel signature** — ``(KernelKey, padded batch width, sharding)`` —
and the batch axis is padded to the next power of two so a bucket that
flushes at 13 requests and one that flushes at 16 share one compiled
kernel instead of compiling per observed batch size (the standard
shape-bucketing trick, here applied to the request axis). Padding lanes
replicate lane 0 (cheapest valid input) and are truncated before
results leave this module, so they cost device FLOPs but never appear
in responses.

Two batch engines (estimators.registry bit-reproducibility contract):

- ``mode="exact"`` (default): ``jax.lax.map`` over the single-request
  program — one dispatch per flush, every lane **bit-identical** to the
  direct ``jit(single)`` call. This is what makes coalescing invisible
  to clients.
- ``mode="vector"``: ``jit(vmap(single))`` — ~5x faster per batch on
  CPU; ``rho_hat`` still bit-identical, CI endpoints within 1 ulp of
  the scalar program (lanes bit-identical across widths ≥ 2, so results
  still don't depend on how requests were coalesced).

When the process holds more than one device, flushes wide enough to
split evenly are executed through
``parallel.make_serve_batch_sharded`` — the request axis sharded over
the ``rep`` mesh, composing the serving layer with the existing mesh
backend. Sharding preserves each engine's contract (measured; pinned by
tests/test_serve.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable

import numpy as np

from dpcorr.models.estimators.registry import serving_entry
from dpcorr.serve.request import KernelKey
from dpcorr.serve.stats import ServeStats


def pad_batch(b: int) -> int:
    """Next power of two ≥ b: the compiled batch-width bucket."""
    return 1 << (b - 1).bit_length() if b > 1 else 1


class KernelCache:
    """(KernelKey, b_pad, shards) → compiled batched kernel.

    ``jax.jit`` already memoizes compilations, but an explicit cache (a)
    makes the signature an auditable object instead of an implicit
    closure identity — rebuilding ``serving_entry`` closures per flush
    would defeat jit's cache entirely — (b) feeds the
    compile/hit counters the stats endpoint reports, and (c) bounds
    live compilations: signatures include the exact n, so a client
    sweeping sample sizes would otherwise grow the kernel set without
    limit in a long-running server. ``max_kernels`` caps it with LRU
    eviction (evicting our reference also releases the underlying jit
    wrapper and its executables); the live count is a stats gauge
    (``kernel_cache_size``). Steady-state traffic — a working set
    smaller than the cap — still never recompiles.
    """

    def __init__(self, stats: ServeStats | None = None,
                 shard: str = "auto", mode: str = "exact",
                 max_kernels: int = 128):
        if shard not in ("auto", "off"):
            raise ValueError(f"shard must be 'auto' or 'off', got {shard!r}")
        if mode not in ("exact", "vector"):
            raise ValueError(f"mode must be 'exact' or 'vector', got {mode!r}")
        if max_kernels < 1:
            raise ValueError(f"max_kernels must be >= 1, got {max_kernels}")
        self.stats = stats or ServeStats()
        self.shard = shard
        self.mode = mode
        self.max_kernels = max_kernels
        self._lock = threading.Lock()
        self._fns: OrderedDict[tuple, Callable] = OrderedDict()  # guarded by: _lock

    def _n_shards(self, b_pad: int) -> int:
        """How many mesh shards this launch uses (1 = unsharded)."""
        if self.shard == "off":
            return 1
        import jax

        n_dev = len(jax.devices())
        # shard only when the padded axis splits evenly with at least
        # one full lane per device — tiny flushes stay single-device
        # (a 2-lane launch spread over 8 devices is all dispatch cost)
        return n_dev if n_dev > 1 and b_pad % n_dev == 0 else 1

    def get(self, kkey: KernelKey, b_pad: int) -> tuple[Callable, int]:
        """The compiled kernel for this signature + its shard count."""
        import jax

        shards = self._n_shards(b_pad)
        cache_key = (kkey, b_pad, shards)
        with self._lock:
            fn = self._fns.get(cache_key)
            if fn is not None:
                self._fns.move_to_end(cache_key)  # LRU freshness
                self.stats.kernel(hit=True)
                return fn, shards
        single = serving_entry(kkey.family, kkey.eps1, kkey.eps2,
                               alpha=kkey.alpha, normalise=kkey.normalise)
        if shards > 1:
            from dpcorr.parallel import make_serve_batch_sharded

            fn = make_serve_batch_sharded(single, engine=self.mode)
        elif self.mode == "vector":
            fn = jax.jit(jax.vmap(single))
        else:
            fn = jax.jit(
                lambda keys, xs, ys: jax.lax.map(
                    lambda t: single(*t), (keys, xs, ys)))
        with self._lock:
            self._fns[cache_key] = fn
            self._fns.move_to_end(cache_key)
            while len(self._fns) > self.max_kernels:
                self._fns.popitem(last=False)  # evict least-recently-used
            self.stats.kernel(hit=False)
            self.stats.set_kernel_cache_size(len(self._fns))
        return fn, shards

    def run_batch(self, kkey: KernelKey, keys, xs: np.ndarray,
                  ys: np.ndarray) -> tuple[np.ndarray, ...]:
        """Execute one flushed launch: pad the batch axis, run the
        cached kernel, truncate. ``keys``: (b,) jax PRNG keys; ``xs``/
        ``ys``: (b, n) float32. Returns (rho_hat, ci_low, ci_high) as
        (b,) numpy arrays."""
        import jax.numpy as jnp

        b = xs.shape[0]
        b_pad = pad_batch(b)
        fn, _ = self.get(kkey, b_pad)
        if b_pad != b:
            pad = b_pad - b
            keys = jnp.concatenate([keys, jnp.repeat(keys[:1], pad, axis=0)])
            xs = np.concatenate([xs, np.repeat(xs[:1], pad, axis=0)])
            ys = np.concatenate([ys, np.repeat(ys[:1], pad, axis=0)])
        out = fn(keys, jnp.asarray(xs), jnp.asarray(ys))
        return tuple(np.asarray(a)[:b] for a in out)
