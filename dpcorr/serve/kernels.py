"""Compiled-kernel cache + batched execution for the serving layer.

Steady-state traffic must never recompile: the cache is keyed on the
**kernel signature** — ``(KernelKey, padded batch width, sharding)`` —
and the batch axis is padded to the next power of two so a bucket that
flushes at 13 requests and one that flushes at 16 share one compiled
kernel instead of compiling per observed batch size (the standard
shape-bucketing trick, here applied to the request axis). Padding lanes
replicate lane 0 (cheapest valid input) and are truncated before
results leave this module, so they cost device FLOPs but never appear
in responses.

Compilation and dispatch go through the plan/executor layer
(:mod:`dpcorr.plan`, which owns the only ``lower().compile()`` site in
:mod:`dpcorr.utils.compile`):

- misses are **single-flight** — concurrent misses for one signature
  wait on a single inflight compile (the pre-ISSUE-4 race had both
  threads compiling and the second overwriting the first); the dedup
  is observable as ``kernel_compile_dedup`` in stats. Distinct
  signatures still compile concurrently (XLA releases the GIL).
- kernels are **AOT-compiled** plan units (``Executor.prepare``) at the
  exact signature shapes, so the cost is paid at ``get`` time — which
  warmup moves off the request path entirely (serve.server) — and
  measured into ``dpcorr_compile_seconds`` / ``kernel.compile`` spans.
- each flush is one plan: operands pre-placed on the launch's declared
  sharding, one dispatch, one counted host fetch (``obs.transfer``).
- with ``export_dir`` set, unsharded compiled programs are serialized
  via ``jax.export`` (version-gated, raw-key-data boundary — see
  utils.compile) and replayed on the next boot, skipping even the
  persistent-cache retrace. :meth:`manifest` lists the resident
  signatures so a server can persist its working set on shutdown.

Two batch engines (estimators.registry bit-reproducibility contract):

- ``mode="exact"`` (default): ``jax.lax.map`` over the single-request
  program — one dispatch per flush, every lane **bit-identical** to the
  direct ``jit(single)`` call. This is what makes coalescing invisible
  to clients.
- ``mode="vector"``: ``jit(vmap(single))`` — ~5x faster per batch on
  CPU; ``rho_hat`` still bit-identical, CI endpoints within 1 ulp of
  the scalar program (lanes bit-identical across widths ≥ 2, so results
  still don't depend on how requests were coalesced).

The AOT artifact is the same engine program lazily-jit would build —
identical HLO — so responses stay bit-identical to the pre-AOT path
(pinned by tests/test_compile.py for all four families).

When the process holds more than one device, flushes wide enough to
split evenly are executed through
``parallel.make_serve_batch_sharded`` — the request axis sharded over
the ``rep`` mesh, composing the serving layer with the existing mesh
backend. Sharding preserves each engine's contract (measured; pinned by
tests/test_serve.py).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable

import numpy as np

from dpcorr import chaos
from dpcorr import plan as plan_mod
from dpcorr.models.estimators.registry import serving_entry
from dpcorr.serve.request import KernelKey
from dpcorr.serve.stats import ServeStats
from dpcorr.utils import compile as compile_mod
from dpcorr.utils import rng


def pad_batch(b: int) -> int:
    """Next power of two ≥ b: the compiled batch-width bucket."""
    return 1 << (b - 1).bit_length() if b > 1 else 1


def _pad_rows(a: np.ndarray, b_pad: int) -> np.ndarray:
    """Pad the leading axis to ``b_pad`` lanes replicating row 0, in
    ONE preallocated buffer — the previous ``np.concatenate`` +
    ``jnp.asarray`` pair copied every padded flush twice."""
    out = np.empty((b_pad,) + a.shape[1:], dtype=a.dtype)
    out[:a.shape[0]] = a
    out[a.shape[0]:] = a[0]
    return out


class KernelCache:
    """(KernelKey, b_pad, shards) → compiled batched kernel.

    ``jax.jit`` already memoizes compilations, but an explicit cache (a)
    makes the signature an auditable object instead of an implicit
    closure identity — rebuilding ``serving_entry`` closures per flush
    would defeat jit's cache entirely — (b) feeds the
    compile/hit counters the stats endpoint reports, and (c) bounds
    live compilations: signatures include the exact n, so a client
    sweeping sample sizes would otherwise grow the kernel set without
    limit in a long-running server. ``max_kernels`` caps it with LRU
    eviction (evicting our reference also releases the underlying
    executables); the live count is a stats gauge
    (``kernel_cache_size``). Steady-state traffic — a working set
    smaller than the cap — still never recompiles.

    ``aot=False`` turns the compile-ahead layer off (plain lazy jit —
    the pre-ISSUE-4 behavior, kept for A/B measurement);
    ``export_dir`` opts into ``jax.export`` persistence of compiled
    programs across restarts. ``_compile_hook`` (test seam) is invoked
    by the *leader* build of each signature, so a thread-race test can
    count actual compilations.
    """

    def __init__(self, stats: ServeStats | None = None,
                 shard: str = "auto", mode: str = "exact",
                 max_kernels: int = 128, aot: bool = True,
                 export_dir: str | None = None,
                 tracer=None):
        if shard not in ("auto", "off"):
            raise ValueError(f"shard must be 'auto' or 'off', got {shard!r}")
        if mode not in ("exact", "vector"):
            raise ValueError(f"mode must be 'exact' or 'vector', got {mode!r}")
        if max_kernels < 1:
            raise ValueError(f"max_kernels must be >= 1, got {max_kernels}")
        self.stats = stats or ServeStats()
        self.shard = shard
        self.mode = mode
        self.max_kernels = max_kernels
        self.aot = aot
        self.export_dir = export_dir
        # the cache's compile/dispatch/fetch engine: one local-placement
        # plan executor whose observer reports into the server's registry
        self._plan = plan_mod.Executor(
            "local", observer=compile_mod.CompileObserver(
                registry=self.stats.registry, tracer=tracer))
        self._cobs = self._plan.observer
        self._flight = self._plan.flight
        self._mesh_placement: plan_mod.MeshPlacement | None = None
        self._compile_hook: Callable | None = None  # test seam
        self._lock = threading.Lock()
        self._fns: OrderedDict[tuple, Callable] = OrderedDict()  # guarded by: _lock
        # per-thread compile wait of the most recent get(): zero on a
        # hit, the blocked time on a miss (leader build OR follower
        # wait — both are wall time the launch spent without a kernel).
        # Thread-local so the warmup thread's gets never clobber the
        # flush thread's cost attribution (obs.cost).
        self._tls = threading.local()

    def last_compile_wait_s(self) -> float:
        """Compile wait of the calling thread's most recent ``get``."""
        return getattr(self._tls, "compile_wait_s", 0.0)

    def _n_shards(self, b_pad: int) -> int:
        """How many mesh shards this launch uses (1 = unsharded)."""
        if self.shard == "off":
            return 1
        import jax

        n_dev = len(jax.devices())
        # shard only when the padded axis splits evenly with at least
        # one full lane per device — tiny flushes stay single-device
        # (a 2-lane launch spread over 8 devices is all dispatch cost)
        return n_dev if n_dev > 1 and b_pad % n_dev == 0 else 1

    def get(self, kkey: KernelKey, b_pad: int) -> tuple[Callable, int]:
        """The compiled kernel for this signature + its shard count.

        Misses are single-flight: one build per concurrently-missed
        signature, followers share the leader's result (and count into
        ``kernel_compile_dedup`` instead of compiles/hits)."""
        shards = self._n_shards(b_pad)
        cache_key = (kkey, b_pad, shards)
        self._tls.compile_wait_s = 0.0
        with self._lock:
            fn = self._fns.get(cache_key)
            if fn is not None:
                self._fns.move_to_end(cache_key)  # LRU freshness
                self.stats.kernel(hit=True)
                return fn, shards

        def build():
            # leader path: compile, then install under the cache lock
            # BEFORE the flight completes (SingleFlight publishes value
            # before clearing the key), so no third thread can miss in
            # between and rebuild
            fn = self._build(kkey, b_pad, shards)
            with self._lock:
                self._fns[cache_key] = fn
                self._fns.move_to_end(cache_key)
                while len(self._fns) > self.max_kernels:
                    evk, _ = self._fns.popitem(last=False)  # evict LRU
                    # a later compile of this signature is a recompile
                    # caused by eviction, not a new signature
                    self._cobs.note_evicted(compile_mod.signature_key(
                        self._signature(evk[0], evk[1], evk[2])))
                self.stats.kernel(hit=False)
                self.stats.set_kernel_cache_size(len(self._fns))
            return fn

        t_miss = time.perf_counter()
        fn, leader = self._flight.do(cache_key, build)
        self._tls.compile_wait_s = time.perf_counter() - t_miss
        if not leader:
            self.stats.kernel_dedup()
        return fn, shards

    # ------------------------------------------------------- building ----
    def _signature(self, kkey: KernelKey, b_pad: int, shards: int) -> dict:
        return {"family": kkey.family, "n": kkey.n,
                "eps1": kkey.eps1, "eps2": kkey.eps2,
                "b_pad": b_pad, "shards": shards, "mode": self.mode}

    def _export_file(self, kkey: KernelKey, b_pad: int) -> str:
        digest = compile_mod.signature_digest(
            "serve", kkey.family, kkey.n, kkey.eps1, kkey.eps2,
            kkey.alpha, kkey.normalise, b_pad, self.mode, rng.impl_tag())
        return compile_mod.export_path(self.export_dir, digest)

    def _build(self, kkey: KernelKey, b_pad: int,
               shards: int) -> plan_mod.Prepared:
        import jax

        if self._compile_hook is not None:
            self._compile_hook((kkey, b_pad, shards))
        single = serving_entry(kkey.family, kkey.eps1, kkey.eps2,
                               alpha=kkey.alpha, normalise=kkey.normalise)
        if shards > 1:
            from dpcorr.parallel import make_serve_batch_sharded

            jfn = make_serve_batch_sharded(single, engine=self.mode)
        elif self.mode == "vector":
            jfn = jax.jit(jax.vmap(single))
        else:
            jfn = jax.jit(
                lambda keys, xs, ys: jax.lax.map(
                    lambda t: single(*t), (keys, xs, ys)))
        if not self.aot:
            # lazy plan unit: the pre-ISSUE-4 behavior for A/B runs
            return self._plan.lazy_unit(jfn)
        avals = (rng.key_aval(b_pad),
                 jax.ShapeDtypeStruct((b_pad, kkey.n), np.float32),
                 jax.ShapeDtypeStruct((b_pad, kkey.n), np.float32))
        sig = self._signature(kkey, b_pad, shards)
        # export replay first: a prior boot's serialized program skips
        # tracing AND the XLA retrace of the persistent compile cache.
        # Unsharded only — exported programs pin device assignments.
        # (The cache's LRU owns unit lifetime, so the executor's own
        # unit cache is off; the outer single-flight in `get` already
        # dedups concurrent builds per signature.)
        path = None
        if self.export_dir and shards == 1:
            path = self._export_file(kkey, b_pad)
            call = compile_mod.load_exported(path)
            if call is not None:
                wrapped = jax.jit(
                    lambda keys, xs, ys: call(rng.key_data(keys), xs, ys))
                unit = self._plan.prepare(
                    (kkey, b_pad, shards, "export"), wrapped, avals,
                    signature={**sig, "source": "export"}, cache=False)
                if unit.aot_ok:
                    return unit
        unit = self._plan.prepare((kkey, b_pad, shards), jfn, avals,
                                  signature=sig, fallback=jfn, cache=False)
        if unit.aot_ok and path is not None:
            # serialize for the NEXT boot, through the raw-key-data
            # boundary (typed key avals can't cross jax.export); best
            # effort — failure just means a cold next boot
            ejit = jax.jit(
                lambda kd, xs, ys: jfn(rng.keys_from_data(kd), xs, ys))
            compile_mod.save_exported(
                path, ejit, (rng.key_data_aval(b_pad), avals[1], avals[2]))
        return unit

    # ------------------------------------------------------- warm set ----
    def manifest(self) -> list[dict]:
        """The resident kernel signatures, JSON-shaped — what the
        server persists on shutdown and replays as the next boot's
        warmup set (serve.warmup)."""
        with self._lock:
            sigs = list(self._fns.keys())
        return [{"family": k.family, "n": k.n, "eps1": k.eps1,
                 "eps2": k.eps2, "alpha": k.alpha,
                 "normalise": k.normalise, "b_pad": b_pad}
                for (k, b_pad, _shards) in sigs]

    # ------------------------------------------------------ execution ----
    def run_batch(self, kkey: KernelKey, keys, xs: np.ndarray,
                  ys: np.ndarray) -> tuple[np.ndarray, ...]:
        """Execute one flushed launch: pad the batch axis, run the
        cached kernel, truncate. ``keys``: (b,) jax PRNG keys; ``xs``/
        ``ys``: (b, n) float32. Returns (rho_hat, ci_low, ci_high) as
        (b,) numpy arrays."""
        import jax.numpy as jnp

        # fault sites (chaos.FAULT_POINTS): a planned SimulatedFault
        # here stands in for a lowering error / device OOM, a planned
        # sleep for a kernel blowing its latency budget — both land
        # before the launch so no padded lane ever half-executes
        chaos.fault("serve.kernel_slow")
        chaos.fault("serve.kernel")
        b = xs.shape[0]
        b_pad = pad_batch(b)
        fn, shards = self.get(kkey, b_pad)
        if b_pad != b:
            keys = jnp.concatenate([keys, jnp.repeat(keys[:1], b_pad - b,
                                                     axis=0)])
            xs = _pad_rows(xs, b_pad)
            ys = _pad_rows(ys, b_pad)
        # one plan per flush: pre-place operands on the launch's
        # declared sharding, dispatch, and pay exactly one counted
        # host sync at the truncation boundary
        pl = self._placement_for(shards)
        keys, xs, ys = pl.preshard((keys, xs, ys), self._plan.counters())
        out = self._plan.fetch(fn(keys, xs, ys))
        return tuple(np.asarray(a)[:b] for a in out)

    def _placement_for(self, shards: int) -> plan_mod.Placement:
        """The sharding a launch's operands must land on: the local
        single-device placement, or the ``rep`` mesh the sharded batch
        kernel was built over (``parallel.make_serve_batch_sharded``
        defaults to the full ``rep_mesh()`` — same devices)."""
        if shards == 1:
            return self._plan.placement
        if self._mesh_placement is None:
            self._mesh_placement = plan_mod.MeshPlacement()
        return self._mesh_placement
