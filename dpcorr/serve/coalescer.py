"""Micro-batching coalescer: concurrent requests → vmap-batched launches.

The serving analogue of continuous batching (Orca/vLLM applied to DP
query answering, ISSUE 1): client threads ``submit()`` single requests;
a dedicated flush thread holds them briefly in per-:class:`BucketKey`
queues and launches each bucket as one batched kernel, trading a
bounded admission latency (``max_delay_s``) for device-side batching.

Flush policy per bucket (first condition wins):

- **size**: the bucket reached ``max_batch`` live requests → flush now.
- **age**: the bucket's OLDEST request has waited ``max_delay_s`` →
  flush whatever is there. A bucket that never fills still answers
  within one delay window.

Within a flushed bucket, requests are grouped by exact n (shapes are
static in the estimator kernels — request.kernel_key) and every group
is dispatched before any is fetched, so groups execute concurrently on
device (the grid driver's dispatch-ahead pattern, grid.py phase 1/2).

Degradation paths (both recorded in stats, never silent):

- a flush of ONE request skips the vmap machinery and runs the direct
  single-request kernel — a bucket that can't fill costs no batching
  overhead;
- a batched launch that fails (lowering, OOM, device error) falls back
  to per-request direct execution, so one poisoned lane degrades its
  batch to unbatched service instead of failing every rider.

Backpressure: ``submit`` raises :class:`ServerOverloadedError` once
``max_queue`` requests are pending — the caller sheds load explicitly
instead of the queue growing without bound.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future

import numpy as np

from dpcorr import chaos
from dpcorr.obs import trace as obs_trace
from dpcorr.serve.kernels import KernelCache
from dpcorr.serve.request import (
    EstimateRequest,
    EstimateResponse,
    bucket_key,
    kernel_key,
)
from dpcorr.serve.stats import ServeStats


class ServerOverloadedError(Exception):
    """Admission refused: the pending queue is at capacity."""


@dataclasses.dataclass
class _Pending:
    req: EstimateRequest
    key: object  # jax PRNG key for this request's noise stream
    seed: int
    future: Future
    t_enq: float
    #: the request's root span (serve.request), opened on the client
    #: thread at admission and ended here when the future resolves —
    #: how one trace ID links admission to flush across threads. The
    #: disabled tracer's null span when tracing is off.
    span: object = obs_trace._NULL_SPAN


class Coalescer:
    def __init__(self, cache: KernelCache, stats: ServeStats,
                 max_batch: int = 64, max_delay_s: float = 0.005,
                 max_queue: int = 4096,
                 tracer: obs_trace.Tracer | None = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.cache = cache
        self.stats = stats
        self.tracer = tracer if tracer is not None else obs_trace.tracer()
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._buckets: dict[tuple, list[_Pending]] = {}  # guarded by: _cond
        self._depth = 0  # guarded by: _cond
        self._closed = False  # guarded by: _cond
        self._thread = threading.Thread(target=self._flush_loop,
                                        name="dpcorr-serve-flush",
                                        daemon=True)
        self._thread.start()

    # -- admission -------------------------------------------------------
    def submit(self, req: EstimateRequest, key, seed: int,
               span=None) -> Future:
        """Enqueue one admitted request; resolves to EstimateResponse.
        ``span`` is the request's root span (or None/null when
        untraced); it rides the queue so the flush thread can parent
        its spans under the same trace ID."""
        fut: Future = Future()
        p = _Pending(req, key, seed, fut, time.perf_counter(),
                     span if span is not None else obs_trace._NULL_SPAN)
        with self._cond:
            if self._closed:
                raise RuntimeError("coalescer is closed")
            if self._depth >= self.max_queue:
                self.stats.refused_overload()
                raise ServerOverloadedError(
                    f"{self._depth} requests pending >= max_queue="
                    f"{self.max_queue}")
            self._buckets.setdefault(bucket_key(req), []).append(p)
            self._depth += 1
            self.stats.set_queue_depth(self._depth)
            self._cond.notify()
        return fut

    # -- flush thread ----------------------------------------------------
    def _take_ready_locked(self, now: float) -> list[list[_Pending]]:
        """Pop every bucket that is full or whose head has aged out."""
        ready = []
        for bkey in list(self._buckets):
            q = self._buckets[bkey]
            if (len(q) >= self.max_batch
                    or now - q[0].t_enq >= self.max_delay_s):
                ready.append(q[: self.max_batch])
                rest = q[self.max_batch:]
                if rest:
                    self._buckets[bkey] = rest
                else:
                    del self._buckets[bkey]
        return ready

    def _next_deadline_locked(self) -> float | None:
        heads = [q[0].t_enq for q in self._buckets.values()]
        return min(heads) + self.max_delay_s if heads else None

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._closed and not self._buckets:
                        return
                    now = time.perf_counter()
                    # drain immediately on close — pending clients must
                    # get answers, not wait out the delay window
                    if self._closed:
                        ready = [q[i:i + self.max_batch]
                                 for q in self._buckets.values()
                                 for i in range(0, len(q), self.max_batch)]
                        self._buckets.clear()
                    else:
                        ready = self._take_ready_locked(now)
                    if ready:
                        break
                    deadline = self._next_deadline_locked()
                    self._cond.wait(timeout=None if deadline is None
                                    else max(deadline - now, 1e-4))
                n_taken = sum(len(g) for g in ready)
                self._depth -= n_taken
                self.stats.set_queue_depth(self._depth)
            for group in ready:
                self._flush(group)

    # -- execution -------------------------------------------------------
    def _flush(self, group: list[_Pending]) -> None:
        """Run one flushed bucket: dispatch every exact-n subgroup, then
        fetch (dispatch-ahead), resolving futures with responses.

        Span model (docs/OBSERVABILITY.md): every rider gets its own
        ``serve.flush`` span parented under its request's trace, so one
        trace ID follows the request from admission into the launch
        that served it; the physical launch itself is one
        ``serve.kernel`` span (dispatch through fetch barrier) under
        the first rider's flush span, carrying the batch size."""
        # crash points bracketing the launch: pre_flush models a crash
        # after charge but before any kernel ran (budget wasted, nothing
        # leaked — server module docstring), post_flush one after the
        # answers landed but before the client read them
        chaos.point("coalescer.pre_flush")
        by_kernel: dict[tuple, list[_Pending]] = {}
        for p in group:
            by_kernel.setdefault(kernel_key(p.req), []).append(p)

        launches = []
        for kkey, ps in by_kernel.items():
            fspans = [self.tracer.start_span(
                "serve.flush", parent=p.span.context,
                family=kkey.family, n=kkey.n, batch_size=len(ps))
                for p in ps]
            ksp = self.tracer.start_span(
                "serve.kernel", parent=fspans[0],
                family=kkey.family, n=kkey.n, batch_size=len(ps))
            try:
                raw = self._dispatch(kkey, ps)
            except Exception:
                # batched dispatch failed — degrade this subgroup
                raw = None
                ksp.set(error="dispatch")
            launches.append((kkey, ps, raw, fspans, ksp))

        for kkey, ps, raw, fspans, ksp in launches:
            batched = len(ps) > 1 and raw is not None
            if raw is not None:
                try:
                    raw = tuple(np.asarray(a) for a in raw)  # fetch barrier
                except Exception:
                    raw, batched = None, False
                    ksp.set(error="fetch")
            ksp.end()
            if raw is None:
                self._flush_unbatched(kkey, ps, fspans)
                continue
            self.stats.flushed(len(ps), batched=batched)
            t_done = time.perf_counter()
            for j, p in enumerate(ps):
                lat = t_done - p.t_enq
                self.stats.observe_latency(lat)
                p.future.set_result(EstimateResponse(
                    rho_hat=float(raw[0][j]), ci_low=float(raw[1][j]),
                    ci_high=float(raw[2][j]), batched=batched,
                    batch_size=len(ps), latency_s=lat, seed=p.seed))
                fspans[j].set(batched=batched)
                fspans[j].end()
                # the respond point: the request's root span closes with
                # its end-to-end latency
                p.span.set(latency_s=lat, batch_size=len(ps),
                           batched=batched)
                p.span.end()
        chaos.point("coalescer.post_flush")

    def _dispatch(self, kkey, ps: list[_Pending]):
        """Launch one exact-n subgroup asynchronously (no fetch)."""
        import jax.numpy as jnp

        if len(ps) == 1:
            # graceful degradation: a bucket that never filled runs the
            # plain single-request kernel — same estimator code path a
            # standalone caller would hit, no vmap/padding overhead
            return self._run_direct(kkey, ps[0])
        keys = jnp.stack([p.key for p in ps])
        xs = np.stack([p.req.x for p in ps])
        ys = np.stack([p.req.y for p in ps])
        return self.cache.run_batch(kkey, keys, xs, ys)

    def _run_direct(self, kkey, p: _Pending):
        """The unbatched path: the cached batch kernel at width 1 (one
        compiled signature shared by every singleton flush of this
        bucket, and by the batch-failure fallback)."""
        import jax.numpy as jnp

        return self.cache.run_batch(kkey, jnp.stack([p.key]),
                                    np.stack([p.req.x]),
                                    np.stack([p.req.y]))

    def _flush_unbatched(self, kkey, ps: list[_Pending],
                         fspans=None) -> None:
        """Batch-path failure fallback: serve each rider individually;
        only requests that fail on their own fail."""
        for idx, p in enumerate(ps):
            sp = fspans[idx] if fspans else obs_trace._NULL_SPAN
            sp.set(degraded=True)
            try:
                raw = self._run_direct(kkey, p)
                self.stats.flushed(1, batched=False)
                lat = time.perf_counter() - p.t_enq
                self.stats.observe_latency(lat)
                p.future.set_result(EstimateResponse(
                    rho_hat=float(raw[0][0]), ci_low=float(raw[1][0]),
                    ci_high=float(raw[2][0]), batched=False,
                    batch_size=1, latency_s=lat, seed=p.seed))
                sp.end()
                p.span.set(latency_s=lat, batch_size=1, batched=False)
                p.span.end()
            except Exception as e:
                self.stats.failed()
                p.future.set_exception(e)
                sp.set(error=type(e).__name__)
                sp.end()
                p.span.set(error=type(e).__name__)
                p.span.end()

    # -- lifecycle -------------------------------------------------------
    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, drain pending requests, join the thread."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=timeout)
