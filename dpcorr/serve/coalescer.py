"""Micro-batching coalescer: concurrent requests → vmap-batched launches.

The serving analogue of continuous batching (Orca/vLLM applied to DP
query answering, ISSUE 1): client threads ``submit()`` single requests;
a dedicated flush thread holds them briefly in per-:class:`BucketKey`
queues and launches each bucket as one batched kernel, trading a
bounded admission latency (``max_delay_s``) for device-side batching.

Flush policy per bucket (first condition wins):

- **size**: the bucket reached ``max_batch`` live requests → flush now.
- **age**: the bucket's OLDEST request has waited ``max_delay_s`` →
  flush whatever is there. A bucket that never fills still answers
  within one delay window.

Within a flushed bucket, requests are grouped by exact n (shapes are
static in the estimator kernels — request.kernel_key) and every group
is dispatched before any is fetched, so groups execute concurrently on
device (the grid driver's dispatch-ahead pattern, grid.py phase 1/2).

Degradation paths (both recorded in stats, never silent):

- a flush of ONE request skips the vmap machinery and runs the direct
  single-request kernel — a bucket that can't fill costs no batching
  overhead;
- a batched launch that fails (lowering, OOM, device error) falls back
  to per-request direct execution, so one poisoned lane degrades its
  batch to unbatched service instead of failing every rider; under
  **brownout** (sustained pressure — serve.overload) every flush takes
  this unbatched path up front, keeping launches small and predictable.

Overload discipline (ISSUE 8) — every shed request is an *admitted*
(charged) request dropped **before** its kernel launched, so the
coalescer refunds its charge (``ledger.refund`` with the shed reason)
and the drop provably consumes zero ε:

- **deadline expiry**: a request whose ``deadline_s`` passed while
  queued resolves to :class:`~dpcorr.serve.overload.DeadlineExpiredError`
  at flush time, before any dispatch.
- **priority eviction**: ``submit`` at capacity no longer blindly
  refuses the newcomer — it sheds the pending request with the lowest
  ``(priority, remaining-deadline)`` rank when the newcomer outranks
  it, so a queue full of idle low-priority work cannot starve urgent
  queries. The victim's future gets :class:`ServerOverloadedError`
  with a ``retry_after_s`` estimate.
- **client abandonment**: a future the client managed to ``cancel()``
  (estimate-timeout path, serve.server) is dropped at flush claim time.
- **shutdown**: ``close()`` refuse-drains the queue — every pending
  request resolves to :class:`ServerClosedError` with its charge
  refunded; an answer computed after the front end stopped would spend
  ε on a response nobody reads.

The refusal constructors live in per-reason ``_refuse_*`` helpers next
to their refunds on purpose: the ``budget-shed-missing-refund`` lint
rule (analysis.rules.budget) checks exactly this pairing.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import TYPE_CHECKING

import numpy as np

from dpcorr import chaos
from dpcorr.obs import recorder as obs_recorder
from dpcorr.obs import trace as obs_trace
if TYPE_CHECKING:  # annotation only: kernels imports jax, this
    # module stays importable by the jax-free client/fleet layers
    from dpcorr.serve.kernels import KernelCache
from dpcorr.serve.overload import (
    BrownoutController,
    CircuitBreaker,
    DeadlineExpiredError,
)
from dpcorr.serve.request import (
    EstimateRequest,
    EstimateResponse,
    bucket_key,
    kernel_key,
)
from dpcorr.serve.stats import ServeStats

#: ceiling on the Retry-After estimate — a hint, not a promise.
_MAX_RETRY_AFTER_S = 5.0


class ServerOverloadedError(Exception):
    """Admission refused (queue at capacity) or an admitted request
    evicted by a higher-(priority, urgency) arrival. ``retry_after_s``
    estimates when capacity should free up — surfaced as the HTTP
    ``Retry-After`` header and honored by the retrying client."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        self.retry_after_s = retry_after_s
        super().__init__(msg)


class ServerClosedError(ServerOverloadedError):
    """The coalescer is shut down; pending work was refuse-drained."""


@dataclasses.dataclass
class _Pending:
    req: EstimateRequest
    key: object  # jax PRNG key for this request's noise stream
    seed: int
    future: Future
    t_enq: float
    #: the request's root span (serve.request), opened on the client
    #: thread at admission and ended here when the future resolves —
    #: how one trace ID links admission to flush across threads. The
    #: disabled tracer's null span when tracing is off.
    span: object = obs_trace._NULL_SPAN
    #: shedding rank (request.priority) — higher survives eviction
    priority: int = 0
    #: absolute perf_counter deadline, or None for no deadline
    t_deadline: float | None = None
    #: what admission charged, so a pre-launch drop can refund exactly
    charges: dict | None = None
    #: the charge's durable idempotency id (fleet retries): a refund
    #: must forget it so a genuinely new attempt can charge again
    charge_id: str | None = None
    #: the request's CostRecord (obs.cost), opened at admission and
    #: filled in here: queue wait at the claim boundary, compile wait
    #: and an even share of kernel time at launch, shed events + ε
    #: refunds on every refusal path. None when the server runs
    #: without cost attribution.
    cost: object = None

    def rank(self, now: float) -> tuple:
        """Eviction order: cancelled futures are free victims, then
        lowest priority, then least remaining deadline slack."""
        slack = (self.t_deadline - now if self.t_deadline is not None
                 else float("inf"))
        return (not self.future.cancelled(), self.priority, slack)


class Coalescer:
    def __init__(self, cache: KernelCache, stats: ServeStats,
                 max_batch: int = 64, max_delay_s: float = 0.005,
                 max_queue: int = 4096,
                 tracer: obs_trace.Tracer | None = None,
                 ledger=None, breaker: CircuitBreaker | None = None,
                 brownout: BrownoutController | None = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.cache = cache
        self.stats = stats
        self.tracer = tracer if tracer is not None else obs_trace.tracer()
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        #: refund sink for shed requests (None → charges are the
        #: caller's problem, the pre-ISSUE-8 behavior)
        self.ledger = ledger
        self.breaker = breaker
        self.brownout = brownout
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._buckets: dict[tuple, list[_Pending]] = {}  # guarded by: _cond
        self._depth = 0  # guarded by: _cond
        self._closed = False  # guarded by: _cond
        self._thread = threading.Thread(target=self._flush_loop,
                                        name="dpcorr-serve-flush",
                                        daemon=True)
        self._thread.start()

    # -- admission -------------------------------------------------------
    def submit(self, req: EstimateRequest, key, seed: int,
               span=None, charges: dict | None = None,
               cost=None, charge_id: str | None = None) -> Future:
        """Enqueue one admitted request; resolves to EstimateResponse.
        ``span`` is the request's root span (or None/null when
        untraced); it rides the queue so the flush thread can parent
        its spans under the same trace ID. ``charges`` is what
        admission charged the ledger — carried so any pre-launch shed
        can refund it (``charge_id`` rides along so the refund forgets
        the durable retry id — without that, the NEXT attempt of the
        shed request would dedup against a charge that was just
        reversed and execute unpaid). ``cost`` is the request's
        CostRecord, filled in on the flush thread."""
        fut: Future = Future()
        now = time.perf_counter()
        t_deadline = (now + req.deadline_s if req.deadline_s is not None
                      else None)
        p = _Pending(req, key, seed, fut, now,
                     span if span is not None else obs_trace._NULL_SPAN,
                     priority=req.priority, t_deadline=t_deadline,
                     charges=charges, cost=cost, charge_id=charge_id)
        victim = None
        retry_after = None
        with self._cond:
            if self._closed:
                raise ServerClosedError("coalescer is closed")
            if self._depth >= self.max_queue:
                victim = self._pick_victim_locked(p, now)
                if victim is None:
                    self.stats.refused_overload()
                    raise ServerOverloadedError(
                        f"{self._depth} requests pending >= max_queue="
                        f"{self.max_queue}",
                        retry_after_s=self._retry_after_locked())
                retry_after = self._retry_after_locked()
            self._buckets.setdefault(bucket_key(req), []).append(p)
            self._depth += 1
            self.stats.set_queue_depth(self._depth)
            self._observe_pressure_locked()
            self._cond.notify()
        if victim is not None:
            self._refuse_evicted(victim, retry_after)
        return fut

    def _pick_victim_locked(self, incoming: _Pending,
                            now: float) -> _Pending | None:
        """At capacity: the lowest-ranked pending request, removed from
        its bucket — but only when the newcomer STRICTLY outranks it
        (equal-rank arrivals are refused, preserving FIFO fairness
        within a priority class)."""
        best = best_rank = best_loc = None
        for bkey, q in self._buckets.items():
            for i, p in enumerate(q):
                rank = p.rank(now)
                if best_rank is None or rank < best_rank:
                    best, best_rank, best_loc = p, rank, (bkey, i)
        if best is None or not best_rank < incoming.rank(now):
            return None
        bkey, i = best_loc
        q = self._buckets[bkey]
        q.pop(i)
        if not q:
            del self._buckets[bkey]
        self._depth -= 1
        return best

    def _retry_after_locked(self) -> float:
        """Back-of-envelope drain estimate: flushes left in the queue
        times the observed (EWMA) flush duration."""
        per_flush = max(self.stats.flush_ewma(), self.max_delay_s)
        flushes = self._depth / max(self.max_batch, 1) + 1.0
        return min(flushes * per_flush, _MAX_RETRY_AFTER_S)

    def retry_after_s(self) -> float:
        with self._cond:
            return self._retry_after_locked()

    def _observe_pressure_locked(self) -> None:
        if self.brownout is not None:
            self.brownout.observe(self._depth / max(self.max_queue, 1),
                                  self.stats.flush_ewma())

    def observe_pressure(self) -> None:
        """Feed the brownout controller the CURRENT queue pressure —
        called from the admission gate so the hysteresis clock keeps
        moving even when every arrival is refused before enqueue
        (otherwise brownout could latch active after the queue drains,
        refusing low-priority work forever)."""
        with self._cond:
            self._observe_pressure_locked()

    # -- shed refusals (refund + resolve, one helper per reason) ---------
    def _refund(self, p: _Pending, reason: str) -> None:
        """Reverse the shed request's admission charge — valid exactly
        because every caller drops ``p`` BEFORE any kernel launched
        (ledger.refund contract)."""
        if self.ledger is not None and p.charges:
            self.ledger.refund(p.charges, trace_id=p.span.trace_id,
                               charge_id=p.charge_id, reason=reason)
        if p.cost is not None:
            p.cost.event(reason)
            if p.charges:
                p.cost.refund(p.charges, reason)

    def _refuse_evicted(self, p: _Pending,
                        retry_after: float | None) -> None:
        self._refund(p, "queue_evict")
        self.stats.shed("queue_evict")
        if p.future.set_running_or_notify_cancel():
            p.future.set_exception(ServerOverloadedError(
                "evicted from the pending queue by a higher-priority "
                "arrival", retry_after_s=retry_after))
        p.span.set(refused="queue_evict")
        p.span.end()

    def _refuse_expired(self, p: _Pending, now: float) -> None:
        self._refund(p, "expired")
        self.stats.shed("expired")
        late_ms = (now - p.t_deadline) * 1e3
        p.future.set_exception(DeadlineExpiredError(
            f"deadline_s={p.req.deadline_s} expired {late_ms:.1f} ms "
            "before the kernel launched (charge refunded)",
            retry_after_s=self.retry_after_s()))
        p.span.set(refused="expired")
        p.span.end()

    def _refuse_closed(self, p: _Pending) -> None:
        self._refund(p, "closed")
        self.stats.shed("closed")
        if p.future.set_running_or_notify_cancel():
            p.future.set_exception(ServerClosedError(
                "server shut down before this request launched "
                "(charge refunded)"))
        p.span.set(refused="closed")
        p.span.end()

    def _drop_cancelled(self, p: _Pending) -> None:
        """The client's ``cancel()`` won the claim race: it already
        sees CancelledError; the request never launched, so the charge
        reverses like any other shed."""
        self._refund(p, "cancelled")
        self.stats.shed("cancelled")
        p.span.set(refused="cancelled")
        p.span.end()

    # -- flush thread ----------------------------------------------------
    def _take_ready_locked(self, now: float) -> list[list[_Pending]]:
        """Pop every bucket that is full or whose head has aged out."""
        ready = []
        for bkey in list(self._buckets):
            q = self._buckets[bkey]
            if (len(q) >= self.max_batch
                    or now - q[0].t_enq >= self.max_delay_s):
                ready.append(q[: self.max_batch])
                rest = q[self.max_batch:]
                if rest:
                    self._buckets[bkey] = rest
                else:
                    del self._buckets[bkey]
        return ready

    def _next_deadline_locked(self) -> float | None:
        heads = [q[0].t_enq for q in self._buckets.values()]
        return min(heads) + self.max_delay_s if heads else None

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._closed:
                        # close() refuse-drains the queue itself; the
                        # flush thread just stops picking up work
                        return
                    now = time.perf_counter()
                    ready = self._take_ready_locked(now)
                    if ready:
                        break
                    deadline = self._next_deadline_locked()
                    self._cond.wait(timeout=None if deadline is None
                                    else max(deadline - now, 1e-4))
                n_taken = sum(len(g) for g in ready)
                self._depth -= n_taken
                self.stats.set_queue_depth(self._depth)
            for group in ready:
                try:
                    self._flush(group)
                except Exception as e:
                    # a bug in the flush path must not kill the flush
                    # thread (every later request would hang): fail the
                    # group's unresolved futures, dump the flight
                    # recorder, keep serving. SimulatedCrash is a
                    # BaseException on purpose — chaos kills still kill.
                    logging.getLogger("dpcorr.serve").exception(
                        "unhandled error flushing group of %d",
                        len(group))
                    obs_recorder.trigger(
                        "coalescer_unhandled",
                        error=type(e).__name__, detail=str(e),
                        group_size=len(group))
                    for p in group:
                        if p.future.done():
                            continue  # resolved before the error
                        self.stats.failed()
                        if p.cost is not None:
                            p.cost.event(
                                f"flush_error:{type(e).__name__}")
                        p.future.set_running_or_notify_cancel()
                        try:
                            p.future.set_exception(e)
                        except InvalidStateError:
                            pass
                        p.span.set(error=type(e).__name__)
                        p.span.end()

    # -- execution -------------------------------------------------------
    def _claim_live(self, group: list[_Pending]) -> list[_Pending]:
        """The pre-launch boundary: claim each pending future (after
        which a client ``cancel()`` can no longer race a resolution),
        dropping the already-cancelled and the deadline-expired — both
        refunded, neither reaches a kernel."""
        now = time.perf_counter()
        live = []
        for p in group:
            if not p.future.set_running_or_notify_cancel():
                self._drop_cancelled(p)
                continue
            if p.t_deadline is not None and now >= p.t_deadline:
                self._refuse_expired(p, now)
                continue
            if p.cost is not None:
                # claim boundary = end of queue wait: everything after
                # this point is compile/kernel/fetch work
                p.cost.set_queue_wait(now - p.t_enq)
            live.append(p)
        return live

    def _flush(self, group: list[_Pending]) -> None:
        """Run one flushed bucket: dispatch every exact-n subgroup, then
        fetch (dispatch-ahead), resolving futures with responses.

        Span model (docs/OBSERVABILITY.md): every rider gets its own
        ``serve.flush`` span parented under its request's trace, so one
        trace ID follows the request from admission into the launch
        that served it; the physical launch itself is one
        ``serve.kernel`` span (dispatch through fetch barrier) under
        the first rider's flush span, carrying the batch size."""
        # crash points bracketing the launch: pre_flush models a crash
        # after charge but before any kernel ran (budget wasted, nothing
        # leaked — server module docstring), post_flush one after the
        # answers landed but before the client read them
        chaos.point("coalescer.pre_flush")
        chaos.fault("serve.flush_stall")
        t0 = time.perf_counter()
        group = self._claim_live(group)
        if not group:
            chaos.point("coalescer.post_flush")
            return
        by_kernel: dict[tuple, list[_Pending]] = {}
        for p in group:
            by_kernel.setdefault(kernel_key(p.req), []).append(p)
        browned = self.brownout is not None and self.brownout.active()

        launches = []
        for kkey, ps in by_kernel.items():
            # dpcorr-lint: ignore[span-no-finally] — flush spans ride the launch list; each ends when its future resolves
            fspans = [self.tracer.start_span(
                "serve.flush", parent=p.span.context,
                family=kkey.family, n=kkey.n, batch_size=len(ps))
                for p in ps]
            if browned and len(ps) > 1:
                # brownout: skip the batched machinery up front —
                # small, predictable unbatched launches under pressure
                launches.append((kkey, ps, None, fspans, None, None, 0.0))
                continue
            # dpcorr-lint: ignore[span-no-finally] — kernel span spans dispatch→fetch; ends at the fetch barrier below
            ksp = self.tracer.start_span(
                "serve.kernel", parent=fspans[0],
                family=kkey.family, n=kkey.n, batch_size=len(ps))
            t_disp = time.perf_counter()
            try:
                raw = self._dispatch(kkey, ps)
            except Exception:
                # batched dispatch failed — degrade this subgroup
                raw = None
                ksp.set(error="dispatch")
            compile_s = self.cache.last_compile_wait_s()
            launches.append((kkey, ps, raw, fspans, ksp, t_disp,
                             compile_s))

        for kkey, ps, raw, fspans, ksp, t_disp, compile_s in launches:
            batched = len(ps) > 1 and raw is not None
            if raw is not None:
                try:
                    raw = tuple(np.asarray(a) for a in raw)  # fetch barrier
                except Exception:
                    raw, batched = None, False
                    ksp.set(error="fetch")
            if ksp is not None:
                ksp.end()
            if raw is None:
                self._flush_unbatched(kkey, ps, fspans)
                continue
            if self.breaker is not None:
                self.breaker.record_success(bucket_key(ps[0].req))
            self.stats.flushed(len(ps), batched=batched)
            t_done = time.perf_counter()
            # kernel attribution: one histogram observation per launch
            # (dispatch → fetch barrier, compile wait excluded), divided
            # evenly across the riders so the sum of per-request shares
            # equals the histogram total (serve_load --cost gate)
            kernel_s = max(t_done - t_disp - compile_s, 0.0)
            self.stats.observe_kernel(kernel_s)
            share = kernel_s / len(ps)
            for j, p in enumerate(ps):
                lat = t_done - p.t_enq
                self.stats.observe_latency(lat,
                                           trace_id=p.span.trace_id)
                if p.cost is not None:
                    p.cost.add_kernel(share)
                    if compile_s > 0.0:
                        # every rider waited out the whole compile
                        p.cost.add_compile_wait(compile_s)
                p.future.set_result(EstimateResponse(
                    rho_hat=float(raw[0][j]), ci_low=float(raw[1][j]),
                    ci_high=float(raw[2][j]), batched=batched,
                    batch_size=len(ps), latency_s=lat, seed=p.seed,
                    cost=(p.cost.to_dict() if p.cost is not None
                          else None)))
                fspans[j].set(batched=batched)
                fspans[j].end()
                # the respond point: the request's root span closes with
                # its end-to-end latency
                p.span.set(latency_s=lat, batch_size=len(ps),
                           batched=batched)
                p.span.end()
        self.stats.observe_flush(time.perf_counter() - t0)
        with self._cond:
            self._observe_pressure_locked()
        chaos.point("coalescer.post_flush")

    def _dispatch(self, kkey, ps: list[_Pending]):
        """Launch one exact-n subgroup asynchronously (no fetch)."""
        import jax.numpy as jnp

        if len(ps) == 1:
            # graceful degradation: a bucket that never filled runs the
            # plain single-request kernel — same estimator code path a
            # standalone caller would hit, no vmap/padding overhead
            return self._run_direct(kkey, ps[0])
        keys = jnp.stack([p.key for p in ps])
        xs = np.stack([p.req.x for p in ps])
        ys = np.stack([p.req.y for p in ps])
        return self.cache.run_batch(kkey, keys, xs, ys)

    def _run_direct(self, kkey, p: _Pending):
        """The unbatched path: the cached batch kernel at width 1 (one
        compiled signature shared by every singleton flush of this
        bucket, and by the batch-failure fallback)."""
        import jax.numpy as jnp

        return self.cache.run_batch(kkey, jnp.stack([p.key]),
                                    np.stack([p.req.x]),
                                    np.stack([p.req.y]))

    def _flush_unbatched(self, kkey, ps: list[_Pending],
                         fspans=None) -> None:
        """Batch-path failure fallback (and the brownout fast path):
        serve each rider individually; only requests that fail on
        their own fail. Per-request outcomes feed the circuit breaker
        — this is where consecutive kernel failures accumulate into a
        bucket trip (serve.overload)."""
        bkey = bucket_key(ps[0].req)
        for idx, p in enumerate(ps):
            sp = fspans[idx] if fspans else obs_trace._NULL_SPAN
            sp.set(degraded=True)
            try:
                t_disp = time.perf_counter()
                raw = self._run_direct(kkey, p)
                raw = tuple(np.asarray(a) for a in raw)  # fetch barrier
                t_done = time.perf_counter()
                compile_s = self.cache.last_compile_wait_s()
                kernel_s = max(t_done - t_disp - compile_s, 0.0)
                self.stats.observe_kernel(kernel_s)
                self.stats.flushed(1, batched=False)
                lat = t_done - p.t_enq
                self.stats.observe_latency(lat,
                                           trace_id=p.span.trace_id)
                if p.cost is not None:
                    p.cost.event("degraded_unbatched")
                    p.cost.add_kernel(kernel_s)
                    if compile_s > 0.0:
                        p.cost.add_compile_wait(compile_s)
                p.future.set_result(EstimateResponse(
                    rho_hat=float(raw[0][0]), ci_low=float(raw[1][0]),
                    ci_high=float(raw[2][0]), batched=False,
                    batch_size=1, latency_s=lat, seed=p.seed,
                    cost=(p.cost.to_dict() if p.cost is not None
                          else None)))
                sp.end()
                p.span.set(latency_s=lat, batch_size=1, batched=False)
                p.span.end()
                if self.breaker is not None:
                    self.breaker.record_success(bkey)
            except Exception as e:
                self.stats.failed()
                if p.cost is not None:
                    p.cost.event(f"kernel_error:{type(e).__name__}")
                p.future.set_exception(e)
                sp.set(error=type(e).__name__)
                sp.end()
                p.span.set(error=type(e).__name__)
                p.span.end()
                if self.breaker is not None:
                    self.breaker.record_failure(bkey)

    # -- lifecycle -------------------------------------------------------
    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, refuse-drain pending requests, join the
        flush thread; raises if the thread fails to stop.

        Draining means REFUSING, not executing: each pending request
        resolves to :class:`ServerClosedError` with its charge
        refunded. Executing them would spend ε computing answers for
        clients the shutdown is about to disconnect — the retrying
        client re-runs them against a live replica instead."""
        with self._cond:
            self._closed = True
            drained = [p for q in self._buckets.values() for p in q]
            self._buckets.clear()
            self._depth = 0
            self.stats.set_queue_depth(0)
            self._cond.notify()
        for p in drained:
            self._refuse_closed(p)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            raise RuntimeError(
                f"coalescer flush thread did not stop within {timeout}s")
