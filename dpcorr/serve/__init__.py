"""Online serving subsystem (ISSUE 1): micro-batched DP-correlation
queries with a per-party privacy-budget ledger.

The offline layers answer *campaigns* (grids of design points, B
replications each); this package answers *queries*: a client holds an
(x, y) sample pair and wants one DP estimate now. The pieces, bottom
up — each module's docstring carries its own contract:

- :mod:`request`   — request/response types; coalescing bucket and
  compile-signature keys.
- :mod:`ledger`    — per-party ε accounting under basic composition:
  refusal before execution, write-ahead persistence (no double-spend
  across restarts).
- :mod:`budget_dir` — sharded per-user budget directory (millions of
  principals): per-shard write-ahead journal + snapshot compaction,
  LRU cold-user eviction, renewal/burst policies, and the
  CompositeLedger folding per-user + per-party + global admission into
  one atomic charge with one refund path.
- :mod:`kernels`   — compiled-kernel cache keyed on (signature, padded
  batch width); optional mesh sharding of wide flushes.
- :mod:`stats`     — live counters: queue depth, flush sizes,
  batch-fill ratio, latency percentiles, ε spend.
- :mod:`coalescer` — the micro-batcher: per-bucket queues, size/age
  flush policy, backpressure, unbatched degradation; deadline drops,
  priority eviction and refuse-draining shutdown (every shed refunds).
- :mod:`overload`  — circuit breaker (per-bucket failure isolation,
  half-open probing) and brownout (sustained-pressure degradation).
- :mod:`client`    — retrying clients: jittered backoff honoring
  ``Retry-After``, one idempotency key across attempts (charge-once),
  plus the HTTP client speaking the serve front end's refusal codes.
- :mod:`warmup`    — compile-ahead signature sets (``--warmup`` spec
  parsing, kernel-cache manifest persistence) behind the ``/readyz``
  readiness gate.
- :mod:`server`    — composition root + in-process client + stdlib
  HTTP front end (``python -m dpcorr serve``).

See docs/SERVING.md for the end-to-end story and the bit-identity
contract (estimators.registry).
"""

import importlib

# Lazy re-exports (PEP 562): importing :mod:`dpcorr.serve` — or any of
# its submodules — must NOT load jax. The serve tree splits into
# jax-free leaves (request, ledger, budget_dir, stats, overload,
# coalescer, client, fleet/*) and jax-heavy roots (kernels, server,
# warmup); an eager ``from .server import DpcorrServer`` here would
# weld them back together and drag jax into the fleet front end, the
# lease keeper, and the jax-free benchmark drivers. Attribute access
# (``dpcorr.serve.DpcorrServer`` or ``from dpcorr.serve import ...``)
# resolves through ``__getattr__`` below, importing the owning module
# on first touch only.
_EXPORTS = {
    # client
    "HttpEstimateClient": "client",
    "RetriableTransportError": "client",
    "RetryingClient": "client",
    "RetryPolicy": "client",
    "request_to_json": "client",
    # coalescer
    "Coalescer": "coalescer",
    "ServerClosedError": "coalescer",
    "ServerOverloadedError": "coalescer",
    # kernels (jax)
    "KernelCache": "kernels",
    "pad_batch": "kernels",
    # overload
    "BrownoutController": "overload",
    "CircuitBreaker": "overload",
    "CircuitOpenError": "overload",
    "DeadlineExpiredError": "overload",
    # budget_dir
    "BudgetDirectory": "budget_dir",
    "CompositeLedger": "budget_dir",
    "DirectoryCorruptError": "budget_dir",
    "RenewalPolicy": "budget_dir",
    "party_view": "budget_dir",
    "user_view": "budget_dir",
    # ledger
    "BudgetExceededError": "ledger",
    "PrivacyLedger": "ledger",
    "request_charges": "ledger",
    # request
    "BucketKey": "request",
    "EstimateRequest": "request",
    "EstimateResponse": "request",
    "KernelKey": "request",
    "bucket_key": "request",
    "kernel_key": "request",
    "pad_n": "request",
    # server (jax)
    "DpcorrServer": "server",
    "InProcessClient": "server",
    "make_http_server": "server",
    "pinned_request_key": "server",
    "serve_http": "server",
    # stats
    "ServeStats": "stats",
    "percentiles": "stats",
    # warmup (jax)
    "load_manifest": "warmup",
    "parse_warmup_spec": "warmup",
    "save_manifest": "warmup",
    "signatures_to_keys": "warmup",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    value = getattr(
        importlib.import_module(f"{__name__}.{mod}"), name)
    globals()[name] = value  # cache: resolve each name once
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
