"""Online serving subsystem (ISSUE 1): micro-batched DP-correlation
queries with a per-party privacy-budget ledger.

The offline layers answer *campaigns* (grids of design points, B
replications each); this package answers *queries*: a client holds an
(x, y) sample pair and wants one DP estimate now. The pieces, bottom
up — each module's docstring carries its own contract:

- :mod:`request`   — request/response types; coalescing bucket and
  compile-signature keys.
- :mod:`ledger`    — per-party ε accounting under basic composition:
  refusal before execution, write-ahead persistence (no double-spend
  across restarts).
- :mod:`budget_dir` — sharded per-user budget directory (millions of
  principals): per-shard write-ahead journal + snapshot compaction,
  LRU cold-user eviction, renewal/burst policies, and the
  CompositeLedger folding per-user + per-party + global admission into
  one atomic charge with one refund path.
- :mod:`kernels`   — compiled-kernel cache keyed on (signature, padded
  batch width); optional mesh sharding of wide flushes.
- :mod:`stats`     — live counters: queue depth, flush sizes,
  batch-fill ratio, latency percentiles, ε spend.
- :mod:`coalescer` — the micro-batcher: per-bucket queues, size/age
  flush policy, backpressure, unbatched degradation; deadline drops,
  priority eviction and refuse-draining shutdown (every shed refunds).
- :mod:`overload`  — circuit breaker (per-bucket failure isolation,
  half-open probing) and brownout (sustained-pressure degradation).
- :mod:`client`    — retrying clients: jittered backoff honoring
  ``Retry-After``, one idempotency key across attempts (charge-once),
  plus the HTTP client speaking the serve front end's refusal codes.
- :mod:`warmup`    — compile-ahead signature sets (``--warmup`` spec
  parsing, kernel-cache manifest persistence) behind the ``/readyz``
  readiness gate.
- :mod:`server`    — composition root + in-process client + stdlib
  HTTP front end (``python -m dpcorr serve``).

See docs/SERVING.md for the end-to-end story and the bit-identity
contract (estimators.registry).
"""

from dpcorr.serve.client import (  # noqa: F401
    HttpEstimateClient,
    RetriableTransportError,
    RetryingClient,
    RetryPolicy,
    request_to_json,
)
from dpcorr.serve.coalescer import (  # noqa: F401
    Coalescer,
    ServerClosedError,
    ServerOverloadedError,
)
from dpcorr.serve.kernels import KernelCache, pad_batch  # noqa: F401
from dpcorr.serve.overload import (  # noqa: F401
    BrownoutController,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExpiredError,
)
from dpcorr.serve.budget_dir import (  # noqa: F401
    BudgetDirectory,
    CompositeLedger,
    DirectoryCorruptError,
    RenewalPolicy,
    party_view,
    user_view,
)
from dpcorr.serve.ledger import (  # noqa: F401
    BudgetExceededError,
    PrivacyLedger,
    request_charges,
)
from dpcorr.serve.request import (  # noqa: F401
    BucketKey,
    EstimateRequest,
    EstimateResponse,
    KernelKey,
    bucket_key,
    kernel_key,
    pad_n,
)
from dpcorr.serve.server import (  # noqa: F401
    DpcorrServer,
    InProcessClient,
    make_http_server,
    pinned_request_key,
    serve_http,
)
from dpcorr.serve.stats import ServeStats, percentiles  # noqa: F401
from dpcorr.serve.warmup import (  # noqa: F401
    load_manifest,
    parse_warmup_spec,
    save_manifest,
    signatures_to_keys,
)
