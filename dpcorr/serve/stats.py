"""Serving observability: counters, latency percentiles, fill ratios.

The grid driver reports throughput after the fact (grid.py timings
frame); an online server needs live counters an operator can poll while
traffic flows. One :class:`ServeStats` instance is shared by the
coalescer, kernel cache and server; ``snapshot()`` is the single JSON
shape exposed by the ``/stats`` endpoint, ``benchmarks/serve_load.py``
and the tests.

:func:`percentiles` is the one quantile implementation shared with the
offline bench (bench.py block-latency reporting) so a reported p99
always means the same estimator (nearest-rank).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Iterable, Sequence


def percentiles(values: Iterable[float],
                qs: Sequence[float] = (0.5, 0.99)) -> dict[str, float]:
    """Nearest-rank percentiles, keyed ``"p50"``-style. Empty input →
    empty dict (callers render absent, not fake-zero, metrics)."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return {}
    out = {}
    for q in qs:
        rank = max(0, min(len(vals) - 1, int(round(q * len(vals))) - 1))
        out[f"p{int(q * 100)}"] = vals[rank]
    return out


class ServeStats:
    """Thread-safe serving counters.

    Counters are monotone totals (Prometheus-counter style) except
    ``queue_depth`` (a gauge maintained by the coalescer) and the
    latency reservoir (last ``reservoir`` completions — bounded memory,
    recency-biased percentiles, same trade-off as production servers'
    sliding-window summaries).
    """

    def __init__(self, reservoir: int = 8192):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.requests_refused_budget = 0
        self.requests_refused_overload = 0
        self.requests_failed = 0
        self.batches_flushed = 0
        self.batched_requests = 0
        self.unbatched_requests = 0
        self.flush_size_max = 0
        self.kernel_compiles = 0
        self.kernel_hits = 0
        self.kernel_cache_size = 0
        self.queue_depth = 0
        self._latencies: deque[float] = deque(maxlen=reservoir)

    # -- recording -------------------------------------------------------
    def admitted(self) -> None:
        with self._lock:
            self.requests_total += 1

    def refused_budget(self) -> None:
        with self._lock:
            self.requests_refused_budget += 1

    def refused_overload(self) -> None:
        with self._lock:
            self.requests_refused_overload += 1

    def failed(self, k: int = 1) -> None:
        with self._lock:
            self.requests_failed += k

    def flushed(self, size: int, batched: bool) -> None:
        with self._lock:
            self.batches_flushed += 1
            self.flush_size_max = max(self.flush_size_max, size)
            if batched:
                self.batched_requests += size
            else:
                self.unbatched_requests += size

    def kernel(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.kernel_hits += 1
            else:
                self.kernel_compiles += 1

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    def set_kernel_cache_size(self, n: int) -> None:
        """Gauge: live compiled kernels held by the LRU-bounded cache
        (serve.kernels) — lets an operator see eviction pressure."""
        with self._lock:
            self.kernel_cache_size = n

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(float(seconds))

    # -- reading ---------------------------------------------------------
    def batch_fill_ratio(self) -> float:
        """Mean live requests per flushed launch — the number the load
        test gates on (> 1 means real coalescing happened)."""
        with self._lock:
            if not self.batches_flushed:
                return 0.0
            return (self.batched_requests + self.unbatched_requests) \
                / self.batches_flushed

    def snapshot(self, ledger_snapshot: dict | None = None) -> dict:
        with self._lock:
            done = self.batched_requests + self.unbatched_requests
            snap = {
                "requests_total": self.requests_total,
                "requests_refused_budget": self.requests_refused_budget,
                "requests_refused_overload": self.requests_refused_overload,
                "requests_failed": self.requests_failed,
                "batches_flushed": self.batches_flushed,
                "batched_requests": self.batched_requests,
                "unbatched_requests": self.unbatched_requests,
                "batch_fill_ratio": (done / self.batches_flushed
                                     if self.batches_flushed else 0.0),
                "flush_size_max": self.flush_size_max,
                "kernel_compiles": self.kernel_compiles,
                "kernel_hits": self.kernel_hits,
                "kernel_cache_size": self.kernel_cache_size,
                "queue_depth": self.queue_depth,
                "latency_s": percentiles(self._latencies),
            }
        if ledger_snapshot is not None:
            snap["ledger"] = ledger_snapshot
        return snap
