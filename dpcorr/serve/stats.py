"""Serving observability: counters, latency percentiles, fill ratios.

The grid driver reports throughput after the fact (grid.py timings
frame); an online server needs live counters an operator can poll while
traffic flows. One :class:`ServeStats` instance is shared by the
coalescer, kernel cache and server; ``snapshot()`` is the single JSON
shape exposed by the ``/stats`` endpoint, ``benchmarks/serve_load.py``
and the tests.

Since ISSUE 2 the counters live in an :class:`dpcorr.obs.metrics.Registry`
(one per ServeStats, so concurrent in-process servers never
cross-contaminate) rather than in ad-hoc attributes: the same metric
objects back both the legacy ``/stats`` JSON snapshot and the
Prometheus text exposition at ``GET /metrics`` — single source of
truth, checked end-to-end by ``benchmarks/serve_load.py``. The old
attribute reads (``stats.kernel_compiles`` etc.) remain as properties.

Latency is recorded twice, deliberately: a sliding reservoir feeding
the nearest-rank percentiles ``snapshot()["latency_s"]`` always
reported (recency-biased, byte-compatible), and a fixed-bucket
histogram exposing Prometheus ``_bucket``/``_sum``/``_count`` series a
scraper can aggregate across servers (cumulative since boot).

:func:`percentiles` is the one quantile implementation shared with the
offline bench (bench.py block-latency reporting) so a reported p99
always means the same estimator (nearest-rank).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Iterable, Sequence

from dpcorr.obs.cost import ExemplarStore
from dpcorr.obs.metrics import LATENCY_BUCKETS, Registry

#: Label vocabularies the JSON snapshot enumerates (the Prometheus side
#: discovers labels dynamically; the fixed JSON shape needs the list).
SHED_REASONS = ("expired", "queue_evict", "cancelled", "closed",
                "admission")
REFUSED_REASONS = ("budget", "overload", "breaker", "brownout",
                   "not_owner")
ABANDONED_STAGES = ("cancelled", "detached")


def percentiles(values: Iterable[float],
                qs: Sequence[float] = (0.5, 0.99)) -> dict[str, float]:
    """Nearest-rank percentiles, keyed ``"p50"``-style. Empty input →
    empty dict (callers render absent, not fake-zero, metrics)."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return {}
    out = {}
    for q in qs:
        rank = max(0, min(len(vals) - 1, int(round(q * len(vals))) - 1))
        out[f"p{int(q * 100)}"] = vals[rank]
    return out


class ServeStats:
    """Thread-safe serving counters, backed by an obs metrics registry.

    Counters are monotone totals (Prometheus-counter style) except
    ``queue_depth`` / ``flush_size_max`` / ``kernel_cache_size``
    (gauges) and the latency reservoir (last ``reservoir`` completions —
    bounded memory, recency-biased percentiles, same trade-off as
    production servers' sliding-window summaries).
    """

    def __init__(self, reservoir: int = 8192,
                 registry: Registry | None = None,
                 slo_s: float = 0.25, slo_window_s: float = 60.0,
                 instance: str | None = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        # fleet identity (ISSUE 11): the instance name rides every
        # snapshot and an info-style gauge, so the fleet collector can
        # cross-check its target map against what the process claims
        self.instance = instance
        self._instance_info = r.gauge(
            "dpcorr_serve_instance_info",
            "Constant 1; the label carries this process's fleet "
            "instance name", labelnames=("instance",))
        if instance is not None:
            self._instance_info.set(1, instance=str(instance))
        self._requests = r.counter(
            "dpcorr_serve_requests_total",
            "Requests admitted (charged and enqueued)")
        self._refused = r.counter(
            "dpcorr_serve_requests_refused_total",
            "Requests refused at admission", labelnames=("reason",))
        self._failed = r.counter(
            "dpcorr_serve_requests_failed_total",
            "Requests that failed during execution")
        self._flushes = r.counter(
            "dpcorr_serve_batches_flushed_total",
            "Coalescer flush launches")
        self._completed = r.counter(
            "dpcorr_serve_requests_completed_total",
            "Requests served, by execution mode", labelnames=("mode",))
        self._flush_max = r.gauge(
            "dpcorr_serve_flush_size_max",
            "Largest flush (live requests in one launch) seen so far")
        self._compiles = r.counter(
            "dpcorr_serve_kernel_compiles_total",
            "Batch-kernel cache misses (fresh compilations)")
        self._hits = r.counter(
            "dpcorr_serve_kernel_cache_hits_total",
            "Batch-kernel cache hits")
        self._dedup = r.counter(
            "dpcorr_serve_kernel_compile_dedup_total",
            "Concurrent cache misses that waited on another thread's "
            "inflight compile instead of compiling again (single-flight"
            " — utils.compile)")
        self._cache_size = r.gauge(
            "dpcorr_serve_kernel_cache_size",
            "Live compiled kernels held by the LRU-bounded cache")
        self._depth = r.gauge(
            "dpcorr_serve_queue_depth", "Requests pending in the coalescer")
        self._idem = r.counter(
            "dpcorr_serve_idempotent_hits_total",
            "Requests answered from the idempotency cache instead of "
            "re-executing — 'completed' replays a cached response, "
            "'inflight' attaches to a duplicate already running",
            labelnames=("stage",))
        self._latency = r.histogram(
            "dpcorr_serve_latency_seconds",
            "Admission-to-completion request latency",
            buckets=LATENCY_BUCKETS)
        # -- overload resilience (ISSUE 8) --------------------------------
        self._shed = r.counter(
            "dpcorr_serve_shed_total",
            "Requests shed by the overload layer before any kernel "
            "launched (admitted ones get their charge refunded): "
            "'expired' deadline passed in queue, "
            "'queue_evict' displaced by a higher-(priority, urgency) "
            "arrival, 'cancelled' client abandoned the future, "
            "'closed' drained as refusals at shutdown, 'admission' "
            "refused by the brownout priority floor",
            labelnames=("reason",))
        self._abandoned = r.counter(
            "dpcorr_serve_abandoned_total",
            "estimate() timeouts: 'cancelled' the pending request was "
            "withdrawn before launch, 'detached' it was already "
            "running and completes unobserved", labelnames=("stage",))
        self._breaker_state = r.gauge(
            "dpcorr_serve_breaker_state",
            "Per-bucket circuit breaker state "
            "(0=closed, 1=open, 2=half-open)",
            labelnames=("family", "bucket"))
        self._breaker_trans = r.counter(
            "dpcorr_serve_breaker_transitions_total",
            "Circuit breaker state transitions, by destination state",
            labelnames=("to",))
        self._brownout = r.gauge(
            "dpcorr_serve_brownout_active",
            "1 while the server is browned out (unbatched fallback + "
            "low-priority rejection under sustained pressure)")
        self._flush_ewma_g = r.gauge(
            "dpcorr_serve_flush_ewma_seconds",
            "Exponentially weighted moving average of flush duration "
            "— the load-shedding pressure signal")
        # -- cost attribution + SLO burn rate (ISSUE 9) -------------------
        self._kernel_hist = r.histogram(
            "dpcorr_serve_kernel_seconds",
            "Per-launch kernel wall time (dispatch through fetch "
            "barrier) — the denominator the per-request kernel-time "
            "attributions must sum back to (obs.cost; serve_load "
            "--cost gates on exactly this)",
            buckets=LATENCY_BUCKETS)
        self._slo_burn = r.gauge(
            "dpcorr_serve_slo_burn_rate",
            "Fraction of requests in the rolling window whose latency "
            "exceeded the SLO threshold — the burn-rate signal "
            "`dpcorr obs top` renders")
        self._slo_window_n = r.gauge(
            "dpcorr_serve_slo_window_requests",
            "Requests currently inside the SLO rolling window")
        self.slo_s = float(slo_s)
        self.slo_window_s = float(slo_window_s)
        #: latency-histogram trace exemplars: slow bucket → trace ID
        self.exemplars = ExemplarStore(buckets=LATENCY_BUCKETS)
        self._lock = threading.Lock()
        self._latencies: deque[float] = deque(maxlen=reservoir)  # guarded by: _lock
        self._slo_events: deque[tuple] = deque()  # guarded by: _lock
        self._flush_ewma_val: float | None = None  # guarded by: _lock
        self._ewma_alpha = 0.2

    # -- legacy attribute reads (tests, report layer) --------------------
    @property
    def requests_total(self) -> int:
        return int(self._requests.value())

    @property
    def requests_refused_budget(self) -> int:
        return int(self._refused.value(reason="budget"))

    @property
    def requests_refused_overload(self) -> int:
        return int(self._refused.value(reason="overload"))

    @property
    def requests_failed(self) -> int:
        return int(self._failed.value())

    @property
    def batches_flushed(self) -> int:
        return int(self._flushes.value())

    @property
    def batched_requests(self) -> int:
        return int(self._completed.value(mode="batched"))

    @property
    def unbatched_requests(self) -> int:
        return int(self._completed.value(mode="unbatched"))

    @property
    def flush_size_max(self) -> int:
        return int(self._flush_max.value())

    @property
    def kernel_compiles(self) -> int:
        return int(self._compiles.value())

    @property
    def kernel_hits(self) -> int:
        return int(self._hits.value())

    @property
    def kernel_compile_dedup(self) -> int:
        return int(self._dedup.value())

    @property
    def kernel_cache_size(self) -> int:
        return int(self._cache_size.value())

    @property
    def queue_depth(self) -> int:
        return int(self._depth.value())

    @property
    def idempotent_hits_completed(self) -> int:
        return int(self._idem.value(stage="completed"))

    @property
    def idempotent_hits_inflight(self) -> int:
        return int(self._idem.value(stage="inflight"))

    # -- recording -------------------------------------------------------
    def admitted(self) -> None:
        self._requests.inc()

    def refused_budget(self) -> None:
        self._refused.inc(reason="budget")

    def refused_overload(self) -> None:
        self._refused.inc(reason="overload")

    def refused(self, reason: str) -> None:
        """Generic admission refusal by reason — the overload layer's
        reasons ('breaker', 'brownout', 'expired') land next to the
        legacy 'budget'/'overload' series."""
        self._refused.inc(reason=reason)

    def shed(self, reason: str) -> None:
        """An ADMITTED (charged) request dropped before launch, charge
        refunded — see the counter help for the reason vocabulary."""
        self._shed.inc(reason=reason)

    def abandoned(self, stage: str) -> None:
        """An ``estimate()`` timeout outcome: ``"cancelled"`` (pending
        request withdrawn, ε refunded by the coalescer) or
        ``"detached"`` (already running; completes unobserved)."""
        self._abandoned.inc(stage=stage)

    def breaker_state(self, family: str, bucket: str, code: int) -> None:
        self._breaker_state.set(code, family=family, bucket=bucket)

    def breaker_transition(self, to: str) -> None:
        self._breaker_trans.inc(to=to)

    def brownout(self, active: bool) -> None:
        self._brownout.set(1.0 if active else 0.0)

    def observe_flush(self, seconds: float) -> None:
        """Feed one flush duration into the EWMA pressure signal."""
        s = float(seconds)
        with self._lock:
            prev = self._flush_ewma_val
            self._flush_ewma_val = s if prev is None else (
                self._ewma_alpha * s + (1.0 - self._ewma_alpha) * prev)
            self._flush_ewma_g.set(self._flush_ewma_val)

    def flush_ewma(self) -> float:
        with self._lock:
            return self._flush_ewma_val or 0.0

    def failed(self, k: int = 1) -> None:
        self._failed.inc(k)

    def flushed(self, size: int, batched: bool) -> None:
        self._flushes.inc()
        self._completed.inc(size, mode="batched" if batched
                            else "unbatched")
        # max-tracking needs read-modify-write; the stats lock arbitrates
        with self._lock:
            if size > self._flush_max.value():
                self._flush_max.set(size)

    def kernel(self, hit: bool) -> None:
        if hit:
            self._hits.inc()
        else:
            self._compiles.inc()

    def kernel_dedup(self) -> None:
        """A miss that piggybacked on an inflight compile (single-flight
        follower): neither a hit nor a compile — its own counter, so
        the dedup the race fix buys is observable."""
        self._dedup.inc()

    def set_queue_depth(self, depth: int) -> None:
        self._depth.set(depth)

    def idempotent_hit(self, stage: str) -> None:
        """A duplicate submission short-circuited — ``stage`` is
        ``"completed"`` (cached response replayed) or ``"inflight"``
        (attached to the original's future)."""
        self._idem.inc(stage=stage)

    def set_kernel_cache_size(self, n: int) -> None:
        """Gauge: live compiled kernels held by the LRU-bounded cache
        (serve.kernels) — lets an operator see eviction pressure."""
        self._cache_size.set(n)

    def observe_kernel(self, seconds: float) -> None:
        """One launch's dispatch-to-fetch wall time (batched launches
        observe once; their riders' cost records carry equal shares —
        the two views sum to the same total by construction)."""
        self._kernel_hist.observe(float(seconds))

    def observe_latency(self, seconds: float,
                        trace_id: str | None = None) -> None:
        s = float(seconds)
        self._latency.observe(s)
        self.exemplars.record(s, trace_id)
        now = time.monotonic()
        with self._lock:
            self._latencies.append(s)
            self._slo_events.append((now, s > self.slo_s))
            self._slo_update_locked(now)

    def _slo_update_locked(self, now: float) -> None:
        """Trim the rolling window and refresh the burn-rate gauges."""
        cutoff = now - self.slo_window_s
        ev = self._slo_events
        while ev and ev[0][0] < cutoff:
            ev.popleft()
        n = len(ev)
        over = sum(1 for _, o in ev if o)
        self._slo_window_n.set(n)
        self._slo_burn.set(over / n if n else 0.0)

    def slo_snapshot(self) -> dict:
        """The ``/stats`` SLO view (also refreshes the gauges, so a
        scrape after traffic stops sees the window drain)."""
        now = time.monotonic()
        with self._lock:
            self._slo_update_locked(now)
            n = len(self._slo_events)
            over = sum(1 for _, o in self._slo_events if o)
        return {"slo_s": self.slo_s, "window_s": self.slo_window_s,
                "window_requests": n,
                "burn_rate": over / n if n else 0.0}

    # -- reading ---------------------------------------------------------
    def batch_fill_ratio(self) -> float:
        """Mean live requests per flushed launch — the number the load
        test gates on (> 1 means real coalescing happened)."""
        flushes = self.batches_flushed
        if not flushes:
            return 0.0
        return (self.batched_requests + self.unbatched_requests) / flushes

    def render_prometheus(self) -> str:
        """The ``GET /metrics`` body: every instrument this server
        publishes (incl. the ledger's, which registers into the same
        registry via the server wiring), followed by the latency
        exemplars as comment lines — exposition 0.0.4 has no exemplar
        syntax, and comments keep every scraper (incl. our own
        parse_exposition) compatible while still shipping the
        bucket→trace links in the same scrape."""
        body = self.registry.render()
        ex = self.exemplars.snapshot()
        if not ex:
            return body
        lines = [f'# EXEMPLAR dpcorr_serve_latency_seconds_bucket'
                 f'{{le="{le}"}} trace_id={x["trace_id"]} '
                 f'value={x["value"]}'
                 for le, x in sorted(ex.items())]
        return body + "\n".join(lines) + "\n"

    def _recompile_snapshot(self) -> dict:
        # the KernelCache's CompileObserver registers this counter on
        # our registry; before any compile it simply isn't there yet
        from dpcorr.utils.compile import RECOMPILE_CAUSES

        rc = self.registry.get("dpcorr_compile_recompile_total")
        if rc is None:
            return {}
        return {c: int(rc.value(cause=c)) for c in RECOMPILE_CAUSES}

    def snapshot(self, ledger_snapshot: dict | None = None,
                 cost_aggregate: dict | None = None,
                 budget_dir: dict | None = None) -> dict:
        done = self.batched_requests + self.unbatched_requests
        flushes = self.batches_flushed
        with self._lock:
            lat = percentiles(self._latencies)
        snap = {
            "requests_total": self.requests_total,
            "requests_refused_budget": self.requests_refused_budget,
            "requests_refused_overload": self.requests_refused_overload,
            "requests_failed": self.requests_failed,
            "batches_flushed": flushes,
            "batched_requests": self.batched_requests,
            "unbatched_requests": self.unbatched_requests,
            "batch_fill_ratio": done / flushes if flushes else 0.0,
            "flush_size_max": self.flush_size_max,
            "kernel_compiles": self.kernel_compiles,
            "kernel_hits": self.kernel_hits,
            "kernel_compile_dedup": self.kernel_compile_dedup,
            "kernel_cache_size": self.kernel_cache_size,
            "queue_depth": self.queue_depth,
            "latency_s": lat,
            "idempotent_hits_completed": self.idempotent_hits_completed,
            "idempotent_hits_inflight": self.idempotent_hits_inflight,
            # additive (the pre-ISSUE-2 keys above are a stable shape):
            # the bucketed view behind the /metrics histogram series
            "latency_histogram": self._latency.snapshot(),
            # overload resilience (ISSUE 8), additive too
            "refused": {r: int(self._refused.value(reason=r))
                        for r in REFUSED_REASONS},
            "shed": {r: int(self._shed.value(reason=r))
                     for r in SHED_REASONS},
            "abandoned": {s: int(self._abandoned.value(stage=s))
                          for s in ABANDONED_STAGES},
            "brownout_active": bool(self._brownout.value()),
            "flush_ewma_s": self.flush_ewma(),
            # cost attribution + SLO burn (ISSUE 9), additive as well
            "kernel_histogram": self._kernel_hist.snapshot(),
            "slo": self.slo_snapshot(),
            "exemplars": self.exemplars.snapshot(),
            # fleet identity (ISSUE 11): None for a standalone server
            "instance": self.instance,
            # recompile attribution (ISSUE 15): why kernels compiled —
            # a warm boot showing nonzero zero-traffic compiles is
            # self-explaining through the cause split
            "recompiles": self._recompile_snapshot(),
        }
        if cost_aggregate is not None:
            snap["costs"] = cost_aggregate
        if ledger_snapshot is not None:
            snap["ledger"] = ledger_snapshot
        if budget_dir is not None:
            # per-user budget directory block (ISSUE 10): shard count,
            # residency, eviction/rehydration counters, refusals by
            # level — CompositeLedger.directory_snapshot()'s shape
            snap["budget_dir"] = budget_dir
        return snap
