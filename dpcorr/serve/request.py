"""Request/response types and bucket signatures for the serving layer.

Two levels of grouping, both explicit:

- :func:`bucket_key` — the **coalescing** bucket ``(family, padded-n,
  ε-pair, α, normalise)``. Requests landing in the same bucket are held
  together by the coalescer and flushed as one unit; n is quantized to
  the next power of two so near-miss sample sizes share a flush queue
  (and its timer) instead of each opening a singleton bucket.
- :func:`kernel_key` — the **compile** signature: the bucket key plus
  the *exact* n. Shapes are static in every estimator kernel
  (common.batch_geometry), so a flushed bucket launches one vmap batch
  per distinct n it contains; at steady state traffic per client is
  fixed-n and a flush is a single launch. The compiled-kernel cache
  (serve.kernels) is keyed here, so the number of live compilations is
  bounded by live (family, n, ε) combinations, not by request count.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

from dpcorr.models.estimators.families import FAMILIES

#: Smallest padded-n bucket — below this every n shares one bucket.
MIN_N_BUCKET = 64


def pad_n(n: int, floor: int = MIN_N_BUCKET) -> int:
    """Next power of two ≥ max(n, floor): the coalescing n-bucket."""
    v = max(int(n), floor)
    return 1 << (v - 1).bit_length()


class BucketKey(NamedTuple):
    """Coalescing bucket: which requests may share a flush."""

    family: str
    n_pad: int
    eps1: float
    eps2: float
    alpha: float
    normalise: bool


class KernelKey(NamedTuple):
    """Compile signature: which requests share one vmap-batched kernel."""

    family: str
    n: int
    eps1: float
    eps2: float
    alpha: float
    normalise: bool


@dataclasses.dataclass(frozen=True)
class EstimateRequest:
    """One online DP-correlation query.

    ``party_x`` / ``party_y`` name the data owners whose privacy budget
    the query spends (ε₁ against x's owner, ε₂ against y's — doubled
    for sign families with ``normalise``, see serve.ledger). ``seed``
    pins the request's noise stream for reproducible replays of this
    exact request — the stream is bound to the request content
    (server.pinned_request_key), so reusing a seed over different data
    draws independent noise rather than enabling differencing. ``None``
    lets the server assign a stream from its per-boot subtree.
    """

    family: str
    x: np.ndarray
    y: np.ndarray
    eps1: float
    eps2: float
    party_x: str = "party-x"
    party_y: str = "party-y"
    alpha: float = 0.05
    normalise: bool = True
    seed: int | None = None
    #: client retry token: two submissions with the same key are the
    #: same logical request — the second returns the first's response
    #: without a second ledger charge or noise draw (server idempotency
    #: cache). Pinned-seed requests get a content-derived default key,
    #: so a dropped-response retry is always safe without client
    #: bookkeeping.
    idempotency_key: str | None = None
    #: shedding rank under overload: when the queue is at capacity the
    #: coalescer evicts the pending request with the LOWEST (priority,
    #: remaining-deadline) in favor of a strictly better newcomer, and
    #: brownout mode refuses work below the server's priority floor.
    #: Routing metadata like the party names — deliberately NOT part of
    #: the request digest (same content at different priority is the
    #: same query, same noise stream, same idempotency identity).
    priority: int = 0
    #: seconds this request is worth waiting for, measured from
    #: admission. A request still queued when it expires is dropped
    #: BEFORE its kernel launches and its charge refunded
    #: (DeadlineExpiredError / HTTP 504) — late answers to departed
    #: clients must not consume ε. ``None`` = no deadline.
    deadline_s: float | None = None
    #: requesting principal for per-user budget accounting
    #: (serve.budget_dir): when the server runs a budget directory the
    #: request's total party ε is also charged against ``user/<user>``.
    #: Routing metadata like priority — deliberately NOT part of the
    #: request digest (the same query from the same user retried is the
    #: same noise stream), but folded into the idempotency identity so
    #: two *different* users submitting identical content each get
    #: their own charge. ``None`` = no user leg.
    user: str | None = None

    def __post_init__(self):
        if self.family not in FAMILIES:
            raise ValueError(f"unknown estimator family {self.family!r}; "
                             f"expected one of {FAMILIES}")
        if self.idempotency_key is not None \
                and not isinstance(self.idempotency_key, str):
            raise ValueError("idempotency_key must be a string or None, "
                             f"got {type(self.idempotency_key).__name__}")
        x = np.asarray(self.x, dtype=np.float32)
        y = np.asarray(self.y, dtype=np.float32)
        if x.ndim != 1 or y.ndim != 1 or x.shape != y.shape:
            raise ValueError(f"x and y must be equal-length 1-D vectors, "
                             f"got {x.shape} and {y.shape}")
        if x.shape[0] < 2:
            raise ValueError(f"need at least two observations, "
                             f"got n={x.shape[0]}")
        if not (self.eps1 > 0.0 and self.eps2 > 0.0):
            raise ValueError(f"eps must be positive, got "
                             f"({self.eps1}, {self.eps2})")
        if not isinstance(self.priority, int) \
                or isinstance(self.priority, bool):
            raise ValueError("priority must be an int, got "
                             f"{type(self.priority).__name__}")
        if self.deadline_s is not None and not self.deadline_s > 0.0:
            raise ValueError("deadline_s must be positive or None, "
                             f"got {self.deadline_s}")
        if self.user is not None and not isinstance(self.user, str):
            raise ValueError("user must be a string or None, got "
                             f"{type(self.user).__name__}")
        object.__setattr__(self, "x", x)
        object.__setattr__(self, "y", y)

    @property
    def n(self) -> int:
        return int(self.x.shape[0])


def bucket_key(req: EstimateRequest) -> BucketKey:
    return BucketKey(req.family, pad_n(req.n), float(req.eps1),
                     float(req.eps2), float(req.alpha), bool(req.normalise))


def kernel_key(req: EstimateRequest) -> KernelKey:
    return KernelKey(req.family, req.n, float(req.eps1), float(req.eps2),
                     float(req.alpha), bool(req.normalise))


@dataclasses.dataclass(frozen=True)
class EstimateResponse:
    """The answer plus serving metadata (how the request was executed)."""

    rho_hat: float
    ci_low: float
    ci_high: float
    #: True when the request ran inside a coalesced vmap batch; False on
    #: the unbatched degradation path (bucket never filled / batch-path
    #: failure fallback).
    batched: bool
    #: number of live requests in the flushed launch (1 when unbatched)
    batch_size: int
    #: admission-to-completion wall seconds
    latency_s: float
    #: seed the noise stream was derived from — replayable only when
    #: the request pinned it (server-assigned streams also fold in a
    #: per-boot nonce, deliberately not reproducible across restarts)
    seed: int
    #: per-request cost attribution (obs.cost.CostRecord.to_dict():
    #: queue/compile/kernel seconds, retries, shed events, ε charged
    #: and refunded per party). Trailing with a default so the
    #: pre-ISSUE-9 positional construction sites stay valid; ``None``
    #: only for responses replayed from pre-cost idempotency caches.
    cost: dict | None = None
