"""The online DP-correlation server: admission → ledger → coalescer.

:class:`DpcorrServer` is the in-process composition root the tests and
the load generator drive directly; :func:`serve_http` wraps it in a
stdlib threaded HTTP front end for ``python -m dpcorr serve``:

- ``POST /estimate`` — one request (JSON body; arrays as lists) →
  estimate, or 403 (budget refused) / 429 (overloaded or shed, with
  ``Retry-After``) / 503 (circuit breaker open, with ``Retry-After``)
  / 504 (deadline expired before launch, charge refunded) / 400
  (invalid).
- ``GET /stats`` — live counters + ledger snapshot (serve.stats shape).
- ``GET /healthz`` — liveness.
- ``GET /readyz`` — readiness: 503 until the warmup signature set is
  compiled and resident (serve.warmup) and 503 again while any
  circuit breaker is open, 200 otherwise — so a balancer never routes
  traffic onto a cold kernel cache or a tripped replica.

Admission order is the privacy invariant: the ledger is charged (and
durably persisted) BEFORE the request is enqueued, so no query ever
computes without its spend on disk; a crash after charge and before
answer wastes budget rather than leaking it (ledger module docstring).
The one exception is a request the enqueue itself refuses (queue
backpressure / closed coalescer): no kernel ran and nothing was
released, so the charge is reversed before the refusal propagates —
overload sheds load, it must not drain budgets.

Request noise streams extend the repo's key-tree contract (utils.rng)
with two disjoint named subtrees under the server's master key. The
privacy requirement is that two admissions NEVER share a noise stream
unless they are the same query — a repeated stream over different data
lets a client difference the Laplace noise away, voiding the ledger's
composition accounting:

- **pinned** (``req.seed`` set): ``stream(master, "serve/pinned") →
  fold_in(seed) → fold_in(sha256(request content))`` — see
  :func:`pinned_request_key`, which the bit-identity tests and
  ``benchmarks/serve_load.py`` recompute. Replaying the same seed with
  the SAME request is exactly reproducible; the same seed over
  different data lands on an independent stream.
- **assigned** (``req.seed is None``): ``stream(master, "serve/boot")
  → fold_in(boot nonce) → fold_in(admission counter)``. The nonce is
  drawn fresh from the OS CSPRNG at every server construction, so
  counter reuse across restarts (the counter restarts at 0; the ledger
  does not) cannot repeat a stream, and assigned streams can never
  collide with the pinned subtree.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import secrets
import threading
from collections import OrderedDict
from concurrent.futures import Future

from concurrent.futures import TimeoutError as _FuturesTimeout
from concurrent.futures import InvalidStateError

import numpy as np

from dpcorr import chaos
from dpcorr.obs import recorder as obs_recorder
from dpcorr.obs import trace as obs_trace
from dpcorr.obs.audit import AuditTrail
from dpcorr.obs.cost import CostRegistry
from dpcorr.obs.metrics import CONTENT_TYPE as _PROM_CONTENT_TYPE
from dpcorr.serve.budget_dir import (
    BudgetDirectory,
    CompositeLedger,
    RenewalPolicy,
    party_view,
)
from dpcorr.serve.coalescer import Coalescer, ServerOverloadedError
from dpcorr.serve.fleet.lease import ShardNotOwnedError
from dpcorr.serve.kernels import KernelCache
from dpcorr.serve.ledger import BudgetExceededError, PrivacyLedger
from dpcorr.serve.overload import (
    BrownoutController,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExpiredError,
    _bucket_label,
)
from dpcorr.serve.request import EstimateRequest, EstimateResponse, bucket_key
from dpcorr.serve.stats import ServeStats
from dpcorr.serve import warmup as warmup_mod
from dpcorr.utils import rng

log = logging.getLogger("dpcorr.serve")


def request_digest(req: EstimateRequest) -> bytes:
    """SHA-256 over the request's kernel inputs — everything the noise
    touches is digested (family, ε, α, normalise, the data vectors);
    party names are not, as they only route budget accounting. Feeds
    both the pinned-key derivation words and the default idempotency
    key, so "same content" means the same thing in both places."""
    h = hashlib.sha256()
    h.update(req.family.encode())
    h.update(np.asarray([req.eps1, req.eps2, req.alpha],
                        dtype=np.float64).tobytes())
    h.update(b"\x01" if req.normalise else b"\x00")
    h.update(req.x.tobytes())
    h.update(req.y.tobytes())
    return h.digest()


def request_digest_words(req: EstimateRequest) -> tuple[int, ...]:
    """The request digest as eight 31-bit ``fold_in`` words — a 248-bit
    content binding, far past birthday range for any realistic query
    volume."""
    d = request_digest(req)
    return tuple(int.from_bytes(d[4 * i:4 * i + 4], "big") & 0x7FFFFFFF
                 for i in range(8))


def pinned_request_key(master, req: EstimateRequest, seed: int):
    """Noise key for a client-pinned seed: the seed folded into the
    dedicated pinned subtree, then bound to the request content, so a
    seed replayed over different data yields an independent stream (the
    anti-differencing guarantee) while an identical request stays
    exactly reproducible. This is the single derivation the server, the
    bit-identity tests and the load generator all share."""
    key = rng.design_key(rng.stream(master, "serve/pinned"), seed)
    for w in request_digest_words(req):
        key = rng.design_key(key, w)
    return key


class DpcorrServer:
    """In-process serving stack. Thread-safe; close() drains."""

    def __init__(self, budget: float = 100.0,
                 ledger_path: str | None = None,
                 per_party_budget=None,
                 seed: int = rng.MASTER_SEED,
                 max_batch: int = 64, max_delay_s: float = 0.005,
                 max_queue: int = 4096, shard: str = "auto",
                 batch_mode: str = "exact", max_kernels: int = 128,
                 tracer: obs_trace.Tracer | None = None,
                 audit: AuditTrail | str | None = None,
                 warmup: str | list | None = None,
                 warmup_manifest: str | None = None,
                 aot: bool = True, export_dir: str | None = None,
                 warmup_autostart: bool = True,
                 max_idempotency_cache: int = 1024,
                 breaker_threshold: int = 5,
                 breaker_reset_s: float = 30.0,
                 shed_queue_frac: float = 0.75,
                 flush_slo_s: float | None = None,
                 brownout_enter_s: float = 0.5,
                 brownout_exit_s: float = 2.0,
                 brownout_min_priority: int = 0,
                 user_dir: str | None = None,
                 user_budget: float = 1.0,
                 user_shards: int = 8,
                 user_max_resident: int | None = None,
                 user_compact_every: int | None = 256,
                 user_renew_period_s: float = 86400.0,
                 user_burst_cap: float = 0.0,
                 user_fsync: bool = True,
                 global_budget: float | None = None,
                 instance: str | None = None,
                 lease_dir: str | None = None,
                 lease_ttl_s: float = 3.0,
                 lease_target: int | None = None,
                 advertise_url: str | None = None):
        self.seed = seed
        #: fleet identity (ISSUE 11): label on /stats + /metrics so the
        #: fleet collector can cross-check its target map
        self.instance = instance
        # obs wiring (ISSUE 2): one tracer spans the request lifecycle
        # (admit → charge → enqueue → flush → respond; default is the
        # process tracer, disabled unless configured), one per-server
        # metrics registry backs BOTH /stats and /metrics, and the
        # ledger's audit trail stamps budget events with trace IDs
        self.tracer = tracer if tracer is not None else obs_trace.tracer()
        self.audit = AuditTrail(audit) if isinstance(audit, str) else audit
        self.stats = ServeStats(instance=instance)
        # per-request cost attribution (ISSUE 9): a CostRecord per
        # admission, filled in across the queue/compile/kernel path and
        # returned in response metadata; the bounded registry keeps the
        # recent window for /stats aggregation and flight-recorder dumps
        self.costs = CostRegistry()
        self._recorder = None  # set by attach_recorder
        self._crash_hook = None  # set by attach_recorder
        self.ledger = PrivacyLedger(budget, path=ledger_path,
                                    per_party=per_party_budget,
                                    audit=self.audit,
                                    registry=self.stats.registry)
        # per-user budget directory (ISSUE 10): with --user-dir the
        # ledger becomes a CompositeLedger — per-user + per-party +
        # global admission as one atomic charge with one refund path.
        # Drop-in: the coalescer's shed-refund and the overload refund
        # below reverse every leg through the same refund() call.
        # fleet mode (ISSUE 20): with --lease-dir the budget directory
        # is SHARED across replicas and this server only opens a shard
        # journal while it holds that shard's lease — the keeper
        # heartbeats renewals and picks up free/orphaned shards
        self.leases = None
        self._lease_keeper = None
        if lease_dir is not None and user_dir is None:
            raise ValueError("--lease-dir requires --user-dir: leases "
                             "grant budget-directory shards")
        if user_dir is not None or global_budget is not None:
            directory = None
            if user_dir is not None:
                if lease_dir is not None:
                    from dpcorr.serve.fleet.lease import (LeaseKeeper,
                                                          LeaseManager)
                    self.leases = LeaseManager(
                        lease_dir,
                        owner=instance if instance is not None
                        else f"serve-pid-{secrets.token_hex(4)}",
                        url=advertise_url, ttl_s=lease_ttl_s)
                directory = BudgetDirectory(
                    user_dir, shards=user_shards,
                    user_budget=user_budget,
                    renewal=RenewalPolicy(period_s=user_renew_period_s,
                                          burst_cap=user_burst_cap),
                    max_resident=user_max_resident,
                    compact_every=user_compact_every,
                    fsync=user_fsync, audit=self.audit,
                    lease=self.leases)
                if self.leases is not None:
                    self._lease_keeper = LeaseKeeper(self.leases,
                                                     target=lease_target)
                    self._lease_keeper.start()
            self.ledger = CompositeLedger(self.ledger, directory,
                                          global_budget=global_budget)
        self.cache = KernelCache(stats=self.stats, shard=shard,
                                 mode=batch_mode, max_kernels=max_kernels,
                                 aot=aot, export_dir=export_dir,
                                 tracer=self.tracer)
        # overload resilience (ISSUE 8): the breaker fail-fasts a
        # poisoned kernel bucket BEFORE ε is charged; brownout degrades
        # execution (unbatched launches, low-priority rejection) under
        # sustained pressure — both observed by the coalescer, which
        # also holds the ledger so every pre-launch shed is refunded
        self.brownout_min_priority = int(brownout_min_priority)
        self.breaker = CircuitBreaker(fail_threshold=breaker_threshold,
                                      reset_after_s=breaker_reset_s,
                                      stats=self.stats)
        self.brownout = BrownoutController(queue_frac=shed_queue_frac,
                                           flush_slo_s=flush_slo_s,
                                           enter_after_s=brownout_enter_s,
                                           exit_after_s=brownout_exit_s,
                                           stats=self.stats)
        self.coalescer = Coalescer(self.cache, self.stats,
                                   max_batch=max_batch,
                                   max_delay_s=max_delay_s,
                                   max_queue=max_queue,
                                   tracer=self.tracer,
                                   ledger=self.ledger,
                                   breaker=self.breaker,
                                   brownout=self.brownout)
        self._master = None  # guarded by: _master_lock
        self._master_lock = threading.Lock()
        self._req_counter = itertools.count()
        # fresh per construction: makes counter-assigned streams unique
        # across restarts even though the counter itself restarts at 0
        # (module docstring — the ledger persists, the counter must not
        # need to)
        self._boot_nonce = secrets.randbits(31)
        # -- idempotency (ISSUE 7) ----------------------------------------
        # a retried request (client timeout, dropped response) must not
        # charge ε or draw noise twice: completed responses are cached
        # under the request's idempotency key and replayed verbatim;
        # duplicates of a still-running request attach to its future.
        # Failures are never cached — a retry after a refusal genuinely
        # re-runs.
        self._idem_cap = max(int(max_idempotency_cache), 0)
        self._idem_lock = threading.Lock()
        self._idem_done: OrderedDict[str, EstimateResponse] = \
            OrderedDict()  # guarded by: _idem_lock
        self._idem_inflight: dict[str, Future] = {}  # guarded by: _idem_lock
        # -- warmup / readiness (ISSUE 4; serve.warmup) -------------------
        # signature sources: explicit spec (CLI --warmup) + the previous
        # boot's manifest, merged and deduplicated. An empty set means
        # the server is ready immediately (the pre-warmup behavior).
        self._warmup_manifest = warmup_manifest
        sigs: list[dict] = []
        if warmup:
            sigs += (warmup_mod.parse_warmup_spec(warmup, max_batch)
                     if isinstance(warmup, str) else list(warmup))
        if warmup_manifest:
            sigs += warmup_mod.load_manifest(warmup_manifest)
        self._warm_set = warmup_mod.signatures_to_keys(sigs)
        self._warm_lock = threading.Lock()
        self._warm_done = 0  # guarded by: _warm_lock
        self._warm_errors = 0  # guarded by: _warm_lock
        self._warm_state = "ready" if not self._warm_set else "pending"  # guarded by: _warm_lock
        self._warm_thread = None  # guarded by: _warm_lock
        self._ready = threading.Event()
        if not self._warm_set:
            self._ready.set()
        elif warmup_autostart:
            self.start_warmup()

    # -- warmup / readiness ----------------------------------------------
    def start_warmup(self) -> None:
        """Kick the background warmup thread (idempotent). Split from
        construction (``warmup_autostart=False``) so tests can observe
        the not-ready → warming → ready lifecycle."""
        with self._warm_lock:
            if self._warm_thread is not None or not self._warm_set:
                return
            self._warm_state = "warming"
            t = threading.Thread(target=self._warm_loop,
                                 name="dpcorr-serve-warmup", daemon=True)
            self._warm_thread = t
        t.start()

    def _warm_loop(self) -> None:
        with self.tracer.span("serve.warmup", signatures=len(self._warm_set)):
            for kkey, b_pad in self._warm_set:
                try:
                    self.cache.get(kkey, b_pad)
                except Exception as e:
                    # a single bad signature (typo'd family in a spec,
                    # stale manifest entry) must not hold readiness
                    # hostage — log it, count it, keep warming
                    log.warning("warmup signature %s b_pad=%d failed: %s",
                                kkey, b_pad, e)
                    with self._warm_lock:
                        self._warm_errors += 1
                else:
                    # ``warmed`` counts signatures actually resident —
                    # warmed + warm_errors == total once the loop ends
                    with self._warm_lock:
                        self._warm_done += 1
        with self._warm_lock:
            self._warm_state = "ready"
        self._ready.set()

    def readiness(self) -> dict:
        """The ``GET /readyz`` body: ready only once the warmup set is
        resident (or there was none) AND no circuit breaker is open —
        a replica with a tripped bucket reports 503 so a balancer
        drains it while the breaker cools down and probes."""
        breakers_open = self.breaker.any_open()
        with self._warm_lock:
            return {"ready": self._ready.is_set() and not breakers_open,
                    "state": self._warm_state,
                    "warmed": self._warm_done,
                    "warm_errors": self._warm_errors,
                    "total": len(self._warm_set),
                    "breakers_open": breakers_open}

    def wait_ready(self, timeout: float | None = None) -> bool:
        """Block until the warmup set is resident (True) or ``timeout``
        elapses (False) — the load generator's wait-for-ready hook."""
        return self._ready.wait(timeout)

    def _master_locked(self):
        with self._master_lock:
            if self._master is None:
                # deferred: no jax touch until the first admission
                self._master = rng.master_key(self.seed)
        return self._master

    def _request_key(self, req: EstimateRequest, seed: int):
        master = self._master_locked()
        if req.seed is not None:
            return pinned_request_key(master, req, seed)
        return rng.design_key(
            rng.design_key(rng.stream(master, "serve/boot"),
                           self._boot_nonce), seed)

    # -- idempotency -----------------------------------------------------
    def _idem_key(self, req: EstimateRequest) -> str | None:
        """The request's retry identity. Explicit key wins; pinned-seed
        requests default to their content digest (the same bytes the
        noise stream is bound to, so "same key" implies "same answer")
        plus the charged party names — the digest itself excludes them
        (they only route budget), but two submissions billing different
        parties are different ledger operations and must not dedupe;
        assigned-stream requests have no stable identity to key on —
        every submission is a fresh draw by design."""
        if req.idempotency_key is not None:
            return req.idempotency_key
        if req.seed is not None:
            h = hashlib.sha256(request_digest(req))
            for party in (req.party_x, req.party_y):
                raw = party.encode()
                h.update(len(raw).to_bytes(4, "big"))
                h.update(raw)
            if req.user is not None:
                # same reasoning as the party names: the user routes a
                # budget leg (serve.budget_dir), so two users submitting
                # identical content are different ledger operations.
                # Folded only when set, so pre-user keys stay identical.
                raw = req.user.encode()
                h.update(b"user")
                h.update(len(raw).to_bytes(4, "big"))
                h.update(raw)
            return f"pinned:{req.seed}:{h.hexdigest()}"
        return None

    def _idem_complete(self, idem: str, fut: Future) -> None:
        """Done-callback for the original submission: publish success
        into the completed cache (bounded, LRU eviction) and resolve
        the shared placeholder every duplicate is holding."""
        err = fut.exception()
        with self._idem_lock:
            placeholder = self._idem_inflight.pop(idem, None)
            if err is None:
                # dpcorr-lint: ignore[blocking-under-lock] — done-callback: fut is already settled, result() cannot block
                self._idem_done[idem] = fut.result()
                self._idem_done.move_to_end(idem)
                while len(self._idem_done) > self._idem_cap:
                    self._idem_done.popitem(last=False)
        if placeholder is not None:
            # resolve outside the lock: waiter callbacks run inline.
            # The placeholder may have been cancelled by an
            # estimate() timeout — the response is still cached above,
            # so a retry under the same key replays it.
            try:
                if err is None:
                    placeholder.set_result(fut.result())
                else:
                    placeholder.set_exception(err)
            except InvalidStateError:
                pass

    # -- API -------------------------------------------------------------
    def submit(self, req: EstimateRequest) -> Future:
        """Admit one request: charge the ledger (may raise
        BudgetExceededError), then enqueue (may raise
        ServerOverloadedError). Returns a Future[EstimateResponse].

        Idempotency runs first: a key that already completed returns
        the ORIGINAL response object (byte-identical on the wire) with
        no charge, no noise draw and no kernel execution; a key still
        in flight returns the original's future. The reservation is
        taken BEFORE the charge so a concurrent duplicate can never
        race past the cache into a second spend."""
        idem = self._idem_key(req)
        if idem is not None and self._idem_cap > 0:
            with self._idem_lock:
                done = self._idem_done.get(idem)
                if done is not None:
                    self._idem_done.move_to_end(idem)
                    self.stats.idempotent_hit("completed")
                    fut: Future = Future()
                    fut.set_result(done)
                    return fut
                running = self._idem_inflight.get(idem)
                if running is not None:
                    self.stats.idempotent_hit("inflight")
                    return running
                placeholder: Future = Future()
                self._idem_inflight[idem] = placeholder
            try:
                inner = self._admit(req, idem=idem)
            except BaseException as e:
                # refused admissions are not cached (a retry genuinely
                # re-runs), but duplicates already attached must fail too
                with self._idem_lock:
                    self._idem_inflight.pop(idem, None)
                placeholder.set_exception(e)
                raise
            inner.add_done_callback(
                lambda f, k=idem: self._idem_complete(k, f))
            return placeholder
        return self._admit(req)

    def _admit(self, req: EstimateRequest,
               idem: str | None = None) -> Future:
        """Charge + enqueue (the pre-idempotency submit).

        The root ``serve.request`` span opens here and closes on the
        flush thread when the response lands; its trace ID stamps the
        ledger's audit events, so one ID joins the latency chain and
        the budget decision (docs/OBSERVABILITY.md).

        ``idem`` (the request's retry identity, when it has one)
        doubles as the charge's durable charge_id: in a fleet the
        budget directory is shared, so a retry of a dying replica's
        request dedups against the WAL-recovered id on whichever
        replica serves it — charged exactly once, fleet-wide."""
        charge_id = None if idem is None else f"req:{idem}"
        seed = req.seed if req.seed is not None else next(self._req_counter)
        key = self._request_key(req, seed)
        # dpcorr-lint: ignore[span-no-finally] — request root span; closes on the flush thread when the response lands
        root = self.tracer.start_span("serve.request", family=req.family,
                                      n=req.n, seed=seed)
        # the cost record opens with the root span and shares its trace
        # ID — refused requests keep theirs in the registry too, so the
        # "refused ⇒ zero ε net of refunds" invariant is checkable
        cost = self.costs.new(root.trace_id)
        try:
            with self.tracer.span("serve.admit", parent=root):
                # inner spans parent implicitly under serve.admit (the
                # thread's current span) — all on root's trace ID
                try:
                    # fail-fast gates run BEFORE the charge: a request
                    # the breaker or the brownout floor refuses never
                    # touches the ledger, so it trivially consumes zero ε
                    self._overload_gate(req)
                except CircuitOpenError:
                    self.stats.refused("breaker")
                    root.set(refused="breaker")
                    cost.event("refused_breaker")
                    raise
                except ServerOverloadedError:
                    self.stats.refused("brownout")
                    self.stats.shed("admission")
                    root.set(refused="brownout")
                    cost.event("refused_brownout")
                    raise
                try:
                    with self.tracer.span("serve.ledger.charge"):
                        charges = self.ledger.charge_request(
                            req, trace_id=root.trace_id,
                            charge_id=charge_id)
                    # cost attribution is party ε (what crossed into a
                    # kernel) — the directory's derived user/global
                    # legs are bookkeeping views of the same spend
                    cost.charge(party_view(charges))
                except ShardNotOwnedError as e:
                    # fleet routing miss: another replica holds the
                    # user's budget shard. Charge-free by construction
                    # (the lease gate runs before any leg applies) —
                    # the front end forwards to the owner named in e.
                    self.stats.refused("not_owner")
                    root.set(refused="not_owner", shard=e.shard)
                    cost.event("refused_not_owner")
                    raise
                except BudgetExceededError as e:
                    self.stats.refused_budget()
                    root.set(refused="budget", refused_level=e.level)
                    # the event names WHICH budget level refused
                    # (user | party | global) — obs top / flight
                    # recorder attribution without parsing principals
                    cost.event(f"refused_budget_{e.level}")
                    raise
                try:
                    with self.tracer.span("serve.enqueue"):
                        fut = self.coalescer.submit(req, key, seed,
                                                    span=root,
                                                    charges=charges,
                                                    cost=cost,
                                                    charge_id=charge_id)
                except Exception:
                    # the enqueue refused (backpressure / closed): no
                    # kernel ran and nothing was released, so reversing
                    # the charge is safe — shed load must not consume ε
                    # (ledger.refund); the charge_id is forgotten with
                    # it so the client's next attempt charges cleanly
                    self.ledger.refund(charges, trace_id=root.trace_id,
                                       charge_id=charge_id,
                                       reason="overload")
                    cost.event("refused_overload")
                    cost.refund(party_view(charges), "overload")
                    root.set(refused="overload")
                    raise
        except Exception:
            root.end()  # refused requests never reach the flush thread
            raise
        self.stats.admitted()
        return fut

    def _overload_gate(self, req: EstimateRequest) -> None:
        """Pre-charge admission gates: the request's bucket breaker
        (raises :class:`CircuitOpenError` while open) and the brownout
        priority floor (raises :class:`ServerOverloadedError` for work
        below ``brownout_min_priority`` while browned out)."""
        self.breaker.allow(bucket_key(req))
        # keep the brownout hysteresis fed from the gate itself: with
        # every arrival refused pre-enqueue, nothing else would observe
        # the (now calm) queue and brownout would never exit
        self.coalescer.observe_pressure()
        if self.brownout.active() \
                and req.priority < self.brownout_min_priority:
            raise ServerOverloadedError(
                f"brownout: priority {req.priority} below the floor "
                f"{self.brownout_min_priority} under sustained pressure",
                retry_after_s=self.coalescer.retry_after_s())

    def estimate(self, req: EstimateRequest,
                 timeout: float | None = 60.0) -> EstimateResponse:
        """Blocking convenience wrapper around :meth:`submit`.

        A timeout no longer leaks the in-flight request silently
        (ISSUE 8 satellite): the pending future is cancelled — if the
        cancel wins (the flush thread had not claimed it) the request
        is withdrawn and the coalescer refunds its charge at claim
        time; if it loses, the request was already launching and
        completes unobserved (``detached`` — its spend stands, its
        response still lands in the idempotency cache). Either way the
        outcome is counted in the ``abandoned`` stat."""
        fut = self.submit(req)
        try:
            return fut.result(timeout=timeout)
        except _FuturesTimeout:
            self.stats.abandoned("cancelled" if fut.cancel()
                                 else "detached")
            raise

    def stats_snapshot(self) -> dict:
        snap = self.stats.snapshot(
            ledger_snapshot=self.ledger.snapshot(),
            cost_aggregate=self.costs.aggregate(),
            budget_dir=(self.ledger.directory_snapshot()
                        if isinstance(self.ledger, CompositeLedger)
                        else None))
        snap["breaker"] = self.breaker.snapshot()
        if self.leases is not None:
            # fleet mode: which budget shards this replica owns, at
            # which epochs — obs top --fleet renders the fold
            snap["leases"] = self.leases.snapshot()
        return snap

    # -- flight recorder (ISSUE 9) ---------------------------------------
    def attach_recorder(self, rec) -> None:
        """Wire a :class:`~dpcorr.obs.recorder.FlightRecorder` into
        every capture point of this server: span + audit observers,
        the metrics registry and cost registry for dump snapshots,
        breaker-trip / brownout-transition / chaos-crash dump triggers,
        and the ``dpcorr`` logging ring. Installs the recorder as the
        process-wide trigger target (``dpcorr obs`` + SIGUSR2 path)."""
        self._recorder = rec
        self.tracer.add_observer(rec.record_span)
        if self.audit is not None:
            self.audit.add_observer(rec.record_audit)
        rec.watch_registry(self.stats.registry)
        rec.watch_costs(self.costs)
        # dump triggers: all three callbacks fire OUTSIDE their
        # component's lock (overload.py / chaos.py contracts), so the
        # recorder may take its ring lock and do file I/O safely
        self.breaker.on_open = lambda bkey, consecutive: \
            obs_recorder.trigger(
                "breaker_open", family=bkey.family,
                bucket=_bucket_label(bkey), consecutive=consecutive)
        self.brownout.on_change = lambda active: obs_recorder.trigger(
            "brownout_enter" if active else "brownout_exit")
        self._crash_hook = lambda point: rec.dump("chaos", point=point)
        chaos.on_crash(self._crash_hook)
        rec.attach_logging("dpcorr")
        obs_recorder.install(rec)

    def close(self) -> None:
        if self._crash_hook is not None:
            chaos.remove_crash_hook(self._crash_hook)
            self._crash_hook = None
        if self._lease_keeper is not None:
            self._lease_keeper.stop()
        self.coalescer.close()
        if self.leases is not None:
            # graceful handback AFTER the drain: successors take over
            # immediately instead of waiting out the TTL
            self.leases.release_all()
        if isinstance(self.ledger, CompositeLedger):
            self.ledger.close()
        if self._warmup_manifest:
            # persist the working set AFTER the drain: every kernel the
            # final flushes compiled is in the manifest the next boot
            # replays
            try:
                warmup_mod.save_manifest(self._warmup_manifest,
                                         self.cache.manifest())
            except OSError as e:
                log.warning("could not persist warmup manifest %s: %s",
                            self._warmup_manifest, e)


class InProcessClient:
    """The client surface tests and the load generator program against —
    the same calls a network client would make, minus the wire."""

    def __init__(self, server: DpcorrServer):
        self._server = server

    def submit(self, req: EstimateRequest) -> Future:
        return self._server.submit(req)

    def estimate(self, req: EstimateRequest,
                 timeout: float | None = 60.0) -> EstimateResponse:
        return self._server.estimate(req, timeout=timeout)

    def stats(self) -> dict:
        return self._server.stats_snapshot()

    def readiness(self) -> dict:
        return self._server.readiness()

    def wait_ready(self, timeout: float | None = None) -> bool:
        """Wait-for-ready hook: what ``GET /readyz`` polling would do,
        minus the wire (benchmarks/serve_load.py warm-boot mode)."""
        return self._server.wait_ready(timeout)


# ---------------------------------------------------------------- HTTP ----
def _request_from_json(body: dict) -> EstimateRequest:
    try:
        return EstimateRequest(
            family=body["family"],
            x=np.asarray(body["x"], dtype=np.float32),
            y=np.asarray(body["y"], dtype=np.float32),
            eps1=float(body["eps1"]), eps2=float(body["eps2"]),
            party_x=str(body.get("party_x", "party-x")),
            party_y=str(body.get("party_y", "party-y")),
            alpha=float(body.get("alpha", 0.05)),
            normalise=bool(body.get("normalise", True)),
            seed=(int(body["seed"]) if body.get("seed") is not None
                  else None),
            idempotency_key=(str(body["idempotency_key"])
                             if body.get("idempotency_key") is not None
                             else None),
            priority=int(body.get("priority", 0)),
            deadline_s=(float(body["deadline_s"])
                        if body.get("deadline_s") is not None
                        else None),
            user=(str(body["user"]) if body.get("user") is not None
                  else None))
    except KeyError as e:
        raise ValueError(f"missing required field {e.args[0]!r}") from e


def _response_json(resp: EstimateResponse) -> dict:
    return {"rho_hat": resp.rho_hat, "ci_low": resp.ci_low,
            "ci_high": resp.ci_high, "batched": resp.batched,
            "batch_size": resp.batch_size,
            "latency_s": round(resp.latency_s, 6), "seed": resp.seed,
            "cost": resp.cost}


def make_http_server(server: DpcorrServer, host: str = "127.0.0.1",
                     port: int = 8321, sock=None):
    """Build (not start) the threaded HTTP front end; the caller owns
    ``serve_forever`` / ``shutdown`` so tests can run it on a thread.
    ``sock`` adopts a pre-bound listening socket: the CLI binds before
    the (slow) server build so the port — and the instance name
    derived from it — is known up front."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict,
                  headers: tuple = ()) -> None:
            blob = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(blob)

        @staticmethod
        def _retry_after(e) -> tuple:
            """``Retry-After`` header (whole seconds, ceil'd so a
            client never retries early) when the refusal carries an
            estimate."""
            ra = getattr(e, "retry_after_s", None)
            if ra is None:
                return ()
            secs = max(1, int(ra) + (1 if ra % 1 else 0))
            return (("Retry-After", str(secs)),)

        def _send_text(self, code: int, text: str,
                       content_type: str) -> None:
            blob = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def do_GET(self):  # noqa: N802 (stdlib handler casing)
            if self.path == "/stats":
                self._send(200, server.stats_snapshot())
            elif self.path == "/metrics":
                # Prometheus text exposition off the same registry that
                # backs /stats — single source of truth (obs.metrics)
                self._send_text(200, server.stats.render_prometheus(),
                                _PROM_CONTENT_TYPE)
            elif self.path == "/healthz":
                self._send(200, {"ok": True})
            elif self.path == "/readyz":
                # readiness ≠ liveness: 503 while the warmup set is
                # still compiling, so a load balancer holds traffic
                # until steady-state is compile-free
                r = server.readiness()
                self._send(200 if r["ready"] else 503, r)
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path == "/obs/trigger":
                # fleet SLO plane (ISSUE 11): a burn-rate page arms
                # THIS instance's flight recorder through its existing
                # trigger hook — the dump happens here, next to the
                # rings, not in the collector process. Reasons are
                # validated against the recorder's append-only registry
                # so a typo'd page cannot mint an unknown reason.
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length))
                    reason = body.get("reason")
                    detail = body.get("detail") or {}
                    if reason not in obs_recorder.TRIGGER_REASONS:
                        raise ValueError(
                            f"unknown trigger reason {reason!r}")
                    if not isinstance(detail, dict):
                        raise ValueError("detail must be an object")
                except (ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                path = obs_recorder.trigger(
                    reason, **{str(k): v for k, v in detail.items()})
                self._send(200, {"dumped": path,
                                 "armed": obs_recorder.active()
                                 is not None})
                return
            if self.path != "/estimate":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = _request_from_json(json.loads(self.rfile.read(length)))
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})
                return
            try:
                resp = server.estimate(req)
            except ShardNotOwnedError as e:
                # fleet routing miss (ISSUE 20): 421 Misdirected
                # Request naming the owner so the front end forwards
                # instead of failing — charge-free on this replica
                self._send(421, {"error": str(e),
                                 "refused": "not_owner",
                                 "shard": e.shard, "owner": e.owner,
                                 "owner_url": e.owner_url},
                           headers=self._retry_after(e))
            except BudgetExceededError as e:
                # enough detail for the client to reconstruct the typed
                # refusal (serve.client.HttpEstimateClient) — a budget
                # refusal is terminal, retrying it is never right
                self._send(403, {"error": str(e), "refused": "budget",
                                 "party": e.party, "spent": e.spent,
                                 "charge": e.charge, "budget": e.budget,
                                 "level": e.level})
            except DeadlineExpiredError as e:
                self._send(504, {"error": str(e), "refused": "expired"},
                           headers=self._retry_after(e))
            except CircuitOpenError as e:
                self._send(503, {"error": str(e), "refused": "breaker"},
                           headers=self._retry_after(e))
            except ServerOverloadedError as e:
                self._send(429, {"error": str(e), "refused": "overload"},
                           headers=self._retry_after(e))
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
            else:
                self._send(200, _response_json(resp))

        def log_message(self, *args):  # quiet by default
            pass

    if sock is None:
        return ThreadingHTTPServer((host, port), Handler)
    httpd = ThreadingHTTPServer((host, port), Handler,
                                bind_and_activate=False)
    httpd.socket.close()
    httpd.socket = sock
    httpd.server_address = sock.getsockname()[:2]
    httpd.server_activate()
    return httpd


def serve_http(server: DpcorrServer, host: str = "127.0.0.1",
               port: int = 8321) -> None:
    """Run the HTTP front end until interrupted (the CLI entry)."""
    httpd = make_http_server(server, host=host, port=port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        server.close()
