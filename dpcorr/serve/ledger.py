"""Per-party privacy-budget ledger under basic composition.

The reference handles privacy accounting implicitly: a grid run spends
exactly the (ε₁, ε₂) its design row names, once, offline. An online
service has no such luxury — each admitted query *permanently* consumes
budget from the data owners it touches, and the correctness invariant
is that the sum of admitted spends never exceeds a party's configured
budget, across restarts. This module is that invariant:

- **Basic composition** (the paper's setting — pure ε-DP Laplace
  mechanisms): total spend per party is the plain sum of per-query ε.
  :func:`request_charges` maps a request to its per-party spend: ε₁
  against x's owner and ε₂ against y's, doubled for the sign families
  under ``normalise`` because the private centering pass spends the
  same ε again before the sign-batch release (vert-cor.R:211-215; the
  subG families clip with data-independent λ_n bounds instead, so they
  spend once).
- **Refusal before execution**: :meth:`PrivacyLedger.charge` is
  all-or-nothing across the request's parties and raises
  :class:`BudgetExceededError` without mutating anything if *any* party
  would exceed its budget. The server charges at admission, before the
  kernel runs.
- **Write-ahead persistence**: when constructed with a path, the spend
  table is fsync-rename persisted *before* ``charge`` returns, so a
  server killed at any point can never have answered a query whose
  spend is not on disk. A restart therefore under-counts never,
  over-counts at most the in-flight queries that were admitted but
  never answered — the safe direction for privacy.
- **Refund only for never-executed queries**:
  :meth:`PrivacyLedger.refund` reverses a charge when the server can
  prove no kernel ran (the enqueue itself refused the request), so
  backpressure sheds load without consuming ε.
- **Audit trail + metrics** (ISSUE 2): constructed with an
  :class:`dpcorr.obs.AuditTrail`, every charge/refund/refusal is
  appended as a structured event carrying the caller's trace ID —
  ``python -m dpcorr obs budget`` replays the trail into this ledger's
  spend table. Constructed with an obs registry, per-party spend and
  the charge/refund/refusal totals are published as Prometheus series
  next to the serving counters. Both are observers: the fsync-rename
  snapshot stays the accounting source of truth, and the trail line is
  written only after the charge is durably persisted.

Thread-safe: one lock around check+spend+persist (the coalescer admits
from many client threads).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Mapping

from dpcorr import chaos
from dpcorr.obs.audit import AuditTrail
from dpcorr.obs.budget_replay import quarantine_corrupt, sweep_stale_tmp
from dpcorr.obs.metrics import Registry
from dpcorr.serve.request import EstimateRequest

__all__ = [
    "BudgetExceededError", "LedgerCorruptError", "PrivacyLedger",
    "quarantine_corrupt", "release_factor", "request_charges",
    "sweep_stale_tmp",
]

_STATE_VERSION = 1

# Idempotency memory: how many distinct charge_ids the ledger remembers
# (FIFO). Far above any live session's outstanding charges — the bound
# only exists so a long-lived server's snapshot cannot grow unboundedly.
_CHARGE_ID_CAP = 4096


class LedgerCorruptError(ValueError):
    """The persisted ledger snapshot could not be parsed. The bad file
    has been quarantined to a ``.corrupt`` sidecar; the message says
    exactly what to do next."""


# sweep_stale_tmp / quarantine_corrupt live in obs.budget_replay (the
# jax-free layer) so the budget directory's shard reader shares them;
# re-exported here because they are ledger durability idioms first.


class BudgetExceededError(Exception):
    """Admission refused: the query would overdraw a principal's ε
    budget. ``level`` names which budget refused — ``party`` for data
    owners, ``user`` / ``global`` for the reserved directory
    namespaces (serve.budget_dir) — so refusal stats and cost events
    can attribute the refusing level without parsing principal names."""

    def __init__(self, party: str, spent: float, charge: float,
                 budget: float):
        self.party = party
        self.spent = spent
        self.charge = charge
        self.budget = budget
        self.level = ("user" if party.startswith("user/")
                      else "global" if party.startswith("global/")
                      else "party")
        super().__init__(
            f"party {party!r}: spent {spent:.6g} + charge {charge:.6g} "
            f"> budget {budget:.6g}")


def release_factor(family: str, normalise: bool) -> float:
    """Spend multiplier for one side's release under basic composition.

    Sign families with ``normalise`` privately center the variable
    first, spending that side's ε a second time before the sign-batch /
    flip release (vert-cor.R:211-215); the subG families clip with
    data-independent λ_n bounds instead, so they spend once. Shared by
    the serving admission path (:func:`request_charges`) and the
    two-party protocol's per-role charge (protocol.party) so the two
    deployment modes can never drift on what a release costs.
    """
    return 2.0 if (family in ("ni_sign", "int_sign") and normalise) else 1.0


def request_charges(req: EstimateRequest) -> dict[str, float]:
    """Per-party ε spend of one request under basic composition.

    Sign families with ``normalise`` privately center each variable
    first, spending that side's ε a second time (see module docstring);
    a request whose two sides name the same party accumulates both
    charges against it.
    """
    factor = release_factor(req.family, req.normalise)
    charges: dict[str, float] = {}
    for party, eps in ((req.party_x, req.eps1 * factor),
                       (req.party_y, req.eps2 * factor)):
        charges[party] = charges.get(party, 0.0) + float(eps)
    return charges


class PrivacyLedger:
    """Cumulative per-party ε under basic composition, with refusal.

    ``budget``: default per-party budget; ``per_party`` overrides it for
    named parties. ``path``: JSON persistence file — loaded on
    construction (restart continuity) and rewritten atomically on every
    successful charge.
    """

    def __init__(self, budget: float, path: str | None = None,
                 per_party: Mapping[str, float] | None = None,
                 audit: AuditTrail | None = None,
                 registry: Registry | None = None):
        if budget <= 0.0:
            raise ValueError(f"budget must be positive, got {budget}")
        self.budget = float(budget)
        self.per_party = dict(per_party or {})
        self.path = path
        self.audit = audit
        self._lock = threading.Lock()
        self._spent: dict[str, float] = {}  # guarded by: _lock
        # insertion-ordered set of applied charge_ids (dict keys) — what
        # makes a resumed session's re-charge a no-op
        self._charge_ids: dict[str, None] = {}  # guarded by: _lock
        self._events = self._spent_gauge = None
        if registry is not None:
            self._events = registry.counter(
                "dpcorr_ledger_events_total",
                "Ledger mutations by kind", labelnames=("kind",))
            self._spent_gauge = registry.gauge(
                "dpcorr_ledger_spent_eps",
                "Cumulative per-party eps spend under basic composition",
                labelnames=("party",))
        if path:
            self._sweep_stale_tmp(path)
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    state = json.load(f)
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                quarantine = quarantine_corrupt(path)
                raise LedgerCorruptError(
                    f"ledger snapshot {path!r} is corrupt ({e}); the bad "
                    f"file was moved to {quarantine!r}. To recover, "
                    "rebuild the spend table from the audit trail "
                    "(`python -m dpcorr obs budget --audit <trail>`) and "
                    "restart, or delete the sidecar to start from zero "
                    "spend (spends budget-safety: never do this in "
                    "production without the audit replay).") from e
            if state.get("version") != _STATE_VERSION:
                raise ValueError(
                    f"ledger state {path!r} has version "
                    f"{state.get('version')!r}, expected {_STATE_VERSION}")
            self._spent = {str(k): float(v)
                           for k, v in state["spent"].items()}
            # absent in pre-idempotency snapshots — same version, additive
            self._charge_ids = {str(c): None
                                for c in state.get("charge_ids", [])}
            self._publish_locked()

    # kept as a staticmethod alias — external callers use the module
    # function; the constructor predates it
    _sweep_stale_tmp = staticmethod(sweep_stale_tmp)

    def _publish_locked(self) -> None:
        """Mirror the spend table into the per-party gauge (caller holds
        the lock, or is the constructor before any concurrency)."""
        if self._spent_gauge is not None:
            for party, spent in self._spent.items():
                self._spent_gauge.set(spent, party=party)

    def budget_for(self, party: str) -> float:
        return float(self.per_party.get(party, self.budget))

    def spent(self, party: str) -> float:
        with self._lock:
            return self._spent.get(party, 0.0)

    def remaining(self, party: str) -> float:
        with self._lock:
            return self.budget_for(party) - self._spent.get(party, 0.0)

    def charge(self, charges: Mapping[str, float],
               trace_id: str | None = None,
               charge_id: str | None = None) -> None:
        """Atomically spend ``{party: ε}`` across all named parties.

        All-or-nothing: if any party would exceed its budget the whole
        charge is refused (no partial spend) and
        :class:`BudgetExceededError` raised for the first violator. On
        success the new state is durably persisted before returning.
        ``trace_id`` stamps the audit event so a budget decision joins
        the request's span chain.

        ``charge_id`` makes the charge idempotent: a charge whose id the
        persisted snapshot already contains is a no-op (recorded as a
        deduped audit event, spending nothing). This is how a resumed
        protocol session re-runs its charge-then-send sequence without
        double-spending — the ledger and the session journal are two
        separate durable stores that cannot commit atomically, so the
        charge itself must be safely repeatable. A later ``refund`` with
        the same id forgets it, so a genuinely new charge can reuse it.
        """
        for party, eps in charges.items():
            if eps < 0.0:
                raise ValueError(f"negative charge {eps} for {party!r}")
        with self._lock:
            if charge_id is not None and charge_id in self._charge_ids:
                if self._events is not None:
                    self._events.inc(kind="dedup")
                if self.audit is not None:
                    self.audit.record("charge", charges, trace_id=trace_id,
                                      charge_id=charge_id, dedup=True)
                return
            for party, eps in charges.items():
                spent = self._spent.get(party, 0.0)
                # strict >: a charge landing exactly on the budget is
                # admitted (the budget is a spend *cap*, not an open bound)
                if spent + eps > self.budget_for(party) + 1e-12:
                    if self._events is not None:
                        self._events.inc(kind="refusal")
                    if self.audit is not None:
                        self.audit.record(
                            "refusal", charges, trace_id=trace_id,
                            party=party, spent=spent,
                            budget=self.budget_for(party))
                    raise BudgetExceededError(party, spent, eps,
                                              self.budget_for(party))
            for party, eps in charges.items():
                self._spent[party] = self._spent.get(party, 0.0) + eps
            if charge_id is not None:
                self._charge_ids[charge_id] = None
                while len(self._charge_ids) > _CHARGE_ID_CAP:
                    self._charge_ids.pop(next(iter(self._charge_ids)))
            chaos.point("ledger.pre_persist")
            # dpcorr-lint: ignore[blocking-under-lock] — spend must be durable before the ack leaves the lock
            self._persist_locked()
            chaos.point("ledger.post_persist")
            # observers fire only after the spend is durably on disk —
            # a crash here under-reports the audit view, never the budget
            if self._events is not None:
                self._events.inc(kind="charge")
            self._publish_locked()
            if self.audit is not None:
                detail = {} if charge_id is None else {"charge_id": charge_id}
                self.audit.record("charge", charges, trace_id=trace_id,
                                  **detail)

    def charge_request(self, req: EstimateRequest,
                       trace_id: str | None = None,
                       charge_id: str | None = None) -> dict[str, float]:
        """Charge one request's spend; returns what was charged.
        ``charge_id`` (the request's durable retry identity, when it
        has one) makes the charge idempotent across a crash-retry."""
        charges = request_charges(req)
        self.charge(charges, trace_id=trace_id, charge_id=charge_id)
        return charges

    def refund(self, charges: Mapping[str, float],
               trace_id: str | None = None,
               charge_id: str | None = None,
               reason: str | None = None) -> None:
        """Reverse a charge whose query provably never executed.

        Only valid when no kernel ran and nothing was released under
        the charged ε — the server uses it when the enqueue itself
        refuses an already-charged request (queue backpressure) and
        when an admitted request is shed before launch (deadline
        expiry, priority eviction, shutdown drain, client abandonment
        — serve.coalescer), so sustained overload cannot drain budgets
        to exhaustion with zero queries served. The reversal is
        persisted like a charge; spends clamp at zero so a stray refund
        can only err toward privacy (over-counting), never
        under-counting. ``reason`` stamps the audit event with which
        shed path fired, so an audit replay can account every refund.
        """
        for party, eps in charges.items():
            if eps < 0.0:
                raise ValueError(f"negative refund {eps} for {party!r}")
        with self._lock:
            for party, eps in charges.items():
                self._spent[party] = max(
                    0.0, self._spent.get(party, 0.0) - eps)
            # the id is forgotten so a genuinely new attempt may charge
            # under it again — refund means "that charge never happened"
            if charge_id is not None:
                self._charge_ids.pop(charge_id, None)
            # dpcorr-lint: ignore[blocking-under-lock] — refund must be durable before the ack leaves the lock
            self._persist_locked()
            if self._events is not None:
                self._events.inc(kind="refund")
            self._publish_locked()
            if self.audit is not None:
                detail = {} if charge_id is None else {"charge_id": charge_id}
                if reason is not None:
                    detail["reason"] = reason
                self.audit.record("refund", charges, trace_id=trace_id,
                                  **detail)

    def snapshot(self) -> dict:
        """Point-in-time accounting view (the stats endpoint's shape)."""
        with self._lock:
            return {
                "budget_default": self.budget,
                "parties": {
                    p: {"spent": s, "budget": self.budget_for(p),
                        "remaining": self.budget_for(p) - s}
                    for p, s in sorted(self._spent.items())},
            }

    def _persist_locked(self) -> None:
        """Atomic write-ahead persist (caller holds the lock): tmp +
        fsync + rename, so a crash mid-write leaves the previous state
        intact and a completed charge is never lost."""
        if not self.path:
            return
        state = {"version": _STATE_VERSION, "spent": self._spent,
                 "charge_ids": list(self._charge_ids)}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
