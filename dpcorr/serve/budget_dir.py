"""Sharded per-user budget directory: crash-safe ε accounting at scale.

The per-party ledger (serve.ledger) answers "how much has this *data
owner* spent"; a multi-tenant deployment also has to answer "how much
has this *user* spent" for millions of principals, under the same
refuse-before-execute, never-double-charge discipline — a budget store
that loses or duplicates a charge across a crash is a privacy
violation, not just a bug. Three pieces:

- :class:`BudgetDirectory` — users consistent-hashed onto shards
  (sha256 ring, deterministic across processes; the shard count is
  pinned in ``meta.json`` so a reopen can never re-route a user).
  Each shard is a **write-ahead journal**: an appended, fsynced WAL
  line per mutation, folded periodically into a tmp+fsync+rename
  snapshot (compaction), with the snapshot/WAL pair versioned by a
  generation number so a crash *between* the snapshot rename and the
  WAL reset can never replay already-folded entries. Charge/refund
  lines carry the user's window start and burst so a recovery that
  must re-create a user from the WAL alone (not yet compacted into a
  snapshot) restores the true window — never ``w=0.0``, which would
  fire a spurious renewal on the first post-restart charge. Cold
  users are LRU-evicted to a per-shard spill file that is only a
  within-process memory-relief cache — restart recovery is always
  snapshot + WAL, so a crash mid-eviction loses nothing; the spill is
  rewritten compactly at compaction and whenever dead (rehydrated)
  lines outnumber live ones, and an unparseable spill fails the whole
  shard loudly (every later call re-raises the quarantine error)
  rather than silently forgetting evicted users' spend. Charges carry
  idempotent ``charge_id``s exactly like protocol/journal.py: a
  resumed session's re-charge is a durable no-op.
- **Renewal/decay** — :class:`RenewalPolicy`: each user's window spend
  resets every ``period_s`` (daily ε refresh), carrying unused
  headroom forward as burst credit up to ``burst_cap``. The clock is
  injectable, so policies are testable under a scripted clock.
  Renewals are journaled as absolute resulting state (idempotent to
  replay), riding the **same fsynced append** as the charge they
  admit — a refused charge journals nothing, renewal included — and
  draw **no** audit event: the audit trail tracks the monotone
  *lifetime* spend, which renewal does not touch — that is what keeps
  the jax-free ``obs budget`` replay an exact equality over the
  sharded trails.
- :class:`CompositeLedger` — composes per-user + per-party + global
  budgets into **one atomic charge with one refund path**. User legs
  live under the reserved ``user/`` principal namespace, the global
  cap under ``global/total`` (charged inside the *same*
  ``PrivacyLedger.charge`` as the party legs, hence atomic with them);
  :meth:`CompositeLedger.charge` augments a per-party charge dict with
  the derived legs, charges the directory first and compensates it on
  a party/global refusal, so a refused request consumes zero ε at
  every level. :meth:`CompositeLedger.refund` performs the same
  augmentation, so the coalescer's shed-refund path and the protocol
  gate's transport-failure refund reverse every leg symmetrically
  without knowing the directory exists.

Crash windows (all four registered as chaos points; ``dpcorr chaos``
kills a party at each and proves kill-and-restart recovers to exact
per-user balances):

- ``budget.pre_journal`` — before the WAL append: nothing durable, the
  resumed session's re-charge applies exactly once.
- ``budget.post_journal`` — after the fsynced append, before the
  in-memory apply: recovery replays the WAL, the re-charge dedups on
  its charge_id.
- ``budget.mid_compaction`` — after the new snapshot renamed, before
  the WAL reset: the WAL's generation is now *behind* the snapshot's,
  so recovery discards it instead of double-applying folded entries.
- ``budget.mid_eviction`` — after the cold-spill append, before the
  resident drop: the spill file is non-authoritative (reset on open),
  so the authoritative snapshot+WAL state is untouched.

WAL appends are a single ``write``+``flush``+``fsync`` per admission;
the chaos points bracket that write, so every registered window leaves
either no entry or a complete fsynced line. Any *unparseable* shard
file — snapshot, WAL, or spill — is quarantined whole to a
``.corrupt`` sidecar and refused loudly (:class:`DirectoryCorruptError`)
rather than half-applied, with the same stale-``.tmp`` sweep the
ledger uses.

This module is the *write* side; the snapshot/WAL arithmetic that
recovery and auditing share — :func:`load_shard`,
:func:`read_user_balances`, the ``.corrupt`` quarantine — lives in the
jax-free :mod:`dpcorr.obs.budget_replay`, because the chaos driver's
exact-balance assertions and ``obs budget --budget-dir`` must run with
no accelerator stack importable at all (importing ``dpcorr.serve``
pulls jax).
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Mapping

from dpcorr import chaos
from dpcorr.obs.audit import AuditTrail
from dpcorr.obs.budget_replay import (
    DIR_VERSION as _DIR_VERSION,
    GLOBAL_KEY,
    RESERVED_PREFIXES,
    USER_PREFIX,
    DirectoryCorruptError,
    corrupt_error as _corrupt,
    fresh_user as _fresh_user,
    load_shard,
    sweep_stale_tmp,
)
from dpcorr.serve.ledger import BudgetExceededError, PrivacyLedger

__all__ = [
    "GLOBAL_KEY", "RESERVED_PREFIXES", "USER_PREFIX",
    "BudgetDirectory", "CompositeLedger", "DirectoryCorruptError",
    "RenewalPolicy", "is_reserved", "party_view", "user_view",
]

#: idempotency memory per shard, mirroring serve.ledger's bound: far
#: above any live session's outstanding charges, capped only so a
#: long-lived shard snapshot cannot grow unboundedly.
_CHARGE_ID_CAP = 4096


def is_reserved(principal: str) -> bool:
    """True for directory-managed principals (``user/``, ``global/``)."""
    return principal.startswith(RESERVED_PREFIXES)


def party_view(charges: Mapping[str, float]) -> dict[str, float]:
    """The per-party legs of a (possibly augmented) charge dict — what
    actually crossed the wire / reached a kernel, for cost attribution
    and transcript matching."""
    return {k: float(v) for k, v in charges.items() if not is_reserved(k)}


def user_view(charges: Mapping[str, float]) -> dict[str, float]:
    """The per-user legs, keyed by bare user id."""
    return {k[len(USER_PREFIX):]: float(v) for k, v in charges.items()
            if k.startswith(USER_PREFIX)}


@dataclasses.dataclass(frozen=True)
class RenewalPolicy:
    """Per-user window refresh: every ``period_s`` the window spend
    resets and unused headroom carries forward as burst credit, capped
    at ``burst_cap`` (0.0 = plain daily refresh, no carry). Admission
    checks the window spend against ``user_budget + burst``."""

    period_s: float = 86400.0
    burst_cap: float = 0.0

    def __post_init__(self):
        if self.period_s <= 0.0:
            raise ValueError(f"period_s must be positive, got "
                             f"{self.period_s}")
        if self.burst_cap < 0.0:
            raise ValueError(f"burst_cap must be >= 0, got "
                             f"{self.burst_cap}")


def _hash64(s: str) -> int:
    """Deterministic placement hash (never Python's salted hash())."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


def build_ring(n_shards: int,
               replicas: int = 16) -> tuple[list[int], list[int]]:
    """The directory's consistent-hash ring as ``(keys, shard_ids)``.

    Module-level (and jax-free) so the fleet front end can compute
    ``user -> shard`` with the exact arithmetic the directory routes
    by, without opening any journal."""
    points = sorted((_hash64(f"shard-{i}:{r}"), i)
                    for i in range(n_shards) for r in range(replicas))
    return [h for h, _ in points], [i for _, i in points]


def ring_shard_index(user: str, ring_keys: list[int],
                     ring_shards: list[int]) -> int:
    """Route ``user`` on a ring built by :func:`build_ring`."""
    j = bisect.bisect_right(ring_keys, _hash64(user)) % len(ring_keys)
    return ring_shards[j]


def _atomic_write(path: str, text: str, fsync: bool = True) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
        fh.flush()
        if fsync:
            os.fsync(fh.fileno())
    os.replace(tmp, path)


class _Shard:
    """One shard: resident user table + WAL + snapshot + cold spill.

    All state is guarded by one lock; every mutation is journaled
    (write-ahead) before it is applied in memory.
    """

    def __init__(self, base: str, user_budget: float,
                 renewal: RenewalPolicy, clock, fsync: bool,
                 max_resident: int | None, compact_every: int | None):
        self.snap_path = base + ".json"
        self.wal_path = base + ".wal"
        self.cold_path = base + ".cold"
        self.user_budget = float(user_budget)
        self.renewal = renewal
        self.clock = clock
        self.fsync = fsync
        self.max_resident = max_resident
        self.compact_every = compact_every
        self._lock = threading.Lock()
        self._users: OrderedDict[str, dict] = OrderedDict()  # guarded by: _lock
        self._cold_index: dict[str, int] = {}  # guarded by: _lock
        self._charge_ids: dict[str, None] = {}  # guarded by: _lock
        self._gen = 0  # guarded by: _lock
        self._dirty = 0  # guarded by: _lock
        self._cold_end = 0  # guarded by: _lock
        self._cold_dead = 0  # dead (superseded) spill lines, guarded by: _lock
        self._failed: DirectoryCorruptError | None = None  # guarded by: _lock
        self.counters = {  # guarded by: _lock
            "charges": 0, "refunds": 0, "dedups": 0, "refusals": 0,
            "renewals": 0, "evictions": 0, "rehydrations": 0,
            "compactions": 0, "charged_eps": 0.0, "refunded_eps": 0.0,
        }
        # recovery is the shared jax-free core (obs.budget_replay):
        # snapshot + generation-checked WAL replay, quarantining
        # anything unparseable. Constructor-only, so no concurrency and
        # no chaos points — the registered crash windows are in the
        # live mutation paths; recovery itself must run to completion.
        rec = load_shard(base)
        self._gen = rec["gen"]
        self._users = OrderedDict(rec["users"])
        self._charge_ids = dict(rec["charge_ids"])
        while len(self._charge_ids) > _CHARGE_ID_CAP:
            self._charge_ids.pop(next(iter(self._charge_ids)))
        self._dirty = rec["wal_entries"]
        if rec["wal_fresh_needed"]:
            self._write_fresh_wal_locked()
        # dpcorr-lint: ignore[durability-bare-write] — within-process spill cache, reset on open
        self._cold = open(self.cold_path, "w+", encoding="utf-8")  # guarded by: _lock
        self._evict_down_locked(fire_chaos=False)

    # -- journaling --------------------------------------------------

    def _write_fresh_wal_locked(self) -> None:
        _atomic_write(self.wal_path,
                      json.dumps({"k": "wal", "gen": self._gen}) + "\n",
                      fsync=self.fsync)

    def _wal_append_locked(self, entries: list[dict]) -> None:
        data = "".join(json.dumps(e) + "\n" for e in entries)
        with open(self.wal_path, "a", encoding="utf-8") as fh:
            fh.write(data)
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())

    def _remember_locked(self, charge_id: str) -> None:
        self._charge_ids[charge_id] = None
        while len(self._charge_ids) > _CHARGE_ID_CAP:
            self._charge_ids.pop(next(iter(self._charge_ids)))

    # -- residency ---------------------------------------------------

    def _check_failed_locked(self) -> None:
        if self._failed is not None:
            raise self._failed

    def _touch_locked(self, user: str) -> dict:
        st = self._users.get(user)
        if st is not None:
            self._users.move_to_end(user)
            return st
        off = self._cold_index.pop(user, None)
        if off is not None:
            st = self._read_cold_locked(user, off)
            self.counters["rehydrations"] += 1
            # the user's spill line is now dead; reclaimed once dead
            # lines outnumber live ones (_evict_down_locked)
            self._cold_dead += 1
        else:
            st = _fresh_user(float(self.clock()))
        self._users[user] = st
        return st

    def _read_cold_locked(self, user: str, off: int) -> dict:
        try:
            self._cold.seek(off)
            entry = json.loads(self._cold.readline())
            if entry["u"] != user:
                raise ValueError(f"spill offset {off} holds "
                                 f"{entry['u']!r}, wanted {user!r}")
            st = entry["st"]
            return {"s": float(st["s"]), "l": float(st["l"]),
                    "b": float(st["b"]), "w": float(st["w"])}
        except (json.JSONDecodeError, OSError, KeyError, TypeError,
                ValueError) as e:
            # fail the whole shard, not just this read: evicted users'
            # in-memory state lived only in the spill, so continuing
            # would silently forget their spend. Every later mutation
            # or read re-raises the same loud quarantine error; a
            # restart recovers from the authoritative snapshot + WAL.
            self._cold.close()
            self._failed = _corrupt(self.cold_path, str(e))
            raise self._failed from e

    def _peek_locked(self, user: str) -> dict | None:
        """Read-only view: no LRU touch, no rehydration churn."""
        st = self._users.get(user)
        if st is not None:
            return st
        off = self._cold_index.get(user)
        if off is not None:
            return self._read_cold_locked(user, off)
        return None

    def _evict_down_locked(self, fire_chaos: bool = True) -> None:
        if self.max_resident is None:
            return
        while len(self._users) > self.max_resident:
            user = next(iter(self._users))
            st = self._users[user]
            off = self._cold_end
            line = json.dumps({"u": user, "st": st}) + "\n"
            self._cold.seek(off)
            self._cold.write(line)
            self._cold.flush()
            self._cold_end = off + len(line)
            if fire_chaos:
                # the spill append landed but the user is still
                # resident: the authoritative snapshot+WAL state is
                # untouched, so a kill here loses nothing
                chaos.point("budget.mid_eviction")
            del self._users[user]
            self._cold_index[user] = off
            self.counters["evictions"] += 1
        # rehydration leaves the old spill line behind and _cold_end
        # only advances, so under residency churn dead lines would
        # otherwise grow the file forever; rewriting once they
        # outnumber live ones bounds it at ~2x the live set
        if self._cold_dead > max(16, len(self._cold_index)):
            self._write_cold_locked(
                {u: self._read_cold_locked(u, off)
                 for u, off in self._cold_index.items()})

    def _write_cold_locked(self, states: dict[str, dict]) -> None:
        """Rewrite the spill to hold exactly ``states``, compactly."""
        self._cold.seek(0)
        self._cold.truncate()
        self._cold_end = 0
        self._cold_dead = 0
        self._cold_index = {}
        for user, st in states.items():
            line = json.dumps({"u": user, "st": st}) + "\n"
            self._cold.write(line)
            self._cold_index[user] = self._cold_end
            self._cold_end += len(line)
        self._cold.flush()

    # -- renewal -----------------------------------------------------

    def _pending_renewal_locked(self, st: dict
                                ) -> tuple[float, float] | None:
        """The post-renewal ``(window_start, burst)`` for ``st`` when a
        window refresh is due, else None — computed WITHOUT mutating
        anything: admission is checked against this view first, and
        the renewal is journaled together with the charge it admits in
        one fsynced append, so a refused request leaves no durable
        trace at all (not even the renewal)."""
        now = float(self.clock())
        if now < st["w"] + self.renewal.period_s:
            return None
        periods = int((now - st["w"]) // self.renewal.period_s)
        s, b = st["s"], st["b"]
        # after two spend-free iterations the carry is at a fixed
        # point, so a long-idle user needs at most a few steps
        for _ in range(min(periods, 4)):
            b = min(self.renewal.burst_cap,
                    max(0.0, self.user_budget + b - s))
            s = 0.0
        return st["w"] + self.renewal.period_s * periods, b

    # -- mutations ---------------------------------------------------

    def charge(self, user: str, eps: float,
               charge_id: str | None = None) -> bool:
        """Admit-or-refuse one user-leg charge. Returns True when the
        charge applied, False when ``charge_id`` dedup'd it; raises
        :class:`~dpcorr.serve.ledger.BudgetExceededError` (level
        ``user``) when the window budget + burst would be overdrawn —
        without journaling or applying anything (a due renewal is
        checked against, but journaled and applied only together with
        an admitted charge, so refusals are trace-free exactly)."""
        if eps < 0.0:
            raise ValueError(f"negative charge {eps} for user {user!r}")
        with self._lock:
            self._check_failed_locked()
            if charge_id is not None and charge_id in self._charge_ids:
                self.counters["dedups"] += 1
                return False
            st = self._touch_locked(user)
            renewed = self._pending_renewal_locked(st)
            win_s = 0.0 if renewed is not None else st["s"]
            win_b = renewed[1] if renewed is not None else st["b"]
            cap = self.user_budget + win_b
            # strict > with tolerance, matching the party ledger: a
            # charge landing exactly on the cap is admitted
            if win_s + eps > cap + 1e-12:
                self.counters["refusals"] += 1
                raise BudgetExceededError(USER_PREFIX + user, win_s,
                                          eps, cap)
            lines = []
            if renewed is not None:
                lines.append({"k": "n", "u": user, "w": renewed[0],
                              "b": renewed[1]})
            # the entry carries the (post-renewal) window state: a
            # recovery that has to re-CREATE this user from the WAL
            # (no snapshot line yet) must restore the true window
            # start — rebuilding with w=0.0 would fire a spurious
            # renewal on the first post-restart charge and let the
            # window budget be overspent
            lines.append({"k": "c", "u": user, "e": eps,
                          "id": charge_id,
                          "w": renewed[0] if renewed is not None
                          else st["w"], "b": win_b})
            chaos.point("budget.pre_journal")
            # dpcorr-lint: ignore[blocking-under-lock] — WAL-before-ack: fsync order IS the serialization order
            self._wal_append_locked(lines)
            chaos.point("budget.post_journal")
            if renewed is not None:
                st["w"], st["b"] = renewed
                st["s"] = 0.0
                self.counters["renewals"] += 1
            st["s"] += eps
            st["l"] += eps
            if charge_id is not None:
                self._remember_locked(charge_id)
            self.counters["charges"] += 1
            self.counters["charged_eps"] += eps
            self._dirty += len(lines)
            self._evict_down_locked()
            # dpcorr-lint: ignore[blocking-under-lock] — compaction must see a quiesced shard
            self._maybe_compact_locked()
            return True

    def refund(self, user: str, eps: float,
               charge_id: str | None = None) -> None:
        """Reverse a user-leg charge whose query never executed.
        Clamps at zero like the party ledger (a stray refund can only
        over-count, never under-count) and forgets the charge_id so a
        genuinely new charge may reuse it."""
        if eps < 0.0:
            raise ValueError(f"negative refund {eps} for user {user!r}")
        with self._lock:
            self._check_failed_locked()
            st = self._touch_locked(user)
            # w/b carried for the same WAL-only re-creation case as
            # charge entries
            # dpcorr-lint: ignore[blocking-under-lock] — WAL-before-ack: fsync order IS the serialization order
            self._wal_append_locked(
                [{"k": "r", "u": user, "e": eps, "id": charge_id,
                  "w": st["w"], "b": st["b"]}])
            st["s"] = max(0.0, st["s"] - eps)
            st["l"] = max(0.0, st["l"] - eps)
            if charge_id is not None:
                self._charge_ids.pop(charge_id, None)
            self.counters["refunds"] += 1
            self.counters["refunded_eps"] += eps
            self._dirty += 1
            self._evict_down_locked()
            # dpcorr-lint: ignore[blocking-under-lock] — compaction must see a quiesced shard
            self._maybe_compact_locked()

    # -- compaction --------------------------------------------------

    def _maybe_compact_locked(self) -> None:
        if self.compact_every is None or self._dirty < self.compact_every:
            return
        self._compact_locked()

    def _compact_locked(self) -> None:
        users = dict(self._users)
        cold_states = {user: self._read_cold_locked(user, off)
                       for user, off in self._cold_index.items()}
        users.update(cold_states)
        gen = self._gen + 1
        state = {"version": _DIR_VERSION, "gen": gen, "users": users,
                 "charge_ids": list(self._charge_ids)}
        _atomic_write(self.snap_path, json.dumps(state),
                      fsync=self.fsync)
        # the torn window: snapshot now says gen+1, the WAL still says
        # gen — recovery discards the stale WAL instead of replaying
        # entries the snapshot already folded in
        chaos.point("budget.mid_compaction")
        self._gen = gen
        self._write_fresh_wal_locked()
        self._dirty = 0
        self.counters["compactions"] += 1
        # every spilled state was just read anyway — rewrite the spill
        # compactly so dead bytes from rehydration churn are reclaimed
        self._write_cold_locked(cold_states)

    # -- views -------------------------------------------------------

    def spent(self, user: str) -> float:
        with self._lock:
            self._check_failed_locked()
            st = self._peek_locked(user)
            return st["s"] if st is not None else 0.0

    def lifetime(self, user: str) -> float:
        with self._lock:
            self._check_failed_locked()
            st = self._peek_locked(user)
            return st["l"] if st is not None else 0.0

    def headroom(self, user: str) -> float:
        with self._lock:
            self._check_failed_locked()
            st = self._peek_locked(user)
            if st is None:
                return self.user_budget
            return self.user_budget + st["b"] - st["s"]

    def stats_locked_view(self) -> dict:
        with self._lock:
            return {"resident": len(self._users),
                    "evicted": len(self._cold_index),
                    "counters": dict(self.counters)}

    def close(self) -> None:
        with self._lock:
            if not self._cold.closed:  # quarantine already closed it
                self._cold.close()


class BudgetDirectory:
    """Consistent-hash directory of :class:`_Shard` budget journals.

    ``root`` is a directory; the shard count is written to
    ``meta.json`` on first creation and **pinned** — a reopen adopts
    the persisted count (re-hashing users onto a different ring would
    silently split balances). All reads/writes are routed by a sha256
    ring (``replicas`` points per shard), deterministic across
    processes and restarts.
    """

    def __init__(self, root: str, shards: int = 8,
                 user_budget: float = 1.0,
                 renewal: RenewalPolicy | None = None,
                 max_resident: int | None = None,
                 compact_every: int | None = 256,
                 replicas: int = 16, clock=time.time,
                 fsync: bool = True,
                 audit: AuditTrail | None = None,
                 lease=None):
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.root = str(root)
        self.audit = audit
        os.makedirs(self.root, exist_ok=True)
        meta_path = os.path.join(self.root, "meta.json")
        sweep_stale_tmp(meta_path)
        if os.path.exists(meta_path):
            try:
                with open(meta_path, encoding="utf-8") as fh:
                    meta = json.load(fh)
                shards = int(meta["shards"])
            except (json.JSONDecodeError, UnicodeDecodeError, OSError,
                    KeyError, TypeError, ValueError) as e:
                raise _corrupt(meta_path, str(e)) from e
        else:
            _atomic_write(meta_path, json.dumps(
                {"version": _DIR_VERSION, "shards": shards}))
        self.n_shards = shards
        self.renewal = renewal if renewal is not None else RenewalPolicy()
        self.user_budget = float(user_budget)
        self._mk = lambda i: _Shard(
            os.path.join(self.root, f"shard-{i:04d}"),
            self.user_budget, self.renewal, clock, fsync,
            max_resident, compact_every)
        self._lease = lease
        self._open_lock = threading.Lock()
        if lease is None:
            # single-owner mode: eager, exactly the pre-fleet behavior
            self._shards: list[_Shard | None] = \
                [self._mk(i) for i in range(shards)]
        else:
            # fleet mode: the directory is SHARED on disk; a shard's
            # journal opens lazily and only while this process holds
            # its lease, so two replicas never have the same WAL open
            self._shards = [None] * shards
            lease.bind(shards, on_lost=self.drop_shard)
        self._ring_keys, self._ring_shards = build_ring(shards, replicas)

    def shard_index(self, user: str) -> int:
        return ring_shard_index(user, self._ring_keys, self._ring_shards)

    def _shard_at(self, i: int) -> _Shard:
        """The open shard journal, gated on lease ownership when the
        directory is fleet-shared. Raises the lease layer's
        ``ShardNotOwnedError`` (charge-free — nothing was touched)
        when another replica owns shard ``i``."""
        if self._lease is not None:
            self._lease.ensure_owned(i)
        s = self._shards[i]
        if s is None:
            with self._open_lock:
                s = self._shards[i]
                if s is None:
                    s = self._mk(i)
                    self._shards[i] = s
        return s

    def drop_shard(self, i: int) -> None:
        """Close shard ``i``'s journal (lease lost/released): the next
        owner replays the WAL; this process re-opens only after it
        re-acquires."""
        with self._open_lock:
            s = self._shards[i]
            self._shards[i] = None
        if s is not None:
            s.close()

    def _shard(self, user: str) -> _Shard:
        return self._shard_at(self.shard_index(user))

    # -- accounting --------------------------------------------------

    def charge(self, user: str, eps: float,
               trace_id: str | None = None,
               charge_id: str | None = None) -> bool:
        """Charge one user leg; audit-recorded under the ``user/``
        principal after the WAL append is durable (the same
        observe-after-persist ordering the party ledger keeps).
        Returns whether the charge applied (False = the shard already
        held ``charge_id`` and this call spent nothing)."""
        key = USER_PREFIX + user
        try:
            applied = self._shard(user).charge(user, eps,
                                               charge_id=charge_id)
        except BudgetExceededError as e:
            if self.audit is not None:
                self.audit.record("refusal", {key: eps},
                                  trace_id=trace_id, party=key,
                                  spent=e.spent, budget=e.budget)
            raise
        if self.audit is not None:
            detail = {} if charge_id is None else {"charge_id": charge_id}
            if not applied:
                detail["dedup"] = True
            self.audit.record("charge", {key: eps}, trace_id=trace_id,
                              **detail)
        return applied

    def refund(self, user: str, eps: float,
               trace_id: str | None = None,
               charge_id: str | None = None,
               reason: str | None = None) -> None:
        key = USER_PREFIX + user
        self._shard(user).refund(user, eps, charge_id=charge_id)
        if self.audit is not None:
            detail = {} if charge_id is None else {"charge_id": charge_id}
            if reason is not None:
                detail["reason"] = reason
            self.audit.record("refund", {key: eps}, trace_id=trace_id,
                              **detail)

    # -- views -------------------------------------------------------

    def spent(self, user: str) -> float:
        return self._shard(user).spent(user)

    def lifetime(self, user: str) -> float:
        return self._shard(user).lifetime(user)

    def headroom(self, user: str) -> float:
        return self._shard(user).headroom(user)

    def counters(self) -> dict:
        totals: dict = {}
        resident = evicted = 0
        for s in self._shards:
            if s is None:  # fleet mode: lease not held, journal closed
                continue
            view = s.stats_locked_view()
            resident += view["resident"]
            evicted += view["evicted"]
            for k, v in view["counters"].items():
                totals[k] = totals.get(k, 0) + v
        totals["resident_users"] = resident
        totals["evicted_users"] = evicted
        return totals

    def snapshot(self) -> dict:
        """Point-in-time directory view (the /stats block's shape)."""
        c = self.counters()
        return {"shards": self.n_shards,
                "user_budget": self.user_budget,
                "renew_period_s": self.renewal.period_s,
                "burst_cap": self.renewal.burst_cap,
                "resident_users": c.pop("resident_users"),
                "evicted_users": c.pop("evicted_users"),
                "counters": c}

    def close(self) -> None:
        for i in range(self.n_shards):
            self.drop_shard(i)


def _leg_id(charge_id: str | None, key: str) -> str | None:
    """Derived per-leg charge_id: keeps the directory's idempotency
    keyed to the same logical charge as the party ledger's, without
    the two stores sharing an id namespace."""
    return None if charge_id is None else f"{charge_id}#{key}"


class CompositeLedger:
    """Per-user + per-party + global admission as one atomic charge.

    Drop-in for :class:`~dpcorr.serve.ledger.PrivacyLedger` wherever a
    charge/refund sink is expected (the coalescer's refund path, the
    protocol :class:`~dpcorr.protocol.gate.ReleaseGate`): ``charge``
    augments the per-party dict with a ``user/<id>`` leg (the bound
    ``user``, or per-request via :meth:`charge_request`) and a
    ``global/total`` leg, each equal to the total party ε of the
    charge. The global leg is charged inside the *same*
    ``PrivacyLedger.charge`` as the party legs (as a reserved
    principal with its own budget override), so party+global are
    atomic by construction; the user leg is charged first in the
    directory and compensated on any party/global refusal — hence a
    refused request consumes zero ε at every level, and the refusal's
    :class:`~dpcorr.serve.ledger.BudgetExceededError` names which
    level refused (``e.level``: user | party | global).

    ``refund`` performs the same augmentation, so a caller holding
    only the original per-party dict (the gate's transport-failure
    path) and a caller holding the augmented dict (the coalescer's
    shed path) both reverse every leg — one refund path.
    """

    def __init__(self, ledger: PrivacyLedger,
                 directory: BudgetDirectory | None,
                 user: str | None = None,
                 global_budget: float | None = None):
        self.ledger = ledger
        self.directory = directory
        self.user = user
        self.global_budget = (None if global_budget is None
                              else float(global_budget))
        if self.global_budget is not None:
            # the reserved principal rides the party ledger's own
            # atomic check+spend+persist — no second commit point
            ledger.per_party[GLOBAL_KEY] = self.global_budget
        self._lock = threading.Lock()
        self._refusals = {"user": 0, "party": 0, "global": 0}  # guarded by: _lock

    # -- augmentation ------------------------------------------------

    def augment(self, charges: Mapping[str, float],
                user: str | None = None) -> dict[str, float]:
        """Add the derived user/global legs to a per-party charge
        dict. Idempotent: legs already present are left untouched, so
        an augmented dict can round-trip through the coalescer's
        refund path unchanged."""
        out = {k: float(v) for k, v in charges.items()}
        total = sum(v for k, v in out.items() if not is_reserved(k))
        uid = user if user is not None else self.user
        if uid is not None \
                and not any(k.startswith(USER_PREFIX) for k in out):
            out[USER_PREFIX + uid] = total
        if self.global_budget is not None and GLOBAL_KEY not in out:
            out[GLOBAL_KEY] = total
        return out

    # -- the one atomic charge / one refund path ---------------------

    def charge(self, charges: Mapping[str, float],
               trace_id: str | None = None,
               charge_id: str | None = None) -> list[str]:
        """All-or-nothing across every level. User legs charge the
        directory first (idempotent per-leg charge_ids derived from
        ``charge_id``); the party+global legs then charge the wrapped
        ledger atomically. ANY in-process failure of a later leg — a
        budget refusal, but equally an OSError or corruption error
        persisting the party snapshot — compensates the directory legs
        THIS call applied and re-raises, so no exception path leaves a
        user leg charged for a query that never executed. A leg the
        directory deduped (its derived charge_id already durable — a
        retry of a charge a dying replica made) spent nothing here, so
        compensation must not reverse it: the earlier charge stands
        until the logical request succeeds (then the success dedups
        too — exactly one spend) or is abandoned (over-count, the
        privacy-safe direction). Only a hard process death between the
        two stores escapes compensation (``SimulatedCrash`` is a
        BaseException for exactly this reason): recovered the same way
        when a ``charge_id`` is present. Returns the deduped user-leg
        keys so callers can strip them from the dict they would later
        refund."""
        aug = self.augment(charges)
        user_legs = [(k, v) for k, v in aug.items()
                     if k.startswith(USER_PREFIX)]
        rest = {k: v for k, v in aug.items()
                if not k.startswith(USER_PREFIX)}
        done: list[tuple[str, float]] = []
        deduped: list[str] = []
        try:
            if self.directory is not None:
                for key, eps in user_legs:
                    applied = self.directory.charge(
                        key[len(USER_PREFIX):], eps, trace_id=trace_id,
                        charge_id=_leg_id(charge_id, key))
                    if applied:
                        done.append((key, eps))
                    else:
                        deduped.append(key)
            self.ledger.charge(rest, trace_id=trace_id,
                               charge_id=charge_id)
        except Exception as e:
            if isinstance(e, BudgetExceededError):
                with self._lock:
                    self._refusals[e.level] = \
                        self._refusals.get(e.level, 0) + 1
                reason = f"refused_{e.level}"
            else:
                reason = "charge_failed"
            for key, eps in done:
                self.directory.refund(key[len(USER_PREFIX):], eps,
                                      trace_id=trace_id,
                                      charge_id=_leg_id(charge_id, key),
                                      reason=reason)
            raise
        return deduped

    def charge_request(self, req, trace_id: str | None = None,
                       charge_id: str | None = None) -> dict[str, float]:
        """Charge one request's spend across every level; returns the
        AUGMENTED charge dict — the server carries it through the
        coalescer so a shed refund reverses every leg. ``charge_id``
        (the request's durable retry identity) makes the user legs
        idempotent fleet-wide: the directory is shared, so a retry
        landing on a different replica dedups against the WAL-recovered
        charge_id set instead of double-spending. Deduped legs are
        stripped from the returned dict — this attempt did not make
        that spend, so no shed-path refund of this attempt may reverse
        it."""
        from dpcorr.serve.ledger import request_charges

        charges = self.augment(request_charges(req),
                               user=getattr(req, "user", None))
        deduped = self.charge(charges, trace_id=trace_id,
                              charge_id=charge_id)
        if deduped:
            charges = {k: v for k, v in charges.items()
                       if k not in deduped}
        return charges

    def refund(self, charges: Mapping[str, float],
               trace_id: str | None = None,
               charge_id: str | None = None,
               reason: str | None = None) -> None:
        """The one refund path: augments exactly like :meth:`charge`
        (no-op on an already-augmented dict) and reverses every leg —
        directory and ledger — for a query that provably never
        executed."""
        aug = self.augment(charges)
        if self.directory is not None:
            for k, v in aug.items():
                if k.startswith(USER_PREFIX):
                    self.directory.refund(k[len(USER_PREFIX):], v,
                                          trace_id=trace_id,
                                          charge_id=_leg_id(charge_id,
                                                            k),
                                          reason=reason)
        rest = {k: v for k, v in aug.items()
                if not k.startswith(USER_PREFIX)}
        self.ledger.refund(rest, trace_id=trace_id, charge_id=charge_id,
                           reason=reason)

    # -- passthrough views -------------------------------------------

    def spent(self, principal: str) -> float:
        if principal.startswith(USER_PREFIX) and self.directory is not None:
            return self.directory.spent(principal[len(USER_PREFIX):])
        return self.ledger.spent(principal)

    def remaining(self, principal: str) -> float:
        if principal.startswith(USER_PREFIX) and self.directory is not None:
            return self.directory.headroom(principal[len(USER_PREFIX):])
        return self.ledger.remaining(principal)

    def budget_for(self, party: str) -> float:
        return self.ledger.budget_for(party)

    def snapshot(self) -> dict:
        return self.ledger.snapshot()

    def refusals_by_level(self) -> dict[str, int]:
        with self._lock:
            return dict(self._refusals)

    def directory_snapshot(self) -> dict | None:
        """The /stats ``budget_dir`` block: shard/residency/counter
        view plus which level refused how often."""
        if self.directory is None:
            return None
        snap = self.directory.snapshot()
        snap["refusals_by_level"] = self.refusals_by_level()
        return snap

    @property
    def audit(self):
        return self.ledger.audit

    def close(self) -> None:
        if self.directory is not None:
            self.directory.close()
