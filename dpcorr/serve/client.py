"""Retrying clients: jittered backoff over the idempotent serve API.

PR 7 gave every request a retry identity (idempotency keys); this
module is the client half that makes retries *safe by construction*
(ISSUE 8):

- :class:`RetryingClient` wraps any estimate client (the in-process
  one or :class:`HttpEstimateClient`) and retries **refusals that can
  heal** — overload sheds, open circuit breakers, deadline expiries,
  timeouts, transport drops — with jittered exponential backoff that
  honors the server's ``Retry-After`` estimate and an overall deadline
  budget. Budget refusals are terminal and never retried: ε exhaustion
  does not heal by waiting.
- Every attempt of one logical request reuses ONE idempotency key
  (requests without an identity get a generated ``rc:`` key up front),
  so a retry whose predecessor actually executed replays the cached
  response — byte-identical, charge-once, noise-drawn-once — instead
  of re-running. The overload harness's duplicate storm proves this
  end-to-end (``idempotent_hits`` with a single ledger charge).

All jax-free: retry arithmetic is stdlib, and the HTTP client speaks
plain ``urllib`` against the serve front end.
"""

from __future__ import annotations

import dataclasses
import json
import random
import secrets
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import TimeoutError as _FuturesTimeout

from dpcorr.serve.coalescer import ServerOverloadedError
from dpcorr.serve.fleet.lease import ShardNotOwnedError
from dpcorr.serve.ledger import BudgetExceededError
from dpcorr.serve.overload import CircuitOpenError, DeadlineExpiredError
from dpcorr.serve.request import EstimateRequest, EstimateResponse


class RetriableTransportError(Exception):
    """The wire failed (connection refused/reset, 5xx without a typed
    refusal) — nothing is known about server state, but the request's
    idempotency key makes blind retry safe."""


#: refusals that can heal with time — what the client retries.
#: ShardNotOwnedError heals too: leases move (TTL expiry, on-demand
#: takeover), and the refusal was charge-free by construction.
RETRIABLE = (ServerOverloadedError, CircuitOpenError,
             DeadlineExpiredError, RetriableTransportError,
             ShardNotOwnedError, _FuturesTimeout, TimeoutError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff shape: ``base_delay_s * multiplier**k`` capped at
    ``max_delay_s``, multiplied by a uniform jitter in
    ``[1 - jitter, 1 + jitter]``, floored by the server's
    ``Retry-After`` when one was sent. ``deadline_s`` bounds the whole
    logical request (attempts + sleeps); ``max_attempts`` bounds the
    count."""

    max_attempts: int = 6
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: float | None = 30.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, "
                             f"got {self.max_attempts}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], "
                             f"got {self.jitter}")

    def delay_for(self, attempt: int, retry_after_s: float | None,
                  rng: random.Random) -> float:
        """Sleep before attempt ``attempt + 1`` (attempt is 1-based)."""
        d = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        if retry_after_s is not None:
            d = max(d, retry_after_s)
        return max(d, 0.0)


class RetryingClient:
    """Retry wrapper around an estimate client.

    ``client`` needs one method: ``estimate(req, timeout=...)``.
    ``clock``/``sleep``/``seed`` are injectable so tests can script
    time; ``seed`` pins the jitter stream (default: OS entropy).
    """

    def __init__(self, client, policy: RetryPolicy | None = None,
                 clock=time.monotonic, sleep=time.sleep,
                 seed: int | None = None):
        self.client = client
        self.policy = policy if policy is not None else RetryPolicy()
        self.clock = clock
        self.sleep = sleep
        self._rng = random.Random(seed if seed is not None
                                  else secrets.randbits(64))
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}  # guarded by: _lock

    def _count(self, what: str, k: int = 1) -> None:
        with self._lock:
            self._counts[what] = self._counts.get(what, 0) + k

    def stats(self) -> dict:
        with self._lock:
            return dict(self._counts)

    @staticmethod
    def _with_identity(req: EstimateRequest) -> EstimateRequest:
        """Pin ONE retry identity for every attempt of this logical
        request. Pinned-seed requests already have a content-derived
        key (serve.server); assigned-stream requests get a generated
        one so their retries are charge-once too — without it every
        retry would be a fresh draw and a fresh spend."""
        if req.idempotency_key is not None or req.seed is not None:
            return req
        return dataclasses.replace(
            req, idempotency_key=f"rc:{secrets.token_hex(16)}")

    def estimate(self, req: EstimateRequest,
                 timeout: float | None = 60.0) -> EstimateResponse:
        req = self._with_identity(req)
        t0 = self.clock()
        budget = self.policy.deadline_s
        last: Exception | None = None
        for attempt in range(1, self.policy.max_attempts + 1):
            self._count("attempts")
            try:
                resp = self.client.estimate(req, timeout=timeout)
            except RETRIABLE as e:
                last = e
                self._count("retryable")
                self._count(f"retryable:{type(e).__name__}")
            except BudgetExceededError:
                # terminal: waiting cannot un-spend ε
                self._count("terminal")
                raise
            else:
                self._count("successes")
                if attempt > 1:
                    self._count("recovered")
                    if isinstance(resp.cost, dict):
                        # client-side cost annotation: the server only
                        # sees attempts, the retry count is ours to
                        # stamp (the dict rides the frozen dataclass)
                        resp.cost["retries"] = \
                            resp.cost.get("retries", 0) + attempt - 1
                return resp
            if attempt == self.policy.max_attempts:
                break
            delay = self.policy.delay_for(
                attempt, getattr(last, "retry_after_s", None), self._rng)
            if budget is not None and \
                    self.clock() - t0 + delay > budget:
                break
            self._count("retries")
            self.sleep(delay)
        self._count("gave_up")
        raise last

    def submit(self, req: EstimateRequest):
        """Pass-through (no retry) — callers managing futures
        themselves own their retry loop."""
        return self.client.submit(req)


def request_to_json(req: EstimateRequest) -> dict:
    """The ``POST /estimate`` body for one request."""
    body = {"family": req.family,
            "x": [float(v) for v in req.x],
            "y": [float(v) for v in req.y],
            "eps1": req.eps1, "eps2": req.eps2,
            "party_x": req.party_x, "party_y": req.party_y,
            "alpha": req.alpha, "normalise": req.normalise,
            "seed": req.seed, "idempotency_key": req.idempotency_key,
            "priority": req.priority, "deadline_s": req.deadline_s,
            "user": req.user}
    return body


def _retry_after_from(headers) -> float | None:
    raw = headers.get("Retry-After") if headers is not None else None
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


class HttpEstimateClient:
    """Estimate client over the serve HTTP front end, mapping the
    typed refusal codes back onto the same exceptions the in-process
    client raises — so :class:`RetryingClient` composes with either."""

    def __init__(self, base_url: str, timeout_s: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def estimate(self, req: EstimateRequest,
                 timeout: float | None = None) -> EstimateResponse:
        blob = json.dumps(request_to_json(req)).encode()
        http_req = urllib.request.Request(
            f"{self.base_url}/estimate", data=blob,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    http_req, timeout=timeout if timeout is not None
                    else self.timeout_s) as r:
                body = json.load(r)
        except urllib.error.HTTPError as e:
            raise self._refusal(e) from e
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise RetriableTransportError(
                f"POST {self.base_url}/estimate failed: {e}") from e
        return EstimateResponse(
            rho_hat=body["rho_hat"], ci_low=body["ci_low"],
            ci_high=body["ci_high"], batched=body["batched"],
            batch_size=body["batch_size"], latency_s=body["latency_s"],
            seed=body["seed"], cost=body.get("cost"))

    @staticmethod
    def _refusal(e: urllib.error.HTTPError) -> Exception:
        try:
            body = json.load(e)
        except (json.JSONDecodeError, UnicodeDecodeError):
            body = {}
        msg = body.get("error", f"HTTP {e.code}")
        ra = _retry_after_from(e.headers)
        if e.code == 403 and body.get("refused") == "budget":
            return BudgetExceededError(
                body.get("party", "?"), float(body.get("spent", 0.0)),
                float(body.get("charge", 0.0)),
                float(body.get("budget", 0.0)))
        if e.code == 504:
            return DeadlineExpiredError(msg, retry_after_s=ra)
        if e.code == 503:
            return CircuitOpenError(msg, retry_after_s=ra)
        if e.code == 429:
            return ServerOverloadedError(msg, retry_after_s=ra)
        if e.code == 421:
            # fleet routing miss: this replica does not own the user's
            # budget shard (the front end normally forwards before a
            # client ever sees this; a direct client just retries)
            return ShardNotOwnedError(
                int(body.get("shard", -1)), owner=body.get("owner"),
                owner_url=body.get("owner_url"), retry_after_s=ra)
        if e.code >= 500:
            return RetriableTransportError(msg)
        return ValueError(msg)
