"""Replica supervision: boot, monitor, restart with identical argv.

The fleet's process manager. Each replica is a real ``dpcorr serve``
subprocess that prints a one-line JSON banner after binding; the
supervisor reads the banner to learn the bound port (replicas run
``--port 0``), then watches the process and — when it dies for any
reason, including the SIGKILL the failover drill throws — relaunches
it with the SAME argv. Identical argv is the failover contract: the
restarted replica reopens the same ledger/audit/WAL paths, recovers
its balances exactly, and (because its ``--instance`` name is stable)
reclaims its own shard leases instantly instead of waiting out the
TTL.

stdlib-only (jax-free): the heavy jax work happens inside the
replicas.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import threading
import time


@dataclasses.dataclass
class ReplicaSpec:
    """How to (re)launch one replica — the whole contract is "run
    exactly this again"."""

    name: str
    argv: list[str]
    env: dict[str, str] | None = None
    cwd: str | None = None
    stderr_path: str | None = None


class ReplicaDiedError(RuntimeError):
    pass


def read_banner(proc: subprocess.Popen, name: str,
                deadline_s: float = 300.0) -> dict:
    """The serve banner: first stdout line, a JSON object with a
    ``serving`` block. Slow under cold jax import — the deadline is
    generous and a dead process fails fast."""
    t0 = time.monotonic()
    while True:
        line = proc.stdout.readline()
        if line:
            line = line.strip()
            if not line:
                continue
            try:
                banner = json.loads(line)
            except json.JSONDecodeError:
                continue  # stray output before the banner
            if "serving" in banner:
                return banner
            continue
        if proc.poll() is not None:
            raise ReplicaDiedError(
                f"replica {name} exited rc={proc.returncode} "
                "before printing its banner")
        if time.monotonic() - t0 > deadline_s:
            raise TimeoutError(
                f"replica {name}: no banner within {deadline_s}s")
        time.sleep(0.05)


class Supervisor:
    """Boot N replicas, keep them running.

    ``on_up(name, url, banner)`` fires after every (re)boot once the
    banner is read — the front end re-targets a restarted replica
    there (``--port 0`` means the port changes across restarts even
    though the argv does not). ``on_down(name, returncode)`` fires
    when a death is noticed. ``kill(name)`` is the chaos input: the
    monitor treats an operator SIGKILL exactly like any other death.
    """

    def __init__(self, specs: list[ReplicaSpec], *,
                 restart: bool = True, max_restarts: int = 5,
                 backoff_s: float = 0.25, poll_s: float = 0.1,
                 banner_deadline_s: float = 300.0,
                 on_up=None, on_down=None):
        self.specs = {s.name: s for s in specs}
        if len(self.specs) != len(specs):
            raise ValueError("replica names must be unique")
        self.restart = restart
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.poll_s = poll_s
        self.banner_deadline_s = banner_deadline_s
        self.on_up = on_up
        self.on_down = on_down
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}  # guarded by: _lock
        self._urls: dict[str, str] = {}                # guarded by: _lock
        self._banners: dict[str, dict] = {}            # guarded by: _lock
        self.restarts: dict[str, int] = {}             # guarded by: _lock
        self._stopping = threading.Event()
        self._monitor: threading.Thread | None = None

    # -- launch ------------------------------------------------------

    def _spawn(self, spec: ReplicaSpec) -> subprocess.Popen:
        env = dict(os.environ)
        if spec.env:
            env.update(spec.env)
        stderr = (open(spec.stderr_path, "ab")
                  if spec.stderr_path else subprocess.DEVNULL)
        try:
            proc = subprocess.Popen(
                spec.argv, stdout=subprocess.PIPE, stderr=stderr,
                env=env, cwd=spec.cwd, text=True)
        finally:
            if stderr is not subprocess.DEVNULL:
                stderr.close()  # the child holds its own fd now
        return proc

    def _boot(self, spec: ReplicaSpec) -> None:
        proc = self._spawn(spec)
        banner = read_banner(proc, spec.name, self.banner_deadline_s)
        srv = banner.get("serving", {})
        host = srv.get("host", "127.0.0.1")
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
        url = f"http://{host}:{srv['port']}"
        with self._lock:
            self._procs[spec.name] = proc
            self._urls[spec.name] = url
            self._banners[spec.name] = banner
        if self.on_up is not None:
            self.on_up(spec.name, url, banner)

    def start(self) -> None:
        """Boot every replica (waiting for each banner), then start
        the monitor thread."""
        for spec in self.specs.values():
            self._boot(spec)
        self._monitor = threading.Thread(
            target=self._watch, name="fleet-supervisor", daemon=True)
        self._monitor.start()

    # -- monitoring --------------------------------------------------

    def _watch(self) -> None:
        while not self._stopping.is_set():
            for name, spec in list(self.specs.items()):
                with self._lock:
                    proc = self._procs.get(name)
                if proc is None:
                    continue
                rc = proc.poll()
                if rc is None or self._stopping.is_set():
                    continue
                with self._lock:
                    self._procs.pop(name, None)
                    self._urls.pop(name, None)
                    n = self.restarts.get(name, 0)
                if self.on_down is not None:
                    self.on_down(name, rc)
                if not self.restart or n >= self.max_restarts:
                    continue
                time.sleep(self.backoff_s)
                try:
                    self._boot(spec)  # IDENTICAL argv: the contract
                except (ReplicaDiedError, TimeoutError, OSError):
                    continue  # next poll retries while budget lasts
                with self._lock:
                    self.restarts[name] = n + 1
            self._stopping.wait(self.poll_s)

    # -- operator surface --------------------------------------------

    def url(self, name: str) -> str:
        with self._lock:
            return self._urls[name]

    def urls(self) -> dict[str, str]:
        with self._lock:
            return dict(self._urls)

    def pid(self, name: str) -> int | None:
        with self._lock:
            proc = self._procs.get(name)
        return None if proc is None else proc.pid

    def kill(self, name: str, sig: int = signal.SIGKILL) -> int:
        """Send ``sig`` to a replica (the failover drill's SIGKILL);
        returns the pid signalled. The monitor notices the death and
        restarts per policy."""
        with self._lock:
            proc = self._procs[name]
        proc.send_signal(sig)
        return proc.pid

    def wait_restarted(self, name: str, n: int = 1,
                       timeout_s: float = 300.0) -> str:
        """Block until ``name`` has been restarted at least ``n``
        times and is back up; returns its new url."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            with self._lock:
                if (self.restarts.get(name, 0) >= n
                        and name in self._urls):
                    return self._urls[name]
            time.sleep(0.05)
        raise TimeoutError(f"replica {name} not restarted within "
                           f"{timeout_s}s")

    def stop(self, timeout_s: float = 10.0) -> None:
        """Graceful teardown: terminate, wait, escalate to kill."""
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        with self._lock:
            procs = dict(self._procs)
            self._procs.clear()
            self._urls.clear()
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + timeout_s
        for proc in procs.values():
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
            if proc.stdout is not None:
                proc.stdout.close()
