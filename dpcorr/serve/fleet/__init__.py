"""Horizontally scaled serve: front-end router, leased budget shards,
process supervision.

The fleet subsystem (ISSUE 20) turns the single-process serve node
into N replicas behind one jax-free HTTP front end, without giving up
a single exactness invariant:

- :mod:`~dpcorr.serve.fleet.lease` — durable fsynced lease files grant
  each :class:`~dpcorr.serve.budget_dir.BudgetDirectory` shard to
  exactly one replica at a time (epoch-numbered, TTL + heartbeat),
  so any replica can admit any user without double-spend.
- :mod:`~dpcorr.serve.fleet.frontend` — health-checked routing with
  per-replica circuit state, Retry-After passthrough, and
  consistent-hash shard affinity keyed on the request's user.
- :mod:`~dpcorr.serve.fleet.supervisor` — boots/monitors/restarts
  replicas with identical argv, so a killed replica's shards are
  re-leased and its WAL-recovered balances stay exact.

Everything here is importable without jax: the front end and
supervisor are deployment-plane processes.
"""

from dpcorr.serve.fleet.frontend import (FleetFrontend,
                                         make_frontend_http_server)
from dpcorr.serve.fleet.lease import (LeaseKeeper, LeaseManager,
                                      ShardNotOwnedError, lease_table)
from dpcorr.serve.fleet.supervisor import ReplicaSpec, Supervisor

__all__ = [
    "FleetFrontend",
    "LeaseKeeper",
    "make_frontend_http_server",
    "LeaseManager",
    "ReplicaSpec",
    "ShardNotOwnedError",
    "Supervisor",
    "lease_table",
]
