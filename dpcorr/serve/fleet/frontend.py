"""The fleet front end: one jax-free HTTP router over N serve replicas.

Routing is three concentric hints, strongest first:

1. **Lease ownership** — a request carrying a ``user`` is routed to
   the replica whose lease file currently covers the user's budget
   shard (the shard is computed with the budget directory's own ring
   arithmetic, :func:`dpcorr.serve.budget_dir.build_ring`; the lease
   table is re-read on a short cadence). Routing to the owner makes
   ``ShardNotOwnedError`` the exception, not the rule.
2. **Shard affinity** — an unowned shard hashes onto the replica ring
   (consistent hashing over replica names), and the chosen replica
   acquires the lease on first touch (``acquire_on_demand``), so
   ownership converges onto the routing and stays stable as replicas
   come and go.
3. **Health** — replicas publish ``/readyz`` and the front end keeps
   per-replica circuit state (consecutive transport failures open the
   circuit; a cooldown probe closes it), so traffic flows around a
   dead or cold replica without waiting for its lease to expire.

Refusals pass through untouched — status code, body and
``Retry-After`` header — so :class:`~dpcorr.serve.client.
RetryingClient` pointed at the front end behaves exactly as if
pointed at a replica. The one code a client never sees is 421
(``ShardNotOwnedError``): the front end forwards to the owner the
refusing replica named, and only after the hop budget is exhausted
degrades to a 503 with a Retry-After, which the client's existing
breaker-retry path already handles. Requests without an idempotency
key or pinned seed get a generated ``fe:`` key before the first hop,
so a failover retry is charge-once even for raw (non-RetryingClient)
clients.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from dpcorr.serve.budget_dir import _hash64, build_ring, ring_shard_index
from dpcorr.serve.fleet import lease as lease_mod

_HOP_HEADER = "X-Dpcorr-Fleet-Hops"


class _Circuit:
    """Per-replica transport circuit: consecutive failures open it,
    a cooldown probe half-opens it. Guarded by the frontend lock."""

    def __init__(self, fail_threshold: int, cooldown_s: float):
        self.fail_threshold = fail_threshold
        self.cooldown_s = cooldown_s
        self.failures = 0
        self.open_until = 0.0
        self.opened = 0

    def ok(self) -> None:
        self.failures = 0
        self.open_until = 0.0

    def fail(self, now: float) -> None:
        self.failures += 1
        if self.failures >= self.fail_threshold:
            self.open_until = now + self.cooldown_s
            self.opened += 1

    def allows(self, now: float) -> bool:
        # past open_until the circuit half-opens: one probe rides
        return now >= self.open_until

    def snapshot(self, now: float) -> dict:
        return {"failures": self.failures, "opened": self.opened,
                "open": now < self.open_until}


class FleetFrontend:
    """Routing core (transport-agnostic): :meth:`route` takes a raw
    ``POST /estimate`` body and returns ``(status, headers, body)``.
    :func:`make_frontend_http_server` wraps it for the wire.

    ``replicas`` maps instance name → base url; the supervisor's
    ``on_up`` callback re-targets restarted replicas through
    :meth:`set_replica`. ``lease_dir`` (shared with the replicas)
    supplies the shard count and ownership table; without it, routing
    falls back to user-keyed affinity over healthy replicas.
    """

    def __init__(self, replicas: dict[str, str],
                 lease_dir: str | None = None, *,
                 affinity_points: int = 16, fail_threshold: int = 3,
                 cooldown_s: float = 1.0, table_ttl_s: float = 0.5,
                 timeout_s: float = 60.0, max_hops: int = 4,
                 retry_after_s: float = 0.5,
                 clock=time.monotonic):
        self.lease_dir = lease_dir
        self.affinity_points = int(affinity_points)
        self.timeout_s = float(timeout_s)
        self.max_hops = int(max_hops)
        self.retry_after_s = float(retry_after_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._urls: dict[str, str] = {}        # guarded by: _lock
        self._circuits: dict[str, _Circuit] = {}  # guarded by: _lock
        self._ready: dict[str, bool] = {}      # guarded by: _lock
        self._rr = 0                           # guarded by: _lock
        self._counts: dict[str, int] = {}      # guarded by: _lock
        self._fail_threshold = int(fail_threshold)
        self._cooldown_s = float(cooldown_s)
        self._table_ttl_s = float(table_ttl_s)
        self._table: dict[int, dict] = {}      # guarded by: _lock
        self._table_at = -1e18                 # guarded by: _lock
        self._ring = None  # (keys, shards) once the lease meta exists
        for name, url in replicas.items():
            self.set_replica(name, url)

    # -- fleet membership --------------------------------------------

    def set_replica(self, name: str, url: str) -> None:
        """Add or re-target a replica (the supervisor's on_up hook —
        a restarted replica on ``--port 0`` keeps its name, changes
        its url). Resets its circuit: a fresh boot deserves traffic."""
        with self._lock:
            self._urls[name] = url.rstrip("/")
            self._circuits[name] = _Circuit(self._fail_threshold,
                                            self._cooldown_s)
            self._ready.setdefault(name, True)

    def drop_replica(self, name: str) -> None:
        with self._lock:
            self._urls.pop(name, None)
            self._circuits.pop(name, None)
            self._ready.pop(name, None)

    def set_ready(self, name: str, ready: bool) -> None:
        with self._lock:
            if name in self._urls:
                self._ready[name] = bool(ready)

    def _count(self, what: str, k: int = 1) -> None:
        with self._lock:
            self._counts[what] = self._counts.get(what, 0) + k

    # -- shard arithmetic / lease table ------------------------------

    def _shard_of(self, user: str) -> int | None:
        if self.lease_dir is None:
            return None
        if self._ring is None:
            meta = lease_mod.read_meta(self.lease_dir)
            if meta is None:
                return None  # no replica has bound yet
            self._ring = build_ring(int(meta["shards"]))
        return ring_shard_index(user, *self._ring)

    def _lease_owner(self, shard: int) -> tuple[str | None, str | None]:
        """(owner, url) for a shard whose lease is live, else Nones."""
        if self.lease_dir is None:
            return None, None
        with self._lock:
            stale = self.clock() - self._table_at > self._table_ttl_s
        if stale:
            table = lease_mod.lease_table(self.lease_dir)
            with self._lock:
                self._table = table
                self._table_at = self.clock()
        with self._lock:
            rec = self._table.get(shard)
        if rec is None:
            return None, None
        if time.time() >= float(rec.get("expires_at", 0.0)):
            return None, None
        return rec.get("owner"), rec.get("url")

    def _affinity(self, key: str, names: list[str]) -> list[str]:
        """Consistent-hash order of ``names`` for ``key``: the ring
        walk from the key's position — stable under membership
        change, which is the whole point."""
        if not names:
            return []
        points = sorted((_hash64(f"replica:{n}:{r}"), n)
                        for n in names for r in range(self.affinity_points))
        h = _hash64(key)
        order: list[str] = []
        start = 0
        while start < len(points) and points[start][0] <= h:
            start += 1
        for i in range(len(points)):
            n = points[(start + i) % len(points)][1]
            if n not in order:
                order.append(n)
        return order

    def _candidates(self, user: str | None) -> list[str]:
        """Route order: lease owner first, then shard-affinity walk,
        then the remaining healthy replicas; round-robin for userless
        requests."""
        now = self.clock()
        with self._lock:
            healthy = [n for n, u in sorted(self._urls.items())
                       if self._ready.get(n, True)
                       and self._circuits[n].allows(now)]
            everyone = sorted(self._urls)
            self._rr += 1
            rr = self._rr
        pool = healthy if healthy else everyone  # last resort: probe
        if not pool:
            return []
        if user is None:
            return pool[rr % len(pool):] + pool[:rr % len(pool)]
        shard = self._shard_of(user)
        key = user if shard is None else f"shard:{shard}"
        order = self._affinity(key, pool)
        if shard is not None:
            owner, _url = self._lease_owner(shard)
            if owner in order:
                order.remove(owner)
                order.insert(0, owner)
        return order

    # -- the hop loop ------------------------------------------------

    def _post(self, url: str, body: bytes, hops: int):
        req = urllib.request.Request(
            f"{url}/estimate", data=body,
            headers={"Content-Type": "application/json",
                     _HOP_HEADER: str(hops)})
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    def _mark(self, name: str, ok: bool) -> None:
        with self._lock:
            c = self._circuits.get(name)
            if c is None:
                return
            if ok:
                c.ok()
            else:
                c.fail(self.clock())

    def route(self, body: bytes) -> tuple[int, list[tuple[str, str]],
                                          bytes]:
        """One logical ``POST /estimate``: pick candidates, hop until
        a replica answers (any HTTP status except 421 is an answer —
        passthrough), forward 421s to the named owner, and degrade to
        a retryable 503 when the hop budget runs out."""
        try:
            parsed = json.loads(body)
            user = parsed.get("user")
        except (json.JSONDecodeError, UnicodeDecodeError, AttributeError):
            user, parsed = None, None
        if (parsed is not None and parsed.get("idempotency_key") is None
                and parsed.get("seed") is None):
            # failover identity for raw clients: every hop/retry of
            # this logical request now dedups server-side
            import secrets as _secrets

            parsed["idempotency_key"] = f"fe:{_secrets.token_hex(16)}"
            body = json.dumps(parsed).encode()
        self._count("requests")
        tried: list[str] = []
        queue = self._candidates(None if user is None else str(user))
        hops = 0
        while queue and hops < self.max_hops:
            name = queue.pop(0)
            if name in tried:
                continue
            tried.append(name)
            hops += 1
            with self._lock:
                url = self._urls.get(name)
            if url is None:
                continue
            try:
                with self._post(url, body, hops) as r:
                    payload = r.read()
                    self._mark(name, ok=True)
                    self._count(f"routed:{name}")
                    return (r.status, self._passthrough(r.headers),
                            payload)
            except urllib.error.HTTPError as e:
                payload = e.read()
                self._mark(name, ok=True)  # the wire worked
                if e.code == 421:
                    self._count("forwards")
                    nxt = self._owner_from_421(payload)
                    if nxt is not None and nxt not in tried:
                        queue.insert(0, nxt)
                    continue
                self._count(f"routed:{name}")
                return e.code, self._passthrough(e.headers), payload
            except (urllib.error.URLError, ConnectionError,
                    TimeoutError, OSError):
                self._mark(name, ok=False)
                self._count("transport_errors")
                continue
        self._count("no_owner")
        blob = json.dumps({
            "error": "no healthy replica could serve the request "
                     f"(tried {tried or 'none'})",
            "refused": "breaker"}).encode()
        ra = str(max(1, int(self.retry_after_s + 0.999)))
        return 503, [("Content-Type", "application/json"),
                     ("Retry-After", ra)], blob

    @staticmethod
    def _passthrough(headers) -> list[tuple[str, str]]:
        out = [("Content-Type", "application/json")]
        ra = headers.get("Retry-After") if headers is not None else None
        if ra is not None:
            out.append(("Retry-After", ra))
        return out

    def _owner_from_421(self, payload: bytes) -> str | None:
        """The refusing replica names the current owner; route there
        next if we know it (by name), or learn its url on the fly."""
        try:
            body = json.loads(payload)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None
        owner = body.get("owner")
        url = body.get("owner_url")
        with self._lock:
            if owner is not None and owner in self._urls:
                return owner
            if owner is not None and url:
                self._urls[owner] = url.rstrip("/")
                self._circuits[owner] = _Circuit(self._fail_threshold,
                                                 self._cooldown_s)
                self._ready[owner] = True
                return owner
        return None

    # -- health polling ----------------------------------------------

    def poll_ready(self) -> dict[str, bool]:
        """One readiness sweep (call on a cadence, or rely on circuit
        state alone): GET /readyz per replica, 200 → ready."""
        with self._lock:
            targets = dict(self._urls)
        out: dict[str, bool] = {}
        for name, url in targets.items():
            try:
                with urllib.request.urlopen(f"{url}/readyz",
                                            timeout=2.0) as r:
                    out[name] = r.status == 200
            except urllib.error.HTTPError:
                out[name] = False
            except (urllib.error.URLError, ConnectionError, OSError):
                out[name] = False
            self.set_ready(name, out[name])
        return out

    # -- views -------------------------------------------------------

    def stats(self) -> dict:
        now = self.clock()
        if self.lease_dir is not None:
            table = lease_mod.lease_table(self.lease_dir)
        else:
            table = {}
        with self._lock:
            return {
                "replicas": {
                    n: {"url": self._urls[n],
                        "ready": self._ready.get(n, True),
                        "circuit": self._circuits[n].snapshot(now)}
                    for n in sorted(self._urls)},
                "counts": dict(self._counts),
                "leases": {
                    str(s): {"owner": rec.get("owner"),
                             "epoch": rec.get("epoch"),
                             "expires_in_s": round(
                                 float(rec.get("expires_at", 0.0))
                                 - time.time(), 3)}
                    for s, rec in sorted(table.items())},
            }


def make_frontend_http_server(frontend: FleetFrontend,
                              host: str = "127.0.0.1", port: int = 0):
    """Build (not start) the front end's HTTP server — same contract
    as :func:`dpcorr.serve.server.make_http_server`."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, headers, blob: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Length", str(len(blob)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(blob)

        def do_GET(self):  # noqa: N802 (stdlib handler casing)
            hdr = [("Content-Type", "application/json")]
            if self.path == "/stats":
                self._reply(200, hdr,
                            json.dumps(frontend.stats()).encode())
            elif self.path == "/healthz":
                self._reply(200, hdr, b'{"ok": true}')
            elif self.path == "/readyz":
                ready = frontend.poll_ready()
                ok = any(ready.values())
                self._reply(200 if ok else 503, hdr,
                            json.dumps({"ready": ok,
                                        "replicas": ready}).encode())
            else:
                self._reply(404, hdr, json.dumps(
                    {"error": f"no route {self.path}"}).encode())

        def do_POST(self):  # noqa: N802
            if self.path != "/estimate":
                self._reply(404, [("Content-Type", "application/json")],
                            json.dumps(
                                {"error": f"no route {self.path}"}
                            ).encode())
                return
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            code, headers, payload = frontend.route(body)
            self._reply(code, headers, payload)

        def log_message(self, *args):  # quiet by default
            pass

    return ThreadingHTTPServer((host, port), Handler)
