"""Durable shard leases: exactly-one-writer for fleet budget shards.

The :class:`~dpcorr.serve.budget_dir.BudgetDirectory` keeps per-user
balances in per-shard WAL+snapshot journals that assume ONE writer. A
fleet shares the directory on disk, so something must make "one
writer per shard" true across N replicas and survive any of them
dying mid-write. That something is this module:

- one **lease file** per shard (``shard-0007.lease``, JSON, written
  tmp+fsync+rename so it is never torn), naming the owning replica,
  an **epoch** that increments on every ownership change, and an
  ``expires_at`` wall-clock deadline;
- a **heartbeat** (``renew``) that extends ``expires_at`` while the
  owner is alive; a silent owner loses the shard TTL seconds after
  its last renewal, and only then may another replica take over;
- an ``O_CREAT|O_EXCL`` **claim file** per (shard, epoch) so two
  replicas racing for an expired lease resolve to exactly one winner
  before either touches the lease file — the loser walks away without
  writing anything. The ``fleet.pre_lease_commit`` chaos point sits
  between winning the claim and committing the lease: a crash there
  leaves a stale claim that the next claimant breaks (atomically, by
  rename) once it is TTL-old;
- **epoch fencing** on the admission path: ``ensure_owned`` re-reads
  the lease whenever its in-memory grant is within the safety margin
  of expiry, and a file showing a different owner or a newer epoch
  means this replica's grant is history — it closes the shard journal
  (``on_lost``) and refuses the charge charge-free with
  :class:`ShardNotOwnedError`, which carries the current owner so the
  front end can forward instead of failing.

Charges stay exactly-once across takeover because the lease only
gates WHO may write; WHAT was written is replayed from the shard's
own WAL by the next owner, and per-request charge_ids dedup a retry
of a dying replica's charge no matter which replica serves it.

Everything here is stdlib-only (jax-free): the front end reads lease
tables, tests script the clock.
"""

from __future__ import annotations

import json
import os
import threading
import time

from dpcorr import chaos
from dpcorr.serve.budget_dir import _atomic_write

_LEASE_VERSION = 1
_META_NAME = "meta.json"


class ShardNotOwnedError(Exception):
    """This replica does not hold the lease for the user's budget
    shard. Raised BEFORE anything is charged — the refusal is
    charge-free by construction — and carries the current owner (when
    the lease file names one) so the caller can forward the request
    instead of failing it."""

    def __init__(self, shard: int, owner: str | None = None,
                 owner_url: str | None = None,
                 retry_after_s: float | None = None):
        self.shard = int(shard)
        self.owner = owner
        self.owner_url = owner_url
        self.retry_after_s = retry_after_s
        who = f"held by {owner!r}" if owner else "not held here"
        super().__init__(f"budget shard {self.shard} {who}")


def _read_json(path: str) -> dict | None:
    """A lease/claim file, or None when absent (or unreadable — lease
    files are written atomically, so a torn read means "not there
    yet"; the claim protocol, not this read, decides ownership)."""
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (FileNotFoundError, json.JSONDecodeError, UnicodeDecodeError,
            OSError):
        return None


def read_meta(lease_dir: str) -> dict | None:
    return _read_json(os.path.join(str(lease_dir), _META_NAME))


def lease_table(lease_dir: str) -> dict[int, dict]:
    """Every shard's current lease record, keyed by shard index — the
    front end's routing table. Purely a directory scan; expired
    entries are included (``expires_at`` is the reader's to judge)."""
    out: dict[int, dict] = {}
    try:
        names = os.listdir(str(lease_dir))
    except FileNotFoundError:
        return out
    for name in names:
        if not (name.startswith("shard-") and name.endswith(".lease")):
            continue
        rec = _read_json(os.path.join(str(lease_dir), name))
        if rec is None:
            continue
        try:
            out[int(rec["shard"])] = rec
        except (KeyError, TypeError, ValueError):
            continue
    return out


class LeaseManager:
    """One replica's view of the shard leases under ``lease_dir``.

    ``owner`` is the replica's stable instance name (stable across
    restart, so a rebooted replica reclaims its own expired leases
    instantly); ``url`` is advertised in the lease file for forwarding.
    ``clock`` is injectable (tests script expiry). With
    ``acquire_on_demand`` (the default), ``ensure_owned`` takes over a
    free or expired shard on first touch, so ownership converges onto
    whichever replicas actually receive the traffic.
    """

    def __init__(self, lease_dir: str, owner: str,
                 n_shards: int | None = None, *,
                 url: str | None = None, ttl_s: float = 3.0,
                 clock=time.time, acquire_on_demand: bool = True):
        if ttl_s <= 0.0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.lease_dir = str(lease_dir)
        os.makedirs(self.lease_dir, exist_ok=True)
        self.owner = str(owner)
        self.url = url
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self.acquire_on_demand = acquire_on_demand
        self.n_shards: int | None = None
        self._on_lost = None
        self._lock = threading.RLock()
        self._mine: dict[int, dict] = {}  # guarded by: _lock
        self._counts: dict[str, int] = {}  # guarded by: _lock
        if n_shards is not None:
            self.bind(n_shards)

    # -- binding -----------------------------------------------------

    def bind(self, n_shards: int, on_lost=None) -> None:
        """Pin the shard count (it must match the budget directory's
        persisted count — re-ringing users would split balances) and
        install the lease-lost callback (the directory closes the
        shard journal there)."""
        n_shards = int(n_shards)
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        meta = read_meta(self.lease_dir)
        if meta is None:
            _atomic_write(os.path.join(self.lease_dir, _META_NAME),
                          json.dumps({"version": _LEASE_VERSION,
                                      "shards": n_shards}))
        elif int(meta.get("shards", -1)) != n_shards:
            raise ValueError(
                f"lease dir {self.lease_dir} pins "
                f"{meta.get('shards')} shards, directory has "
                f"{n_shards}: one fleet, one ring")
        self.n_shards = n_shards
        if on_lost is not None:
            self._on_lost = on_lost

    # -- paths / reads -----------------------------------------------

    def _lease_path(self, shard: int) -> str:
        return os.path.join(self.lease_dir, f"shard-{shard:04d}.lease")

    def _claim_path(self, shard: int, epoch: int) -> str:
        return os.path.join(self.lease_dir,
                            f"shard-{shard:04d}.claim.{epoch}")

    def owner_of(self, shard: int) -> dict | None:
        """The shard's lease record as persisted (owner may be
        expired — the caller judges ``expires_at``)."""
        return _read_json(self._lease_path(shard))

    def _count(self, what: str, k: int = 1) -> None:
        with self._lock:
            self._counts[what] = self._counts.get(what, 0) + k

    # -- the claim protocol ------------------------------------------

    def _win_claim(self, path: str, now: float) -> bool:
        """Exactly-one-winner for a (shard, epoch) takeover. The claim
        file is created ``O_CREAT|O_EXCL`` — atomic on POSIX — and a
        crashed claimant's stale claim (TTL-old by its embedded stamp)
        is consumed by an atomic rename, so at most one breaker
        proceeds to retry the exclusive create."""
        flags = os.O_CREAT | os.O_EXCL | os.O_WRONLY
        try:
            fd = os.open(path, flags, 0o644)
        except FileExistsError:
            st = _read_json(path)
            ts = None if st is None else st.get("ts")
            fresh = ts is not None and now < float(ts) + self.ttl_s
            if fresh:
                return False  # someone else is mid-takeover, live
            tomb = f"{path}.stale.{self.owner}.{os.getpid()}"
            try:
                os.rename(path, tomb)  # atomic: one breaker wins
            except FileNotFoundError:
                pass  # another breaker consumed it first
            else:
                try:
                    os.unlink(tomb)
                except FileNotFoundError:
                    pass
            try:
                fd = os.open(path, flags, 0o644)
            except FileExistsError:
                return False
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"owner": self.owner, "ts": now}))
            fh.flush()
            os.fsync(fh.fileno())
        return True

    # -- lifecycle ---------------------------------------------------

    def acquire(self, shard: int) -> bool:
        """Try to take shard ``shard``: free, expired, or already ours
        (a restart reclaiming its own name re-grants even before
        expiry — same owner, no second writer). Returns False without
        writing anything when another replica holds it validly or
        wins the claim race."""
        shard = int(shard)
        with self._lock:
            now = self.clock()
            cur = self.owner_of(shard)
            if cur is not None:
                valid = now < float(cur["expires_at"])
                if valid and cur["owner"] != self.owner:
                    return False
                if valid and cur["owner"] == self.owner:
                    # ours already (this process or our previous
                    # incarnation): adopt the live grant as-is
                    self._mine[shard] = {
                        "epoch": int(cur["epoch"]),
                        "expires_at": float(cur["expires_at"])}
                    self._count("reclaimed")
                    return True
            epoch = (int(cur["epoch"]) if cur is not None else 0) + 1
            claim = self._claim_path(shard, epoch)
            # dpcorr-lint: ignore[blocking-under-lock] — the claim must be durable before the grant proceeds
            if not self._win_claim(claim, now):
                return False
            # claim won but nothing granted yet: a crash here (the
            # chaos point below) leaves only the stale claim, which
            # the next claimant breaks after TTL — no lease is ever
            # half-written
            chaos.point("fleet.pre_lease_commit")
            rec = {"version": _LEASE_VERSION, "shard": shard,
                   "owner": self.owner, "url": self.url,
                   "epoch": epoch, "granted_at": now,
                   "expires_at": now + self.ttl_s}
            # dpcorr-lint: ignore[blocking-under-lock] — the lease must be durable before the grant is visible
            _atomic_write(self._lease_path(shard), json.dumps(rec))
            try:
                os.unlink(claim)
            except FileNotFoundError:
                pass
            self._mine[shard] = {"epoch": epoch,
                                 "expires_at": rec["expires_at"]}
            self._count("acquired")
            if epoch > 1:
                self._count("takeovers")
            return True

    def renew(self, shard: int) -> bool:
        """Heartbeat one held shard. The file is re-read first: a
        different owner or epoch means we were fenced while silent —
        the grant is dropped (``on_lost`` fires), never revived."""
        shard = int(shard)
        with self._lock:
            mine = self._mine.get(shard)
            if mine is None:
                return False
            now = self.clock()
            cur = self.owner_of(shard)
            if (cur is None or cur["owner"] != self.owner
                    or int(cur["epoch"]) != mine["epoch"]
                    or now >= float(cur["expires_at"])):
                self._lost(shard)
                return False
            rec = dict(cur)
            rec["url"] = self.url
            rec["renewed_at"] = now
            rec["expires_at"] = now + self.ttl_s
            # dpcorr-lint: ignore[blocking-under-lock] — the heartbeat must be durable before the grant is extended
            _atomic_write(self._lease_path(shard), json.dumps(rec))
            mine["expires_at"] = rec["expires_at"]
            self._count("renewed")
            return True

    def renew_all(self) -> int:
        with self._lock:
            # dpcorr-lint: ignore[blocking-under-lock] — each renew's durable write is the heartbeat itself
            return sum(self.renew(s) for s in sorted(self._mine))

    def _lost(self, shard: int) -> None:
        # dpcorr-lint: ignore[lock-unguarded-write] — callers hold _lock (RLock); not re-taken so on_lost sees the same hold depth
        self._mine.pop(shard, None)
        self._count("lost")
        if self._on_lost is not None:
            self._on_lost(shard)

    def ensure_owned(self, shard: int, *,
                     acquire: bool | None = None) -> None:
        """The admission-path gate: cheap in-memory check while the
        grant is comfortably live (a TTL/4 safety margin keeps a
        charge from landing after a fence), one file re-read when in
        doubt, optional on-demand takeover of a free shard, and a
        charge-free :class:`ShardNotOwnedError` naming the real owner
        otherwise."""
        shard = int(shard)
        if self.n_shards is not None and not 0 <= shard < self.n_shards:
            raise ValueError(f"shard {shard} out of range "
                             f"[0, {self.n_shards})")
        with self._lock:
            now = self.clock()
            margin = self.ttl_s * 0.25
            mine = self._mine.get(shard)
            if mine is not None and now < mine["expires_at"] - margin:
                return
            cur = self.owner_of(shard)
            if (mine is not None and cur is not None
                    and cur["owner"] == self.owner
                    and int(cur["epoch"]) == mine["epoch"]
                    and now < float(cur["expires_at"]) - margin):
                # a concurrent renew advanced the file; adopt it
                mine["expires_at"] = float(cur["expires_at"])
                return
            if mine is not None:
                self._lost(shard)
            want = (acquire if acquire is not None
                    else self.acquire_on_demand)
            # dpcorr-lint: ignore[blocking-under-lock] — on-demand takeover: the admission path must wait out the durable grant
            if want and self.acquire(shard):
                return
            self._count("refused")
            cur = self.owner_of(shard)
            owner = cur.get("owner") if cur is not None else None
            url = cur.get("url") if cur is not None else None
            if cur is not None:
                left = float(cur["expires_at"]) - now
                retry = min(self.ttl_s, max(0.05, left))
            else:
                retry = 0.1
            raise ShardNotOwnedError(
                shard, owner=owner if owner != self.owner else None,
                owner_url=url, retry_after_s=retry)

    def release(self, shard: int) -> None:
        """Graceful handback: the lease is rewritten already-expired
        (same epoch — the next owner still bumps it), so a successor
        takes over immediately instead of waiting out the TTL."""
        shard = int(shard)
        with self._lock:
            mine = self._mine.pop(shard, None)
            if mine is None:
                return
            cur = self.owner_of(shard)
            if (cur is not None and cur["owner"] == self.owner
                    and int(cur["epoch"]) == mine["epoch"]):
                rec = dict(cur)
                rec["expires_at"] = self.clock()
                rec["released"] = True
                # dpcorr-lint: ignore[blocking-under-lock] — the handback must be durable before the journal closes
                _atomic_write(self._lease_path(shard), json.dumps(rec))
            self._count("released")
            if self._on_lost is not None:
                self._on_lost(shard)

    def release_all(self) -> None:
        with self._lock:
            for shard in sorted(self._mine):
                # dpcorr-lint: ignore[blocking-under-lock] — each release's durable write is the handback itself
                self.release(shard)

    # -- views -------------------------------------------------------

    def owned(self) -> list[int]:
        with self._lock:
            return sorted(self._mine)

    def snapshot(self) -> dict:
        """The /stats ``leases`` block: what this replica holds, at
        which epochs, plus lifecycle counters."""
        with self._lock:
            return {"owner": self.owner,
                    "n_shards": self.n_shards,
                    "ttl_s": self.ttl_s,
                    "owned": sorted(self._mine),
                    "epochs": {str(s): m["epoch"]
                               for s, m in sorted(self._mine.items())},
                    "counts": dict(self._counts)}


class LeaseKeeper:
    """The replica's lease heartbeat loop: renew everything held, then
    scan for shards to pick up — our own from a previous incarnation
    (instantly), free/expired ones up to ``target`` (the supervisor
    passes ceil(shards/N) so a first-booted replica doesn't hoard the
    whole ring), and ANY shard orphaned longer than ``rescue_after_s``
    regardless of target (a dead replica's users must not wait for
    fleet-size arithmetic). ``step()`` is callable directly so tests
    drive it under scripted clocks; ``start()`` runs it on a daemon
    thread every ``interval_s`` (default TTL/3)."""

    def __init__(self, manager: LeaseManager, *,
                 interval_s: float | None = None,
                 target: int | None = None,
                 rescue_after_s: float | None = None):
        self.manager = manager
        self.interval_s = (float(interval_s) if interval_s is not None
                           else manager.ttl_s / 3.0)
        self.target = target
        self.rescue_after_s = (float(rescue_after_s)
                               if rescue_after_s is not None
                               else 2.0 * manager.ttl_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def step(self) -> None:
        m = self.manager
        if m.n_shards is None:
            return
        m.renew_all()
        held = len(m.owned())
        mine = set(m.owned())
        for shard in range(m.n_shards):
            if shard in mine:
                continue
            now = m.clock()
            cur = m.owner_of(shard)
            expired = cur is None or now >= float(cur["expires_at"])
            if not expired:
                continue
            was_mine = cur is not None and cur["owner"] == m.owner
            orphaned = (cur is not None and
                        now >= float(cur["expires_at"]) +
                        self.rescue_after_s)
            if (was_mine or self.target is None or held < self.target
                    or orphaned):
                if m.acquire(shard):
                    held += 1

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"lease-keeper-{self.manager.owner}",
            daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:  # keep the heartbeat alive; admission
                pass           # still fences via ensure_owned
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
