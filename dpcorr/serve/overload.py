"""Overload resilience: circuit breaker + brownout state machines.

The serving stack's only pre-ISSUE-8 defense against pressure was the
coalescer's hard ``max_queue`` refusal. This module holds the two
stateful controllers the resilient admission path composes (ISSUE 8):

- :class:`CircuitBreaker` — per-:class:`~dpcorr.serve.request.BucketKey`
  failure isolation. Consecutive kernel/compile failures in one bucket
  trip its breaker OPEN; while open, admissions for that bucket fail
  fast with :class:`CircuitOpenError` (HTTP 503 + ``Retry-After``)
  *before* any ε is charged — a poisoned kernel signature must not burn
  budget or queue slots on requests it cannot answer. After
  ``reset_after_s`` the breaker goes HALF-OPEN and admits exactly one
  probe; the probe's outcome closes the breaker (service restored,
  bit-identical results — nothing about the kernel path changed) or
  re-opens it for another cooldown.
- :class:`BrownoutController` — sustained-pressure degradation. When
  queue occupancy or the flush-latency EWMA stays over threshold for
  ``enter_after_s``, the server browns out: the coalescer drops to the
  unbatched fallback path (smaller, predictable launches) and admission
  rejects work below ``min_priority``. Hysteresis (``exit_after_s`` of
  sustained calm) prevents flapping at the threshold.

Both are jax-free, clock-injectable (tests script ``clock=``), and
publish transitions into :class:`~dpcorr.serve.stats.ServeStats` so
``/metrics`` carries a breaker state gauge and a brownout gauge.

Deadline errors live here too: :class:`DeadlineExpiredError` is what a
request's future resolves to when its deadline passed while queued —
the flush thread drops it *before* launch and refunds the charge, so
an expired request provably consumes zero ε (coalescer module
docstring).
"""

from __future__ import annotations

import threading
import time

from dpcorr.serve.request import BucketKey
from dpcorr.serve.stats import ServeStats

#: Gauge encoding for the per-bucket breaker state series.
STATE_CODES = {"closed": 0, "open": 1, "half_open": 2}


class DeadlineExpiredError(Exception):
    """The request's deadline passed before its kernel launched. The
    charge was refunded — retrying (with a fresh deadline) is safe."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        self.retry_after_s = retry_after_s
        super().__init__(msg)


class CircuitOpenError(Exception):
    """Admission refused fast: this request's (family, bucket) breaker
    is open after consecutive kernel failures. Nothing was charged.
    ``retry_after_s`` is the remaining cooldown."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        self.retry_after_s = retry_after_s
        super().__init__(msg)


def _bucket_label(bkey: BucketKey) -> str:
    """Compact label for the per-bucket metrics series."""
    return (f"{bkey.n_pad}/{bkey.eps1:g}/{bkey.eps2:g}/"
            f"{bkey.alpha:g}/{int(bkey.normalise)}")


class _Entry:
    """One bucket's breaker state (owner holds the breaker lock)."""

    __slots__ = ("state", "consecutive", "opened_at", "probe_at")

    def __init__(self):
        self.state = "closed"
        self.consecutive = 0
        self.opened_at = 0.0
        self.probe_at: float | None = None


class CircuitBreaker:
    """Per-bucket trip / cooldown / half-open-probe state machine.

    ``allow`` runs at admission (before the ledger charge);
    ``record_success`` / ``record_failure`` run on the flush thread per
    launch outcome. All transitions are published to ``stats`` when one
    is wired (state gauge + transition counter).
    """

    def __init__(self, fail_threshold: int = 5,
                 reset_after_s: float = 30.0,
                 stats: ServeStats | None = None,
                 clock=time.monotonic, on_open=None):
        if fail_threshold < 1:
            raise ValueError(f"fail_threshold must be >= 1, "
                             f"got {fail_threshold}")
        if reset_after_s <= 0.0:
            raise ValueError(f"reset_after_s must be > 0, "
                             f"got {reset_after_s}")
        self.fail_threshold = int(fail_threshold)
        self.reset_after_s = float(reset_after_s)
        self.stats = stats
        self.clock = clock
        #: ``on_open(bkey, consecutive)`` fires when a bucket trips
        #: open — OUTSIDE the breaker lock, so the flight recorder can
        #: dump (file I/O) without stalling concurrent admissions.
        #: Settable after construction (server wiring).
        self.on_open = on_open
        self._lock = threading.Lock()
        self._entries: dict[BucketKey, _Entry] = {}  # guarded by: _lock

    def _transition_locked(self, bkey: BucketKey, e: _Entry,
                           state: str) -> None:
        e.state = state
        if self.stats is not None:
            self.stats.breaker_state(bkey.family, _bucket_label(bkey),
                                     STATE_CODES[state])
            self.stats.breaker_transition(state)

    def allow(self, bkey: BucketKey) -> None:
        """Gate one admission. Raises :class:`CircuitOpenError` while
        the bucket's breaker is open (or a half-open probe is already
        in flight); after the cooldown the caller becomes the probe."""
        with self._lock:
            e = self._entries.get(bkey)
            if e is None or e.state == "closed":
                return
            now = self.clock()
            if e.state == "open":
                remaining = e.opened_at + self.reset_after_s - now
                if remaining > 0.0:
                    raise CircuitOpenError(
                        f"breaker open for {bkey.family} bucket "
                        f"{_bucket_label(bkey)} "
                        f"({e.consecutive} consecutive failures)",
                        retry_after_s=remaining)
                self._transition_locked(bkey, e, "half_open")
                e.probe_at = now
                return
            # half-open: one probe at a time; a probe that never came
            # back (refused downstream, client vanished) goes stale
            # after one more cooldown so recovery cannot deadlock
            if e.probe_at is not None \
                    and now - e.probe_at < self.reset_after_s:
                raise CircuitOpenError(
                    f"breaker half-open for {bkey.family} bucket "
                    f"{_bucket_label(bkey)}: probe in flight",
                    retry_after_s=e.probe_at + self.reset_after_s - now)
            e.probe_at = now

    def record_success(self, bkey: BucketKey) -> None:
        with self._lock:
            e = self._entries.get(bkey)
            if e is None:
                return
            e.consecutive = 0
            e.probe_at = None
            if e.state != "closed":
                self._transition_locked(bkey, e, "closed")

    def record_failure(self, bkey: BucketKey) -> None:
        tripped = None
        with self._lock:
            e = self._entries.setdefault(bkey, _Entry())
            e.consecutive += 1
            e.probe_at = None
            now = self.clock()
            if e.state == "half_open":
                # the probe failed: straight back to another cooldown
                e.opened_at = now
                self._transition_locked(bkey, e, "open")
                tripped = e.consecutive
            elif e.state == "closed" \
                    and e.consecutive >= self.fail_threshold:
                e.opened_at = now
                self._transition_locked(bkey, e, "open")
                tripped = e.consecutive
            elif e.state == "open":
                # a queued straggler failing while open: the bucket is
                # still sick — restart the cooldown
                e.opened_at = now
        if tripped is not None and self.on_open is not None:
            self.on_open(bkey, tripped)

    def state(self, bkey: BucketKey) -> str:
        with self._lock:
            e = self._entries.get(bkey)
            return e.state if e is not None else "closed"

    def any_open(self) -> bool:
        """True while any bucket is open or half-open — what degrades
        ``/readyz`` to 503 so a balancer drains this replica."""
        with self._lock:
            return any(e.state != "closed"
                       for e in self._entries.values())

    def snapshot(self) -> dict:
        with self._lock:
            states = {f"{k.family}:{_bucket_label(k)}": e.state
                      for k, e in self._entries.items()
                      if e.state != "closed"}
            return {"open": sum(1 for s in states.values()
                                if s == "open"),
                    "half_open": sum(1 for s in states.values()
                                     if s == "half_open"),
                    "tripped_buckets": states}


class BrownoutController:
    """Hysteretic sustained-pressure detector.

    ``observe(queue_fraction, flush_ewma_s)`` is called from the
    coalescer's admission and flush paths; pressure must persist for
    ``enter_after_s`` before brownout activates, and calm for
    ``exit_after_s`` before it deactivates — transient bursts ride
    through on the queue alone.
    """

    def __init__(self, queue_frac: float = 0.75,
                 flush_slo_s: float | None = None,
                 enter_after_s: float = 0.5, exit_after_s: float = 2.0,
                 stats: ServeStats | None = None,
                 clock=time.monotonic, on_change=None):
        if not 0.0 <= queue_frac <= 1.0:
            raise ValueError(f"queue_frac must be in [0, 1], "
                             f"got {queue_frac}")
        self.queue_frac = float(queue_frac)
        self.flush_slo_s = flush_slo_s
        self.enter_after_s = float(enter_after_s)
        self.exit_after_s = float(exit_after_s)
        self.stats = stats
        self.clock = clock
        #: ``on_change(active)`` fires on every enter/exit transition —
        #: OUTSIDE the controller lock (flight-recorder dump hook).
        #: Settable after construction (server wiring).
        self.on_change = on_change
        self._lock = threading.Lock()
        self._active = False  # guarded by: _lock
        self._pressured_since: float | None = None  # guarded by: _lock
        self._calm_since: float | None = None  # guarded by: _lock

    def _set_locked(self, active: bool) -> None:
        if active == self._active:
            return
        self._active = active
        if self.stats is not None:
            self.stats.brownout(active)

    def observe(self, queue_fraction: float,
                flush_ewma_s: float) -> None:
        pressured = queue_fraction >= self.queue_frac or (
            self.flush_slo_s is not None
            and flush_ewma_s > self.flush_slo_s)
        changed = None
        with self._lock:
            now = self.clock()
            if pressured:
                self._calm_since = None
                if self._pressured_since is None:
                    self._pressured_since = now
                if not self._active and \
                        now - self._pressured_since >= self.enter_after_s:
                    self._set_locked(True)
                    changed = True
            else:
                self._pressured_since = None
                if self._calm_since is None:
                    self._calm_since = now
                if self._active and \
                        now - self._calm_since >= self.exit_after_s:
                    self._set_locked(False)
                    changed = False
        if changed is not None and self.on_change is not None:
            self.on_change(changed)

    def active(self) -> bool:
        with self._lock:
            return self._active
