"""Serve warmup: the signature set compiled ahead of traffic.

A cold server pays one XLA compile per kernel signature at the worst
moment — the first flush that needs it. Warmup moves that cost to boot:
the operator names the expected signatures (CLI ``--warmup`` spec
and/or a manifest the previous run persisted on shutdown), a background
thread compiles them through the single-flight cache, and ``GET
/readyz`` reports ready only once the set is resident — the standard
readiness-gate shape, so a load balancer never routes traffic onto a
cold kernel cache.

Two sources, merged and deduplicated:

- **spec strings** — ``family:n:eps1:eps2[:bpads[:alpha[:normalise]]]``
  entries separated by ``;`` (or whitespace). ``bpads`` is a
  comma-separated list of batch widths to warm (each rounded up to its
  power-of-two bucket), or ``auto``: every power of two from 1 up to
  the server's ``max_batch`` — the full set steady traffic can flush.
  Example: ``ni_sign:500:1.0:0.5:auto;int_subg:1000:1.0:1.0:1,64``.
- **manifest files** — JSON written by :func:`save_manifest` from
  ``KernelCache.manifest()`` on server shutdown; replaying it on boot
  warms exactly the working set the previous process served.

Warmup entries are *signatures*, not queries: nothing is charged to any
ledger and no noise stream is consumed — compilation only.
"""

from __future__ import annotations

import json
import logging
import os

from dpcorr.serve.kernels import pad_batch
from dpcorr.serve.request import KernelKey

log = logging.getLogger("dpcorr.serve")

MANIFEST_VERSION = 1


def _parse_bpads(tok: str, max_batch: int) -> list[int]:
    if tok == "auto":
        out, b = [], 1
        while b <= max_batch:
            out.append(b)
            b *= 2
        return out
    return [pad_batch(int(t)) for t in tok.split(",") if t]


def parse_warmup_spec(spec: str, max_batch: int) -> list[dict]:
    """``--warmup`` spec string → signature dicts (manifest shape).
    Raises ValueError on malformed entries — a typo'd warmup silently
    warming nothing defeats its purpose."""
    sigs: list[dict] = []
    for entry in spec.replace(";", " ").split():
        parts = entry.split(":")
        if not 4 <= len(parts) <= 7:
            raise ValueError(
                f"bad --warmup entry {entry!r}: expected "
                "family:n:eps1:eps2[:bpads[:alpha[:normalise]]]")
        family, n, e1, e2 = parts[0], int(parts[1]), float(parts[2]), \
            float(parts[3])
        bpads = _parse_bpads(parts[4] if len(parts) > 4 and parts[4]
                             else "auto", max_batch)
        alpha = float(parts[5]) if len(parts) > 5 else 0.05
        normalise = parts[6].lower() in ("1", "true", "yes") \
            if len(parts) > 6 else True
        for b_pad in bpads:
            sigs.append({"family": family, "n": n, "eps1": e1, "eps2": e2,
                         "alpha": alpha, "normalise": normalise,
                         "b_pad": b_pad})
    return sigs


def signatures_to_keys(sigs: list[dict]) -> list[tuple[KernelKey, int]]:
    """Signature dicts → deduplicated ``(KernelKey, b_pad)`` warm list,
    order-preserving (first-mentioned compiles first)."""
    seen, out = set(), []
    for s in sigs:
        kkey = KernelKey(str(s["family"]), int(s["n"]),
                         float(s["eps1"]), float(s["eps2"]),
                         float(s.get("alpha", 0.05)),
                         bool(s.get("normalise", True)))
        item = (kkey, pad_batch(int(s["b_pad"])))
        if item not in seen:
            seen.add(item)
            out.append(item)
    return out


def load_manifest(path: str) -> list[dict]:
    """Read a kernel-cache manifest; missing file → empty (first boot),
    unreadable/mismatched-version → empty with a warning (a stale
    manifest must degrade to a cold boot, never crash the server)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return []
    except (OSError, json.JSONDecodeError) as e:
        log.warning("warmup manifest %s unreadable (%s); cold boot", path, e)
        return []
    if not isinstance(doc, dict) \
            or doc.get("version") != MANIFEST_VERSION \
            or not isinstance(doc.get("signatures"), list):
        log.warning("warmup manifest %s has unknown shape/version; "
                    "cold boot", path)
        return []
    return [s for s in doc["signatures"] if isinstance(s, dict)]


def save_manifest(path: str, sigs: list[dict]) -> None:
    """Persist the resident signature set (atomic tmp+rename)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"version": MANIFEST_VERSION, "signatures": sigs}, f,
                  indent=2)
    os.replace(tmp, path)
