"""Multi-host grid fan-out (SURVEY.md §2.3: "grid axis → host-level scan or
multi-host DCN fan-out").

The design-grid axis is embarrassingly parallel across *hosts* exactly as it
is across the reference's forked R processes (vert-cor.R:534-554) — no
cross-host communication exists until the final merge, so the right
transport is none at all: each host runs a deterministic slice of the grid
into the shared per-point ``.npz`` cache (the same one the single-host
driver uses for resume, ``grid.py``), and any host — or a later single-host
run — assembles the full result from the cache. On a real multi-host TPU
pod the hosts are the pod's workers and the shared cache is the job's
filesystem (the pattern DCN-connected slices use for independent work);
here the same code path is exercised with local worker subprocesses.

Slicing is by *shape bucket*, not by design row: a host owns whole (n, ε)
buckets (round-robin by bucket index) so the bucketed backend's
one-kernel-per-bucket speedup survives the split and no two hosts ever
compile the same kernel.

Within each host, replications can additionally shard over that host's
device mesh (``backend="sharded"``) — the two axes compose exactly like the
reference's mclapply-over-grid × vectorized-reps split.
"""

from __future__ import annotations

import json
import dataclasses
import os
import subprocess
import sys
from pathlib import Path

import pandas as pd

from dpcorr.grid import GridConfig, GridResult, run_grid

__all__ = ["grid_slice", "run_grid_host", "run_grid_multihost"]


def grid_slice(design: pd.DataFrame, host_id: int,
               n_hosts: int) -> pd.DataFrame:
    """The design rows host ``host_id`` owns: whole (n, ε) buckets,
    round-robin by bucket order. Deterministic — every host computes the
    same partition with no coordination."""
    if not 0 <= host_id < n_hosts:
        raise ValueError(f"host_id {host_id} not in [0, {n_hosts})")
    keys = design[["n", "eps1", "eps2"]].drop_duplicates().reset_index(
        drop=True)
    mine = keys.iloc[host_id::n_hosts]
    take = design.merge(mine, on=["n", "eps1", "eps2"], how="inner")
    return take.sort_values("i").reset_index(drop=True)


def run_grid_host(gcfg: GridConfig, host_id: int, n_hosts: int) -> int:
    """Run this host's slice into the shared npz cache; returns the number
    of design points this host owned. ``gcfg.out_dir`` must be set (it is
    the only channel between hosts). ``gcfg.backend`` is honored — each
    host runs its buckets through the bucketed kernel, or its rows through
    the local/sharded per-point path (replications over this host's own
    device mesh)."""
    if not gcfg.out_dir:
        raise ValueError("multi-host execution needs a shared out_dir")
    design = gcfg.design_points()
    mine = grid_slice(design, host_id, n_hosts)
    if not len(mine):
        return 0

    import numpy as np

    from dpcorr import grid as grid_mod
    from dpcorr.utils import rng

    out_dir = Path(gcfg.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    # keys fold the *global* design index i, so the result is
    # bit-identical to a single-host run of the full grid
    master = rng.master_key(gcfg.seed)
    if gcfg.backend in ("bucketed", "bucketed-sharded"):
        _, _, failures = grid_mod._run_grid_bucketed(gcfg, mine, master,
                                                     out_dir)
        grid_mod._raise_if_failed(failures, len(mine))
        return len(mine)

    failures = []
    for row in mine.itertuples(index=False):
        i = int(row.i)
        cfg = gcfg.sim_config(row._asdict())
        stamp = grid_mod._stamp(cfg)
        path = grid_mod._design_path(out_dir, i)
        if grid_mod._load_cached(path, gcfg.resume, stamp) is not None:
            continue
        try:
            res = grid_mod._run_point(gcfg, cfg,
                                      rng.design_key(master, i), None)
            np.savez(path, config_stamp=stamp,
                     **{k: np.asarray(v) for k, v in res.detail.items()})
        except Exception as e:
            failures.append((i, e))
    grid_mod._raise_if_failed(failures, len(mine))
    return len(mine)


def run_grid_multihost(gcfg: GridConfig, n_hosts: int = 2,
                       python: str | None = None,
                       platform: str | None = None) -> GridResult:
    """Fan the grid out over ``n_hosts`` local worker processes, then
    assemble the merged result from the shared cache.

    Each worker is a fresh process (its own JAX runtime — the single-host
    stand-in for a pod worker); the parent merges by re-running the grid
    through the resume cache, which by then is fully populated, so the
    merge never recomputes anything. ``platform`` forces each worker's JAX
    platform (the site hook ignores JAX_PLATFORMS env, so workers apply it
    via config.update — see ``_worker_main``); leave ``None`` on a real
    pod, where each worker should claim its own chips.
    """
    if not gcfg.out_dir:
        raise ValueError("multi-host execution needs a shared out_dir")
    env = dict(os.environ)
    if platform:
        env["DPCORR_HOST_PLATFORM"] = platform
    procs = []
    for h in range(n_hosts):
        spec = {"host_id": h, "n_hosts": n_hosts,
                "gcfg": {f.name: getattr(gcfg, f.name)
                         for f in dataclasses.fields(gcfg)}}
        procs.append(subprocess.Popen(
            [python or sys.executable, "-m", "dpcorr.parallel.multihost"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env))
        # deliver the spec at spawn time so hosts run concurrently; null
        # the handle so the later communicate() won't flush a closed file
        procs[-1].stdin.write(json.dumps(spec))
        procs[-1].stdin.close()
        procs[-1].stdin = None
    errs = []
    for h, p in enumerate(procs):
        # communicate() drains stdout+stderr together — a worker that fills
        # one pipe can never deadlock the join
        out, err = p.communicate()
        if p.returncode != 0:
            tail = err.strip().splitlines()[-3:]
            errs.append(f"host {h}: rc={p.returncode}: " + " | ".join(tail))
    if errs:
        raise RuntimeError(f"{len(errs)}/{n_hosts} hosts failed: "
                           + "; ".join(errs)[:800])
    # assemble from the (now complete) shared cache — pure cache hits even
    # when the caller disabled resume for the compute itself
    return run_grid(dataclasses.replace(gcfg, resume=True))


def _worker_main() -> None:
    # This environment's site hook force-selects the TPU platform at
    # interpreter start regardless of JAX_PLATFORMS; a post-import
    # config.update is the only override that sticks, so honor the
    # requested worker platform here, before any backend initializes.
    platform = os.environ.get("DPCORR_HOST_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    spec = json.loads(sys.stdin.read())
    gd = spec["gcfg"]
    # JSON round-trips tuples as lists; GridConfig fields tolerate
    # sequences, and SimConfig.__post_init__ freezes dgp_args recursively
    gd["eps_pairs"] = tuple(tuple(p) for p in gd["eps_pairs"])
    for k in ("n_grid", "rho_grid"):
        gd[k] = tuple(gd[k])
    gcfg = GridConfig(**gd)
    owned = run_grid_host(gcfg, spec["host_id"], spec["n_hosts"])
    print(json.dumps({"host_id": spec["host_id"], "points": owned}))


if __name__ == "__main__":
    _worker_main()
