"""Multi-host grid fan-out (SURVEY.md §2.3: "grid axis → host-level scan or
multi-host DCN fan-out").

The design-grid axis is embarrassingly parallel across *hosts* exactly as it
is across the reference's forked R processes (vert-cor.R:534-554) — no
cross-host communication exists until the final merge, so the right
transport is none at all: each host runs a deterministic slice of the grid
into the shared per-point ``.npz`` cache (the same one the single-host
driver uses for resume, ``grid.py``), and any host — or a later single-host
run — assembles the full result from the cache. On a real multi-host TPU
pod the hosts are the pod's workers and the shared cache is the job's
filesystem (the pattern DCN-connected slices use for independent work);
here the same code path is exercised with local worker subprocesses.

Slicing is by *shape bucket*, not by design row: a host owns whole (n, ε)
buckets (round-robin by bucket index) so the bucketed backend's
one-kernel-per-bucket speedup survives the split and no two hosts ever
compile the same kernel.

Within each host, replications can additionally shard over that host's
device mesh (``backend="sharded"``) — the two axes compose exactly like the
reference's mclapply-over-grid × vectorized-reps split.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
from pathlib import Path

import pandas as pd

from dpcorr.grid import GridConfig, GridResult, run_grid

__all__ = ["grid_slice", "run_grid_host", "run_grid_multihost",
           "init_distributed", "run_grid_process"]


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int, platform: str | None = None,
                     local_device_count: int | None = None) -> None:
    """Opt-in ``jax.distributed`` runtime init (SURVEY.md §2.3: multi-host
    DCN fan-out).

    On a real pod the launcher supplies the arguments (or JAX infers them
    from the TPU environment and they can all be None); the local
    multi-process CPU cluster test supplies localhost ones, with
    ``platform="cpu"`` and a per-process ``local_device_count`` so each
    worker contributes virtual CPU devices to the global cluster. Must run
    before any JAX backend initializes — platform/device-count config
    cannot change afterwards.
    """
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if local_device_count:
        try:
            jax.config.update("jax_num_cpu_devices", local_device_count)
        except AttributeError:
            # jax < 0.5: no config option; the XLA_FLAGS equivalent is
            # read at backend init, which hasn't happened yet here
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{local_device_count}").strip()
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def run_grid_process(gcfg: GridConfig) -> GridResult | None:
    """SPMD multi-host grid entry: every process of an initialized
    ``jax.distributed`` job calls this with the SAME config (the standard
    multi-controller pattern — one program, all workers).

    Host identity comes from the runtime (``jax.process_index`` /
    ``jax.process_count``), not from caller-passed ids; per-host compute is
    pinned to this host's addressable devices (a local ``rep`` mesh for the
    sharded backends, local default device otherwise); a global-device
    barrier closes the fan-out; then process 0 assembles the merged result
    from the shared cache and returns it (other processes return None).
    """
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh

    host, n_hosts = jax.process_index(), jax.process_count()
    local = jax.local_devices()
    mesh = Mesh(local, axis_names=("rep",))
    with jax.default_device(local[0]):
        run_grid_host(gcfg, host, n_hosts, mesh=mesh)
    # the only cross-host synchronization the problem has (SURVEY.md §2.5):
    # everyone's cache writes must land before rank 0 merges
    multihost_utils.sync_global_devices("dpcorr/grid-fanout-complete")
    if host != 0:
        return None
    with jax.default_device(local[0]):
        return run_grid(dataclasses.replace(gcfg, resume=True), mesh=mesh)


def grid_slice(design: pd.DataFrame, host_id: int,
               n_hosts: int) -> pd.DataFrame:
    """The design rows host ``host_id`` owns: whole (n, ε) buckets,
    round-robin by bucket order. Deterministic — every host computes the
    same partition with no coordination."""
    if not 0 <= host_id < n_hosts:
        raise ValueError(f"host_id {host_id} not in [0, {n_hosts})")
    keys = design[["n", "eps1", "eps2"]].drop_duplicates().reset_index(
        drop=True)
    mine = keys.iloc[host_id::n_hosts]
    take = design.merge(mine, on=["n", "eps1", "eps2"], how="inner")
    return take.sort_values("i").reset_index(drop=True)


def run_grid_host(gcfg: GridConfig, host_id: int, n_hosts: int,
                  mesh=None) -> int:
    """Run this host's slice into the shared npz cache; returns the number
    of design points this host owned. ``gcfg.out_dir`` must be set (it is
    the only channel between hosts). ``gcfg.backend`` is honored — each
    host runs its buckets through the bucketed kernel, or its rows through
    the local/sharded per-point path (replications over this host's own
    device mesh). ``mesh`` (for the sharded backends) must span only
    devices this host can address — under a ``jax.distributed`` runtime
    that is ``jax.local_devices()``, which :func:`run_grid_process` wires
    up."""
    if not gcfg.out_dir:
        raise ValueError("multi-host execution needs a shared out_dir")
    design = gcfg.design_points()
    mine = grid_slice(design, host_id, n_hosts)
    if not len(mine):
        return 0

    import numpy as np

    from dpcorr import grid as grid_mod
    from dpcorr.utils import rng

    out_dir = Path(gcfg.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    # keys fold the *global* design index i, so the result is
    # bit-identical to a single-host run of the full grid
    master = rng.master_key(gcfg.seed)
    if gcfg.backend in ("bucketed", "bucketed-sharded"):
        _, _, failures = grid_mod._run_grid_bucketed(gcfg, mine, master,
                                                     out_dir, mesh=mesh)
        grid_mod._raise_if_failed(failures, len(mine))
        return len(mine)

    failures = []
    for row in mine.itertuples(index=False):
        i = int(row.i)
        cfg = gcfg.sim_config(row._asdict())
        stamp = grid_mod._stamp(cfg)
        path = grid_mod._design_path(out_dir, i)
        if grid_mod._load_cached(path, gcfg.resume, stamp) is not None:
            continue
        try:
            res = grid_mod._run_point(gcfg, cfg,
                                      rng.design_key(master, i), mesh)
            np.savez(path, config_stamp=stamp,  # per-point fetch boundary
                     # dpcorr-lint: ignore[sync-in-loop]
                     **{k: np.asarray(v) for k, v in res.detail.items()})
        except Exception as e:
            failures.append((i, e))
    grid_mod._raise_if_failed(failures, len(mine))
    return len(mine)


def run_grid_multihost(gcfg: GridConfig, n_hosts: int = 2,
                       python: str | None = None,
                       platform: str | None = None,
                       distributed: bool = False,
                       local_device_count: int | None = None) -> GridResult:
    """Fan the grid out over ``n_hosts`` local worker processes, then
    assemble the merged result from the shared cache.

    Each worker is a fresh process (its own JAX runtime — the single-host
    stand-in for a pod worker); the parent merges by re-running the grid
    through the resume cache, which by then is fully populated, so the
    merge never recomputes anything. ``platform`` forces each worker's JAX
    platform (the site hook ignores JAX_PLATFORMS env, so workers apply it
    via config.update — see ``_worker_main``); leave ``None`` on a real
    pod, where each worker should claim its own chips.

    ``distributed=True`` upgrades the workers from independent subprocesses
    to a real ``jax.distributed`` cluster: the parent picks a coordinator
    port, each worker calls :func:`init_distributed` and then the SPMD
    entry :func:`run_grid_process`, so host identity and slicing come from
    ``jax.process_index()``/``process_count()`` and the fan-out closes with
    a global-device barrier — the exact program shape a multi-host pod
    runs, exercised as a local multi-process CPU cluster
    (``local_device_count`` virtual devices per worker).
    """
    if not gcfg.out_dir:
        raise ValueError("multi-host execution needs a shared out_dir")
    env = dict(os.environ)
    if platform:
        env["DPCORR_HOST_PLATFORM"] = platform

    def _free_port() -> int:
        import socket

        with socket.socket() as s:  # free port for the coordinator service
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def _attempt() -> tuple[list[str], list[dict]]:
        dist = None
        if distributed:
            dist = {"coordinator": f"127.0.0.1:{_free_port()}",
                    "num_processes": n_hosts,
                    "local_device_count": local_device_count}
        procs = []
        for h in range(n_hosts):
            spec = {"host_id": h, "n_hosts": n_hosts,
                    "gcfg": {f.name: getattr(gcfg, f.name)
                             for f in dataclasses.fields(gcfg)}}
            if dist:
                spec["dist"] = {**dist, "process_id": h}
            procs.append(subprocess.Popen(
                [python or sys.executable,
                 "-m", "dpcorr.parallel.multihost"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True, env=env))
            # deliver the spec at spawn time so hosts run concurrently;
            # null the handle so communicate() won't flush a closed file
            procs[-1].stdin.write(json.dumps(spec))
            procs[-1].stdin.close()
            procs[-1].stdin = None
        errs, reports = [], []
        for h, p in enumerate(procs):
            # communicate() drains stdout+stderr together — a worker that
            # fills one pipe can never deadlock the join
            out, err = p.communicate()
            if p.returncode != 0:
                tail = err.strip().splitlines()[-3:]
                errs.append(f"host {h}: rc={p.returncode}: "
                            + " | ".join(tail))
            else:
                # tolerant scan (as bench._run_worker): a stray non-JSON
                # line on a worker's stdout must not cost a finished grid
                for line in reversed(out.strip().splitlines()):
                    try:
                        rep = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rep, dict) and "host_id" in rep:
                        reports.append(rep)
                        break
        return errs, reports

    errs, reports = _attempt()
    if errs and distributed and any("bind" in e.lower()
                                    or "address" in e.lower()
                                    for e in errs):
        # the free-port pick above is inherently check-then-use: another
        # process can claim the port before jax.distributed's coordinator
        # binds it. One retry with a fresh port turns that flake into a
        # recovered run; a second failure is a real error.
        errs, reports = _attempt()
    if errs:
        raise RuntimeError(f"{len(errs)}/{n_hosts} hosts failed: "
                           + "; ".join(errs)[:800])
    if distributed:
        # the cluster facts must agree with what we launched — but only
        # for reports that actually surfaced: a worker whose JSON line got
        # lost in stdout noise must not discard a grid that completed.
        # Safe because rc==0 (checked above) already implies the worker
        # finished its slice; and even if a point were somehow absent from
        # the cache, the resume assembly below recomputes it in-parent
        # (correct result, just slower and on the parent's platform)
        bad = [r for r in reports if r["process_count"] != n_hosts]
        merged = sum(r["merged"] for r in reports)
        if bad or merged > 1 or (merged == 0 and len(reports) == n_hosts):
            raise RuntimeError(
                f"distributed cluster inconsistent: {reports!r}")
        if len(reports) < n_hosts:
            import warnings

            warnings.warn(
                f"only {len(reports)}/{n_hosts} worker reports parsed "
                "from stdout; trusting the merged artifacts instead",
                RuntimeWarning, stacklevel=2)
    # assemble from the (now complete) shared cache — pure cache hits even
    # when the caller disabled resume for the compute itself
    res = run_grid(dataclasses.replace(gcfg, resume=True))
    res.timings.attrs["hosts"] = reports
    return res


def _worker_main() -> None:
    # This environment's site hook force-selects the TPU platform at
    # interpreter start regardless of JAX_PLATFORMS; a post-import
    # config.update is the only override that sticks, so honor the
    # requested worker platform here, before any backend initializes.
    spec = json.loads(sys.stdin.read())
    platform = os.environ.get("DPCORR_HOST_PLATFORM")
    dist = spec.get("dist")
    if dist:
        init_distributed(dist["coordinator"], dist["num_processes"],
                         dist["process_id"], platform=platform,
                         local_device_count=dist.get("local_device_count"))
    elif platform:
        import jax

        jax.config.update("jax_platforms", platform)
    gd = spec["gcfg"]
    # JSON round-trips tuples as lists; GridConfig fields tolerate
    # sequences, and SimConfig.__post_init__ freezes dgp_args recursively
    gd["eps_pairs"] = tuple(tuple(p) for p in gd["eps_pairs"])
    for k in ("n_grid", "rho_grid"):
        gd[k] = tuple(gd[k])
    gcfg = GridConfig(**gd)
    if dist:
        import jax

        res = run_grid_process(gcfg)
        print(json.dumps({
            "host_id": jax.process_index(),
            "process_count": jax.process_count(),
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices()),
            "merged": res is not None,
        }))
    else:
        owned = run_grid_host(gcfg, spec["host_id"], spec["n_hosts"])
        print(json.dumps({"host_id": spec["host_id"], "points": owned}))


if __name__ == "__main__":
    _worker_main()
