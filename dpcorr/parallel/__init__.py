"""Device-mesh parallelism (replaces the reference's ``mclapply`` layer L4).

The reference's only parallelism is fork-based multicore over design points
(vert-cor.R:534-554); replications within a task are serial. Here the axes
invert, TPU-style (SURVEY.md §2.3):

- **replications** → ``vmap`` (batched kernel) and ``shard_map`` over the
  device mesh's ``rep`` axis (ICI);
- **metric reductions** → XLA collectives (``psum``) instead of fork/pipe
  joins;
- **design grid** → host-level loop over compiled kernels, or the
  multi-host fan-out in :mod:`dpcorr.parallel.multihost` (hosts own whole
  shape buckets; DCN carries nothing but the final file-system merge).
"""

from dpcorr.parallel.backend import (  # noqa: F401
    make_serve_batch_sharded,
    run_detail_flat_sharded,
    run_detail_sharded,
    run_summary_sharded,
)
from dpcorr.parallel.mesh import local_device_count, rep_mesh  # noqa: F401
from dpcorr.parallel.multihost import (  # noqa: F401
    grid_slice,
    run_grid_host,
    run_grid_multihost,
)
