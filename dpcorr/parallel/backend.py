"""Sharded execution of one design point: replications across the mesh.

This is the TPU replacement for the reference's process fan-out
(vert-cor.R:534-554): instead of forking one R process per design point and
running B replications serially inside it, the B replications of a single
design point are sharded across the ``rep`` mesh axis, each device running a
chunked ``vmap`` over its slice, with metric summaries reduced on-device by
``psum`` — the lone communication the problem actually has (SURVEY.md §2.5).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 re-exports shard_map at top level
    from jax import shard_map
except ImportError:  # jax 0.4.x: experimental module, same semantics
    from jax.experimental.shard_map import shard_map

from dpcorr import sim as sim_mod
from dpcorr.parallel.mesh import rep_mesh
from dpcorr.sim import SimConfig
from dpcorr.utils import rng
from dpcorr.utils.compile import mesh_shardings


def _padded_b(b: int, n_shards: int) -> int:
    return -(-b // n_shards) * n_shards


def _preshard(arrays, sharding, counters=None):
    """Pre-dispatch placement onto the kernel's declared sharding.
    Canonical implementation moved to the plan layer
    (``dpcorr.plan.placement.preshard``); this alias keeps the
    historical call sites and import path working."""
    from dpcorr.plan.placement import preshard

    return preshard(arrays, sharding, counters)


@lru_cache(maxsize=128)
def _detail_fn(cfg_norho: SimConfig, mesh: Mesh):
    """Compiled shard_map kernel: (padded keys, rho) -> detail tuple."""

    def local(keys, rho):
        return sim_mod._detail_from_keys(cfg_norho, keys, rho)

    rep_sh, repl_sh = mesh_shardings(mesh)
    sharded = shard_map(local, mesh=mesh,
                        in_specs=(P("rep"), P()), out_specs=P("rep"))
    return jax.jit(sharded, in_shardings=(rep_sh, repl_sh),
                   out_shardings=rep_sh)


@lru_cache(maxsize=128)
def _summary_fn(cfg_norho: SimConfig, mesh: Mesh):
    """Compiled shard_map kernel: (padded keys, rho, b) -> summary sums.

    Computes per-shard partial sums and ``psum``s them over the ``rep``
    axis, so only a handful of scalars ever leave the devices — the path the
    1M-rep benchmarks use.
    """

    def local(keys, rho, b_real):
        detail = sim_mod._detail_from_keys(cfg_norho, keys, rho)
        named = dict(zip(sim_mod.DETAIL_FIELDS, detail, strict=True))
        # padding mask: global rep index < b_real
        idx = jax.lax.axis_index("rep") * keys.shape[0] + jnp.arange(keys.shape[0])
        w = (idx < b_real).astype(jnp.float32)
        sums = {}
        for meth in ("ni", "int"):
            est = named[f"{meth}_hat"]
            sums[meth] = {
                "sum_hat": jnp.sum(w * est),
                "sum_hat2": jnp.sum(w * est * est),
                "sum_se2": jnp.sum(w * named[f"{meth}_se2"]),
                "sum_cover": jnp.sum(w * named[f"{meth}_cover"]),
                "sum_len": jnp.sum(w * named[f"{meth}_ci_len"]),
            }
        return jax.lax.psum(sums, "rep")

    rep_sh, repl_sh = mesh_shardings(mesh)
    sharded = shard_map(local, mesh=mesh,
                        in_specs=(P("rep"), P(), P()), out_specs=P())
    return jax.jit(sharded, in_shardings=(rep_sh, repl_sh, repl_sh),
                   out_shardings=repl_sh)


@lru_cache(maxsize=128)
def _flat_fn(cfg_norho: SimConfig, mesh: Mesh):
    """Compiled shard_map kernel over per-element (key, ρ) pairs — the
    bucketed grid's flat (points × replications) axis sharded over the
    ``rep`` mesh axis, composing the two parallel axes the reference keeps
    separate (grid fan-out × within-task vectorization, SURVEY.md §2.3)."""

    def local(keys, rhos):
        # delegate to the single source of truth for the flat kernel —
        # the bit-identity contract with the unsharded backend depends on
        # these bodies never diverging (jit composes inside shard_map)
        return sim_mod._run_detail_flat(cfg_norho, keys, rhos)

    rep_sh, _ = mesh_shardings(mesh)
    sharded = shard_map(local, mesh=mesh,
                        in_specs=(P("rep"), P("rep")), out_specs=P("rep"))
    return jax.jit(sharded, in_shardings=(rep_sh, rep_sh),
                   out_shardings=rep_sh)


def run_detail_flat_sharded(cfg_norho: SimConfig, keys: jax.Array,
                            rhos: jax.Array, mesh: Mesh | None = None):
    """Sharded twin of ``sim._run_detail_flat``: same per-element keys ⇒
    bit-identical detail, with the flat axis split across the mesh. Pads
    to a mesh-size multiple (padding reps recompute the first elements and
    are truncated away)."""
    mesh = mesh or rep_mesh()
    n_shards = mesh.devices.size
    total = keys.shape[0]
    padded = _padded_b(total, n_shards)
    if padded != total:
        # modulo gather handles pad > total too (a tiny bucket on a big
        # mesh — e.g. one uncached point at small b after a resume)
        idx = jnp.arange(padded) % total
        keys, rhos = keys[idx], rhos[idx]
    rep_sh, _ = mesh_shardings(mesh)
    keys, rhos = _preshard((keys, rhos), rep_sh)
    out = _flat_fn(cfg_norho, mesh)(keys, rhos)
    return tuple(a[:total] for a in out)


def make_serve_batch_sharded(single, mesh: Mesh | None = None,
                             engine: str = "exact"):
    """Sharded twin of the serving layer's batch kernel (serve.kernels):
    the flushed request axis is split over the ``rep`` mesh axis — the
    same two-level composition as :func:`run_detail_flat_sharded`,
    applied to online traffic instead of a grid bucket.

    ``engine`` picks the per-device body (estimators.registry contract):

    - ``"exact"``: ``lax.map`` — the scalar program compiled once and
      looped, bit-identical to the direct ``jit(single)`` call on every
      lane (measured, including under this shard_map).
    - ``"vector"``: ``vmap`` — fastest; ``rho_hat`` bit-identical, CI
      endpoints within 1 ulp of the scalar program.

    Caller pads the batch axis to a mesh-size multiple (serve.kernels
    does)."""
    if engine not in ("exact", "vector"):
        raise ValueError(f"engine must be 'exact' or 'vector', got {engine!r}")
    mesh = mesh or rep_mesh()

    if engine == "vector":
        def local(keys, xs, ys):
            return jax.vmap(single)(keys, xs, ys)
    else:
        def local(keys, xs, ys):
            return jax.lax.map(lambda t: single(*t), (keys, xs, ys))

    rep_sh, _ = mesh_shardings(mesh)
    sharded = shard_map(local, mesh=mesh,
                        in_specs=(P("rep"), P("rep"), P("rep")),
                        out_specs=P("rep"))
    return jax.jit(sharded, in_shardings=(rep_sh, rep_sh, rep_sh),
                   out_shardings=rep_sh)


def _prep(cfg: SimConfig, key, mesh: Mesh):
    n_shards = mesh.devices.size
    b_pad = _padded_b(cfg.b, n_shards)
    if key is None:
        key = rng.master_key(cfg.seed)
    keys = rng.rep_keys(key, b_pad)
    # seed is host-side-only (key derivation), so drop it from the
    # compiled-kernel cache key along with rho (see sim._run_detail)
    cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
    return cfg_norho, keys, b_pad


def run_detail_sharded(cfg: SimConfig, key=None, mesh: Mesh | None = None):
    """Full (B, ·) detail table, replications sharded over the mesh."""
    mesh = mesh or rep_mesh()
    cfg_norho, keys, _ = _prep(cfg, key, mesh)
    (keys,) = _preshard((keys,), mesh_shardings(mesh)[0])
    out = _detail_fn(cfg_norho, mesh)(keys, jnp.float32(cfg.rho))
    detail = dict(zip(sim_mod.DETAIL_FIELDS,
                      (a[: cfg.b] for a in out), strict=True))
    return sim_mod.SimResult(detail, sim_mod.summarize(detail, cfg.rho), cfg)


def run_summary_sharded(cfg: SimConfig, key=None, mesh: Mesh | None = None):
    """Summary-only sharded run: nothing but ~10 scalars leaves the mesh.

    Returns the reference's 2-row summary (mse, bias, var, coverage,
    ci_length — vert-cor.R:421-443) computed from psum'd partial sums.
    """
    mesh = mesh or rep_mesh()
    cfg_norho, keys, _ = _prep(cfg, key, mesh)
    (keys,) = _preshard((keys,), mesh_shardings(mesh)[0])
    sums = _summary_fn(cfg_norho, mesh)(
        keys, jnp.float32(cfg.rho), jnp.float32(cfg.b))
    b = float(cfg.b)
    out = {}
    for meth in ("ni", "int"):
        s = {k: float(v) for k, v in sums[meth].items()}
        mean_hat = s["sum_hat"] / b
        out[meth.upper()] = {
            "mse": s["sum_se2"] / b,
            "bias": mean_hat - cfg.rho,
            # R var(): sample variance, denominator B-1
            "var": (s["sum_hat2"] - b * mean_hat**2) / (b - 1.0),
            "coverage": s["sum_cover"] / b,
            "ci_length": s["sum_len"] / b,
        }
    return out
