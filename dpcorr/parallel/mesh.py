"""Mesh construction helpers."""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def local_device_count() -> int:
    return len(jax.devices())


def rep_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh with a ``rep`` axis over the first ``n_devices`` devices.

    Monte-Carlo replications are i.i.d., so a single mesh axis suffices; the
    only cross-device traffic is the final metric reduction (SURVEY.md §2.5).
    On a TPU slice the axis rides ICI; under
    ``xla_force_host_platform_device_count`` it maps to virtual CPU devices
    for testing.
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(devs, axis_names=("rep",))
