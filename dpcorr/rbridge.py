"""reticulate-facing bridge: plain-data API for the R front-end.

The reference's only process boundary is the ``mclapply`` fan-out over
design-grid rows (vert-cor.R:534-554); ``r/backend.R`` patches that call
site with ``backend = c("mclapply", "tpu")`` and, for ``"tpu"``, calls into
this module via reticulate. Everything here speaks reticulate-native types
only — lists of dicts in, a pandas DataFrame out (reticulate converts both
ways automatically) — so the R side stays a thin shim.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import pandas as pd

from dpcorr.sim import SimConfig, run_sim_one
from dpcorr.utils import rng


def run_design_rows(rows: Sequence[Mapping], b: int = 250,
                    seed: int = rng.MASTER_SEED,
                    dgp: str = "gaussian", use_subg: bool = False,
                    alpha: float = 0.05, normalise: bool = True,
                    ci_mode: str = "auto",
                    backend: str = "local",
                    fused: str = "off",
                    bucket_merge: str = "off") -> pd.DataFrame:
    """Run design-grid rows and return the replicate-level detail frame.

    ``rows``: list of ``{"n": .., "rho": .., "eps1": .., "eps2": ..}`` —
    exactly the columns of the reference's ``design_df``
    (vert-cor.R:507-511). Each row gets the key-tree equivalent of the
    reference's per-task ``seed = 1e6 + i`` (vert-cor.R:531). Returns one
    data.frame with the reference's metadata-joined detail columns
    (vert-cor.R:557-568), ready for ``data.table`` aggregation on the R
    side.
    """
    master = rng.master_key(int(seed))

    from dpcorr import grid as grid_mod

    # same fail-fast contract as grid.run_grid: a typo'd or silently
    # inapplicable fused value must not run the wrong path
    grid_mod.validate_fused(fused, backend)
    # eps_pairs for validation come from the ROWS' actual pairs (the
    # merged kernel's ε₁ ≥ ε₂ sender contract must be checked against
    # the design that will run, not GridConfig's defaults; the pad bound
    # itself is derived per n-bucket from the same rows inside
    # _run_grid_bucketed). Validated for EVERY backend so a wrong knob
    # value fails identically whether or not the bucketed path runs.
    row_pairs = tuple(sorted({(float(r["eps1"]), float(r["eps2"]))
                              for r in rows}))
    grid_mod.validate_bucket_merge(bucket_merge, backend, bool(use_subg),
                                   row_pairs)

    if backend == "bucketed":
        # the grid speedup (one kernel per (n, ε) shape bucket, ρ traced,
        # dispatch-ahead) — reachable from R, bit-identical per point to
        # the local path (both fold design_key(master, i))
        gcfg = grid_mod.GridConfig(
            b=int(b), alpha=float(alpha), dgp=dgp, use_subg=bool(use_subg),
            normalise=bool(normalise), ci_mode=ci_mode, seed=int(seed),
            backend="bucketed", fused=fused, bucket_merge=bucket_merge,
            eps_pairs=row_pairs)
        design = pd.DataFrame(
            [{"i": i, "n": int(r["n"]), "rho": float(r["rho"]),
              "eps1": float(r["eps1"]), "eps2": float(r["eps2"])}
             for i, r in enumerate(rows)])
        by_i, _, failures = grid_mod._run_grid_bucketed(
            gcfg, design, master, out_dir=None)
        grid_mod._raise_if_failed(failures, len(design))
        return grid_mod._assemble_details(design, by_i, gcfg.b)

    frames = []
    for i, row in enumerate(rows):
        cfg = SimConfig(
            n=int(row["n"]), rho=float(row["rho"]),
            eps1=float(row["eps1"]), eps2=float(row["eps2"]),
            b=int(b), alpha=float(alpha), dgp=dgp, use_subg=bool(use_subg),
            normalise=bool(normalise), ci_mode=ci_mode,
        )
        if backend == "sharded":
            from dpcorr.parallel import run_detail_sharded

            res = run_detail_sharded(cfg, key=rng.design_key(master, i))
        else:
            res = run_sim_one(cfg, key=rng.design_key(master, i))
        frame = pd.DataFrame({k: pd.array(v) for k, v in res.detail.items()})
        frame.insert(0, "repl", range(1, cfg.b + 1))
        frame["n"] = cfg.n
        frame["rho_true"] = cfg.rho
        frame["eps1"] = cfg.eps1
        frame["eps2"] = cfg.eps2
        frames.append(frame)
    return pd.concat(frames, ignore_index=True)


def run_hrs_sweep(eps_grid: Sequence[float], reps: int = 200,
                  seed: int = rng.MASTER_SEED) -> pd.DataFrame:
    """HRS ε-sweep for the R front-end (real-data-sims.R:342-448 seam)."""
    from dpcorr import hrs

    cfg = hrs.HrsConfig(seed=int(seed))
    summ = hrs.eps_sweep(cfg, eps_grid=[float(e) for e in eps_grid],
                         reps=int(reps))
    return summ
