"""dpcorr — TPU-native (JAX/XLA) differentially-private correlation estimation.

A ground-up rebuild of the capabilities of the R reference
``abhinavc3/distributed-correlation`` (simulation code for *"When Data Can't
Meet: Estimating Correlation Across Privacy Barriers"*): NI/INT sign-based and
sub-Gaussian clipped DP correlation estimators with confidence intervals, the
Monte-Carlo simulation grids, and the HRS real-data pipeline — re-designed
TPU-first as ``jit``/``vmap``-batched kernels with replications sharded across
device meshes via ``shard_map``.

Package map (see SURVEY.md §7 for the blueprint):

- ``dpcorr.ops``      — DP primitives: Laplace noise, clipping, clipping
  thresholds (λ rules), mixture quantiles, DP standardization.
- ``dpcorr.models``   — data-generating processes and the four estimator
  families (NI/INT × sign/sub-Gaussian) with their CI constructors.
- ``dpcorr.sim``      — the Monte-Carlo simulator (``run_sim_one``) as one
  ``jit(vmap(...))`` kernel over replications.
- ``dpcorr.parallel`` — device mesh utilities and the sharded grid backend
  (replications across devices, XLA collectives for reductions).
- ``dpcorr.grid``     — the design-grid driver (expand-grid → sharded
  execution → persistence → summaries) replacing the reference's
  ``parallel::mclapply`` fan-out (vert-cor.R:534, ver-cor-subG.R:294).
- ``dpcorr.io``       — native RDS reader + HRS panel ingest.
- ``dpcorr.hrs``      — HRS BMI-vs-Age DP pipeline + ε-sweep.
- ``dpcorr.report``   — summary tables and figure families.
- ``dpcorr.utils``    — RNG key-tree, configs, profiling, checkpointing.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("DPCORR_SYNCWATCH") == "1":
    # must run before any dpcorr submodule allocates a lock: syncwatch
    # wraps the threading.Lock/RLock factories, and only locks created
    # *after* enable() are witnessed (docs/STATIC_ANALYSIS.md §Deep).
    from dpcorr.utils import syncwatch as _syncwatch

    _syncwatch.enable()


def __getattr__(name):  # PEP 562: lazy re-export
    """``dpcorr.MASTER_SEED`` without importing JAX at package-import
    time — keeps JAX-free consumers (``dpcorr.utils.doctor``, the bench
    orchestrator's stray sweep, ``python -m dpcorr doctor``) from paying
    the jax import (and, on machines without the site-hook preload,
    from pulling jax into processes that never touch a device)."""
    if name == "MASTER_SEED":
        from dpcorr.utils.rng import MASTER_SEED
        return MASTER_SEED
    raise AttributeError(f"module 'dpcorr' has no attribute {name!r}")
