"""The executor: compile, dispatch and fetch for one placement.

One :class:`Executor` owns the mechanics every dispatch site used to
hand-roll:

- **compile** — :meth:`Executor.prepare` builds a :class:`Prepared`
  unit through ``utils.compile.aot_compile`` (the only legal
  ``jit(...).lower(...).compile()`` site — lint rule
  ``aot-outside-compile-layer``), de-duplicated per key by a
  :class:`~dpcorr.utils.compile.SingleFlight` so concurrent callers of
  the same signature share one build.
- **dispatch** — operands are placed on the placement's declared
  sharding *before* the call (:meth:`Executor.preshard`), so jit never
  inserts an implicit resharding copy; the call itself stays
  asynchronous.
- **fetch** — :meth:`Executor.fetch` is the single sanctioned host
  sync per plan, counted into ``obs.transfer`` fetches so a rising
  fetches:dispatches ratio is visible in every artifact.

A :class:`Prepared` keeps the lazily-jitted program as its fallback:
the AOT executable is strict about shapes, and an off-signature
dispatch (e.g. a partial-resume bucket with fewer points) degrades to
the jit call it would have made anyway — never an error.
"""

from __future__ import annotations

import logging
import threading

from dpcorr.plan.placement import Placement, resolve_placement
from dpcorr.utils import compile as compile_mod

log = logging.getLogger("dpcorr.plan")


class Prepared:
    """One compiled plan unit. Call it with the *dynamic* arguments
    only: the AOT executable is tried first (when lowering succeeded),
    and any rejection falls back to ``fallback`` — the consumer's
    lazily-jitted call with its static arguments re-bound."""

    __slots__ = ("key", "fn", "fallback", "aot_ok", "signature")

    def __init__(self, key, fn, fallback, aot_ok, signature=None):
        self.key = key
        self.fn = fn
        self.fallback = fallback
        self.aot_ok = aot_ok
        self.signature = dict(signature or {})

    def __call__(self, *dyn):
        if self.aot_ok:
            try:
                return self.fn(*dyn)
            except Exception as e:  # off-signature shapes, mostly
                log.warning("prepared unit %s rejected dispatch args: "
                            "%s -- lazy jit path",
                            self.signature or self.key, e)
        return self.fallback(*dyn)


class Executor:
    """Compile/dispatch/fetch for one placement.

    ``placement`` is a name (``"local"``/``"mesh"``/``"multihost"``) or
    a :class:`~dpcorr.plan.placement.Placement`; ``mesh``/``device``
    feed its resolution. ``observer`` is the
    :class:`~dpcorr.utils.compile.CompileObserver` all of this
    executor's compiles report through (serve passes its per-server
    registry); ``counters`` the ``obs.transfer`` bundle fetches and
    preshards are counted into (tests pass their own so concurrent
    executors never cross-contaminate)."""

    def __init__(self, placement="local", *, mesh=None, device=None,
                 observer=None, counters=None, flight=None):
        self.placement: Placement = resolve_placement(
            placement, mesh=mesh, device=device)
        self.observer = observer
        self.flight = flight if flight is not None \
            else compile_mod.SingleFlight()
        self._counters = counters
        self._units: dict = {}  # written only by flight leaders
        self._lock = threading.Lock()

    # ------------------------------------------------------- compile ----
    def counters(self):
        if self._counters is None:
            from dpcorr.obs import transfer as transfer_mod

            self._counters = transfer_mod.default_counters()
        return self._counters

    def _observer(self):
        if self.observer is None:
            self.observer = compile_mod.CompileObserver()
        return self.observer

    def prepare(self, key, jitted, lower_args, *, fallback=None,
                signature=None, parent=None, cache=True):
        """Build (or fetch from this executor's unit cache) the
        :class:`Prepared` for ``jitted`` lowered at ``lower_args`` (full
        argument list, statics concrete, dynamics as avals —
        ``aot_compile``'s contract). ``fallback`` is the dynamic-args
        call used when AOT fails or rejects a shape; it defaults to
        ``jitted`` itself, which is only correct when the program takes
        no static arguments."""
        if cache:
            with self._lock:
                unit = self._units.get(key)
            if unit is not None:
                return unit

        def _build():
            fn, ok = compile_mod.aot_compile(
                jitted, lower_args, signature=signature,
                observer=self._observer(), parent=parent)
            fb = fallback if fallback is not None else jitted
            unit = Prepared(key, fn, fb, ok, signature=signature)
            if cache:
                with self._lock:
                    self._units[key] = unit
            return unit

        unit, _leader = self.flight.do(("plan.prepare", key), _build)
        return unit

    def lazy_unit(self, fallback, *, key=None, signature=None) -> Prepared:
        """A :class:`Prepared` that never AOT-compiled: dispatching it
        is the plain lazy-jit call. Used by consumers whose precompile
        knob is off (or whose fused path just degraded) so every
        dispatch still flows through one unit type."""
        return Prepared(key, None, fallback, False, signature=signature)

    def evict(self, key) -> None:
        """Drop a cached unit and tell the observer, so the next compile
        for the signature is attributed to eviction, not novelty."""
        with self._lock:
            unit = self._units.pop(key, None)
        if unit is not None:
            self._observer().note_evicted(
                compile_mod.signature_key(unit.signature))

    # ------------------------------------------------------ dispatch ----
    def preshard(self, arrays):
        """Batch-axis operands onto the placement's data sharding."""
        return self.placement.preshard(arrays, self.counters())

    def dispatch(self, prepared, args):
        """Preshard ``args`` and launch; returns device futures (the
        call stays asynchronous — pair with one :meth:`fetch`)."""
        return prepared(*self.preshard(tuple(args)))

    # --------------------------------------------------------- fetch ----
    def fetch(self, out):
        """The single sanctioned host sync of a plan: block until the
        dispatched values are resolved and count one fetch into the
        transfer registry. Returns ``out`` (device arrays, now ready)."""
        import jax

        out = jax.block_until_ready(out)
        self.counters().fetches.inc()
        return out
