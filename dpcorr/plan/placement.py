"""Pluggable placement: where a plan's units run and how operands land.

A :class:`Placement` answers the three questions every dispatch site
used to answer privately: which sharding operands and results are
pinned to (so jit never inserts an implicit resharding copy), how the
batch axis pads (mesh placements need a devices-multiple), and which
mesh (if any) kernels are built over. The executor composes these; the
consumers (grid, serve, federation, ``sim.RepBlockPipeline``) just name
one.
"""

from __future__ import annotations


def preshard(arrays, sharding, counters=None):
    """Place inputs on their kernel's declared sharding *before*
    dispatch, so jit never inserts an implicit resharding copy (free on
    one CPU device; through a TPU tunnel it is the silent per-dispatch
    tax the explicit shardings exist to remove). Placements and any
    committed-but-mismatched inputs are counted into the transfer
    registry (``obs.transfer``) so the bench/roofline artifacts can
    attribute them.

    Canonical home of the helper formerly known as
    ``parallel.backend._preshard`` (which now delegates here)."""
    import jax

    from dpcorr.obs import transfer as transfer_mod

    tc = counters if counters is not None else transfer_mod.default_counters()
    out = []
    for a in arrays:
        sh = getattr(a, "sharding", None)
        if sh is not None and sh.is_equivalent_to(sharding, a.ndim):
            out.append(a)
            continue
        if sh is not None and getattr(a, "_committed", False):
            tc.reshard_mismatch.inc()
        a = jax.device_put(a, sharding)
        tc.device_puts.inc()
        try:
            tc.device_put_bytes.inc(float(a.nbytes))
        except Exception:  # typed-key avals may not report nbytes
            pass
        out.append(a)
    return tuple(out)


class Placement:
    """Interface: one answer to "where does this plan run"."""

    name = "?"

    def data_sharding(self):
        """Sharding for batch-axis operands and per-element results."""
        raise NotImplementedError

    def replicated_sharding(self):
        """Sharding for scalars / whole-array operands."""
        raise NotImplementedError

    @property
    def mesh(self):
        return None

    @property
    def device_count(self) -> int:
        return 1

    def mesh_shape(self):
        """``{axis: size}`` for mesh placements, None otherwise — the
        shape bench stamps into artifact detail and the geometry
        autotuner folds into its cache key."""
        return None

    def pad(self, n: int) -> int:
        """Smallest dispatchable batch size >= n for this placement."""
        return int(n)

    def preshard(self, arrays, counters=None):
        return preshard(arrays, self.data_sharding(), counters)

    def describe(self) -> dict:
        return {
            "placement": self.name,
            "device_count": self.device_count,
            "mesh_shape": self.mesh_shape(),
        }


class LocalPlacement(Placement):
    """Today's single-device behavior, bit-identical: everything pinned
    to one explicit device sharding (``utils.compile.host_sharding``),
    no padding, no mesh."""

    name = "local"

    def __init__(self, device=None):
        self._device = device

    def data_sharding(self):
        from dpcorr.utils.compile import host_sharding

        return host_sharding(self._device)

    def replicated_sharding(self):
        return self.data_sharding()


class MeshPlacement(Placement):
    """shard_map/NamedSharding placement over the 1-axis ``rep`` mesh
    (``parallel.mesh.rep_mesh``). Batch axes arrive pre-sharded
    ``P("rep")`` and results leave sharded the same way, so chained
    stages never reshard (SNIPPETS pjit/pre-sharded-input shape)."""

    name = "mesh"

    def __init__(self, mesh=None, n_devices=None):
        if mesh is None:
            from dpcorr.parallel.mesh import rep_mesh

            mesh = rep_mesh(n_devices)
        self._mesh = mesh

    @property
    def mesh(self):
        return self._mesh

    @property
    def device_count(self) -> int:
        return int(self._mesh.devices.size)

    def mesh_shape(self):
        return {str(name): int(size) for name, size
                in zip(self._mesh.axis_names, self._mesh.devices.shape)}

    def data_sharding(self):
        from dpcorr.utils.compile import mesh_shardings

        return mesh_shardings(self._mesh)[0]

    def replicated_sharding(self):
        from dpcorr.utils.compile import mesh_shardings

        return mesh_shardings(self._mesh)[1]

    def pad(self, n: int) -> int:
        d = self.device_count
        return -(-int(n) // d) * d


class MultihostPlacement(Placement):
    """The multihost/remote seam. Resolvable by name so plans can state
    the intent, but every execution surface raises with the recipe:
    initialize the distributed runtime, then extend
    :class:`MeshPlacement` over the global mesh."""

    name = "multihost"

    @property
    def device_count(self) -> int:
        return 0  # unknown until the distributed runtime is up

    def _unavailable(self):
        raise NotImplementedError(
            "multihost placement is a seam, not an implementation yet: "
            "initialize the distributed runtime first "
            "(dpcorr.parallel.multihost.init_distributed), then build a "
            "MeshPlacement over the global device mesh — see "
            "docs/PERFORMANCE.md §multi-device.")

    def data_sharding(self):
        self._unavailable()

    def replicated_sharding(self):
        self._unavailable()

    def pad(self, n: int) -> int:
        self._unavailable()


def resolve_placement(spec, *, mesh=None, device=None) -> Placement:
    """``spec`` is a Placement (returned as-is) or one of the names
    ``"local"`` / ``"mesh"`` / ``"multihost"`` (None means local).
    ``mesh`` feeds a mesh placement; ``device`` pins a local one."""
    if isinstance(spec, Placement):
        return spec
    if spec is None or spec == "local":
        return LocalPlacement(device)
    if spec == "mesh":
        return MeshPlacement(mesh)
    if spec == "multihost":
        return MultihostPlacement()
    raise ValueError(
        f"unknown placement {spec!r}: expected 'local', 'mesh', or "
        "'multihost' (or a Placement instance)")
