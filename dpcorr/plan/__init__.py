"""Plan/executor layer: bucket → compile → dispatch → fetch, once.

Three subsystems used to reimplement the same four-phase dispatch shape
privately — the grid's bucketed phases (``dpcorr.grid``), the serving
kernel cache + coalescer (``dpcorr.serve.kernels``), and federation's
``finish_batch`` (``dpcorr.models.estimators.split_reference``). This
package owns that shape in one place, with *placement* pluggable:

- ``local``   — today's single-device behavior, bit-identical;
- ``mesh``    — shard_map/NamedSharding over ``parallel.mesh`` with
  matching in/out shardings so no stage reshards;
- ``multihost`` — a named seam (clear NotImplementedError pointing at
  ``parallel.multihost.init_distributed``), not an implementation.

``utils.compile`` stays the only legal ``jit(...).lower(...).compile()``
site (lint rule ``aot-outside-compile-layer``); the executor routes all
AOT builds through it and counts the single sanctioned host fetch per
plan into ``obs.transfer``.
"""

from dpcorr.plan.executor import Executor, Prepared
from dpcorr.plan.placement import (
    LocalPlacement,
    MeshPlacement,
    MultihostPlacement,
    Placement,
    preshard,
    resolve_placement,
)

__all__ = [
    "Executor",
    "LocalPlacement",
    "MeshPlacement",
    "MultihostPlacement",
    "Placement",
    "Prepared",
    "preshard",
    "resolve_placement",
]
