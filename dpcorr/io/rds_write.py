"""Pure-Python writer for R serialization format (RDS), XDR flavor.

The reference persists its replicate tables with ``saveRDS(detail_all,
"sim_detail_all.rds")`` (vert-cor.R:569, ver-cor-subG.R:314) and its
downstream lives in R. The grid driver here writes parquet for the Python
world; this module closes the R-facing half of the checkpoint contract
(SURVEY.md §5 checkpoint/resume): ``write_rds_table`` emits a
``data.frame`` .rds that R's ``readRDS`` consumes directly — no reticulate
needed to hand results back to the reference's own data.table/ggplot code.

Scope: version-3 XDR streams of one data.frame with double / integer /
logical / string columns (exactly what the replicate tables contain —
the write-side mirror of the subset ``rds_py`` reads). Round-trip
validation runs against this repo's two independent readers (pure-Python
and the native C++ one), both of which were validated against real
R-produced files (the HRS panel).

Layout notes (mirrors ``rds_py``'s grammar, R serialize.c):
- item flags word: bits 0-7 SEXP type, 0x100 object bit (class set),
  0x200 has-attributes, 0x400 has-tag; CHARSXP encoding rides the
  levels field (``ASCII << 12`` / ``UTF8 << 12``).
- attributes are a tagged pairlist terminated by NILVALUE (254);
  symbols are emitted inline (legal — the reference table is an
  optimization, not a requirement).
- row.names uses R's compact internal form ``c(NA_integer_, -n)``.
"""

from __future__ import annotations

import gzip
import struct
from typing import Any, Mapping

import numpy as np

from dpcorr.io.rds_py import (
    CHARSXP,
    INTSXP,
    LGLSXP,
    LISTSXP,
    NILVALUE_SXP,
    R_NA_INT,
    R_NA_REAL_BITS,
    REALSXP,
    STRSXP,
    SYMSXP,
    VECSXP,
)

_HAS_ATTR = 0x200
_HAS_TAG = 0x400
_IS_OBJECT = 0x100
_ASCII_MASK = 64  # CHARSXP gp levels bit
_UTF8_MASK = 8


def _na_kind(v) -> str | None:
    """Classify one object-column value: ``"absent"`` for None / pd.NA
    (R's NA), ``"nan"`` for a true float NaN (a computed value — R's NaN),
    ``None`` for a live value."""
    if v is None:
        return "absent"
    try:
        return "nan" if bool(v != v) else None
    except Exception:  # pd.NA: `v != v` is NA and bool(NA) raises
        return "absent"


# R's NA_real_ is a specific quiet-NaN payload (R arithmetic.c, the same
# bits ``rds_py.real_is_na`` recognizes on the read side). numpy reads it
# back as NaN; R's is.na() is TRUE and is.nan() FALSE, as for saveRDS'd NA.
_R_NA_REAL = struct.pack(">Q", R_NA_REAL_BITS)


class _Writer:
    def __init__(self) -> None:
        self.parts: list[bytes] = []

    def raw(self, b: bytes) -> None:
        self.parts.append(b)

    def i32(self, v: int) -> None:
        self.raw(struct.pack(">i", v))

    def flags(self, ptype: int, *, levels: int = 0, is_object: bool = False,
              has_attr: bool = False, has_tag: bool = False) -> None:
        self.i32(ptype | (levels << 12)
                 | (_IS_OBJECT if is_object else 0)
                 | (_HAS_ATTR if has_attr else 0)
                 | (_HAS_TAG if has_tag else 0))

    # ---- header ----
    def header(self) -> None:
        self.raw(b"X\n")
        self.i32(3)        # serialization version 3
        self.i32(0x040301)  # writer "R 4.3.1"
        self.i32(0x030500)  # minimal reader R 3.5.0
        enc = b"UTF-8"
        self.i32(len(enc))
        self.raw(enc)

    # ---- leaf items ----
    def charsxp(self, s: str | None) -> None:
        if s is None:  # NA_character_
            self.flags(CHARSXP, levels=_ASCII_MASK)
            self.i32(-1)
            return
        b = s.encode("utf-8")
        self.flags(CHARSXP,
                   levels=_ASCII_MASK if s.isascii() else _UTF8_MASK)
        self.i32(len(b))
        self.raw(b)

    def strsxp(self, values: list) -> None:
        self.flags(STRSXP)
        self.i32(len(values))
        for v in values:
            self.charsxp(None if v is None else str(v))

    def symbol(self, name: str) -> None:
        self.flags(SYMSXP)
        self.charsxp(name)

    def realsxp(self, arr: np.ndarray, na_mask=None) -> None:
        self.flags(REALSXP)
        self.i32(arr.size)
        buf = np.ascontiguousarray(arr, dtype=">f8").tobytes()
        if na_mask is not None and np.any(na_mask):
            buf = bytearray(buf)
            for i in np.flatnonzero(na_mask):
                buf[8 * i:8 * i + 8] = _R_NA_REAL
            buf = bytes(buf)
        self.raw(buf)

    def intsxp(self, arr: np.ndarray, ptype: int = INTSXP) -> None:
        self.flags(ptype)
        self.i32(arr.size)
        self.raw(np.ascontiguousarray(arr, dtype=">i4").tobytes())

    # ---- the data.frame ----
    def data_frame(self, columns: Mapping[str, Any], n_rows: int) -> None:
        self.flags(VECSXP, is_object=True, has_attr=True)
        self.i32(len(columns))
        for values in columns.values():
            self._column(values)
        # attributes pairlist: names, row.names (compact), class
        self.flags(LISTSXP, has_tag=True)
        self.symbol("names")
        self.strsxp(list(columns.keys()))
        self.flags(LISTSXP, has_tag=True)
        self.symbol("row.names")
        self.intsxp(np.asarray([R_NA_INT, -n_rows], dtype=np.int64))
        self.flags(LISTSXP, has_tag=True)
        self.symbol("class")
        self.strsxp(["data.frame"])
        self.i32(NILVALUE_SXP)  # end of pairlist

    def _column(self, values: Any) -> None:
        arr = values if isinstance(values, np.ndarray) else np.asarray(values)
        if arr.dtype.kind in "OU":
            vals = list(arr)
            kinds = [_na_kind(v) for v in vals]
            na = [k is not None for k in kinds]
            live = [v for v, m in zip(vals, na) if not m]
            if all(isinstance(v, str) for v in live):
                self.strsxp([None if m else str(v)
                             for v, m in zip(vals, na)])
            elif all(isinstance(v, (bool, np.bool_)) for v in live):
                # e.g. a pandas nullable-boolean column via to_numpy()
                self.intsxp(np.asarray(
                    [R_NA_INT if m else int(bool(v))
                     for v, m in zip(vals, na)], dtype=np.int64),
                    ptype=LGLSXP)
            else:
                # object-dtype numerics (pandas nullable Int64, plain
                # number lists): coerce numerically — NEVER silently
                # stringify; a non-numeric mix raises instead
                try:
                    arr_f = np.asarray([np.nan if m else float(v)
                                        for v, m in zip(vals, na)],
                                       dtype=np.float64)
                except (TypeError, ValueError) as e:
                    raise TypeError(
                        "column mixes non-numeric, non-string values "
                        f"({e})") from e
                # absent values (None/pd.NA) get R's NA_real_ payload;
                # a float NaN that was *in* the column stays plain NaN
                self.realsxp(arr_f, na_mask=np.asarray(
                    [k == "absent" for k in kinds], dtype=bool))
            return
        if arr.dtype.kind == "b":
            self.intsxp(arr.astype(np.int64), ptype=LGLSXP)
        elif arr.dtype.kind in "iu":
            if arr.size and (arr.max(initial=0) > 2**31 - 1
                             or arr.min(initial=0) <= -(2**31)):
                self.realsxp(arr.astype(np.float64))  # R ints are 32-bit
            else:
                self.intsxp(arr.astype(np.int64))
        elif arr.dtype.kind == "f":
            self.realsxp(arr.astype(np.float64))
        else:
            raise TypeError(f"unsupported column dtype {arr.dtype}")


def write_rds_table(path: str, columns: Mapping[str, Any],
                    compress: bool = True) -> None:
    """Write ``{name: values}`` as a data.frame .rds (``saveRDS``-shaped:
    version-3 XDR, gzip by default, matching R's default compress="gzip").

    Columns: float arrays → REALSXP (NaN kept as IEEE NaN — R's is.na()
    is TRUE for it but is.nan() distinguishes it from NA_real_; a float64
    array carries no missing/NaN distinction to recover), int arrays →
    INTSXP (64-bit values that overflow R's 32-bit ints are promoted to
    doubles, as R itself would store them), bool → LGLSXP, all-string
    object sequences → STRSXP with None/NaN/pd.NA as NA_character_.
    Object-dtype numerics (plain number lists, pandas nullable
    Int64/boolean via ``to_numpy()``) coerce to REALSXP/LGLSXP where the
    truly *absent* entries (None/pd.NA) are written as R's ``NA_real_``
    payload — bit-faithful to saveRDS — while an actual NaN value stays
    NaN; never silently to strings, and a non-numeric, non-string mix
    raises. All columns must share one length.
    """
    sizes = {len(v) if isinstance(v, (list, tuple)) else np.asarray(v).size
             for v in columns.values()}
    if len(sizes) > 1:
        raise ValueError(f"ragged columns: lengths {sorted(sizes)}")
    n_rows = sizes.pop() if sizes else 0
    w = _Writer()
    w.header()
    w.data_frame(columns, n_rows)
    blob = b"".join(w.parts)
    if compress:
        # mtime=0 → deterministic bytes for identical tables
        blob = gzip.compress(blob, mtime=0)
    with open(path, "wb") as f:
        f.write(blob)


def write_rds_frame(path: str, df, compress: bool = True) -> None:
    """``write_rds_table`` for a pandas DataFrame (the grid's
    ``detail_all`` shape — the reference's ``saveRDS(detail_all, ...)``
    call, vert-cor.R:569)."""
    write_rds_table(path,
                    {str(c): df[c].to_numpy() for c in df.columns},
                    compress=compress)
