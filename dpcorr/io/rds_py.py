"""Pure-Python reader for R serialization format (RDS), XDR flavor.

The reference's real-data pipeline starts at ``readRDS("hrs_long_panel.rds")``
(real-data-sims.R:13). No RDS reader exists in this environment, so the
framework carries its own: this module is the reference implementation and
portable fallback; ``dpcorr.io._native`` is the C++ fast path with the same
output contract (see ``native/rdsread.cpp``).

Scope: the R serialization grammar as emitted by ``saveRDS`` version 2/3 in
XDR ("X\\n") encoding — atomic vectors (LGL/INT/REAL/CPLX/STR/RAW), pairlists
with attributes/tags, generic vectors (lists), symbols with the reference
table, CHARSXP encodings, long vectors, and the ALTREP wrappers R ≥ 3.5
emits for compact sequences and wrapped/deferred vectors. Environments,
closures, promises, bytecode, and S4 are out of scope (``saveRDS`` of plain
data never produces them) and raise.

Output: :class:`RObj` trees of numpy arrays / string lists plus attribute
dicts; :func:`read_rds` returns the root, :func:`read_rds_table` flattens a
data.frame/tibble into a column dict (the shape ``dpcorr.hrs`` consumes).
"""

from __future__ import annotations

import dataclasses
import gzip
import struct
from typing import Any

import numpy as np

# SEXP type codes (R internals)
NILSXP, SYMSXP, LISTSXP = 0, 1, 2
CHARSXP, LGLSXP, INTSXP, REALSXP, CPLXSXP, STRSXP = 9, 10, 13, 14, 15, 16
VECSXP, EXPRSXP, RAWSXP = 19, 20, 24
LANGSXP = 6
# serialization-only pseudo-types
REFSXP, NILVALUE_SXP, GLOBALENV_SXP = 255, 254, 253
NAMESPACESXP, PACKAGESXP, PERSISTSXP = 249, 248, 247
EMPTYENV_SXP, BASEENV_SXP = 242, 241
ATTRLANGSXP, ATTRLISTSXP = 240, 239
ALTREP_SXP = 238

#: R's integer/logical NA payload
R_NA_INT = -0x80000000
#: R's real NA: an NaN with payload 1954 in the low word
R_NA_REAL_BITS = 0x7FF00000000007A2


@dataclasses.dataclass
class RObj:
    """One R object: ``data`` is a numpy array (atomic), list (STRSXP or
    VECSXP elements), str (symbol name), or None."""

    type: int
    data: Any = None
    attributes: dict | None = None

    def attr(self, name: str, default=None):
        return (self.attributes or {}).get(name, default)

    @property
    def names(self):
        nm = self.attr("names")
        return None if nm is None else nm.data

    @property
    def rclass(self):
        cl = self.attr("class")
        return [] if cl is None else list(cl.data)


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0
        self.refs: list[Any] = []
        self.encoding = "utf-8"

    # ---- primitive reads (XDR = big-endian) ----
    def _take(self, n: int) -> bytes:
        b = self.buf[self.pos: self.pos + n]
        if len(b) != n:
            raise EOFError(f"truncated RDS stream at byte {self.pos}")
        self.pos += n
        return b

    def u8(self) -> int:
        return self._take(1)[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def f64(self) -> float:
        return struct.unpack(">d", self._take(8))[0]

    def length(self) -> int:
        n = self.i32()
        if n == -1:  # long vector: two more ints, 2^32*hi + lo
            hi, lo = self.i32(), self.i32()
            n = (hi << 32) + (lo & 0xFFFFFFFF)
        return n

    # ---- header ----
    def header(self) -> None:
        magic = self._take(2)
        if magic != b"X\n":
            raise ValueError(
                f"unsupported RDS encoding {magic!r} (only XDR 'X\\n')")
        version = self.i32()
        self.i32()  # writer R version
        self.i32()  # minimal reader R version
        if version >= 3:
            enc_len = self.i32()
            self.encoding = self._take(enc_len).decode("ascii")
        elif version != 2:
            raise ValueError(f"unsupported RDS version {version}")

    # ---- items ----
    def item(self) -> RObj:
        flags = self.i32()
        ptype = flags & 0xFF
        has_attr = bool(flags & 0x200)
        has_tag = bool(flags & 0x400)

        if ptype == NILVALUE_SXP or ptype == NILSXP:
            return RObj(NILSXP)
        if ptype == REFSXP:
            idx = flags >> 8
            if idx == 0:
                idx = self.i32()
            return self.refs[idx - 1]  # 1-based
        if ptype == SYMSXP:
            char = self.item()
            sym = RObj(SYMSXP, data=char.data)
            self.refs.append(sym)
            return sym
        if ptype in (GLOBALENV_SXP, EMPTYENV_SXP, BASEENV_SXP):
            return RObj(NILSXP)
        if ptype in (NAMESPACESXP, PACKAGESXP, PERSISTSXP):
            # InStringVec format: a compatibility 0, then length, then names
            self.i32()
            obj = RObj(ptype, data=self._strsxp(self.i32()))
            self.refs.append(obj)
            return obj
        if ptype in (LISTSXP, LANGSXP, ATTRLISTSXP, ATTRLANGSXP):
            return self._pairlist(ptype, has_attr, has_tag)
        if ptype == ALTREP_SXP:
            return self._altrep()
        if ptype == CHARSXP:
            n = self.i32()
            if n == -1:
                return RObj(CHARSXP, data=None)  # NA_character_
            return RObj(CHARSXP, data=self._take(n).decode(self.encoding,
                                                           "replace"))
        data: Any
        if ptype in (LGLSXP, INTSXP):
            n = self.length()
            data = np.frombuffer(self._take(4 * n), dtype=">i4").astype(np.int32)
        elif ptype == REALSXP:
            n = self.length()
            data = np.frombuffer(self._take(8 * n), dtype=">f8").astype(np.float64)
        elif ptype == CPLXSXP:
            n = self.length()
            data = np.frombuffer(self._take(16 * n), dtype=">c16").astype(np.complex128)
        elif ptype == RAWSXP:
            n = self.length()
            data = np.frombuffer(self._take(n), dtype=np.uint8).copy()
        elif ptype == STRSXP:
            data = self._strsxp(self.length())
        elif ptype in (VECSXP, EXPRSXP):
            n = self.length()
            data = [self.item() for _ in range(n)]
        else:
            raise ValueError(f"unsupported SEXP type {ptype} in RDS stream "
                             f"(byte {self.pos})")
        obj = RObj(ptype, data=data)
        if has_attr:
            obj.attributes = self._attrs()
        return obj

    def _strsxp(self, n: int) -> list:
        return [self.item().data for _ in range(n)]

    def _pairlist(self, ptype: int, has_attr: bool, has_tag: bool) -> RObj:
        """Pairlist read as a Python list of (tag, value); attributes on the
        whole list are rare for data and folded into the first node."""
        items = []
        attrs = self._attrs() if has_attr else None
        while True:
            tag = None
            if has_tag:
                tag_obj = self.item()
                tag = tag_obj.data
            items.append((tag, self.item()))
            flags = self.i32()
            nxt = flags & 0xFF
            if nxt in (NILVALUE_SXP, NILSXP):
                break
            if nxt not in (LISTSXP, LANGSXP, ATTRLISTSXP, ATTRLANGSXP):
                # cdr is a non-pairlist object: re-dispatch it
                self.pos -= 4
                items.append((None, self.item()))
                break
            if flags & 0x200:
                self._attrs()  # attributes on an interior cons cell: drop
            has_tag = bool(flags & 0x400)
        obj = RObj(LISTSXP, data=items)
        obj.attributes = attrs
        return obj

    def _attrs(self) -> dict:
        plist = self.item()
        if plist.type == NILSXP:
            return {}
        return {tag: val for tag, val in plist.data if tag is not None}

    # ---- ALTREP reconstruction ----
    def _altrep(self) -> RObj:
        info = self.item()   # pairlist: (class-sym, package-sym, type int)
        state = self.item()
        attr = self.item()
        cls = info.data[0][1].data if info.type == LISTSXP else None
        obj = self._expand_altrep(cls, state)
        if attr.type == LISTSXP:
            obj.attributes = {t: v for t, v in attr.data if t is not None}
        return obj

    def _expand_altrep(self, cls: str | None, state: RObj) -> RObj:
        if cls == "compact_intseq":
            n, start, step = (float(v) for v in state.data[:3])
            return RObj(INTSXP, data=np.arange(
                start, start + step * n, step, dtype=np.int32)[: int(n)])
        if cls == "compact_realseq":
            n, start, step = (float(v) for v in state.data[:3])
            return RObj(REALSXP, data=np.arange(
                start, start + step * n, step, dtype=np.float64)[: int(n)])
        if cls in ("wrap_logical", "wrap_integer", "wrap_real", "wrap_string",
                   "wrap_complex", "wrap_raw"):
            return _altrep_payload(state)
        if cls == "deferred_string":
            src = _altrep_payload(state)
            vals = ["" if v is None else _r_num_str(v) for v in
                    np.asarray(src.data).tolist()]
            return RObj(STRSXP, data=vals)
        raise ValueError(f"unsupported ALTREP class {cls!r}")


def _altrep_payload(state: RObj) -> RObj:
    """First element of an ALTREP wrapper's state.

    R serializes wrapper state as CONS(wrapped, metadata) — a LISTSXP whose
    pairs are untagged — though a VECSXP form also exists; atomic state is
    already the payload.
    """
    if state.type == LISTSXP:
        return state.data[0][1]
    if state.type == VECSXP:
        return state.data[0]
    return state


def _r_num_str(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def real_is_na(arr: np.ndarray) -> np.ndarray:
    """Mask of R ``NA_real_`` (distinct from NaN) in a float64 array."""
    return arr.view(np.uint64) == R_NA_REAL_BITS


def decode_real(arr: np.ndarray) -> np.ndarray:
    """R doubles → numpy float64 with NA mapped to NaN (already NaN-valued;
    this is the identity but documents the NA story)."""
    return arr


def decode_int(arr: np.ndarray) -> np.ndarray:
    """R integers → float64 with NA (INT_MIN) mapped to NaN."""
    out = arr.astype(np.float64)
    out[arr == R_NA_INT] = np.nan
    return out


def read_rds(path: str) -> RObj:
    """Read a .rds file (gzip/bzip2/xz-compressed or plain) into an
    :class:`RObj`. All three are first-class ``saveRDS`` compress modes."""
    with open(path, "rb") as f:
        head = f.read(6)
    if head.startswith(b"\x1f\x8b"):
        opener = gzip.open
    elif head.startswith(b"BZh"):
        import bz2
        opener = bz2.open
    elif head.startswith(b"\xfd7zXZ\x00"):
        import lzma
        opener = lzma.open
    else:
        opener = open
    with opener(path, "rb") as f:
        buf = f.read()
    rd = _Reader(buf)
    rd.header()
    return rd.item()


@dataclasses.dataclass
class RColumn:
    """One data.frame column, decoded.

    ``kind``: "double" | "integer" | "logical" | "string" | "factor".
    ``values``: float64 array (NA→NaN) for numerics, list[str|None]
    otherwise; factors keep integer codes (NA→NaN) + ``levels``.
    ``labels``: haven value-labels mapping, if present.
    """

    name: str
    kind: str
    values: Any
    levels: list | None = None
    labels: dict | None = None
    label: str | None = None


def _decode_column(name: str, col: RObj) -> RColumn:
    cls = col.rclass
    lab = col.attr("label")
    label = lab.data[0] if lab is not None and lab.data else None
    labels_attr = col.attr("labels")
    labels = None
    if labels_attr is not None:
        lv = np.asarray(labels_attr.data, dtype=np.float64)
        labels = dict(zip(labels_attr.names or [], lv.tolist()))
    if "factor" in cls:
        levels = col.attr("levels")
        return RColumn(name, "factor", decode_int(col.data),
                       levels=list(levels.data) if levels else [],
                       label=label)
    if col.type == REALSXP:
        return RColumn(name, "double", decode_real(col.data),
                       labels=labels, label=label)
    if col.type == INTSXP:
        return RColumn(name, "integer", decode_int(col.data),
                       labels=labels, label=label)
    if col.type == LGLSXP:
        return RColumn(name, "logical", decode_int(col.data), label=label)
    if col.type == STRSXP:
        return RColumn(name, "string", col.data, label=label)
    raise ValueError(f"column {name!r}: unsupported type {col.type}")


def read_rds_table(path: str) -> dict[str, RColumn]:
    """Read a data.frame/tibble .rds into ``{name: RColumn}`` (ordered)."""
    root = read_rds(path)
    if root.type != VECSXP or "data.frame" not in root.rclass:
        raise ValueError(f"{path}: not a data.frame (class {root.rclass})")
    names = root.names or []
    return {nm: _decode_column(nm, col)
            for nm, col in zip(names, root.data, strict=True)}
