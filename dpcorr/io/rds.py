"""RDS ingest front-end: native C++ fast path, pure-Python fallback.

``read_rds_table(path)`` is the one public entry (the framework's
``readRDS``, reference real-data-sims.R:13). It prefers the C++ reader
(``native/rdsread.cpp`` → ``libdpcorr_rds.so``, loaded via ctypes and built
on demand with ``make -C native`` if a toolchain is present) and falls back
to :mod:`dpcorr.io.rds_py` — both produce identical
:class:`~dpcorr.io.rds_py.RColumn` dicts, enforced by ``tests/test_rds.py``.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path

import numpy as np

from dpcorr.io import rds_py
from dpcorr.io.rds_py import RColumn

log = logging.getLogger("dpcorr.io.rds")

_NATIVE_DIR = Path(__file__).parent / "_native"
_LIB_PATH = _NATIVE_DIR / "libdpcorr_rds.so"
_lib = None
_lib_tried = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64 = ctypes.c_int64
    lib.rds_read_table.restype = ctypes.c_void_p
    lib.rds_read_table.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int]
    lib.rds_table_ncols.argtypes = [ctypes.c_void_p]
    lib.rds_table_nrows.restype = i64
    lib.rds_table_nrows.argtypes = [ctypes.c_void_p]
    lib.rds_col_name.restype = ctypes.c_char_p
    lib.rds_col_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rds_col_kind.restype = ctypes.c_char_p
    lib.rds_col_kind.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rds_col_num.restype = ctypes.POINTER(ctypes.c_double)
    lib.rds_col_num.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rds_col_num_len.restype = i64
    lib.rds_col_num_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rds_col_str_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.rds_col_str_blob.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.POINTER(i64)]
    lib.rds_col_str_offsets.restype = ctypes.POINTER(i64)
    lib.rds_col_str_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(i64)]
    lib.rds_col_nlevels.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rds_col_level.restype = ctypes.c_char_p
    lib.rds_col_level.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.rds_col_nlabels.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rds_col_label_name.restype = ctypes.c_char_p
    lib.rds_col_label_name.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int]
    lib.rds_col_label_value.restype = ctypes.c_double
    lib.rds_col_label_value.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int]
    lib.rds_col_var_label.restype = ctypes.c_char_p
    lib.rds_col_var_label.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rds_table_free.argtypes = [ctypes.c_void_p]
    return lib


def _ensure_native():
    """Load (building if necessary) the native reader; None if unavailable.

    Controlled by ``DPCORR_NO_NATIVE=1`` (force the Python path, used by the
    parity tests) — any build/load failure degrades silently to Python.
    """
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("DPCORR_NO_NATIVE") == "1":
        return None
    try:
        if not _LIB_PATH.exists():
            native_dir = Path(__file__).parents[2] / "native"
            if not (native_dir / "Makefile").exists():
                return None
            subprocess.run(["make", "-C", str(native_dir)], check=True,
                           capture_output=True, timeout=120)
        _lib = _bind(ctypes.CDLL(str(_LIB_PATH)))
    except Exception as e:  # toolchain/load problems → portable path
        log.info("native RDS reader unavailable (%s); using Python parser", e)
        _lib = None
    return _lib


def _native_columns(lib, handle) -> dict[str, RColumn]:
    i64 = ctypes.c_int64
    out: dict[str, RColumn] = {}
    nrows = lib.rds_table_nrows(handle)
    for j in range(lib.rds_table_ncols(handle)):
        name = lib.rds_col_name(handle, j).decode()
        kind = lib.rds_col_kind(handle, j).decode()
        nlab = lib.rds_col_nlabels(handle, j)
        labels = {lib.rds_col_label_name(handle, j, k).decode():
                  lib.rds_col_label_value(handle, j, k)
                  for k in range(nlab)} or None
        raw = lib.rds_col_var_label(handle, j)
        var_label = raw.decode() if raw is not None else None
        if kind == "string":
            blob_len, noff = i64(), i64()
            blob = lib.rds_col_str_blob(handle, j, ctypes.byref(blob_len))
            offs = lib.rds_col_str_offsets(handle, j, ctypes.byref(noff))
            data = ctypes.string_at(blob, blob_len.value)
            off = np.ctypeslib.as_array(offs, shape=(noff.value,))
            values = [None if o < 0 else
                      data[o:data.index(b"\0", o)].decode("utf-8", "replace")
                      for o in off.tolist()]
            out[name] = RColumn(name, kind, values, label=var_label)
            continue
        n = lib.rds_col_num_len(handle, j)
        ptr = lib.rds_col_num(handle, j)
        vals = np.ctypeslib.as_array(ptr, shape=(int(n),)).copy()
        levels = ([lib.rds_col_level(handle, j, k).decode()
                   for k in range(lib.rds_col_nlevels(handle, j))]
                  if kind == "factor" else None)
        out[name] = RColumn(name, kind, vals, levels=levels, labels=labels,
                            label=var_label)
    if out and nrows >= 0:
        pass  # nrows retrievable for API users; RColumns carry lengths
    return out


def read_rds_table(path: str | os.PathLike) -> dict[str, RColumn]:
    """Read a data.frame/tibble ``.rds`` file into ``{name: RColumn}``."""
    path = os.fspath(path)
    lib = _ensure_native()
    if lib is not None:
        err = ctypes.create_string_buffer(512)
        handle = lib.rds_read_table(path.encode(), err, len(err))
        if handle:
            try:
                return _native_columns(lib, handle)
            finally:
                lib.rds_table_free(handle)
        log.warning("native RDS reader failed on %s (%s); falling back",
                    path, err.value.decode(errors="replace"))
    return rds_py.read_rds_table(path)
