"""Command-line entry points: ``python -m dpcorr <command>``.

Replaces the reference's "source the script" workflow (README.md:28-46):

- ``demo``        single-design-point Gaussian demo (vert-cor.R:449-466)
- ``demo-subg``   sub-Gaussian single point (ver-cor-subG.R:224-233)
- ``grid``        v1 Gaussian sign grid + summaries + figures
                  (vert-cor.R:486-721)
- ``grid-subg``   v2 bounded-factor sub-Gaussian grid (ver-cor-subG.R:245-436)
- ``hrs``         HRS point estimates (real-data-sims.R:259-333)
- ``hrs-sweep``   HRS ε-sweep + panels (real-data-sims.R:342-506)
- ``doctor``      environment health triage (tunnel endpoint, stray TPU
                  clients, compile cache, queue markers; no reference
                  analogue — SURVEY.md §5 failure detection is absent
                  there)
- ``serve``       online micro-batched DP-correlation service
                  (docs/SERVING.md)
- ``lint``        AST-based privacy/RNG/concurrency invariant checker
                  over dpcorr's own source (docs/STATIC_ANALYSIS.md);
                  jax-free, wired into CI as the gate before the test
                  matrix
- ``obs``         telemetry tooling (docs/OBSERVABILITY.md): ``obs
                  budget`` replays a ledger audit trail into the
                  per-party ε-spend timeline; ``obs chrome`` converts a
                  span JSONL log to Chrome trace-event format for
                  Perfetto; ``obs dump`` replays a flight-recorder
                  dump (span chains, cost records, ε trail) jax-free;
                  ``obs top`` is the live ops console over a serve
                  replica's /metrics + /stats
- ``party``       one side of the two-party DP protocol over TCP
                  (docs/PROTOCOL.md): role y listens, role x connects;
                  each process holds one raw column and only DP
                  releases cross the socket
- ``protocol``    ``protocol run`` drives both roles in one process
                  (threads, inproc or loopback TCP); ``protocol scan``
                  is the jax-free transcript auditor (schema,
                  no-raw-columns, ε balance)
- ``chaos``       deterministic step-kill matrix over the two-party
                  protocol: crash a party process at each named point,
                  restart it, prove the resumed session bit-identical
                  with ε spent exactly once (docs/ROBUSTNESS.md)

Grids persist per-design-point ``.npz`` + parquet tables into ``--out`` and
resume from them (the reference only saves one blob at the end).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _add_common(p, backends=("local",)):
    """Shared flags. ``backends`` lists only the execution backends the
    subcommand actually implements — anything else is an argparse error
    rather than a silently-ignored flag."""
    p.add_argument("--out", default=None, help="output directory")
    p.add_argument("--b", type=int, default=None, help="MC replications")
    p.add_argument("--seed", type=int, default=2025)
    p.add_argument("--backend", default=backends[0], choices=list(backends))
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                   help="force a JAX platform before backend init (the site "
                        "hook overrides JAX_PLATFORMS env, so this is the "
                        "only reliable off-TPU switch)")


def cmd_demo(args):
    from dpcorr.sim import SimConfig, run_sim_one

    cfg = SimConfig(n=2000, rho=-0.95, eps1=0.5, eps2=1.0,
                    b=args.b or 1000, seed=args.seed,
                    dgp="gaussian", dgp_args={"mu": (2.0, 2.0),
                                              "sigma": (2.0, 0.1)})
    t0 = time.perf_counter()
    res = run_sim_one(cfg)
    # the config echo is the FULL design point (incl. dgp_args/normalise/
    # seed): tests/test_golden_demo.py pins it against vert-cor.R:449-458,
    # so silent drift in any field would invalidate the R-bridge
    # comparison recipe (docs/R_BRIDGE.md)
    print(json.dumps({"config": {"n": cfg.n, "rho": cfg.rho,
                                 "eps": [cfg.eps1, cfg.eps2], "B": cfg.b,
                                 "dgp": cfg.dgp,
                                 "dgp_args": {k: list(v) for k, v in
                                              dict(cfg.dgp_args).items()},
                                 "normalise": cfg.normalise,
                                 "seed": cfg.seed},
                      "summary": res.summary,
                      "seconds": round(time.perf_counter() - t0, 2)},
                     indent=2))


def cmd_demo_subg(args):
    from dpcorr.sim import SimConfig, run_sim_one

    cfg = SimConfig(n=5500, rho=0.6, eps1=5.0, eps2=1.0, b=args.b or 500,
                    seed=args.seed, dgp="bounded_factor", use_subg=True)
    res = run_sim_one(cfg)
    print(json.dumps({"config": {"n": cfg.n, "rho": cfg.rho,
                                 "eps": [cfg.eps1, cfg.eps2], "B": cfg.b},
                      "summary": res.summary}, indent=2))


def _run_grid(args, gcfg, fig1_n, fig1_eps, family="v1"):
    from dpcorr import report
    from dpcorr.grid import run_grid

    t0 = time.perf_counter()
    if getattr(args, "n_hosts", 1) > 1:
        from dpcorr.parallel import run_grid_multihost

        res = run_grid_multihost(gcfg, n_hosts=args.n_hosts,
                                 platform=args.platform,
                                 distributed=getattr(args, "distributed",
                                                     False),
                                 local_device_count=getattr(
                                     args, "local_devices", None))
    else:
        res = run_grid(gcfg)
    dt = time.perf_counter() - t0
    reps = len(res.detail_all)
    print(f"grid: {reps} replicate rows in {dt:.1f}s "
          f"({reps / dt:.0f} reps/sec incl. compile)")
    print(res.summ_all.to_string(index=False, float_format=lambda v: f"{v:.4f}"))
    if args.out:
        if family == "subg":
            paths = report.render_all_subg(
                grid_detail=res.detail_all, grid_summ=res.summ_all,
                out_dir=args.out, fig1_n=fig1_n, fig1_eps=fig1_eps)
        else:
            paths = report.render_all(grid_detail=res.detail_all,
                                      grid_summ=res.summ_all,
                                      out_dir=args.out,
                                      fig1_n=fig1_n, fig1_eps=fig1_eps)
        print("figures:", *(str(p) for p in paths))


def cmd_grid(args):
    from dpcorr.grid import GridConfig

    gcfg = GridConfig(b=args.b or 250, seed=args.seed, backend=args.backend,
                      fused=args.fused, bucket_merge=args.bucket_merge,
                      precompile=args.precompile, out_dir=args.out)
    _run_grid(args, gcfg, fig1_n=1500, fig1_eps=(1.5, 0.5))


def cmd_grid_subg(args):
    from dpcorr.grid import GridConfig

    gcfg = GridConfig(
        n_grid=(2500, 4000, 6000, 9000, 12000),  # ver-cor-subG.R:245
        b=args.b or 250, dgp="bounded_factor", use_subg=True,
        seed=args.seed, backend=args.backend, fused=args.fused,
        bucket_merge=args.bucket_merge, precompile=args.precompile,
        out_dir=args.out)
    # the reference's subG fig1 slices n=6000 (ver-cor-subG.R:342)
    _run_grid(args, gcfg, fig1_n=6000, fig1_eps=(1.5, 0.5), family="subg")


def cmd_hrs(args):
    from dpcorr import hrs

    res = hrs.point_estimates(hrs.HrsConfig(seed=args.seed))
    print(json.dumps({
        "n": res.n,
        "private_moments": {
            "age": {"mean": res.std.age_mean, "sd": res.std.age_sd},
            "bmi": {"mean": res.std.bmi_mean, "sd": res.std.bmi_sd}},
        "lambda": {"age_z": res.std.lam_age, "bmi_z": res.std.lam_bmi},
        "rho_non_private": res.std.rho_np,
        "NI": res.ni, "INT_age_to_bmi": res.int_}, indent=2))


def cmd_stress(args):
    """Stress-scale run (BASELINE.md config 5 shape): streaming n-blocked
    estimators, optionally sharded over the device mesh; prints reps/sec."""
    import jax

    from dpcorr.sim import SimConfig, run_sim_one

    b = args.b or 256
    # replication vmap width: sequential on CPU, wide on TPU — the single
    # measured policy (dpcorr.sim.stress_chunk_size)
    from dpcorr.sim import stress_chunk_size

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    chunk = args.chunk_size or stress_chunk_size(b, on_tpu)
    cfg = SimConfig(
        n=args.n, rho=0.5, eps1=1.0, eps2=1.0, b=b,
        dgp="bounded_factor" if args.family == "subg" else "gaussian",
        use_subg=args.family == "subg",
        stream_n_chunk=args.n_chunk,
        chunk_size=chunk)
    t0 = time.perf_counter()
    if args.backend == "sharded":
        from dpcorr.parallel import run_summary_sharded

        summary = run_summary_sharded(cfg)
    else:
        summary = run_sim_one(cfg).summary
    dt = time.perf_counter() - t0
    print(json.dumps({
        "n": cfg.n, "b": cfg.b, "family": args.family,
        "stream_n_chunk": cfg.stream_n_chunk,
        "seconds": round(dt, 2),
        "reps_per_sec_incl_compile": round(cfg.b / dt, 2),
        "summary": summary}, indent=2))


def cmd_acceptance(args):
    """B≥10⁶ coverage campaign at the BASELINE 1e-3 criterion
    (vert-cor.R:687 oracle; see dpcorr.acceptance)."""
    from dpcorr import acceptance

    table = acceptance.run_campaign(b=args.b or 1_000_000, out=args.out_json)
    print(acceptance.dumps(table))


def cmd_hrs_sweep(args):
    from dpcorr import hrs, report

    summ = hrs.eps_sweep(hrs.HrsConfig(seed=args.seed),
                         reps=args.b or 200, progress=True)
    print(summ.to_string(index=False, float_format=lambda v: f"{v:.4f}"))
    if args.out:
        paths = report.render_all(hrs_summ=summ, out_dir=args.out)
        summ.attrs["runs"].to_parquet(f"{args.out}/hrs_sweep_runs.parquet")
        print("figures:", *(str(p) for p in paths))


def cmd_serve(args):
    """Online serving: micro-batched DP-correlation queries behind a
    per-party ε-budget ledger (dpcorr.serve; docs/SERVING.md)."""
    import socket

    from dpcorr.obs import trace as obs_trace
    from dpcorr.serve.server import make_http_server

    # bind FIRST (cheap, before the jax-heavy build) so the port is
    # known up front: --instance defaults from it, so two replicas on
    # one box without explicit names can't collide on span-spool /
    # recorder / ledger filenames (ISSUE 20), and {instance}/{port}
    # placeholders in those paths resolve before anything opens them
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((args.host, args.port))
    sock.listen(128)
    bound_port = sock.getsockname()[1]
    if args.instance is None:
        args.instance = f"serve-{bound_port}"
    subst = {"instance": args.instance, "port": str(bound_port)}
    for attr in ("trace", "audit", "flight_recorder", "ledger",
                 "warmup_manifest"):
        val = getattr(args, attr)
        if val:
            for k, v in subst.items():
                val = val.replace("{%s}" % k, v)
            setattr(args, attr, val)
    if args.trace:
        # the process tracer, so grid/profiling spans from in-server
        # kernels land in the same log as the serve lifecycle spans
        obs_trace.configure(args.trace)
    if args.fault:
        # chaos faults at boot (testing only): the overload harness and
        # operators drilling breaker/brownout behaviour on a replica
        from dpcorr import chaos

        for spec in args.fault:
            chaos.install_fault(chaos.fault_from_spec(spec))
    rec = None
    if args.flight_recorder:
        # the flight recorder captures into bounded rings from boot;
        # SIGUSR2 dumps on demand (docs/OBSERVABILITY.md), on top of
        # the automatic chaos/breaker/brownout triggers. The handler
        # goes in BEFORE the (slow, jax-heavy) server build so a USR2
        # during init dumps empty rings instead of killing the boot.
        import signal

        from dpcorr.obs.recorder import FlightRecorder

        rec = FlightRecorder(args.flight_recorder)
        signal.signal(signal.SIGUSR2,
                      lambda signum, frame: rec.dump("sigusr2"))
    advertise_url = f"http://{args.host}:{bound_port}" \
        if args.host not in ("0.0.0.0", "::") \
        else f"http://127.0.0.1:{bound_port}"
    server = _build_server(args, advertise_url=advertise_url)
    if rec is not None:
        server.attach_recorder(rec)
    # the socket was bound before the build; the HTTP server adopts it
    # (the banner below is how the fleet harness discovers --port 0)
    httpd = make_http_server(server, host=args.host, port=args.port,
                             sock=sock)
    print(json.dumps({"serving": {"host": args.host, "port": bound_port,
                                  "instance": args.instance,
                                  "lease_dir": args.lease_dir,
                                  "advertise_url": advertise_url,
                                  "budget": args.budget,
                                  "ledger": args.ledger,
                                  "max_batch": args.max_batch,
                                  "max_delay_ms": args.max_delay_ms,
                                  "batch_mode": args.batch_mode,
                                  "trace": args.trace,
                                  "audit": args.audit,
                                  "user_dir": args.user_dir,
                                  "user_budget": args.user_budget,
                                  "global_budget": args.global_budget,
                                  "warmup": server.readiness(),
                                  "warmup_manifest": args.warmup_manifest,
                                  "aot": args.aot,
                                  "flight_recorder": args.flight_recorder,
                                  "breaker": {
                                      "threshold": args.breaker_threshold,
                                      "reset_s": args.breaker_reset_s},
                                  "brownout": {
                                      "queue_frac": args.shed_queue_frac,
                                      "flush_slo_ms": args.flush_slo_ms,
                                      "enter_s": args.brownout_enter_s,
                                      "exit_s": args.brownout_exit_s,
                                      "min_priority":
                                          args.brownout_min_priority},
                                  "faults": args.fault}}),
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()


def _build_server(args, advertise_url=None):
    from dpcorr.serve import DpcorrServer

    # exported-executable persistence rides the same opt-in cache dir as
    # the XLA persistent cache (DPCORR_COMPILE_CACHE; doctor reports it)
    # — one knob, one directory tree, both warm layers on or off together
    export_dir = None
    if args.aot == "on":
        from dpcorr.utils.doctor import resolve_cache_dir

        cache_dir = resolve_cache_dir("cli")
        if cache_dir:
            export_dir = os.path.join(cache_dir, "exported")
    return DpcorrServer(
        budget=args.budget, ledger_path=args.ledger,
        seed=args.seed, max_batch=args.max_batch,
        max_delay_s=args.max_delay_ms / 1000.0,
        max_queue=args.max_queue, shard=args.shard,
        batch_mode=args.batch_mode, max_kernels=args.max_kernels,
        audit=args.audit, warmup=args.warmup,
        warmup_manifest=args.warmup_manifest,
        aot=args.aot == "on", export_dir=export_dir,
        breaker_threshold=args.breaker_threshold,
        breaker_reset_s=args.breaker_reset_s,
        shed_queue_frac=args.shed_queue_frac,
        flush_slo_s=(args.flush_slo_ms / 1000.0
                     if args.flush_slo_ms is not None else None),
        brownout_enter_s=args.brownout_enter_s,
        brownout_exit_s=args.brownout_exit_s,
        brownout_min_priority=args.brownout_min_priority,
        user_dir=args.user_dir, user_budget=args.user_budget,
        user_shards=args.user_shards,
        user_max_resident=args.user_max_resident,
        user_compact_every=args.user_compact_every,
        user_renew_period_s=args.user_renew_period_s,
        user_burst_cap=args.user_burst_cap,
        global_budget=args.global_budget,
        instance=args.instance,
        lease_dir=args.lease_dir,
        lease_ttl_s=args.lease_ttl_s,
        lease_target=args.lease_target,
        advertise_url=advertise_url)


def cmd_fleet(args):
    """Fleet deployment plane (jax-free; docs/SERVING.md 'Running a
    fleet'): `front` routes over already-running replicas, `up` boots
    and supervises N replicas plus a front end in one command."""
    import math
    import sys
    import threading
    import time as time_mod

    from dpcorr.serve.fleet.frontend import (FleetFrontend,
                                             make_frontend_http_server)

    def _serve_front(fe, host, port, banner_extra):
        httpd = make_frontend_http_server(fe, host, port)
        bound = httpd.server_address[1]
        banner = {"host": host, "port": bound,
                  "lease_dir": args.lease_dir}
        banner.update(banner_extra)
        print(json.dumps({"fleet_front": banner}), flush=True)

        def _poll():
            while True:
                try:
                    fe.poll_ready()
                except Exception:
                    pass
                time_mod.sleep(args.health_interval_s)

        threading.Thread(target=_poll, name="fleet-health",
                         daemon=True).start()
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.shutdown()

    if args.fleet_cmd == "front":
        replicas = {}
        for spec in args.replica:
            name, sep, url = spec.partition("=")
            if not sep or not url:
                raise SystemExit(f"--replica wants name=url, got {spec!r}")
            replicas[name] = url
        fe = FleetFrontend(replicas, lease_dir=args.lease_dir)
        _serve_front(fe, args.host, args.port,
                     {"replicas": dict(sorted(replicas.items()))})
        return

    # fleet up: boot N real `dpcorr serve` replicas over one shared
    # budget directory + lease dir, supervise them, front them
    from dpcorr.serve.fleet.supervisor import ReplicaSpec, Supervisor

    os.makedirs(args.workdir, exist_ok=True)
    budget_root = os.path.join(args.workdir, "budget")
    lease_dir = os.path.join(args.workdir, "leases")
    args.lease_dir = lease_dir
    target = math.ceil(args.user_shards / args.replicas)
    specs = []
    for i in range(args.replicas):
        name = f"r{i}"
        argv = [sys.executable, "-m", "dpcorr", "serve",
                "--port", "0", "--instance", name,
                "--budget", str(args.budget),
                "--ledger", os.path.join(args.workdir,
                                         f"{name}_ledger.json"),
                "--audit", os.path.join(args.workdir,
                                        f"{name}_audit.jsonl"),
                "--user-dir", budget_root,
                "--user-shards", str(args.user_shards),
                "--user-budget", str(args.user_budget),
                "--lease-dir", lease_dir,
                "--lease-ttl-s", str(args.lease_ttl_s),
                "--lease-target", str(target),
                "--max-delay-ms", str(args.max_delay_ms)]
        if args.platform:
            argv += ["--platform", args.platform]
        specs.append(ReplicaSpec(
            name=name, argv=argv,
            stderr_path=os.path.join(args.workdir, f"{name}.log")))
    fe = FleetFrontend({}, lease_dir=lease_dir)
    sup = Supervisor(specs,
                     on_up=lambda name, url, banner:
                     fe.set_replica(name, url))
    print(json.dumps({"fleet_up": {"replicas": args.replicas,
                                   "workdir": args.workdir,
                                   "booting": True}}), flush=True)
    sup.start()
    try:
        _serve_front(fe, args.host, args.port,
                     {"replicas": sup.urls()})
    finally:
        sup.stop()


def cmd_stream(args):
    """Always-on windowed DP correlation over an ingest stream
    (dpcorr.stream; docs/STREAMING.md): event-time windows, one atomic
    ε charge per window, crash-exact releases."""
    from dpcorr import chaos
    from dpcorr.stream.http import make_stream_http_server
    from dpcorr.stream.service import StreamService
    from dpcorr.stream.windows import WindowSpec

    plan = (chaos.plan_from_spec(args.chaos) if args.chaos
            else chaos.plan_from_env())
    if plan is not None:
        chaos.install(plan)
    rec = None
    if args.flight_recorder:
        import signal

        from dpcorr.obs.recorder import FlightRecorder, install

        rec = FlightRecorder(args.flight_recorder)
        install(rec)
        signal.signal(signal.SIGUSR2,
                      lambda signum, frame: rec.dump("sigusr2"))
    spec = WindowSpec(size_s=args.window_s, slide_s=args.slide_s,
                      late_s=args.late_s)
    placement = None
    if args.placement is not None:
        from dpcorr.plan.placement import MeshPlacement, resolve_placement

        if args.placement == "mesh" and args.mesh_devices:
            placement = MeshPlacement(n_devices=args.mesh_devices)
        else:
            placement = resolve_placement(args.placement)
    service = StreamService(
        args.workdir, spec, args.families.split(","),
        args.eps1, args.eps2, normalise=args.normalise == "on",
        budget=args.budget, seed=args.seed,
        party_x=args.party_x, party_y=args.party_y,
        stream_id=args.stream_id, user=args.user,
        user_budget=args.user_budget, global_budget=args.global_budget,
        max_pending_rows=args.max_pending_rows, placement=placement)
    if rec is not None:
        rec.watch_registry(service.registry)
        rec.watch_costs(service.costs)
    # fleet identity: the self-claim gauge FleetCollector verifies
    # against the target name (serve/party parity)
    instance = args.instance or args.stream_id
    service.registry.gauge(
        "dpcorr_stream_instance_info",
        "stream identity: constant 1 labelled by instance name",
        labelnames=("instance",)).set(1, instance=instance)
    obs_server = obs_port = None
    if args.obs_port is not None:
        from dpcorr.obs.endpoint import start_obs_server

        obs_server, obs_port = start_obs_server(
            service.registry, stats_fn=service.stats,
            host=args.host, port=args.obs_port)
    # bind BEFORE the banner so --port 0 (ephemeral) is discoverable:
    # the load harness reads the bound port out of the banner line
    httpd = make_stream_http_server(service, host=args.host,
                                    port=args.port)
    bound_port = httpd.server_address[1]
    print(json.dumps({"streaming": {
        "host": args.host, "port": bound_port,
        "instance": instance, "obs_port": obs_port,
        "workdir": args.workdir, "stream_id": args.stream_id,
        "families": list(service.families),
        "window_s": args.window_s, "slide_s": args.slide_s,
        "late_s": args.late_s, "eps1": args.eps1, "eps2": args.eps2,
        "normalise": args.normalise == "on", "budget": args.budget,
        "eps_per_window": service.per_window_charges,
        "released": len(service.journal.entries()),
        "chaos": plan.to_dict() if plan is not None else None,
        "flight_recorder": args.flight_recorder}}), flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        if obs_server is not None:
            obs_server.shutdown()
        service.close()


def cmd_obs_budget(args):
    """Replay a privacy-budget audit trail (docs/OBSERVABILITY.md):
    per-event ε timeline plus the replayed per-party spend table, which
    must equal the ledger snapshot's ``spent`` values.

    With ``--budget-dir`` the replay additionally folds the sharded
    per-user trails (``user/<id>`` legs) and proves them against the
    directory's own on-disk arithmetic: every user's replayed lifetime
    spend must equal the lifetime the shard files reconstruct to
    (snapshot + WAL, the exact recovery path a restart takes). All
    jax-free — this audits a production directory from a laptop."""
    from dpcorr.obs import read_events, replay, timeline
    from dpcorr.obs.budget_replay import USER_PREFIX, read_user_balances

    events = read_events(args.audit)
    rows = timeline(events, party=args.party)
    totals = replay(events)
    dir_check = None
    if args.budget_dir:
        replayed_users = {p[len(USER_PREFIX):]: s
                          for p, s in totals.items()
                          if p.startswith(USER_PREFIX)}
        bal = read_user_balances(args.budget_dir)
        mismatches = []
        for user in sorted(set(replayed_users) | set(bal)):
            want = replayed_users.get(user, 0.0)
            got = bal.get(user, {}).get("l", 0.0)
            if abs(want - got) > 1e-9:
                mismatches.append({"user": user, "replayed": want,
                                   "directory": got})
        dir_check = {"ok": not mismatches, "users": len(bal),
                     "replayed_users": len(replayed_users),
                     "mismatches": mismatches}
    if args.party is not None:
        totals = {args.party: totals.get(args.party, 0.0)}
    if args.json:
        out = {"events": len(events), "timeline": rows, "spent": totals}
        if dir_check is not None:
            out["budget_dir"] = dir_check
        print(json.dumps(out, indent=2))
    else:
        for r in rows:
            after = " ".join(f"{p}={s:.6g}"
                             for p, s in sorted(r["spent_after"].items()))
            print(f"[{r['seq']:6d}] {r['kind']:<8} "
                  f"trace={r['trace_id'] or '-':<17} {after}")
        print(f"{len(events)} events; replayed spend:")
        for p, s in sorted(totals.items()):
            print(f"  {p}: {s:.6g}")
        if dir_check is not None:
            print(f"budget dir: {dir_check['users']} users on disk, "
                  f"{dir_check['replayed_users']} in the trail — "
                  f"{'OK' if dir_check['ok'] else 'MISMATCH'}")
            for m in dir_check["mismatches"]:
                print(f"  {m['user']}: replayed {m['replayed']:.6g} != "
                      f"directory {m['directory']:.6g}")
    if dir_check is not None and not dir_check["ok"]:
        sys.exit(1)


def cmd_obs_chrome(args):
    """Convert a span JSONL log to Chrome trace-event JSON (open in
    Perfetto / chrome://tracing)."""
    from dpcorr.obs import read_spans, write_chrome_trace

    n = len(read_spans(args.trace))
    write_chrome_trace(args.trace, args.out)
    print(f"wrote {args.out} ({n} spans)")


def cmd_obs_trajectory(args):
    """Bench-trajectory dashboard (ISSUE 15): normalize the repo's
    BENCH_*/MULTICHIP_*/benchmarks/results artifacts into per-
    (device_kind, metric) series and name the first artifact that bent
    the curve. Jax-free — runs over a bare checkout. ``--check`` exits
    1 when any series regressed below the floor."""
    from dpcorr.obs import trajectory as traj_mod

    roots = args.root or traj_mod.default_roots(args.repo)
    report = traj_mod.build_report(roots, args.floor)
    if args.format == "json":
        sys.stdout.write(traj_mod.render_json(report))
    elif args.format == "markdown":
        sys.stdout.write(traj_mod.render_markdown(report))
    else:
        sys.stdout.write(traj_mod.render_console(report))
    if args.check and report.regressions:
        sys.exit(1)


def cmd_obs_hlo(args):
    """HLO signature-dump tooling (ISSUE 15), jax-free: ``show`` lists
    a persisted dump's signatures with their cost/memory/fingerprint;
    ``diff`` explains what changed between two dumps — fingerprint
    flips, FLOP/byte/memory deltas, and the op-count deltas (fusion /
    copy / transpose) that mark layout or reshard boundaries."""
    from dpcorr.obs import hlo as hlo_mod

    try:
        if args.hlo_cmd == "show":
            sigs = hlo_mod.load_dump(args.path)
            if args.json:
                print(json.dumps(sigs, indent=2, sort_keys=True))
                return
            for key in sorted(sigs):
                rec = sigs[key]
                sig = rec.get("signature") or {}
                label = ",".join(f"{k}={sig[k]}" for k in sorted(sig)) \
                    or "<unsigned>"
                cost = rec.get("cost") or {}
                print(f"{key}  {label}")
                print(f"    fingerprint={rec.get('fingerprint') or '-'} "
                      f"flops={cost.get('flops', '-')} "
                      f"bytes={cost.get('bytes', '-')} "
                      f"cause={rec.get('cause') or '-'}")
            return
        diff = hlo_mod.diff_dumps(hlo_mod.load_dump(args.old),
                                  hlo_mod.load_dump(args.new))
        if args.json:
            print(json.dumps(diff, indent=2, sort_keys=True))
        else:
            sys.stdout.write(hlo_mod.render_diff(diff))
    except (OSError, ValueError) as e:
        print(f"obs hlo: {e}", file=sys.stderr)
        sys.exit(1)


def cmd_obs_geometry(args):
    """Print the geometry autotuner cache (ISSUE 15) per (device_kind,
    family, n, dtype) with provenance: tuned entries with their probe
    throughput and staleness, plus any live env pin
    (``DPCORR_BENCH_CHUNK``/``DPCORR_BENCH_BLOCK_REPS``) that outranks
    every tuned entry. Jax-free; exits 1 on a corrupt cache file (the
    hot path deliberately shrugs — the CLI must not)."""
    import os as _os

    from dpcorr.utils import geometry as geo_mod

    path = args.path or geo_mod.cache_path()
    pin = {k: _os.environ[k] for k in ("DPCORR_BENCH_CHUNK",
                                       "DPCORR_BENCH_BLOCK_REPS")
           if _os.environ.get(k)}
    if path is None:
        print("geometry cache disabled (DPCORR_GEOMETRY_CACHE).")
        rows = []
    elif not _os.path.exists(path):
        print(f"geometry cache {path}: not present (no run has tuned "
              f"on this host yet).")
        rows = []
    else:
        try:
            rows = geo_mod.entries(geo_mod.load_strict(path))
        except (OSError, ValueError) as e:
            print(f"obs geometry: corrupt cache {path}: {e}",
                  file=sys.stderr)
            sys.exit(1)
    if args.json:
        print(json.dumps({"path": path, "env_pin": pin, "entries": rows},
                         indent=2, sort_keys=True))
        return
    if pin:
        print("env pin (outranks every tuned entry): "
              + " ".join(f"{k}={v}" for k, v in sorted(pin.items())))
    if rows:
        print(f"geometry cache {path}: {len(rows)} tuned entries")
        for row in rows:
            if row.get("note"):
                print(f"  {row['key']}: {row['note']}")
                continue
            age = row.get("age_s")
            age_txt = "unstamped" if age is None else \
                f"{age / 86400:.1f}d old" if age >= 86400 else \
                f"{age / 3600:.1f}h old"
            rps = row.get("reps_per_sec")
            rps_txt = f"{rps:,.0f} reps/s probe" if rps else "no probe rate"
            print(f"  [{row['device_kind']}] {row['family']} "
                  f"n={row['n']} {row['dtype']}: "
                  f"chunk={row['chunk_size']} block={row['block_reps']} "
                  f"({rps_txt}, {age_txt}, source=tuned)")


def cmd_obs_dump(args):
    """Replay a flight-recorder dump jax-free (docs/OBSERVABILITY.md):
    summary mode lists what the rings held at dump time; ``--trace-id``
    reconstructs one request's full span chain, cost record and
    ledger-consistent ε trail from the dump alone."""
    from dpcorr.obs.recorder import read_dump, reconstruct

    dump = read_dump(args.path)
    if args.trace_id:
        rc = reconstruct(dump, args.trace_id)
        if args.json:
            print(json.dumps(rc, indent=2))
            return
        print(f"trace {args.trace_id} ({len(rc['spans'])} spans)")
        for s in rc["spans"]:
            dur = s.get("dur_s")
            dur_txt = f"{dur * 1e3:9.3f} ms" if dur is not None else \
                "      open"
            print(f"  {dur_txt}  {s['name']}")
        if rc["cost"] is not None:
            print("cost: " + json.dumps(rc["cost"]))
        if rc["audit"]:
            print(f"audit: {len(rc['audit'])} events, "
                  f"eps_net={json.dumps(rc['eps_net'])}")
        return
    summary = {"reason": dump["reason"], "ts": dump["ts"],
               "detail": dump.get("detail", {}),
               "spans": len(dump["spans"]),
               "audit_events": len(dump["audit"]),
               "log_lines": len(dump["logs"]),
               "metric_samples": len(dump.get("metric_samples", [])),
               "cost_records": len(dump["costs"]),
               "trace_ids": sorted({s.get("trace_id")
                                    for s in dump["spans"]
                                    if s.get("trace_id")})}
    if args.json:
        print(json.dumps(summary, indent=2))
        return
    print(f"flight-recorder dump: reason={summary['reason']} "
          f"detail={json.dumps(summary['detail'])}")
    print(f"  {summary['spans']} spans over "
          f"{len(summary['trace_ids'])} traces, "
          f"{summary['audit_events']} audit events, "
          f"{summary['log_lines']} log lines, "
          f"{summary['cost_records']} cost records")
    for tid in summary["trace_ids"][:20]:
        print(f"  trace {tid}")
    if len(summary["trace_ids"]) > 20:
        print(f"  ... {len(summary['trace_ids']) - 20} more")


def cmd_obs_top(args):
    """Live ops console over a serve replica's /metrics + /stats —
    or, with --fleet / --federation, over every replica or federation
    party process in a target map at once."""
    if getattr(args, "federation", None):
        from dpcorr.obs.console import run_federation_top

        raise SystemExit(run_federation_top(args.federation,
                                            interval_s=args.interval,
                                            once=args.once))
    if args.fleet:
        from dpcorr.obs.console import run_fleet_top

        raise SystemExit(run_fleet_top(args.fleet,
                                       interval_s=args.interval,
                                       once=args.once))
    if getattr(args, "stream", False):
        from dpcorr.obs.console import run_stream_top

        raise SystemExit(run_stream_top(args.url,
                                        interval_s=args.interval,
                                        once=args.once))
    from dpcorr.obs.console import run_top

    raise SystemExit(run_top(args.url, interval_s=args.interval,
                             once=args.once))


def cmd_obs_provenance(args):
    """Build the federation ε-provenance DAG jax-free
    (docs/OBSERVABILITY.md §Federation): merge every party's
    transcripts + audit trails + journals against the plan, prove
    exactly-once charging and byte-identical reuse at the
    ``2·f·ε·(k−1)`` optimum, and exit 1 naming the offending party on
    any divergence. ``--out`` writes the JSON document, ``--dot`` the
    Graphviz rendering, ``--cell I,J`` prints one cell's full story."""
    from dpcorr.obs.provenance import build_provenance, discover_federation

    plan, transcripts, audits, journals = discover_federation(
        args.plan, transcript_dir=args.transcript_dir,
        transcript_specs=args.transcript, audit_specs=args.audit,
        journal_dir=args.journal_dir)
    if not any(transcripts.values()):
        raise SystemExit("no transcripts found: pass --transcript-dir "
                         "or --transcript NAME=PATH")
    prov = build_provenance(plan, transcripts, audits=audits,
                            journals=journals)
    doc = prov.to_doc()
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    if args.dot:
        with open(args.dot, "w", encoding="utf-8") as f:
            f.write(prov.to_dot())
    if args.cell:
        i, _, j = args.cell.partition(",")
        print(json.dumps(prov.cell_story(int(i), int(j)), indent=2))
    elif args.json:
        print(json.dumps(doc, indent=2))
    else:
        eps = doc["eps"]
        print(f"provenance {prov.fed}: "
              f"{doc['counts']['nodes']} nodes, "
              f"{doc['counts']['edges']} edges; "
              f"eps total={eps['total']:.6g} "
              f"optimal={eps['optimal']:.6g} "
              f"{'EXACT' if prov.total_eps == prov.expected_eps else 'MISMATCH'}")
        for pname, rec in sorted(eps["parties"].items()):
            print(f"  {pname}: spent={rec['spent']:.6g} "
                  f"share={rec['share']:.6g}")
        for d in prov.divergences:
            print(f"  DIVERGENCE [{d['kind']}] party={d['party']}: "
                  f"{d['detail']}")
    if not prov.ok:
        from dpcorr.obs import recorder as obs_recorder

        obs_recorder.trigger(
            "federation_scan_violation",
            divergences=[{"kind": d["kind"], "party": d["party"]}
                         for d in prov.divergences])
        sys.exit(1)


def cmd_obs_watch(args):
    """Live invariant sentinel (docs/OBSERVABILITY.md §Sentinel): tail
    the durable artifacts live subsystems write — audit trails, stream
    ingest WAL + release journal, budget directories, federation
    transcripts + session journals — and re-prove ε-conservation and
    durability invariants incrementally, within a poll of the write.
    Typed violations name the offending artifact, arm the offender's
    flight recorder and page through the burn-rate engine; exit 1 when
    this run detected anything. jax-free, restart-safe from its own
    checkpoint."""
    from dpcorr.obs.sentinel import Sentinel

    def specs(pairs, flag):
        out = {}
        for spec in pairs or ():
            name, sep, value = spec.partition("=")
            if not sep or not name or not value:
                raise SystemExit(f"{flag} {spec!r}: expected NAME=PATH")
            out[name] = value
        return out

    streams = specs(args.stream, "--stream")
    audits = specs(args.audit, "--audit")
    budget_dirs = specs(args.budget_dir, "--budget-dir")
    transcripts = specs(args.transcripts, "--transcripts")
    journals = specs(args.journals, "--journals")
    urls = specs(args.url, "--url")
    if not (streams or audits or transcripts or journals):
        raise SystemExit("nothing to watch: pass --stream/--audit/"
                         "--transcripts/--journals NAME=PATH")
    for name in budget_dirs:
        if name not in audits:
            raise SystemExit(f"--budget-dir {name}=...: no matching "
                             f"--audit {name}=... to fold against")
    sentinel = Sentinel(args.checkpoint, urls=urls,
                        instance=args.instance)
    for name, workdir in sorted(streams.items()):
        sentinel.add_stream(name, workdir, url=urls.get(name))
    for name, path in sorted(audits.items()):
        sentinel.add_audit(name, path, url=urls.get(name),
                           budget_dir=budget_dirs.get(name))
    for name, d in sorted(transcripts.items()):
        sentinel.add_transcripts(name, d)
    for name, d in sorted(journals.items()):
        sentinel.add_journals(name, d)

    obs_server = None
    banner = {"instance": args.instance,
              "checkpoint": args.checkpoint,
              "watchers": sentinel.stats()["watchers"]}
    if args.obs_port is not None:
        from dpcorr.obs.endpoint import start_obs_server

        obs_server, obs_port = start_obs_server(
            sentinel.registry, stats_fn=sentinel.stats,
            port=args.obs_port)
        banner["obs_port"] = obs_port
    print(json.dumps({"sentinel": banner}), flush=True)

    def on_violation(v):
        if args.json:
            print(json.dumps({"violation": v.to_dict()}), flush=True)
        else:
            print(f"VIOLATION [{v.kind}] source={v.source} "
                  f"artifact={v.artifact}: {v.detail}", flush=True)
    sentinel.on_violation = on_violation
    try:
        rc = sentinel.run(interval_s=args.interval,
                          max_polls=1 if args.once else args.max_polls)
    except KeyboardInterrupt:
        rc = sentinel.rc
    finally:
        if obs_server is not None:
            obs_server.shutdown()
    if args.json:
        print(json.dumps({"summary": sentinel.stats()}, indent=2))
    sys.exit(rc)


def cmd_obs_fleet_snapshot(args):
    """One scrape of the whole fleet → one JSON artifact: per-instance
    stats, the merged (instance-labelled) exposition, the exact
    aggregate. jax-free — the operator story must not need an
    accelerator stack."""
    from dpcorr.obs.fleet import FleetCollector

    snap = FleetCollector(args.targets).scrape(timeout_s=args.timeout)
    doc = snap.to_doc()
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    errors = snap.errors()
    if args.json or not args.out:
        print(json.dumps(doc if args.json else {
            "instances": sorted(snap.instances),
            "live": sorted(snap.live()),
            "errors": errors,
            "out": args.out,
        }, indent=2))
    else:
        print(f"fleet snapshot: {len(snap.live())}/"
              f"{len(snap.instances)} instances live -> {args.out}")
        for name, err in sorted(errors.items()):
            print(f"  DOWN {name}: {err}")
    raise SystemExit(1 if errors and not snap.live() else 0)


def cmd_obs_fleet_chrome(args):
    """Union many instances' span spools into ONE Chrome trace (one
    pid per instance) — the fleet postmortem timeline."""
    from dpcorr.obs.fleet import parse_targets, write_fleet_chrome_trace

    spools = parse_targets(args.spool)
    out = write_fleet_chrome_trace(spools, args.out)
    print(f"wrote fleet chrome trace for {len(spools)} instances "
          f"-> {out}")


def cmd_obs_fleet_replay(args):
    """Fleet-wide audit replay: per-instance ε tables plus the fleet
    fold (the sum of per-instance ledgers, binary-exact)."""
    from dpcorr.obs.fleet import fleet_replay, parse_targets

    spools = parse_targets(args.audit)
    doc = fleet_replay(spools)
    if args.json:
        print(json.dumps(doc, indent=2))
        return
    for inst in sorted(doc["per_instance"]):
        table = doc["per_instance"][inst]
        spent = ", ".join(f"{p}={e:.6g}"
                          for p, e in sorted(table.items()))
        print(f"{inst}: {spent or '(no spend)'}")
    print("fleet: " + ", ".join(f"{p}={e:.6g}" for p, e in
                                sorted(doc["fleet"].items())))


def _party_columns(args, n: int):
    """Synthetic bivariate-normal columns, derived identically in both
    party processes from the public spec seed (numpy Generator, not the
    jax key tree — the protocol noise streams stay untouched). Each
    process keeps only its own column; the other exists transiently
    here, never in the protocol runtime."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    cov = [[1.0, args.rho], [args.rho, 1.0]]
    xy = rng.multivariate_normal([0.0, 0.0], cov, size=n)
    return (np.asarray(xy[:, 0], np.float32),
            np.asarray(xy[:, 1], np.float32))


def _protocol_spec(args):
    from dpcorr.protocol import ProtocolSpec

    return ProtocolSpec(family=args.family, n=args.n, eps1=args.eps1,
                        eps2=args.eps2, alpha=args.alpha,
                        normalise=args.normalise == "on",
                        seed=args.seed, noise_mode=args.noise_mode,
                        session=args.session or "")


def _result_json(res) -> dict:
    return {"role": res.role, "session": res.session,
            "rho_hat": res.rho_hat, "ci_low": res.ci_low,
            "ci_high": res.ci_high, "trace_id": res.trace_id,
            "stats": res.stats}


def cmd_party(args):
    """One side of the two-party protocol over TCP (docs/PROTOCOL.md).
    Role y listens, role x connects; each process sees one column.

    With ``--journal`` the session is crash-safe (docs/ROBUSTNESS.md):
    state journals durably as the session progresses, the TCP link
    redials through peer restarts, and rerunning this exact command
    after a crash resumes the session instead of restarting it. A
    ``--chaos`` plan (or ``DPCORR_CHAOS``) arms a deterministic kill at
    a named crash point — the chaos harness's victim hook."""
    import numpy as np

    from dpcorr import chaos
    from dpcorr.obs import trace as obs_trace
    from dpcorr.obs.audit import AuditTrail
    from dpcorr.protocol import (
        Party,
        ReliableChannel,
        SessionJournal,
        Transcript,
    )
    from dpcorr.protocol.transport import (
        ReconnectingTcpLink,
        tcp_accept,
        tcp_connect,
        tcp_listen,
    )
    from dpcorr.serve.ledger import PrivacyLedger

    plan = (chaos.plan_from_spec(args.chaos) if args.chaos
            else chaos.plan_from_env())
    if plan is not None:
        chaos.install(plan)
    if args.trace:
        obs_trace.configure(args.trace)
    spec = _protocol_spec(args)
    if args.data:
        col = np.asarray(np.load(args.data), np.float32)
        if col.shape != (spec.n,):
            raise SystemExit(f"--data has shape {col.shape}, spec says "
                             f"({spec.n},)")
    else:
        cols = _party_columns(args, spec.n)
        col = cols[0] if args.role == "x" else cols[1]
    srv = None
    # A journaled RESTART must not block waiting for a live peer before
    # the session logic runs: when the peer already finished and left,
    # the bounded resume handshake concludes peer-gone and the session
    # completes offline from the journal (docs/ROBUSTNESS.md) — so on
    # resume the first accept/connect goes lazily through the
    # reconnecting link instead of an eager blocking call here.
    resuming = bool(args.journal) and os.path.exists(args.journal)
    if args.role == "y":
        srv, bound = tcp_listen(args.host, args.port)
        print(json.dumps({"party": {"role": "y", "session": spec.session,
                                    "instance": args.instance,
                                    "listening": [args.host, bound]}}),
              flush=True)
        if args.journal:
            # keep the server socket: a crashed peer's restart redials
            # the same port, and the reconnecting link re-accepts it
            first = (None if resuming
                     else tcp_accept(srv, timeout_s=args.connect_timeout))
            link = ReconnectingTcpLink(
                lambda: tcp_accept(srv, timeout_s=5.0), link=first,
                max_outage_s=args.connect_timeout)
        else:
            link = tcp_accept(srv, timeout_s=args.connect_timeout)
            srv.close()
            srv = None
    else:
        print(json.dumps({"party": {"role": "x", "session": spec.session,
                                    "instance": args.instance,
                                    "connecting": [args.host, args.port]}}),
              flush=True)
        if args.journal:
            first = (None if resuming
                     else tcp_connect(args.host, args.port,
                                      timeout_s=args.connect_timeout))
            link = ReconnectingTcpLink(
                lambda: tcp_connect(args.host, args.port, timeout_s=5.0),
                link=first, max_outage_s=args.connect_timeout)
        else:
            link = tcp_connect(args.host, args.port,
                               timeout_s=args.connect_timeout)
    audit = AuditTrail(args.audit) if args.audit else None
    ledger = PrivacyLedger(args.budget, path=args.ledger, audit=audit)
    if args.user_dir:
        # per-user admission rides the gate unchanged: the composite
        # derives the user/ leg inside the same charge/refund calls,
        # and both stores recover their exact balances on restart
        from dpcorr.serve.budget_dir import BudgetDirectory, CompositeLedger

        directory = BudgetDirectory(
            args.user_dir, shards=args.user_shards,
            user_budget=args.user_budget,
            max_resident=args.user_max_resident,
            compact_every=args.user_compact_every, audit=audit)
        ledger = CompositeLedger(ledger, directory,
                                 user=args.user or f"user-{args.role}")
    channel = ReliableChannel(link, timeout_s=args.timeout,
                              max_retries=args.max_retries)
    transcript = Transcript(args.transcript)
    if args.instance:
        # fleet identity (ISSUE 11): the union layer maps spools by
        # instance name; the transcript records which one this was
        transcript.meta(instance=args.instance)
    if plan is not None:
        # reproducibility-from-the-artifact: the kill plan is in the
        # transcript header, so any chaos run replays from its own log
        transcript.meta(chaos=plan.to_dict(), session=spec.session)
    journal = SessionJournal(args.journal) if args.journal else None
    party = Party(args.role, col, spec, channel, ledger,
                  transcript=transcript,
                  recv_timeout_s=args.recv_timeout, journal=journal)
    try:
        res = party.run()
    finally:
        link.close()
        if srv is not None:
            srv.close()
        if args.user_dir:
            ledger.close()  # CompositeLedger: releases shard spill files
    print(json.dumps({"result": _result_json(res)}, indent=2))


def cmd_protocol_run(args):
    """Single-command driver: both roles in one process (threads) over
    the chosen transport — the smoke/repro path for docs/PROTOCOL.md."""
    from dpcorr.protocol import ProtocolError, run_inproc, run_tcp

    spec = _protocol_spec(args)
    x, y = _party_columns(args, spec.n)
    fault = None
    if args.fault_drop or args.fault_delay_ms or args.fault_duplicate:
        fault = {"drop": args.fault_drop,
                 "delay_s": args.fault_delay_ms / 1000.0,
                 "duplicate": args.fault_duplicate}
    if args.fault_seed is not None:
        # one knob reproducing both sides' fault streams; the runner
        # stamps it (with the rest of the fault config) into each
        # transcript header, so a failure replays from the artifact
        fault = dict(fault or {})
        fault["seed"] = args.fault_seed
    run = run_tcp if args.transport == "tcp" else run_inproc
    try:
        results = run(spec, x, y, fault=fault,
                      transcript_dir=args.transcript_dir,
                      timeout_s=args.timeout, max_retries=args.max_retries)
    except ProtocolError as e:
        raise SystemExit(f"protocol aborted: {e}") from e
    out = {"spec": spec.to_public(), "session": spec.session,
           "results": {r: _result_json(res)
                       for r, res in sorted(results.items())}}
    agree = (results["x"].rho_hat == results["y"].rho_hat
             and results["x"].ci_low == results["y"].ci_low
             and results["x"].ci_high == results["y"].ci_high)
    out["roles_agree"] = agree
    print(json.dumps(out, indent=2))
    if not agree:
        raise SystemExit("role results diverged")


def cmd_protocol_scan(args):
    """Offline transcript audit (protocol.scan): message schema +
    no-raw-columns, and — with --audit — the ε balance proof. jax-free;
    exit 1 on any violation."""
    from dpcorr.obs import read_events
    from dpcorr.protocol.scan import ledger_balance, scan_transcript

    rep = scan_transcript(args.transcript)
    out = {"scan": rep}
    ok = rep["ok"]
    if args.audit:
        bal = ledger_balance(args.transcript, read_events(args.audit))
        out["balance"] = bal
        ok = ok and bal["ok"]
    print(json.dumps(out, indent=2))
    if not ok:
        sys.exit(1)


def cmd_chaos(args):
    """Deterministic step-kill sweep (docs/ROBUSTNESS.md): per (family,
    victim role, crash point) case, run the two-party protocol as two
    real TCP processes with journals, kill the victim at the named
    point (exit 42), restart it with the identical command line, and
    assert the finished session is bit-identical to an uninterrupted
    in-process reference with each role's ε spent exactly once."""
    import subprocess
    import tempfile

    from dpcorr import chaos
    from dpcorr.obs import read_events
    from dpcorr.protocol import ProtocolSpec, run_inproc
    from dpcorr.protocol.scan import ledger_balance, scan_transcript

    points = (args.points.split(",") if args.points
              else list(chaos.MATRIX_POINTS))
    roles = args.roles.split(",") if args.roles else ["x", "y"]
    families = (args.families.split(",") if args.families
                else [args.family])
    if args.chaos_seed is not None:
        plan = chaos.plan_from_seed(args.chaos_seed)
        points, roles = [plan.point], [plan.role]
    workdir = args.workdir or tempfile.mkdtemp(prefix="dpcorr-chaos-")
    os.makedirs(workdir, exist_ok=True)
    # the restarted victim must NOT re-arm the kill it is recovering from
    env = {k: v for k, v in os.environ.items() if k != "DPCORR_CHAOS"}

    def spec_for(family: str) -> "ProtocolSpec":
        return ProtocolSpec(family=family, n=args.n, eps1=args.eps1,
                            eps2=args.eps2, alpha=args.alpha,
                            normalise=args.normalise == "on",
                            seed=args.seed, noise_mode=args.noise_mode)

    # the oracle every crashed run must match bit-for-bit: one clean
    # uninterrupted run per family, same spec, same synthetic columns
    refs = {}
    for family in families:
        spec = spec_for(family)
        cx, cy = _party_columns(args, spec.n)
        refs[family] = run_inproc(spec, cx, cy)["x"]

    def party_argv(family: str, role: str, port: int,
                   case_dir: str) -> list[str]:
        # every case also runs a per-user budget directory with the
        # most hostile knobs it supports — evict after every release
        # (max-resident 0) and compact after every charge — so each
        # protocol send crosses ALL the directory persist windows, and
        # the post-restart assertion proves exact per-user balances
        return [sys.executable, "-m", "dpcorr", "party",
                "--role", role, "--host", "127.0.0.1",
                "--port", str(port),
                "--family", family, "--n", str(args.n),
                "--eps1", str(args.eps1), "--eps2", str(args.eps2),
                "--alpha", str(args.alpha), "--normalise", args.normalise,
                "--seed", str(args.seed), "--noise-mode", args.noise_mode,
                "--rho", str(args.rho),
                "--timeout", str(args.timeout),
                "--max-retries", str(max(args.max_retries, 40)),
                "--connect-timeout", str(args.case_timeout),
                "--recv-timeout", str(args.case_timeout),
                "--journal", os.path.join(case_dir, f"journal.{role}.json"),
                "--ledger", os.path.join(case_dir, f"ledger.{role}.json"),
                "--audit", os.path.join(case_dir, f"audit.{role}.jsonl"),
                "--user", f"user-{role}",
                "--user-dir", os.path.join(case_dir, f"budget-{role}"),
                "--user-budget", "100", "--user-shards", "2",
                "--user-max-resident", "0", "--user-compact-every", "1",
                "--transcript",
                os.path.join(case_dir, f"transcript.{role}.jsonl")]

    def launch(argv: list[str], case_dir: str, role: str):
        errlog = open(os.path.join(case_dir, f"{role}.stderr.log"), "ab")
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=errlog, env=env, text=True)

    def parse_result(text: str) -> dict:
        """Drop single-line ``{"party": ...}`` banners; parse the
        multi-line ``{"result": ...}`` document that follows."""
        lines = [ln for ln in text.splitlines() if ln.strip()]
        while lines:
            try:
                obj = json.loads(lines[0])
            except json.JSONDecodeError:
                break
            if isinstance(obj, dict) and "party" in obj:
                lines.pop(0)
            else:
                break
        return json.loads("\n".join(lines))["result"]

    reports = []
    failures = []
    fed_refs = {}  # family -> uninterrupted in-process federation oracle
    for family in families:
        for role in roles:
            for point in points:
                case = f"{family}.{role}.{point}"
                case_dir = os.path.join(workdir,
                                        case.replace(".", "_"))
                os.makedirs(case_dir, exist_ok=True)
                if point.startswith("federation."):
                    # federation crash windows never fire in a two-party
                    # session: the case is a 3-party matrix over TCP,
                    # with the victim role mapped onto a party
                    errs = _run_federation_chaos_case(
                        args, family, role, point, case_dir, launch,
                        parse_result, fed_refs)
                else:
                    errs = _run_chaos_case(
                        args, family, role, point, case_dir,
                        refs[family], spec_for(family), party_argv,
                        launch, parse_result, ledger_balance,
                        scan_transcript, read_events, chaos.EXIT_CODE)
                reports.append({"case": case, "ok": not errs,
                                "errors": errs, "dir": case_dir})
                failures.extend(f"{case}: {e}" for e in errs)
    print(json.dumps({"workdir": workdir, "cases": reports,
                      "ok": not failures}, indent=2))
    if failures:
        sys.exit(1)


def _run_chaos_case(args, family, role, point, case_dir, ref, spec,
                    party_argv, launch, parse_result, ledger_balance,
                    scan_transcript, read_events, exit_code) -> list[str]:
    """One (family, victim role, point) case; returns error strings."""
    import subprocess

    # seed-derived sweeps pass the seed form through: the victim
    # re-derives the identical (point, role) and — unlike the concrete
    # point= form — keeps the seed on the plan, so the transcript
    # header records the provenance the run is reproducible from
    if getattr(args, "chaos_seed", None) is not None:
        chaos_spec = f"seed={args.chaos_seed}"
    else:
        chaos_spec = f"point={point},hit=1,mode=exit"
    timeout = args.case_timeout
    procs = {}
    try:
        y_argv = party_argv(family, "y", 0, case_dir)
        procs["y"] = launch(
            y_argv + (["--chaos", chaos_spec] if role == "y" else []),
            case_dir, "y")
        banner = json.loads(procs["y"].stdout.readline())
        port = int(banner["party"]["listening"][1])
        x_argv = party_argv(family, "x", port, case_dir)
        procs["x"] = launch(
            x_argv + (["--chaos", chaos_spec] if role == "x" else []),
            case_dir, "x")
        victim = procs[role]
        try:
            rc = victim.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return [f"victim {role} did not crash at {point} within "
                    f"{timeout:.0f}s"]
        victim.stdout.read()  # drain the dead pipe
        if rc != exit_code:
            return [f"victim {role} exited {rc}, expected the chaos "
                    f"kill code {exit_code}"]
        # restart: the identical command line, minus the kill plan
        # (y rebinds its concrete port — port 0 was only for discovery)
        restart_argv = (party_argv(family, "y", port, case_dir)
                        if role == "y" else x_argv)
        procs[role] = launch(restart_argv, case_dir, role)
        out, results = {}, {}
        for r in ("x", "y"):
            try:
                rc = procs[r].wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                return [f"party {r} hung after restart (>{timeout:.0f}s)"]
            out[r] = procs[r].stdout.read()
            if rc != 0:
                return [f"party {r} exited {rc} after restart; see "
                        f"{case_dir}/{r}.stderr.log"]
            results[r] = parse_result(out[r])
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()

    errs = []
    for r in ("x", "y"):
        got = results[r]
        if (got["rho_hat"] != ref.rho_hat or got["ci_low"] != ref.ci_low
                or got["ci_high"] != ref.ci_high):
            errs.append(
                f"role {r} result {got['rho_hat']!r} diverged from the "
                f"uninterrupted reference {ref.rho_hat!r}")
        transcript = os.path.join(case_dir, f"transcript.{r}.jsonl")
        rep = scan_transcript(transcript)
        if not rep["ok"]:
            errs.append(f"role {r} transcript scan: {rep['violations']}")
        bal = ledger_balance(
            transcript,
            read_events(os.path.join(case_dir, f"audit.{r}.jsonl")))
        if not bal["ok"]:
            errs.append(f"role {r} ledger balance: "
                        f"sends {bal['unmatched_sends']} "
                        f"charges {bal['unmatched_charges']}")
        with open(os.path.join(case_dir, f"ledger.{r}.json")) as fh:
            spent = json.load(fh)["spent"]
        for party_name, eps in spec.charges_for(r).items():
            if abs(spent.get(party_name, 0.0) - eps) > 1e-9:
                errs.append(
                    f"role {r} spent {spent.get(party_name, 0.0)!r} for "
                    f"{party_name}, expected exactly one charge of "
                    f"{eps!r}")
        # the per-user directory must recover to the exact same
        # balance: every release charged the bound user once (the
        # composite's user leg equals the send's party total), through
        # whatever persist window the kill landed in. read_user_balances
        # IS the restart recovery arithmetic (obs.budget_replay), so
        # this also proves the shard files replay clean.
        from dpcorr.obs.budget_replay import read_user_balances

        budget_dir = os.path.join(case_dir, f"budget-{r}")
        want = sum(spec.charges_for(r).values())
        got = read_user_balances(budget_dir).get(
            f"user-{r}", {}).get("l", 0.0)
        if abs(got - want) > 1e-9:
            errs.append(
                f"role {r} user directory recovered lifetime {got!r} "
                f"for user-{r}, expected exactly-once charges "
                f"totalling {want!r}")
        # and the jax-free auditor must agree end-to-end: the sharded
        # per-user trail folded from the audit log equals the
        # directory's own arithmetic (exit 1 on any mismatch)
        chk = subprocess.run(
            [sys.executable, "-m", "dpcorr", "obs", "budget",
             "--audit", os.path.join(case_dir, f"audit.{r}.jsonl"),
             "--budget-dir", budget_dir, "--json"],
            capture_output=True, text=True)
        if chk.returncode != 0:
            errs.append(
                f"role {r} obs budget replay disagreed with the "
                f"directory: {chk.stdout.strip()[-400:]}")
    return errs


def _federation_plan(args):
    """Build the public :class:`FederationPlan` a subcommand runs
    under — from a ``--plan`` JSON file (the byte-identical document
    every party process of one federation must share) or inline
    ``--party`` flags (order is the public plan order)."""
    from dpcorr.protocol.matrix import FederationPlan

    if args.plan:
        with open(args.plan, encoding="utf-8") as fh:
            doc = json.load(fh)
        return FederationPlan.from_public(doc.get("plan", doc))
    if not args.party:
        raise SystemExit("pass --party NAME=LAB1[,LAB2...] (repeatable; "
                         "order is the plan order) or --plan FILE")
    parties = []
    for spec in args.party:
        name, sep, labs = spec.partition("=")
        labels = [s for s in labs.split(",") if s]
        if not sep or not name or not labels:
            raise SystemExit(f"--party {spec!r}: expected "
                             "NAME=LAB1[,LAB2...]")
        parties.append((name, labels))
    return FederationPlan(family=args.family, n=args.n, eps=args.eps,
                          parties=parties, alpha=args.alpha,
                          normalise=args.normalise == "on",
                          seed=args.seed, noise_mode=args.noise_mode,
                          max_cells_per_round=args.max_cells_per_round)


def _federation_columns(plan, rho: float) -> dict:
    """Synthetic equicorrelated columns for all k labels, derived from
    the public plan seed — the federation analogue of _party_columns:
    every party process re-derives the identical draw and keeps only
    its own labels (numpy Generator, disjoint from the jax key tree)."""
    import numpy as np

    k = plan.k
    if not -1.0 / max(k - 1, 1) < rho < 1.0:
        raise SystemExit(f"--rho {rho} is not a valid equicorrelation "
                         f"for k={k} (need -1/(k-1) < rho < 1)")
    cov = np.full((k, k), float(rho))
    np.fill_diagonal(cov, 1.0)
    xy = np.random.default_rng(plan.seed).multivariate_normal(
        np.zeros(k), cov, size=plan.n)
    return {label: np.asarray(xy[:, idx], np.float32)
            for idx, (_owner, label) in enumerate(plan.columns())}


def cmd_federation_plan(args):
    """Compile and print the federation schedule — cells, links,
    rounds, artifact charge venues and the ε arithmetic (optimal vs
    naive per-cell). Pure plan arithmetic, jax-free."""
    print(json.dumps(_federation_plan(args).describe(), indent=2))


def cmd_federation_run(args):
    """Whole federation in one process: every party on a thread over
    inproc or loopback-TCP wires — the smoke/repro path for the
    federation section of docs/PROTOCOL.md."""
    from dpcorr.protocol import ProtocolError
    from dpcorr.protocol.federation import (
        run_federation_inproc,
        run_federation_tcp,
    )

    plan = _federation_plan(args)
    data = _federation_columns(plan, args.rho)
    fault = None
    if args.fault_drop or args.fault_delay_ms or args.fault_duplicate:
        fault = {"drop": args.fault_drop,
                 "delay_s": args.fault_delay_ms / 1000.0,
                 "duplicate": args.fault_duplicate}
    if args.fault_seed is not None:
        fault = dict(fault or {})
        fault["seed"] = args.fault_seed
    run = (run_federation_tcp if args.transport == "tcp"
           else run_federation_inproc)
    try:
        results = run(plan, data, fault=fault,
                      transcript_dir=args.transcript_dir,
                      timeout_s=args.timeout,
                      max_retries=args.max_retries, engine=args.engine)
    except ProtocolError as e:
        raise SystemExit(f"federation aborted: {e}") from e
    # every cell two parties both see must agree bitwise — the wire
    # result IS the finisher's result, so disagreement means corruption
    cells: dict = {}
    agree = True
    for _name, res in sorted(results.items()):
        for key, val in res.cells.items():
            if key in cells and cells[key] != val:
                agree = False
            cells.setdefault(key, val)
    out = {"fed": plan.fed, "fed_hash": plan.fed_hash(),
           "plan": plan.to_public(),
           "cells": {key: cells[key] for key in sorted(cells)},
           "eps": {"optimal": plan.optimal_eps(),
                   "naive_per_cell": plan.naive_eps(),
                   "per_party": plan.party_eps()},
           "parties": {name: {"cells": res.cells, "eps": res.eps,
                              "stats": res.stats}
                       for name, res in sorted(results.items())},
           "parties_agree": agree}
    print(json.dumps(out, indent=2))
    if not agree:
        raise SystemExit("parties diverged on a shared cell")


def cmd_federation_party(args):
    """One real party process of a multi-process federation over TCP
    (docs/PROTOCOL.md): topology is plan-derived — for each pair link
    the lower party dials (``--peer NAME=HOST:PORT``) and the higher
    listens (``--listen``, the bound port announced in the banner).
    With ``--journal-dir`` every link is crash-safe exactly like
    ``dpcorr party --journal``: rerun the identical command after a
    crash and the matrix resumes instead of restarting."""
    from dpcorr import chaos
    from dpcorr.obs import trace as obs_trace
    from dpcorr.obs.audit import AuditTrail
    from dpcorr.obs.endpoint import start_obs_server
    from dpcorr.obs.metrics import Registry
    from dpcorr.protocol.federation import serve_federation_party
    from dpcorr.serve.ledger import PrivacyLedger

    plan = chaos.plan_from_spec(args.chaos) if args.chaos \
        else chaos.plan_from_env()
    if plan is not None:
        chaos.install(plan)
    fed = _federation_plan(args)
    name = args.name
    instance = args.instance or name
    if args.trace:
        # a directory spools per-instance (trace.<instance>.jsonl) so
        # k parties can share one --trace value and the fleet union
        # (obs fleet chrome) gets one spool per party, pre-named
        trace_path = (os.path.join(args.trace,
                                   f"trace.{instance}.jsonl")
                      if os.path.isdir(args.trace) else args.trace)
        obs_trace.configure(trace_path)
    my_idx = fed.party_index(name)
    columns = {lab: col for lab, col
               in _federation_columns(fed, args.rho).items()
               if lab in fed.party_labels(name)}
    listen = None
    if args.listen:
        host, sep, port = args.listen.rpartition(":")
        if not sep:
            raise SystemExit(f"--listen {args.listen!r}: expected "
                             "HOST:PORT")
        listen = (host, int(port))
    peers = {}
    for spec in args.peer or []:
        peer, sep, addr = spec.partition("=")
        host, sep2, port = addr.rpartition(":")
        if not sep or not sep2:
            raise SystemExit(f"--peer {spec!r}: expected "
                             "NAME=HOST:PORT")
        peers[peer] = (host, int(port))
    accepts = any(fed.party_index(q if p == name else p) < my_idx
                  for p, q in fed.party_links(name))
    registry = Registry()
    party_box: list = []
    obs_port = None
    if args.obs_port is not None:
        # the scrape surface up before any banner: FleetCollector,
        # obs top --federation and SLO paging can watch the whole run
        _srv, obs_port = start_obs_server(
            registry,
            stats_fn=lambda: (party_box[0].stats_snapshot()
                              if party_box else
                              {"kind": "federation_party",
                               "instance": instance, "party": name,
                               "fed": fed.fed, "starting": True}),
            port=args.obs_port)

    def banner(**extra):
        doc = {"federation": fed.fed, "name": name,
               "instance": instance}
        if obs_port is not None:
            doc["obs_port"] = obs_port
        doc.update(extra)
        print(json.dumps({"party": doc}), flush=True)

    def on_listening(host, port):
        banner(listening=[host, port])

    if not accepts:
        # pure dialers still print a banner: drivers parse every
        # party's stdout uniformly (banner lines, then the result)
        banner(dialing=sorted(peers))
    audit = AuditTrail(args.audit) if args.audit else None
    ledger = PrivacyLedger(args.budget, path=args.ledger, audit=audit)
    res = serve_federation_party(
        name, fed, columns, ledger=ledger, listen=listen, peers=peers,
        transcript_dir=args.transcript_dir,
        journal_dir=args.journal_dir, timeout_s=args.timeout,
        max_retries=args.max_retries,
        connect_timeout_s=args.connect_timeout,
        recv_timeout_s=args.recv_timeout, engine=args.engine,
        on_listening=on_listening, registry=registry,
        instance=args.instance, on_party=party_box.append)
    print(json.dumps({"result": {"party": res.party, "fed": res.fed,
                                 "cells": res.cells, "eps": res.eps,
                                 "stats": res.stats}}, indent=2))


def cmd_federation_scan(args):
    """Offline federation audit, jax-free: per-transcript schema scan,
    the cross-pair correlation-leak gate (a reused column release must
    be byte-identical in every pair session; exit 1 names the offending
    pair), and — with ``--audit NAME=PATH`` — each party's whole-matrix
    ε balance against its plan-derived local spend."""
    import glob as globmod

    from dpcorr.obs import read_events
    from dpcorr.protocol.scan import (
        federation_balance,
        scan_federation,
        scan_transcript,
    )

    transcripts = list(args.transcript or [])
    if args.transcript_dir:
        for path in sorted(globmod.glob(
                os.path.join(args.transcript_dir, "*.jsonl"))):
            base = os.path.basename(path)
            if not base.startswith(("audit.", "trace.")):
                transcripts.append(path)
    if not transcripts:
        raise SystemExit("pass --transcript (repeatable) or "
                         "--transcript-dir")
    plan = None
    if args.plan:
        from dpcorr.protocol.matrix import FederationPlan

        with open(args.plan, encoding="utf-8") as fh:
            doc = json.load(fh)
        plan = FederationPlan.from_public(doc.get("plan", doc))
    per = {t: scan_transcript(t) for t in transcripts}
    cross = scan_federation(transcripts)
    ok = all(r["ok"] for r in per.values()) and cross["ok"]
    out = {"transcripts": per, "cross_pair": cross}
    balances = {}
    for spec in args.audit or []:
        pname, sep, path = spec.partition("=")
        if not sep:
            raise SystemExit(f"--audit {spec!r}: expected NAME=PATH")
        mine = [t for t in transcripts
                if os.path.basename(t).split(".")[-2] == pname]
        expected = (sum(plan.local_charges(pname)["charges"].values())
                    if plan is not None else 0.0)
        bal = federation_balance(mine, read_events(path),
                                 expected_local_eps=expected)
        balances[pname] = bal
        ok = ok and bal["ok"]
    if balances:
        out["balance"] = balances
    print(json.dumps(out, indent=2))
    if not ok:
        from dpcorr.obs import recorder as obs_recorder

        obs_recorder.trigger(
            "federation_scan_violation",
            violations=cross["violations"],
            transcripts=sorted(os.path.basename(t)
                               for t in transcripts))
        sys.exit(1)


#: Federation chaos cases map the sweep's victim role onto a party of
#: the fixed 3-party case topology (p0:[a,b] p1:[c] p2:[d]) — chosen so
#: each point actually fires in the victim: pre_release fires in link
#: initiators (p0 initiates both its links, p1 initiates p1-p2),
#: pre_finish in finishers (p1 finishes p0-p1, p2 finishes both its
#: links), mid_matrix in any party joining link threads.
_FED_VICTIMS = {
    "federation.pre_release": {"x": "p0", "y": "p1"},
    "federation.pre_finish": {"x": "p1", "y": "p2"},
    "federation.mid_matrix": {"x": "p0", "y": "p1"},
}


def _run_federation_chaos_case(args, family, role, point, case_dir,
                               launch, parse_result,
                               fed_refs) -> list[str]:
    """One federation chaos case: three real party processes over TCP
    computing the 4×4 matrix, kill the mapped victim at the named
    federation point (exit 42), restart it with the identical command
    line, and assert the finished matrix is bit-identical to an
    uninterrupted in-process reference with every party's ε spent
    exactly once at the release-reuse optimum."""
    import subprocess

    from dpcorr import chaos
    from dpcorr.obs import read_events
    from dpcorr.protocol.federation import run_federation_inproc
    from dpcorr.protocol.matrix import FederationPlan
    from dpcorr.protocol.scan import (
        federation_balance,
        scan_federation,
        scan_transcript,
    )

    plan = FederationPlan(
        family=family, n=args.n, eps=args.eps1,
        parties=[("p0", ["a", "b"]), ("p1", ["c"]), ("p2", ["d"])],
        alpha=args.alpha, normalise=args.normalise == "on",
        seed=args.seed, noise_mode=args.noise_mode)
    victim_name = _FED_VICTIMS[point][role]
    if family not in fed_refs:
        fed_refs[family] = run_federation_inproc(
            plan, _federation_columns(plan, args.rho))
    ref = fed_refs[family]
    plan_path = os.path.join(case_dir, "plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump(plan.to_public(), fh)

    def argv(name: str, listen_port, peers: dict) -> list[str]:
        cmd = [sys.executable, "-m", "dpcorr", "federation", "party",
               "--name", name, "--plan", plan_path,
               "--rho", str(args.rho), "--budget", "100",
               "--timeout", str(args.timeout),
               "--max-retries", str(max(args.max_retries, 40)),
               "--connect-timeout", str(args.case_timeout),
               "--recv-timeout", str(args.case_timeout),
               "--ledger", os.path.join(case_dir, f"ledger.{name}.json"),
               "--audit", os.path.join(case_dir, f"audit.{name}.jsonl"),
               "--transcript-dir", case_dir,
               "--journal-dir", case_dir]
        if listen_port is not None:
            cmd += ["--listen", f"127.0.0.1:{listen_port}"]
        for peer, port in sorted(peers.items()):
            cmd += ["--peer", f"{peer}=127.0.0.1:{port}"]
        return cmd

    chaos_spec = f"point={point},hit=1,mode=exit"
    timeout = args.case_timeout
    procs: dict = {}
    ports: dict = {}

    def spawn(name, listen_port, peers):
        extra = ["--chaos", chaos_spec] if name == victim_name else []
        procs[name] = launch(argv(name, listen_port, peers) + extra,
                             case_dir, name)

    def peers_of(name) -> dict:
        # plan topology: the lower party of each link dials the higher
        dials = {"p2": (), "p1": ("p2",), "p0": ("p1", "p2")}[name]
        return {peer: ports[peer] for peer in dials}

    def read_port(name) -> int:
        banner = json.loads(procs[name].stdout.readline())
        return int(banner["party"]["listening"][1])

    try:
        # listeners first: p2 accepts p0+p1; p1 accepts p0, dials p2;
        # p0 dials both (it is the lower party of both its links)
        spawn("p2", 0, {})
        ports["p2"] = read_port("p2")
        spawn("p1", 0, peers_of("p1"))
        ports["p1"] = read_port("p1")
        spawn("p0", None, peers_of("p0"))
        victim = procs[victim_name]
        try:
            rc = victim.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return [f"victim {victim_name} did not crash at {point} "
                    f"within {timeout:.0f}s"]
        victim.stdout.read()  # drain the dead pipe
        if rc != chaos.EXIT_CODE:
            return [f"victim {victim_name} exited {rc}, expected the "
                    f"chaos kill code {chaos.EXIT_CODE}"]
        # restart: the identical command line minus the kill plan
        # (listeners rebind their concrete discovered port — port 0 was
        # only for discovery; the peers' reconnecting links redial it)
        procs[victim_name] = launch(
            argv(victim_name, ports.get(victim_name),
                 peers_of(victim_name)), case_dir, victim_name)
        out, results = {}, {}
        for name in ("p0", "p1", "p2"):
            try:
                rc = procs[name].wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                return [f"party {name} hung after the restart "
                        f"(>{timeout:.0f}s)"]
            out[name] = procs[name].stdout.read()
            if rc != 0:
                return [f"party {name} exited {rc} after the restart; "
                        f"see {case_dir}/{name}.stderr.log"]
            results[name] = parse_result(out[name])
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()

    errs = []
    all_transcripts = []
    for name in ("p0", "p1", "p2"):
        if results[name]["cells"] != ref[name].cells:
            errs.append(f"party {name} matrix diverged from the "
                        "uninterrupted in-process reference")
        # ε spent exactly once, at the release-reuse optimum share
        with open(os.path.join(case_dir, f"ledger.{name}.json")) as fh:
            spent = json.load(fh)["spent"]
        want = plan.party_eps()[name]
        if abs(spent.get(name, 0.0) - want) > 1e-9:
            errs.append(f"party {name} spent {spent.get(name, 0.0)!r}, "
                        f"expected exactly-once charges totalling "
                        f"{want!r}")
        tscripts = [
            os.path.join(case_dir,
                         f"{plan.link_session(p, q)}.{name}.jsonl")
            for p, q in plan.party_links(name)]
        all_transcripts.extend(tscripts)
        for t in tscripts:
            rep = scan_transcript(t)
            if not rep["ok"]:
                errs.append(f"party {name} transcript scan: "
                            f"{rep['violations']}")
        bal = federation_balance(
            tscripts,
            read_events(os.path.join(case_dir, f"audit.{name}.jsonl")),
            expected_local_eps=sum(
                plan.local_charges(name)["charges"].values()))
        if not bal["ok"]:
            errs.append(f"party {name} ledger balance: "
                        f"sends {bal['unmatched_sends']} "
                        f"charges {bal['unmatched_charges']} "
                        f"local {bal['local_eps']!r}")
    cross = scan_federation(all_transcripts)
    if not cross["ok"]:
        errs.append(f"cross-pair federation scan: {cross['violations']}")
    return errs


def cmd_lint(args):
    """Static invariant checker over the repo's own source
    (docs/STATIC_ANALYSIS.md): RNG hygiene, budget discipline, lock
    discipline, jit purity. jax-free; exit code is the gate."""
    from dpcorr.analysis import cli as lint_cli

    sys.exit(lint_cli.run(args))


def cmd_doctor(args):
    from dpcorr.utils import doctor

    report = doctor.diagnose(probe=args.probe, sweep=args.sweep,
                             queue_dir=args.queue_dir)
    try:
        if args.json:
            print(json.dumps(report))
        else:
            print(doctor.render_text(report))
        sys.stdout.flush()
    except BrokenPipeError:
        # `dpcorr doctor | head` must not stack-trace — and the
        # interpreter's exit-time stdout flush would re-raise, so hand
        # it a dead fd instead
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


def main(argv=None):
    ap = argparse.ArgumentParser(prog="dpcorr")
    sub = ap.add_subparsers(dest="cmd", required=True)

    # doctor takes none of the common flags (no JAX import unless --probe)
    pd_ = sub.add_parser("doctor", help="environment health report "
                         "(tunnel endpoint, stray TPU clients, compile "
                         "cache, queue markers)")
    pd_.add_argument("--probe", action="store_true",
                     help="also run the authoritative device probe "
                          "(subprocess, 150s hard timeout)")
    pd_.add_argument("--sweep", action="store_true",
                     help="kill stray bench workers holding the TPU client")
    pd_.add_argument("--json", action="store_true")
    pd_.add_argument("--queue-dir", dest="queue_dir", default=None,
                     help="queue marker dir (default: $TPU_R04_IN or "
                          "/tmp/tpu_r04, same rule as the queue itself)")
    # doctor skips _add_common, so give the shared dispatch code below
    # the one attribute it reads unconditionally; jax_free marks any
    # subcommand that must not touch jax config (the dispatch checks
    # the flag, not function identity, so future jax-free subcommands
    # just set it too)
    pd_.set_defaults(fn=cmd_doctor, platform=None, jax_free=True)

    pl_ = sub.add_parser("lint", help="AST-based privacy/RNG/concurrency "
                         "invariant checker over dpcorr's own source "
                         "(docs/STATIC_ANALYSIS.md); jax-free, exit 1 on "
                         "new violations")
    from dpcorr.analysis import cli as lint_cli

    lint_cli.add_arguments(pl_)
    pl_.set_defaults(fn=cmd_lint, platform=None, jax_free=True)

    ps_ = sub.add_parser("serve", help="online micro-batched DP-correlation "
                         "service with a per-party privacy-budget ledger "
                         "(docs/SERVING.md)")
    ps_.add_argument("--host", default="127.0.0.1")
    ps_.add_argument("--port", type=int, default=8321,
                     help="HTTP port (0 = ephemeral; the bound port is "
                          "printed in the banner line, which is how "
                          "the fleet harness discovers replicas)")
    ps_.add_argument("--instance", default=None,
                     help="fleet instance name: labels this process in "
                          "/stats, the instance_info gauge, and the "
                          "banner, so the fleet collector (obs fleet) "
                          "can cross-check its target map")
    ps_.add_argument("--budget", type=float, default=100.0,
                     help="default per-party ε budget (basic composition)")
    ps_.add_argument("--ledger", default=None,
                     help="ledger persistence path (JSON); restarts resume "
                          "the spend table, so budgets survive crashes")
    ps_.add_argument("--user-dir", dest="user_dir", default=None,
                     help="per-user budget directory root (sharded WAL + "
                          "snapshot store, docs/SERVING.md): enables "
                          "per-user admission for requests carrying "
                          "'user'; restarts recover exact balances")
    ps_.add_argument("--user-budget", dest="user_budget", type=float,
                     default=1.0,
                     help="per-user ε budget per renewal window")
    ps_.add_argument("--user-shards", dest="user_shards", type=int,
                     default=8,
                     help="directory shard count (pinned in meta.json on "
                          "first boot; reopens adopt the persisted count)")
    ps_.add_argument("--user-max-resident", dest="user_max_resident",
                     type=int, default=None,
                     help="LRU cap on in-memory users per shard; colder "
                          "users spill to disk and rehydrate on touch "
                          "(default: unbounded)")
    ps_.add_argument("--user-compact-every", dest="user_compact_every",
                     type=int, default=256,
                     help="fold the shard WAL into its snapshot every "
                          "this many journal appends (None-like 0 "
                          "disables)")
    ps_.add_argument("--user-renew-period-s", dest="user_renew_period_s",
                     type=float, default=86400.0,
                     help="per-user window length: spend resets every "
                          "period (daily ε refresh by default)")
    ps_.add_argument("--user-burst-cap", dest="user_burst_cap",
                     type=float, default=0.0,
                     help="unspent window ε carried into the next window "
                          "as burst credit, capped here (0 disables)")
    ps_.add_argument("--global-budget", dest="global_budget", type=float,
                     default=None,
                     help="whole-replica ε ceiling, charged atomically "
                          "with the per-party legs (reserved principal "
                          "global/total)")
    ps_.add_argument("--lease-dir", dest="lease_dir", default=None,
                     help="fleet mode (requires --user-dir): shard-lease "
                          "directory SHARED by all replicas of one "
                          "budget directory; each shard's journal is "
                          "only ever written by the replica holding its "
                          "lease (docs/SERVING.md 'Running a fleet')")
    ps_.add_argument("--lease-ttl-s", dest="lease_ttl_s", type=float,
                     default=3.0,
                     help="lease validity window; a silent replica "
                          "loses its shards this long after its last "
                          "heartbeat renewal")
    ps_.add_argument("--lease-target", dest="lease_target", type=int,
                     default=None,
                     help="cap on proactively acquired shards (the "
                          "fleet harness passes ceil(shards/replicas) "
                          "so the first replica up doesn't hoard the "
                          "ring); on-demand takeover of free shards "
                          "is not capped")
    ps_.add_argument("--max-batch", dest="max_batch", type=int, default=64,
                     help="flush a bucket at this many live requests")
    ps_.add_argument("--max-delay-ms", dest="max_delay_ms", type=float,
                     default=5.0,
                     help="flush a bucket once its oldest request has "
                          "waited this long")
    ps_.add_argument("--max-queue", dest="max_queue", type=int, default=4096,
                     help="backpressure: refuse admissions beyond this many "
                          "pending requests")
    ps_.add_argument("--shard", default="auto", choices=["auto", "off"],
                     help="shard wide flushes over the device mesh")
    ps_.add_argument("--batch-mode", dest="batch_mode", default="exact",
                     choices=["exact", "vector"],
                     help="batch engine: 'exact' (lax.map; bit-identical "
                          "to direct calls) or 'vector' (vmap; faster, CI "
                          "endpoints within 1 ulp — see docs/SERVING.md)")
    ps_.add_argument("--max-kernels", dest="max_kernels", type=int,
                     default=128,
                     help="LRU cap on live compiled kernels (signatures "
                          "include exact n, so unbounded n-sweeps would "
                          "otherwise grow compilations without limit)")
    ps_.add_argument("--seed", type=int, default=2025)
    ps_.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    ps_.add_argument("--trace", "--span-spool", dest="trace", default=None,
                     help="span-spool JSONL path (docs/OBSERVABILITY.md); "
                          "also settable via DPCORR_TRACE. The fleet "
                          "plane unions many instances' spools into one "
                          "Chrome trace (`dpcorr obs fleet chrome`)")
    ps_.add_argument("--audit", default=None,
                     help="privacy-budget audit-trail JSONL path; replay "
                          "it with `dpcorr obs budget --audit PATH`")
    ps_.add_argument("--warmup", default=None,
                     help="compile-ahead signature spec, entries "
                          "family:n:eps1:eps2[:bpads[:alpha[:normalise]]] "
                          "separated by ';' (bpads: comma list or 'auto' "
                          "= every pow2 up to --max-batch); compiled in "
                          "the background behind GET /readyz "
                          "(docs/SERVING.md)")
    ps_.add_argument("--warmup-manifest", dest="warmup_manifest",
                     default=None,
                     help="kernel-manifest JSON path: replayed as warmup "
                          "on boot, rewritten with the resident kernel "
                          "set on shutdown — restarts come up warm")
    ps_.add_argument("--aot", default="on", choices=["on", "off"],
                     help="ahead-of-time kernel compilation (utils."
                          "compile); 'off' reverts to lazy jit on first "
                          "flush (A/B measurement)")
    ps_.add_argument("--breaker-threshold", dest="breaker_threshold",
                     type=int, default=5,
                     help="circuit breaker: consecutive kernel failures "
                          "in one compile bucket before it opens "
                          "(docs/ROBUSTNESS.md)")
    ps_.add_argument("--breaker-reset-s", dest="breaker_reset_s",
                     type=float, default=30.0,
                     help="circuit breaker: cooldown before an open "
                          "bucket admits one half-open probe")
    ps_.add_argument("--shed-queue-frac", dest="shed_queue_frac",
                     type=float, default=0.75,
                     help="brownout: queue fraction counted as "
                          "sustained pressure")
    ps_.add_argument("--flush-slo-ms", dest="flush_slo_ms", type=float,
                     default=None,
                     help="brownout: flush-latency EWMA above this also "
                          "counts as pressure (default: queue-only)")
    ps_.add_argument("--brownout-enter-s", dest="brownout_enter_s",
                     type=float, default=0.5,
                     help="brownout: sustained-pressure seconds before "
                          "entering (unbatched fallback + low-priority "
                          "rejection)")
    ps_.add_argument("--brownout-exit-s", dest="brownout_exit_s",
                     type=float, default=2.0,
                     help="brownout: calm seconds before exiting")
    ps_.add_argument("--brownout-min-priority", dest="brownout_min_priority",
                     type=int, default=0,
                     help="brownout: reject requests below this priority "
                          "while active")
    ps_.add_argument("--fault", action="append", default=None,
                     metavar="SPEC",
                     help="install a chaos fault before serving, e.g. "
                          "'point=serve.kernel,mode=fail,times=3' "
                          "(repeatable; testing only — dpcorr.chaos)")
    ps_.add_argument("--flight-recorder", dest="flight_recorder",
                     default=None, metavar="PATH",
                     help="flight-recorder dump path: bounded in-memory "
                          "rings of recent spans/audit/logs/metrics, "
                          "dumped atomically here on chaos crashes, "
                          "breaker trips, brownout transitions and "
                          "SIGUSR2; replay with `dpcorr obs dump PATH`")
    ps_.set_defaults(fn=cmd_serve)

    pfl = sub.add_parser("fleet", help="horizontally scaled serve: "
                         "front-end router over N replicas with leased "
                         "budget shards (docs/SERVING.md)")
    pfls = pfl.add_subparsers(dest="fleet_cmd", required=True)
    pff = pfls.add_parser("front", help="jax-free HTTP front end over "
                          "already-running serve replicas")
    pff.add_argument("--replica", action="append", required=True,
                     metavar="NAME=URL",
                     help="one serve replica (repeatable), e.g. "
                          "r0=http://127.0.0.1:8321")
    pff.add_argument("--lease-dir", dest="lease_dir", default=None,
                     help="the fleet's shared lease directory: routes "
                          "each user to the replica owning their "
                          "budget shard")
    pff.add_argument("--host", default="127.0.0.1")
    pff.add_argument("--port", type=int, default=8330)
    pff.add_argument("--health-interval-s", dest="health_interval_s",
                     type=float, default=0.5,
                     help="readyz poll cadence per replica")
    pff.set_defaults(fn=cmd_fleet)
    pfu = pfls.add_parser("up", help="boot + supervise N serve replicas "
                          "over one shared budget directory, plus a "
                          "front end; a dead replica is restarted with "
                          "identical argv and its shards re-leased")
    pfu.add_argument("--workdir", required=True,
                     help="fleet state root: budget/ (shared directory), "
                          "leases/, per-replica ledger/audit/logs")
    pfu.add_argument("--replicas", type=int, default=3)
    pfu.add_argument("--budget", type=float, default=100.0)
    pfu.add_argument("--user-budget", dest="user_budget", type=float,
                     default=1.0)
    pfu.add_argument("--user-shards", dest="user_shards", type=int,
                     default=16)
    pfu.add_argument("--lease-ttl-s", dest="lease_ttl_s", type=float,
                     default=3.0)
    pfu.add_argument("--max-delay-ms", dest="max_delay_ms", type=float,
                     default=5.0)
    pfu.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    pfu.add_argument("--host", default="127.0.0.1")
    pfu.add_argument("--port", type=int, default=8330)
    pfu.add_argument("--health-interval-s", dest="health_interval_s",
                     type=float, default=0.5)
    pfu.set_defaults(fn=cmd_fleet)

    pst = sub.add_parser("stream", help="always-on windowed DP "
                         "correlation over an ingest stream "
                         "(docs/STREAMING.md)")
    pst.add_argument("--workdir", required=True,
                     help="durable state directory: ingest WAL, release "
                          "journal, ledger snapshot, audit trail "
                          "(restart-safe — a kill -9 resumes from here)")
    pst.add_argument("--host", default="127.0.0.1")
    pst.add_argument("--port", type=int, default=8324,
                     help="HTTP ingest/subscribe port (0 = ephemeral; "
                          "read the bound port from the banner)")
    pst.add_argument("--window-s", dest="window_s", type=float,
                     default=10.0, help="event-time window size")
    pst.add_argument("--slide-s", dest="slide_s", type=float,
                     default=None,
                     help="sliding hop (default: tumbling)")
    pst.add_argument("--late-s", dest="late_s", type=float, default=0.0,
                     help="bounded lateness: watermark trails the max "
                          "event time seen by this much")
    pst.add_argument("--families", default="ni_sign",
                     help="comma list of estimator families released "
                          "per window")
    pst.add_argument("--eps1", type=float, default=1.0)
    pst.add_argument("--eps2", type=float, default=0.5)
    pst.add_argument("--normalise", default="on", choices=["on", "off"])
    pst.add_argument("--budget", type=float, default=100.0,
                     help="per-party ε budget (refuse-before-release: "
                          "an exhausted window is refused, never noised)")
    pst.add_argument("--seed", type=int, default=2025)
    pst.add_argument("--party-x", dest="party_x", default="party/x")
    pst.add_argument("--party-y", dest="party_y", default="party/y")
    pst.add_argument("--stream-id", dest="stream_id", default="stream",
                     help="charge-id namespace: per-window charges are "
                          "stream:<stream-id>:<window-id>")
    pst.add_argument("--user", default=None,
                     help="bind every window's charge to this user in a "
                          "per-user budget directory under the workdir "
                          "(renewal period = the window hop)")
    pst.add_argument("--user-budget", dest="user_budget", type=float,
                     default=None,
                     help="per-renewal-window user ε budget "
                          "(default: --budget)")
    pst.add_argument("--global-budget", dest="global_budget", type=float,
                     default=None,
                     help="instance-wide ε cap across every principal")
    pst.add_argument("--max-pending-rows", dest="max_pending_rows",
                     type=int, default=1 << 20,
                     help="bounded ingest: refuse batches (429 + "
                          "Retry-After) past this many buffered rows")
    pst.add_argument("--chaos", default=None, metavar="SPEC",
                     help="install a chaos kill plan, e.g. "
                          "'point=stream.pre_release,hit=1,mode=exit' "
                          "(also honoured from DPCORR_CHAOS; testing "
                          "only — dpcorr.chaos)")
    pst.add_argument("--flight-recorder", dest="flight_recorder",
                     default=None, metavar="PATH",
                     help="flight-recorder dump path (armed for "
                          "stream_release_failed and chaos kills; "
                          "replay with `dpcorr obs dump PATH`)")
    pst.add_argument("--instance", default=None,
                     help="fleet identity claimed in the "
                          "dpcorr_stream_instance_info gauge "
                          "(default: --stream-id)")
    pst.add_argument("--obs-port", dest="obs_port", type=int,
                     default=None,
                     help="observability endpoint port (0 = ephemeral; "
                          "/metrics, /stats, /healthz, POST "
                          "/obs/trigger) for FleetCollector and "
                          "obs top --fleet")
    pst.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    pst.add_argument("--placement", default=None,
                     choices=["local", "mesh"],
                     help="execution placement for window finalize "
                          "(dpcorr.plan): 'mesh' splits each pass's "
                          "chunk set across devices and tree-merges "
                          "the shard sketches — bitwise-equal to the "
                          "default monolithic release")
    pst.add_argument("--mesh-devices", dest="mesh_devices", type=int,
                     default=None,
                     help="device count for --placement mesh "
                          "(default: all visible devices)")
    pst.set_defaults(fn=cmd_stream)

    po_ = sub.add_parser("obs", help="telemetry tooling: audit-trail "
                         "replay and Chrome-trace export "
                         "(docs/OBSERVABILITY.md)")
    obs_sub = po_.add_subparsers(dest="obs_cmd", required=True)
    pob = obs_sub.add_parser("budget", help="per-party ε-spend timeline "
                             "replayed from a ledger audit trail")
    pob.add_argument("--audit", required=True,
                     help="audit-trail JSONL path (serve --audit)")
    pob.add_argument("--party", default=None,
                     help="restrict the timeline to one party")
    pob.add_argument("--budget-dir", dest="budget_dir", default=None,
                     help="per-user budget directory root: fold the "
                          "trail's sharded user/ legs and prove them "
                          "equal to the directory's on-disk recovery "
                          "arithmetic (exit 1 on mismatch); jax-free")
    pob.add_argument("--json", action="store_true")
    pob.set_defaults(fn=cmd_obs_budget, platform=None, jax_free=True)
    poc = obs_sub.add_parser("chrome", help="convert a span JSONL log "
                             "to Chrome trace-event JSON (Perfetto)")
    poc.add_argument("--trace", required=True,
                     help="span-trace JSONL path (serve --trace)")
    poc.add_argument("--out", required=True,
                     help="output Chrome trace JSON path")
    poc.set_defaults(fn=cmd_obs_chrome, platform=None, jax_free=True)
    pod = obs_sub.add_parser("dump", help="replay a flight-recorder "
                             "dump: span chains, cost records and the "
                             "ε trail, reconstructed jax-free")
    pod.add_argument("path", help="dump path (serve --flight-recorder)")
    pod.add_argument("--trace-id", dest="trace_id", default=None,
                     help="reconstruct one request's span chain + "
                          "cost record + ε trail")
    pod.add_argument("--json", action="store_true")
    pod.set_defaults(fn=cmd_obs_dump, platform=None, jax_free=True)
    pot = obs_sub.add_parser("top", help="live ops console over a "
                             "serve replica's /metrics + /stats")
    pot.add_argument("--url", default="http://127.0.0.1:8321",
                     help="serve base URL")
    pot.add_argument("--interval", type=float, default=2.0,
                     help="refresh seconds")
    pot.add_argument("--fleet", default=None, metavar="TARGETS",
                     help="multi-instance view: comma-separated "
                          "name=url targets (bare urls get positional "
                          "names); overrides --url")
    pot.add_argument("--federation", default=None, metavar="TARGETS",
                     help="federation view: comma-separated name=url "
                          "targets pointing at party --obs-port "
                          "endpoints; overrides --url and --fleet")
    pot.add_argument("--stream", action="store_true",
                     help="render the dpcorr-stream console (windows, "
                          "watermark, ε/window) instead of the serve one")
    pot.add_argument("--once", action="store_true",
                     help="render one frame and exit (scripting/CI)")
    pot.set_defaults(fn=cmd_obs_top, platform=None, jax_free=True)
    pop = obs_sub.add_parser(
        "provenance", help="federation ε-provenance DAG (ISSUE 13): "
        "merge per-party transcripts/audits/journals against the "
        "plan, prove exactly-once charging + byte-identical reuse at "
        "the 2fε(k-1) optimum; exit 1 names the offending party")
    pop.add_argument("--plan", required=True,
                     help="federation plan JSON (`dpcorr federation "
                          "plan` output or its `plan` field)")
    pop.add_argument("--transcript-dir", dest="transcript_dir",
                     default=None,
                     help="directory of {session}.{party}.jsonl "
                          "pair-link transcripts (party inferred from "
                          "the filename)")
    pop.add_argument("--transcript", action="append", default=None,
                     metavar="NAME=PATH",
                     help="explicit party transcript (repeatable; "
                          "bare PATH infers the party from the "
                          "filename)")
    pop.add_argument("--audit", action="append", default=None,
                     metavar="NAME=PATH",
                     help="party audit trail (repeatable) — required "
                          "to *prove* exactly-once charging rather "
                          "than infer it from transcripts")
    pop.add_argument("--journal-dir", dest="journal_dir", default=None,
                     help="session-journal directory (adds resume "
                          "lineage to round nodes)")
    pop.add_argument("--out", default=None,
                     help="write the provenance JSON document here")
    pop.add_argument("--dot", default=None,
                     help="write the Graphviz DOT rendering here")
    pop.add_argument("--cell", default=None, metavar="I,J",
                     help="print one cell's full story (rounds, "
                          "artifacts, charges) instead of the summary")
    pop.add_argument("--json", action="store_true",
                     help="print the full document to stdout")
    pop.set_defaults(fn=cmd_obs_provenance, platform=None,
                     jax_free=True)
    pof = obs_sub.add_parser("fleet", help="fleet telemetry plane "
                             "(ISSUE 11): scrape + merge N instances, "
                             "union spools, replay the fleet ε table; "
                             "all jax-free")
    fleet_sub = pof.add_subparsers(dest="fleet_cmd", required=True)
    pofs = fleet_sub.add_parser("snapshot", help="scrape every target's "
                                "/metrics + /stats into one artifact: "
                                "merged instance-labelled exposition + "
                                "exact aggregate + per-instance stats")
    pofs.add_argument("--targets", required=True,
                      help="comma-separated name=url (bare urls get "
                           "positional instance-N names; duplicate "
                           "names are refused)")
    pofs.add_argument("--out", default=None,
                      help="write the snapshot JSON here")
    pofs.add_argument("--timeout", type=float, default=5.0)
    pofs.add_argument("--json", action="store_true",
                      help="print the full snapshot document")
    pofs.set_defaults(fn=cmd_obs_fleet_snapshot, platform=None,
                      jax_free=True)
    pofc = fleet_sub.add_parser("chrome", help="union many span spools "
                                "into ONE Chrome trace, one pid per "
                                "instance (Perfetto-viewable)")
    pofc.add_argument("--spool", action="append", required=True,
                      metavar="NAME=PATH",
                      help="instance span spool (repeatable)")
    pofc.add_argument("--out", required=True)
    pofc.set_defaults(fn=cmd_obs_fleet_chrome, platform=None,
                      jax_free=True)
    pofr = fleet_sub.add_parser("replay", help="fleet-wide audit "
                                "replay: per-instance ε tables + the "
                                "binary-exact fleet fold")
    pofr.add_argument("--audit", action="append", required=True,
                      metavar="NAME=PATH",
                      help="instance audit spool (repeatable)")
    pofr.add_argument("--json", action="store_true")
    pofr.set_defaults(fn=cmd_obs_fleet_replay, platform=None,
                      jax_free=True)
    potr = obs_sub.add_parser(
        "trajectory", help="bench-trajectory dashboard (ISSUE 15): "
        "per-(device_kind, metric) series over the committed "
        "BENCH_*/MULTICHIP_*/benchmarks-results artifacts; names the "
        "first artifact that bent the curve; jax-free")
    potr.add_argument("--root", action="append", default=None,
                      help="artifact root (file or dir, repeatable); "
                           "default: repo root + benchmarks/results")
    potr.add_argument("--repo", default=".",
                      help="repo root for the default artifact roots")
    potr.add_argument("--floor", type=float, default=0.85,
                      help="regression floor vs best-so-far (0.85 = "
                           "flag a drop below 85%%)")
    potr.add_argument("--format", choices=["console", "json", "markdown"],
                      default="console")
    potr.add_argument("--check", action="store_true",
                      help="exit 1 when any series regressed")
    potr.set_defaults(fn=cmd_obs_trajectory, platform=None,
                      jax_free=True)
    poh = obs_sub.add_parser(
        "hlo", help="compiled-signature introspection (ISSUE 15): show "
        "or diff persisted HLO signature dumps (cost, memory, "
        "fingerprints, op histograms); jax-free")
    hlo_sub = poh.add_subparsers(dest="hlo_cmd", required=True)
    pohs = hlo_sub.add_parser("show", help="list one dump's signatures")
    pohs.add_argument("path", help="dpcorr_hlo_dump JSON path")
    pohs.add_argument("--json", action="store_true")
    pohs.set_defaults(fn=cmd_obs_hlo, platform=None, jax_free=True)
    pohd = hlo_sub.add_parser(
        "diff", help="explain what changed between two dumps: "
        "fingerprint flips, FLOP/byte/memory deltas, op-count deltas "
        "(copy/transpose deltas mark layout/reshard boundaries)")
    pohd.add_argument("old", help="baseline dump")
    pohd.add_argument("new", help="candidate dump")
    pohd.add_argument("--json", action="store_true")
    pohd.set_defaults(fn=cmd_obs_hlo, platform=None, jax_free=True)
    pog = obs_sub.add_parser(
        "geometry", help="autotuner cache view (ISSUE 15): tuned "
        "(chunk x block) per (device_kind, family, n, dtype) with "
        "env-pin provenance and staleness; exit 1 on corrupt cache; "
        "jax-free")
    pog.add_argument("--path", default=None,
                     help="cache path (default: the resolved "
                          "DPCORR_GEOMETRY_CACHE / ~/.cache location)")
    pog.add_argument("--json", action="store_true")
    pog.set_defaults(fn=cmd_obs_geometry, platform=None, jax_free=True)
    pow_ = obs_sub.add_parser(
        "watch", help="live invariant sentinel: tail audit trails, "
        "stream WAL/journal, budget dirs and transcripts; typed "
        "violations page, arm the offender's flight recorder and set "
        "exit 1 (docs/OBSERVABILITY.md §Sentinel); jax-free")
    pow_.add_argument("--checkpoint", required=True,
                      help="the sentinel's own fsynced offset/state "
                           "checkpoint: restarts resume mid-file and "
                           "never re-alert on re-read")
    pow_.add_argument("--stream", action="append",
                      metavar="NAME=WORKDIR",
                      help="watch a stream workdir (wal.jsonl, "
                           "releases.jsonl, audit.jsonl, budget_dir)")
    pow_.add_argument("--audit", action="append", metavar="NAME=PATH",
                      help="watch a bare audit trail (serve --audit / "
                           "party --audit)")
    pow_.add_argument("--budget-dir", dest="budget_dir",
                      action="append", metavar="NAME=ROOT",
                      help="ε-conservation leg for --audit NAME: the "
                           "directory's on-disk user balances must "
                           "equal the trail's user/ fold")
    pow_.add_argument("--transcripts", action="append",
                      metavar="NAME=DIR",
                      help="watch pair-link transcripts for re-noised "
                           "or double-charged artifacts")
    pow_.add_argument("--journals", action="append", metavar="NAME=DIR",
                      help="watch session-journal snapshots for "
                           "resume-breaking corruption")
    pow_.add_argument("--url", action="append", metavar="NAME=URL",
                      help="NAME's live base URL: its ledger gauges "
                           "are scraped for the conservation check and "
                           "its flight recorder armed (POST "
                           "/obs/trigger) on violation")
    pow_.add_argument("--interval", type=float, default=1.0,
                      help="poll seconds (detection latency bound)")
    pow_.add_argument("--max-polls", dest="max_polls", type=int,
                      default=None, help="stop after N polls (CI)")
    pow_.add_argument("--once", action="store_true",
                      help="one poll, then exit with the rc")
    pow_.add_argument("--instance", default="sentinel")
    pow_.add_argument("--obs-port", dest="obs_port", type=int,
                      default=None,
                      help="the sentinel's own scrape surface "
                           "(dpcorr_sentinel_* metrics + /stats)")
    pow_.add_argument("--json", action="store_true")
    pow_.set_defaults(fn=cmd_obs_watch, platform=None, jax_free=True)
    def _add_spec_flags(p):
        p.add_argument("--family", default="ni_sign",
                       choices=["ni_sign", "int_sign", "ni_subg",
                                "int_subg"])
        p.add_argument("--n", type=int, default=4000)
        p.add_argument("--eps1", type=float, default=1.0)
        p.add_argument("--eps2", type=float, default=0.5)
        p.add_argument("--alpha", type=float, default=0.05)
        p.add_argument("--normalise", default="on", choices=["on", "off"])
        p.add_argument("--seed", type=int, default=2025)
        p.add_argument("--session", default=None,
                       help="session id (default: derived from the spec "
                            "hash, so both parties agree without "
                            "coordination)")
        p.add_argument("--noise-mode", dest="noise_mode", default="replay",
                       choices=["replay", "hardened"],
                       help="key layout (utils.rng.party_root): 'replay' "
                            "is bit-identical to the monolithic "
                            "estimators; 'hardened' gives each party a "
                            "disjoint key subtree")
        p.add_argument("--rho", type=float, default=0.6,
                       help="synthetic-data correlation (ignored with "
                            "--data)")
        p.add_argument("--timeout", type=float, default=10.0,
                       help="per-message ack timeout (seconds)")
        p.add_argument("--max-retries", dest="max_retries", type=int,
                       default=10)
        p.add_argument("--platform", default=None, choices=["cpu", "tpu"])

    pp_ = sub.add_parser("party", help="one side of the two-party DP "
                         "protocol over TCP: role y listens, role x "
                         "connects; each process holds one column "
                         "(docs/PROTOCOL.md)")
    pp_.add_argument("--role", required=True, choices=["x", "y"])
    pp_.add_argument("--instance", default=None,
                     help="fleet instance name: stamped into the "
                          "banner and the transcript header so this "
                          "party's span/audit spools can be unioned "
                          "into the fleet view (`dpcorr obs fleet`)")
    pp_.add_argument("--host", default="127.0.0.1")
    pp_.add_argument("--port", type=int, required=True)
    pp_.add_argument("--connect-timeout", dest="connect_timeout",
                     type=float, default=30.0,
                     help="seconds to keep dialing (x) or await the "
                          "peer (y)")
    pp_.add_argument("--data", default=None,
                     help="this party's column as a .npy file (shape "
                          "(n,)); default: synthetic from --rho/--seed")
    pp_.add_argument("--budget", type=float, default=100.0,
                     help="this party's ε budget (basic composition)")
    pp_.add_argument("--ledger", default=None,
                     help="ledger persistence path (JSON), same format "
                          "as serve --ledger")
    pp_.add_argument("--user", default=None,
                     help="principal this party's releases are charged "
                          "to in the per-user directory (default with "
                          "--user-dir: user-<role>)")
    pp_.add_argument("--user-dir", dest="user_dir", default=None,
                     help="per-user budget directory root: wraps the "
                          "ledger in a CompositeLedger so every gated "
                          "release also charges the bound user, "
                          "idempotently across crash-restarts")
    pp_.add_argument("--user-budget", dest="user_budget", type=float,
                     default=1.0, help="per-user ε budget per window")
    pp_.add_argument("--user-shards", dest="user_shards", type=int,
                     default=8, help="directory shard count")
    pp_.add_argument("--user-max-resident", dest="user_max_resident",
                     type=int, default=None,
                     help="LRU cap on in-memory users per shard")
    pp_.add_argument("--user-compact-every", dest="user_compact_every",
                     type=int, default=256,
                     help="WAL-to-snapshot compaction interval (appends)")
    pp_.add_argument("--transcript", default=None,
                     help="JSONL wire transcript path (audit it with "
                          "`dpcorr protocol scan`)")
    pp_.add_argument("--trace", default=None,
                     help="span-trace JSONL path; the trace ID crosses "
                          "the wire, so both parties' logs join")
    pp_.add_argument("--audit", default=None,
                     help="budget audit-trail JSONL path (obs.audit)")
    pp_.add_argument("--journal", default=None,
                     help="session journal path (JSON): makes the "
                          "session crash-safe — rerun the identical "
                          "command after a crash and it resumes instead "
                          "of restarting (docs/ROBUSTNESS.md)")
    pp_.add_argument("--chaos", default=None,
                     help="crash plan 'point=NAME[,hit=K][,mode=exit|"
                          "raise]' or 'seed=N' (dpcorr.chaos); default: "
                          "$DPCORR_CHAOS. The plan is recorded in the "
                          "transcript header")
    pp_.add_argument("--recv-timeout", dest="recv_timeout", type=float,
                     default=30.0,
                     help="seconds to wait for the peer's next protocol "
                          "message (raise it when the peer may be "
                          "restarting mid-session)")
    _add_spec_flags(pp_)
    pp_.set_defaults(fn=cmd_party)

    pr_ = sub.add_parser("protocol", help="two-party protocol tooling: "
                         "single-command run (both roles, one process) "
                         "and the jax-free transcript auditor")
    pr_sub = pr_.add_subparsers(dest="protocol_cmd", required=True)
    prr = pr_sub.add_parser("run", help="drive both roles in-process "
                            "over inproc or loopback-TCP transport")
    prr.add_argument("--transport", default="inproc",
                     choices=["inproc", "tcp"])
    prr.add_argument("--transcript-dir", dest="transcript_dir",
                     default=None,
                     help="write each party's wire transcript JSONL "
                          "into this directory")
    prr.add_argument("--fault-drop", dest="fault_drop", type=float,
                     default=0.0, help="fault injection: drop rate")
    prr.add_argument("--fault-delay-ms", dest="fault_delay_ms",
                     type=float, default=0.0,
                     help="fault injection: per-frame delay")
    prr.add_argument("--fault-duplicate", dest="fault_duplicate",
                     type=float, default=0.0,
                     help="fault injection: duplicate rate")
    prr.add_argument("--fault-seed", dest="fault_seed", type=int,
                     default=None,
                     help="base seed for both sides' fault injectors "
                          "(stamped into the transcript headers); "
                          "default: the fixed per-side seeds")
    _add_spec_flags(prr)
    prr.set_defaults(fn=cmd_protocol_run)
    prs = pr_sub.add_parser("scan", help="audit a party transcript: "
                            "schema + no-raw-columns, and with --audit "
                            "the transcript↔ledger ε balance; exit 1 on "
                            "violations")
    prs.add_argument("--transcript", required=True,
                     help="party transcript JSONL (party --transcript / "
                          "protocol run --transcript-dir)")
    prs.add_argument("--audit", default=None,
                     help="that party's audit-trail JSONL; enables the "
                          "ε balance check")
    prs.set_defaults(fn=cmd_protocol_scan, platform=None, jax_free=True)

    pc_ = sub.add_parser("chaos", help="deterministic step-kill sweep: "
                         "two party processes over real TCP, kill the "
                         "victim at each named crash point, restart it, "
                         "assert bit-identical results and exactly-once "
                         "ε spend (docs/ROBUSTNESS.md)")
    pc_.add_argument("--points", default=None,
                     help="comma list of crash points (default: the "
                          "standard matrix, dpcorr.chaos.MATRIX_POINTS)")
    pc_.add_argument("--roles", default=None,
                     help="comma list of victim roles from {x,y} "
                          "(default: both)")
    pc_.add_argument("--families", default=None,
                     help="comma list of estimator families to sweep "
                          "(default: just --family)")
    pc_.add_argument("--workdir", default=None,
                     help="artifact directory — per-case journals, "
                          "ledgers, audits, transcripts, stderr logs "
                          "(default: a fresh temp dir; keep it for CI "
                          "artifact upload)")
    pc_.add_argument("--chaos-seed", dest="chaos_seed", type=int,
                     default=None,
                     help="derive one (point, victim) case from a seed "
                          "(dpcorr.chaos.plan_from_seed) instead of "
                          "sweeping")
    pc_.add_argument("--case-timeout", dest="case_timeout", type=float,
                     default=180.0,
                     help="per-process wait bound within one case "
                          "(seconds)")
    _add_spec_flags(pc_)
    pc_.set_defaults(fn=cmd_chaos)

    pf_ = sub.add_parser("federation", help="N-party federation: the "
                         "full k×k DP correlation matrix over "
                         "multiplexed pair sessions, at the "
                         "column-release-reuse ε optimum "
                         "(docs/PROTOCOL.md)")
    pf_sub = pf_.add_subparsers(dest="federation_cmd", required=True)

    def _add_fed_flags(p):
        p.add_argument("--plan", default=None,
                       help="federation plan JSON file (the document "
                            "`dpcorr federation plan` prints, or its "
                            "inner public dict); overrides the inline "
                            "--party/spec flags — every party process "
                            "of one federation must hold the identical "
                            "plan (the link handshake pins its hash)")
        p.add_argument("--party", action="append", default=None,
                       metavar="NAME=LAB1[,LAB2...]",
                       help="one party and its column labels "
                            "(repeatable; order is the public plan "
                            "order, which decides roles and topology)")
        p.add_argument("--family", default="ni_sign",
                       choices=["ni_sign", "int_sign", "ni_subg",
                                "int_subg"])
        p.add_argument("--n", type=int, default=4000)
        p.add_argument("--eps", type=float, default=1.0,
                       help="the federation's shared per-column ε")
        p.add_argument("--alpha", type=float, default=0.05)
        p.add_argument("--normalise", default="on", choices=["on", "off"])
        p.add_argument("--seed", type=int, default=2025)
        p.add_argument("--noise-mode", dest="noise_mode",
                       default="replay", choices=["replay", "hardened"])
        p.add_argument("--max-cells-per-round",
                       dest="max_cells_per_round", type=int, default=0,
                       help="chunk a link's cells into rounds of this "
                            "size (0: all of a link's cells in one "
                            "batched round)")

    def _add_fed_run_flags(p):
        p.add_argument("--rho", type=float, default=0.6,
                       help="synthetic-data equicorrelation across the "
                            "k columns")
        p.add_argument("--engine", default="exact",
                       choices=["exact", "vector"],
                       help="batched finish engine "
                            "(split_reference.finish_batch): 'exact' is "
                            "the bit-identity contract, 'vector' the "
                            "vmapped opt-in")
        p.add_argument("--timeout", type=float, default=10.0,
                       help="per-message ack timeout (seconds)")
        p.add_argument("--max-retries", dest="max_retries", type=int,
                       default=10)
        p.add_argument("--platform", default=None,
                       choices=["cpu", "tpu"])

    pfp = pf_sub.add_parser("plan", help="compile and print the "
                            "schedule: cells, links, rounds, artifact "
                            "charge venues and the ε arithmetic "
                            "(optimal vs naive per-cell); jax-free")
    _add_fed_flags(pfp)
    pfp.set_defaults(fn=cmd_federation_plan, platform=None,
                     jax_free=True)

    pfr = pf_sub.add_parser("run", help="whole federation in one "
                            "process (every party on a thread) over "
                            "inproc or loopback-TCP transport")
    _add_fed_flags(pfr)
    _add_fed_run_flags(pfr)
    pfr.add_argument("--transport", default="inproc",
                     choices=["inproc", "tcp"])
    pfr.add_argument("--transcript-dir", dest="transcript_dir",
                     default=None,
                     help="write every pair link's per-party wire "
                          "transcript JSONL into this directory "
                          "(audit with `dpcorr federation scan`)")
    pfr.add_argument("--fault-drop", dest="fault_drop", type=float,
                     default=0.0, help="fault injection: drop rate")
    pfr.add_argument("--fault-delay-ms", dest="fault_delay_ms",
                     type=float, default=0.0,
                     help="fault injection: per-frame delay")
    pfr.add_argument("--fault-duplicate", dest="fault_duplicate",
                     type=float, default=0.0,
                     help="fault injection: duplicate rate")
    pfr.add_argument("--fault-seed", dest="fault_seed", type=int,
                     default=None,
                     help="base seed for every endpoint's fault "
                          "injector (per-link-side offsets keep the "
                          "streams distinct but reproducible)")
    pfr.set_defaults(fn=cmd_federation_run)

    pft = pf_sub.add_parser("party", help="one real party process of a "
                            "multi-process federation over TCP: dials "
                            "lower links via --peer, listens for "
                            "higher ones via --listen; with "
                            "--journal-dir the whole matrix is "
                            "crash-safe — rerun the identical command "
                            "after a crash and it resumes")
    _add_fed_flags(pft)
    _add_fed_run_flags(pft)
    pft.add_argument("--name", required=True,
                     help="this process's party name in the plan")
    pft.add_argument("--listen", default=None, metavar="HOST:PORT",
                     help="bind here for peers that dial this party "
                          "(port 0: ephemeral, announced in the "
                          "banner); required iff a lower-indexed peer "
                          "shares a link")
    pft.add_argument("--peer", action="append", default=None,
                     metavar="NAME=HOST:PORT",
                     help="where to dial a higher-indexed link peer "
                          "(repeatable)")
    pft.add_argument("--budget", type=float, default=100.0,
                     help="this party's ε budget (basic composition)")
    pft.add_argument("--ledger", default=None,
                     help="ledger persistence path (JSON), same "
                          "format as serve --ledger")
    pft.add_argument("--audit", default=None,
                     help="budget audit-trail JSONL path (obs.audit)")
    pft.add_argument("--trace", default=None,
                     help="span-trace JSONL path — or a directory, "
                          "which spools to trace.<instance>.jsonl so "
                          "k parties can share one flag value")
    pft.add_argument("--instance", default=None,
                     help="instance name for telemetry (the "
                          "dpcorr_federation_instance_info self-claim "
                          "the fleet merge cross-checks, span-spool "
                          "filenames, the JSON banner); default: "
                          "--name")
    pft.add_argument("--obs-port", dest="obs_port", type=int,
                     default=None, metavar="PORT",
                     help="serve /metrics + /stats + POST /obs/trigger "
                          "on this port (0: ephemeral, announced in "
                          "the banner) for FleetCollector, obs top "
                          "--federation and SLO burn-rate paging")
    pft.add_argument("--transcript-dir", dest="transcript_dir",
                     default=None,
                     help="per-link wire transcript directory")
    pft.add_argument("--journal-dir", dest="journal_dir", default=None,
                     help="per-link session journal directory: makes "
                          "every pair session crash-safe "
                          "(docs/ROBUSTNESS.md)")
    pft.add_argument("--chaos", default=None,
                     help="crash plan 'point=NAME[,hit=K][,mode=exit|"
                          "raise]' or 'seed=N' (dpcorr.chaos); "
                          "default: $DPCORR_CHAOS")
    pft.add_argument("--connect-timeout", dest="connect_timeout",
                     type=float, default=30.0,
                     help="seconds to keep dialing / await each peer")
    pft.add_argument("--recv-timeout", dest="recv_timeout", type=float,
                     default=30.0,
                     help="seconds to wait for a peer's next protocol "
                          "message (raise it when peers may restart "
                          "mid-matrix)")
    pft.set_defaults(fn=cmd_federation_party)

    pfs = pf_sub.add_parser("scan", help="audit a federation's pair "
                            "transcripts: per-transcript schema + "
                            "no-raw-columns, the cross-pair "
                            "correlation-leak gate (reused releases "
                            "must be byte-identical; exit 1 names the "
                            "offending pair), and per-party ε balance")
    pfs.add_argument("--transcript", action="append", default=None,
                     help="pair-link transcript JSONL (repeatable)")
    pfs.add_argument("--transcript-dir", dest="transcript_dir",
                     default=None,
                     help="scan every *.jsonl in this directory "
                          "(audit./trace. prefixes skipped)")
    pfs.add_argument("--audit", action="append", default=None,
                     metavar="NAME=PATH",
                     help="party NAME's audit-trail JSONL: enables "
                          "that party's whole-matrix ε balance check "
                          "(repeatable)")
    pfs.add_argument("--plan", default=None,
                     help="the federation plan JSON: lets the balance "
                          "check derive each party's expected "
                          "local-cell ε (default: 0)")
    pfs.set_defaults(fn=cmd_federation_scan, platform=None,
                     jax_free=True)

    backends_by_cmd = {
        "grid": ("local", "sharded", "bucketed", "bucketed-sharded"),
        "grid-subg": ("local", "sharded", "bucketed", "bucketed-sharded"),
        "stress": ("local", "sharded"),
    }
    for name, fn in [("demo", cmd_demo), ("demo-subg", cmd_demo_subg),
                     ("grid", cmd_grid), ("grid-subg", cmd_grid_subg),
                     ("hrs", cmd_hrs), ("hrs-sweep", cmd_hrs_sweep),
                     ("stress", cmd_stress), ("acceptance", cmd_acceptance)]:
        p = sub.add_parser(name)
        _add_common(p, backends_by_cmd.get(name, ("local",)))
        if name == "stress":
            p.add_argument("--n", type=int, default=1_000_000)
            p.add_argument("--n-chunk", dest="n_chunk", type=int,
                           default=65_536)
            p.add_argument("--family", choices=["sign", "subg"],
                           default="subg")
            p.add_argument("--chunk-size", dest="chunk_size", type=int,
                           default=None,
                           help="replication vmap width (default: "
                                "platform-tuned)")
        if name == "acceptance":
            p.add_argument("--out-json", dest="out_json", default=None)
        if name in ("grid", "grid-subg"):
            p.add_argument("--n-hosts", dest="n_hosts", type=int, default=1,
                           help="fan the grid out over this many worker "
                                "processes (needs --out; see "
                                "dpcorr.parallel.multihost)")
            p.add_argument("--distributed", action="store_true",
                           help="with --n-hosts: run the workers as a real "
                                "jax.distributed cluster (SPMD slicing "
                                "from process_index/count, global barrier, "
                                "rank-0 merge)")
            p.add_argument("--local-devices", dest="local_devices",
                           type=int, default=None,
                           help="with --distributed: virtual CPU devices "
                                "each worker contributes (local cluster "
                                "testing)")
            p.add_argument("--fused", default="off",
                           choices=["off", "auto"],
                           help="run eligible (n, eps) buckets through the "
                                "fused Pallas kernels (TPU + --backend "
                                "bucketed only). auto: only where fused "
                                "measures faster (the Gaussian sign pair, "
                                "4.5x; the former 'all' subG mode was "
                                "retired in r05, see GridConfig.fused)")
            p.add_argument("--bucket-merge", dest="bucket_merge",
                           default="off", choices=["off", "eps"],
                           help="eps: merge subG compile buckets across "
                                "eps-pairs (one kernel per n; traced eps "
                                "+ in-kernel batch geometry — "
                                "GridConfig.bucket_merge; subG + "
                                "--backend bucketed only)")
            p.add_argument("--precompile", default="auto",
                           choices=["off", "auto", "on"],
                           help="AOT-precompile bucket kernels on a "
                                "thread pool during the phase-0 cache "
                                "scan, overlapped with dispatch "
                                "(bit-identical results — "
                                "GridConfig.precompile; --backend "
                                "bucketed only, no-op elsewhere). auto "
                                "enables it on >= 2-core hosts; on "
                                "forces it")
        p.set_defaults(fn=fn)
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        # must run before any backend initialization; no-op if one is live
        jax.config.update("jax_platforms", args.platform)
    if not getattr(args, "jax_free", False):
        # jax_free subcommands (doctor) never compile and must not
        # import jax or mutate its config; everything else may get the
        # opt-in persistent compile cache
        _maybe_compile_cache()
    args.fn(args)


def _maybe_compile_cache() -> None:
    """Opt-in persistent XLA compilation cache (DPCORR_COMPILE_CACHE=dir).

    The grid workloads compile one kernel per (n, ε) shape bucket — the
    dominant cost of short on-chip runs (e.g. the 144-point fused grid is
    compile-bound at B=250, docs/PERFORMANCE.md) — and the cache makes
    re-runs skip all of it. Opt-in because cache entries are
    revision/flag-sensitive and a stale cache dir is confusing in
    benchmarks; point it at a per-revision path for honest timings."""
    # env parsing (incl. the 0/off/none disable tokens) lives canonically
    # in dpcorr.utils.doctor; the CLI consumer is opt-in — unset env
    # resolves to None and the run stays cold
    from dpcorr.utils.doctor import resolve_cache_dir

    cache_dir = resolve_cache_dir("cli")
    if cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


if __name__ == "__main__":
    main()
