"""Design-grid driver (reference layers L4/L5).

Replaces the ``expand.grid`` + ``mclapply`` fan-out + ``rbindlist``
aggregation (vert-cor.R:486-597, ver-cor-subG.R:245-335) with:

- a typed :class:`GridConfig` instead of script globals (SURVEY.md §5);
- per-design-point execution through the local jit backend or the sharded
  mesh backend (``dpcorr.parallel``), with kernels compiled once per
  (n, ε) shape bucket and reused across the ρ sweep;
- per-design-point ``.npz`` persistence with resume (the reference only
  saves one blob at the end, ``saveRDS`` vert-cor.R:569 — here a killed grid
  restarts where it left off);
- fail-loud error handling per design point (the reference's mclapply
  swallows task deaths silently, SURVEY.md §5 failure detection);
- pandas aggregation reproducing the reference's grouped summaries
  (vert-cor.R:575-597).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from pathlib import Path
from typing import Any, Mapping, Sequence

import numpy as np
import pandas as pd

from dpcorr import sim as sim_mod
from dpcorr.obs import prof as prof_mod
from dpcorr.obs import trace as obs_trace
from dpcorr.sim import SimConfig
from dpcorr.utils import compile as compile_mod
from dpcorr.utils import rng

log = logging.getLogger("dpcorr.grid")


@dataclasses.dataclass(frozen=True)
class GridConfig:
    """The design grid + execution knobs.

    Defaults mirror the reference's v1 grid section (vert-cor.R:486-499).
    """

    n_grid: Sequence[int] = (1000, 1500, 2500, 4000, 6000, 9000)
    rho_grid: Sequence[float] = (0.0, 0.15, 0.3, 0.4, 0.5, 0.65, 0.8, 0.9)
    eps_pairs: Sequence[tuple[float, float]] = ((0.5, 0.5), (1.0, 1.0), (1.5, 0.5))
    b: int = 250
    alpha: float = 0.05
    dgp: Any = "gaussian"
    dgp_args: Mapping[str, Any] | tuple = ()
    use_subg: bool = False
    ci_mode: str = "auto"
    normalise: bool = True
    mixquant_mode: str = "det"
    seed: int = rng.MASTER_SEED
    chunk_size: int = 4096
    #: "pinned" (use ``chunk_size`` as given) | "auto": read this host's
    #: persisted geometry cache (``utils.geometry``, populated by the
    #: bench autotuner / an explicit ``autotune()`` call) and use the
    #: tuned chunk width for each bucket's (family, n) when one exists,
    #: falling back to ``chunk_size``. Read-only — the grid never
    #: probe-times (a probe inside a resumable grid would burn reps and
    #: jitter timings); tuning happens at bench start. Bit-safe by
    #: construction: every chunk width ≥ 2 yields bitwise-identical
    #: results (geometry.CHUNK_FLOOR) and the resume-cache stamp
    #: canonicalizes the chunk axis accordingly (see :func:`_stamp`).
    geometry: str = "pinned"
    #: "local" | "sharded" (replications of each point over the mesh) |
    #: "bucketed" (one kernel per (n, ε) shape bucket) |
    #: "bucketed-sharded" (bucket kernels with the flat point×rep axis
    #: split across the mesh — both parallel axes composed)
    backend: str = "local"
    #: "off" | "auto": fused-Pallas bucket selection for the bucketed
    #: backend (on-chip PRNG, whole replication in VMEM). "auto" runs
    #: buckets through a fused kernel only where it measures FASTER
    #: than the XLA kernel: the Gaussian sign pair (ops/pallas_ni.py —
    #: 4.5× on the reference grid,
    #: benchmarks/results/r02_grid_fused_tpu.json). TPU-only;
    #: eligibility also needs det mixquant and m ≤ 128 (see
    #: _fused_bucket_ok). Fused results come from a different PRNG
    #: stream family, so their resume caches are stamped separately and
    #: never mix with XLA-path caches.
    #: A third mode "all" (the perf-neutral fused subG grid pair,
    #: ops/pallas_subg.py) was RETIRED in r05 by STATUS_r04.md's
    #: written deadline decision: measured 0.98× XLA steady-state and
    #: 0.867× wall with a slower Mosaic compile
    #: (r02_grid_fused_subg_tpu.json), and its class of fresh Mosaic
    #: compile is the leading tunnel-wedge suspect. The kernel lives in
    #: git history (r04 tree) should hardware ever favor it.
    fused: str = "off"
    #: "off" | "eps": ε-merged compile buckets for the bucketed backend
    #: (r05). "eps" groups subG buckets by n ONLY — ε becomes a traced
    #: per-point operand and the batch geometry in-kernel masked data
    #: (sim._run_detail_flat_eps), so the reference's subG grid compiles
    #: one kernel per n (5) instead of one per (n, ε) (15). subG
    #: families only (the sign estimators keep static geometry),
    #: non-streaming, and every ε-pair must satisfy ε₁ ≥ ε₂ (the merged
    #: kernel names the sender explicitly). Results are statistically
    #: identical to "off" but NOT bit-identical (the dynamic-geometry
    #: estimator draws per-batch noise from a padded stream layout), so
    #: resume caches are stamped "|geom=dyn" and never mix with "off"
    #: caches — the same contract as the fused stamps.
    bucket_merge: str = "off"
    #: "off" | "auto" | "on": phase-0 parallel AOT precompilation of
    #: bucket kernels (utils.compile). When active, phase 0 scans every
    #: bucket's resume cache first, then submits one
    #: ``jit(...).lower(shapes).compile()`` per bucket that will
    #: actually dispatch to a small thread pool — XLA releases the GIL
    #: while compiling, so bucket kernels compile concurrently with
    #: each other and with the dispatch loop instead of serially at the
    #: head of each bucket. The executable is the same HLO the lazy jit
    #: would build, called at the exact dispatch shapes, so results are
    #: bit-identical to "off" (any shape drift falls back to the jit
    #: path). "auto" enables it only on hosts with >= 2 CPUs: with a
    #: single core the overlap has nowhere to run and the pool
    #: interleaving makes the grid ~8% SLOWER (measured,
    #: benchmarks/results/r06_grid_precompile_cpu.json); "on" forces it
    #: regardless (tests, A/B benchmarks). Single-device ``bucketed``
    #: backend only; fused-Pallas buckets are skipped (their compile is
    #: the Mosaic probe itself).
    precompile: str = "auto"
    out_dir: str | None = None
    resume: bool = True

    def design_points(self) -> pd.DataFrame:
        """expand.grid(n, rho, eps_idx) with n fastest — the reference's
        row order (vert-cor.R:507-511)."""
        rows = []
        i = 0
        for eps_idx, (e1, e2) in enumerate(self.eps_pairs):
            for r in self.rho_grid:
                for n in self.n_grid:
                    rows.append({"i": i, "n": n, "rho": r,
                                 "eps1": e1, "eps2": e2, "eps_idx": eps_idx})
                    i += 1
        # reference order: n varies fastest, then rho, then eps
        return pd.DataFrame(rows)

    def grid_family(self) -> str:
        """Geometry-cache family tag for this grid's estimator pair
        (``utils.geometry`` cache key axis)."""
        return "grid-subg" if self.use_subg else "grid-sign"

    def _resolve_chunk(self, row) -> int:
        if self.geometry != "auto":
            return self.chunk_size
        from dpcorr.utils import geometry as geometry_mod

        import jax

        plat = jax.devices()[0].platform
        geo = geometry_mod.lookup(
            self.grid_family(), int(row["n"]),
            device_kind="tpu" if plat in ("tpu", "axon") else plat,
            eps_pairs=[(float(row["eps1"]), float(row["eps2"]))])
        return geo.chunk_size if geo is not None else self.chunk_size

    def sim_config(self, row) -> SimConfig:
        return SimConfig(
            n=int(row["n"]), rho=float(row["rho"]),
            eps1=float(row["eps1"]), eps2=float(row["eps2"]),
            b=self.b, alpha=self.alpha, dgp=self.dgp, dgp_args=self.dgp_args,
            use_subg=self.use_subg, ci_mode=self.ci_mode,
            normalise=self.normalise, mixquant_mode=self.mixquant_mode,
            seed=self.seed, chunk_size=self._resolve_chunk(row),
        )


@dataclasses.dataclass
class GridResult:
    detail_all: pd.DataFrame
    summ_all: pd.DataFrame
    timings: pd.DataFrame


def _design_path(out_dir: Path, i: int) -> Path:
    return out_dir / f"design_{i:05d}.npz"


def _stamp(cfg: SimConfig) -> str:
    """Cache-validity stamp: the exact SimConfig plus the process PRNG
    implementation — rbg- and threefry-generated results are different
    numbers and a resume must never mix them.

    mc-mode real-variant runs additionally stamp the mixquant draw count:
    ``ci_int_subg``'s default moved 1000 → 2000 for ``variant="real"``
    (the reference's real-data-sims.R:161-164 count), and a resume must
    not mix pre-move cached points with post-move fresh ones.

    The chunk axis is canonicalized (``chunk_size=0``) for every width
    ≥ 2: all such widths produce bitwise-identical results (measured r08,
    ``utils.geometry.CHUNK_FLOOR``), so a geometry retune between runs
    must not invalidate caches it cannot have changed. Width 1 lowers
    differently — different bits — and keeps its literal stamp."""
    if cfg.chunk_size >= 2:
        cfg = dataclasses.replace(cfg, chunk_size=0)
    stamp = f"{cfg!r}|prng={rng.impl_tag()}"
    if cfg.mixquant_mode == "mc" and getattr(cfg, "subg_variant",
                                             "grid") == "real":
        stamp += "|mixquant_nsim=2000"
    return stamp


def _run_point(gcfg: GridConfig, cfg: SimConfig, key, mesh):
    if gcfg.backend == "sharded":
        from dpcorr.parallel import run_detail_sharded

        return run_detail_sharded(cfg, key=key, mesh=mesh)
    if gcfg.backend != "local":
        raise ValueError(f"unknown backend {gcfg.backend!r}")
    return sim_mod.run_sim_one(cfg, key=key)


def _load_cached(path: Path | None, resume: bool, stamp: str):
    if path is not None and resume and path.exists():
        loaded = dict(np.load(path))
        if str(loaded.get("config_stamp")) == stamp:
            return {f: loaded[f] for f in sim_mod.DETAIL_FIELDS}
    return None


def validate_fused(fused: str, backend: str) -> None:
    """Shared fail-fast for the fused knob (run_grid and the R bridge):
    a typo'd value or a silently-never-fusing backend must raise before
    any work is dispatched."""
    if fused == "all":
        raise ValueError(
            "fused='all' (the perf-neutral fused subG pair) was retired "
            "in r05 — measured 0.98x XLA, r02_grid_fused_subg_tpu.json; "
            "use 'auto' (the measured-faster sign kernel) or 'off'")
    if fused not in ("off", "auto"):
        raise ValueError(
            f"fused must be 'off' or 'auto', got {fused!r}")
    if fused != "off" and backend != "bucketed":
        raise ValueError(
            f"fused={fused!r} requires backend='bucketed', got {backend!r}")


def validate_bucket_merge(bucket_merge: str, backend: str,
                          use_subg: bool, eps_pairs) -> None:
    """Fail-fast for the ε-merge knob (GridConfig.bucket_merge): the
    merged kernel exists only for the subG families on the single-device
    bucketed backend, and its named-sender contract needs ε₁ ≥ ε₂ on
    every pair. Value-based signature (like :func:`validate_fused`) so
    the R bridge — which builds its design from external rows — shares
    the one implementation."""
    if bucket_merge not in ("off", "eps"):
        raise ValueError(f"bucket_merge must be 'off' or 'eps', "
                         f"got {bucket_merge!r}")
    if bucket_merge == "off":
        return
    if backend != "bucketed":
        raise ValueError(f"bucket_merge={bucket_merge!r} requires "
                         f"backend='bucketed', got {backend!r}")
    if not use_subg:
        raise ValueError("bucket_merge='eps' is subG-only: the sign "
                         "estimators have no dynamic-geometry variant")
    bad = [(e1, e2) for e1, e2 in eps_pairs if e1 < e2]
    if bad:
        raise ValueError(
            "bucket_merge='eps' names the sender as the ε₁ side, so every "
            f"pair needs ε₁ ≥ ε₂; violating pairs: {bad} (swap the "
            "columns, or use bucket_merge='off')")
    # merged buckets trace ε (batch_geometry_dyn's f32 rule) where the
    # unmerged path uses the static f64 rule — surface, once, any pair
    # sitting in the ~1e-6 band where the two choose adjacent m
    from dpcorr.models.estimators.common import warn_f32_geometry_band_once

    warn_f32_geometry_band_once(eps_pairs, where="validate_bucket_merge")


def _fused_bucket_ok(gcfg: GridConfig, cfg: SimConfig) -> str | None:
    """Which fused Pallas kernel (if any) covers this (n, ε) bucket:
    ``"sign"`` (Gaussian sign-estimator pair, ops/pallas_ni.py) or None.
    Gated on: opt-in (``fused="auto"`` — selects only the
    measured-faster sign kernel; GridConfig.fused has the numbers and
    the r05 retirement note for the former subG kernel), single-device
    bucketed backend, real TPU, det mixquant (the closed-form quantile —
    the kernel emits scalars, the per-CI MC variant draws from the
    key-tree the kernel doesn't carry), and the kernel's (m ≤ 128,
    k ≥ 2) batch geometry."""
    validate_fused(gcfg.fused, "bucketed")  # pure value check here
    if gcfg.fused == "off" or gcfg.backend != "bucketed":
        return None
    if cfg.stream_n_chunk or cfg.mixquant_mode != "det":
        return None
    if cfg.use_subg or cfg.dgp != "gaussian":
        # subG buckets always run the XLA kernel since the r05
        # retirement (GridConfig.fused)
        return None
    kind = "sign"
    import jax

    # "Pallas-capable TPU" in practice means two platform strings: "tpu"
    # (a directly-attached chip) and "axon" (the same chip behind the
    # remote-tunnel transport this image uses — jax.devices() reports the
    # tunnel's platform name, but lowering/Mosaic behave as on "tpu"; the
    # fused-kernel hardware results in GridConfig.fused were measured
    # through it). Anything else (cpu, gpu) has no Mosaic backend.
    if jax.devices()[0].platform not in ("tpu", "axon"):
        return None
    from dpcorr.ops.pallas_ni import use_ni_sign_pallas

    return kind if use_ni_sign_pallas(cfg.n, cfg.eps1, cfg.eps2) else None


def validate_precompile(precompile: str) -> None:
    """Fail-fast for the precompile knob (value check only: unlike
    fused/bucket_merge the knob is backend-agnostic — non-bucketed
    backends simply never precompile)."""
    if precompile not in ("off", "auto", "on"):
        raise ValueError(
            f"precompile must be 'off', 'auto' or 'on', got {precompile!r}")


def validate_geometry(geometry: str) -> None:
    """Fail-fast for the geometry knob (value check only; like
    precompile it is backend-agnostic — every backend builds SimConfigs
    through ``sim_config``)."""
    if geometry not in ("pinned", "auto"):
        raise ValueError(
            f"geometry must be 'pinned' or 'auto', got {geometry!r}")


def _precompile_bucket(executor, cfg: SimConfig, m: int, merged: bool,
                       k_pad, parent):
    """Phase-0 pool worker: build one bucket's flat kernel as a plan
    unit at its exact dispatch shapes (``executor.prepare`` →
    utils.compile — XLA releases the GIL, so workers compile
    concurrently with each other and with the main thread's dispatch
    loop). Returns the :class:`~dpcorr.plan.Prepared`; when AOT fell
    back, dispatching it takes the ordinary lazily-jitted path.

    ``parent`` pins the ``kernel.compile`` span under the caller's
    ``grid.run`` span: the pool thread's implicit span stack is empty.
    """
    import jax
    import jax.numpy as jnp

    keys_aval = rng.key_aval(m)
    f32 = jax.ShapeDtypeStruct((m,), jnp.float32)
    if merged:
        cfg_noeps = dataclasses.replace(cfg, rho=0.0, seed=0,
                                        eps1=1.0, eps2=1.0)
        return executor.prepare(
            ("grid.flat_eps", cfg_noeps, m, k_pad),
            sim_mod._run_detail_flat_eps,
            (cfg_noeps, keys_aval, f32, f32, f32, k_pad),
            fallback=lambda keys, rhos, e1, e2: sim_mod._run_detail_flat_eps(
                cfg_noeps, keys, rhos, e1, e2, k_pad),
            signature={"kernel": "_run_detail_flat_eps", "n": cfg.n,
                       "m": m, "k_pad": k_pad},
            parent=parent)
    cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
    return executor.prepare(
        ("grid.flat", cfg_norho, m),
        sim_mod._run_detail_flat, (cfg_norho, keys_aval, f32),
        fallback=lambda keys, rhos: sim_mod._run_detail_flat(
            cfg_norho, keys, rhos),
        signature={"kernel": "_run_detail_flat", "n": cfg.n,
                   "eps1": cfg.eps1, "eps2": cfg.eps2, "m": m},
        parent=parent)


def _raise_if_failed(failures, n_points: int):
    """Aggregate fail-loud raise shared by all backends (SURVEY.md §5)."""
    if failures:
        raise RuntimeError(
            f"{len(failures)}/{n_points} design points failed; first: "
            f"{failures[0][0]} -> {failures[0][1]!r}")


def _run_grid_bucketed(gcfg: GridConfig, design: pd.DataFrame, master,
                       out_dir: Path | None, mesh=None):
    """Grid-axis vectorization: all design points of one (n, ε) compile
    bucket run as a single kernel invocation over flattened
    (point × replication) pairs — ρ is traced (sim._run_detail_flat), so the
    ε-grid's 8-point ρ sweeps cost one dispatch each instead of eight.

    Per-point keys still fold the design index (``design_key(master, i)``),
    so results are bit-identical to the local backend point by point, and
    the per-point ``.npz`` resume cache is shared with it.
    """
    import dataclasses

    import jax.numpy as jnp

    from dpcorr import plan as plan_mod

    details, timings, failures = {}, [], []
    tr = obs_trace.tracer()

    # one plan executor for the whole grid: the sharded backend runs on
    # a mesh placement (parallel.mesh), everything else on the local
    # single-device placement — bit-identical to the pre-plan dispatch
    ex = plan_mod.Executor(
        placement="mesh" if gcfg.backend == "bucketed-sharded" else "local",
        mesh=mesh)

    merged = gcfg.bucket_merge == "eps"

    def merged_k_pad(n: int, bucket_rows) -> int:
        """ONE derivation for both the kernel's static pad and the cache
        stamp — computed from the BUCKET's full ε set (every design row
        at this n, never a dispatch's cache-miss subset: the compiled
        kernel must be reusable across partial-resume dispatches, and
        the stamp must name the layout the kernel actually used).
        Per-bucket rather than config-wide so a ragged external design
        (the R bridge's seam) doesn't pay padding for ε-pairs this n
        never runs."""
        from dpcorr.models.estimators.common import k_pad_for

        return k_pad_for(n, [float(r.eps1) * float(r.eps2)
                             for r in bucket_rows])

    def xla_dispatch(cfg, to_run, k_pad=None, prepared=None):
        """The XLA bucket dispatch — single source for phase 1 and the
        fetch-time fused fallback, so both stay bit-identical to
        fused="off" by construction. In ε-merged mode ε rides as a
        per-element traced operand next to ρ (one compiled kernel per
        n; GridConfig.bucket_merge). ``prepared`` is the phase-0 plan
        unit for this bucket, if any — same HLO as the jit path; a
        shape drift degrades inside the unit to the lazy jit call it
        would have made anyway. Without one, a lazy unit wraps the jit
        call so every dispatch flows through the executor."""
        keys = jnp.concatenate([
            rng.rep_keys(rng.design_key(master, int(r.i)), gcfg.b)
            for r in to_run])
        rhos = jnp.repeat(jnp.asarray([r.rho for r in to_run], jnp.float32),
                          gcfg.b)
        if merged:
            eps1s = jnp.repeat(jnp.asarray([r.eps1 for r in to_run],
                                           jnp.float32), gcfg.b)
            eps2s = jnp.repeat(jnp.asarray([r.eps2 for r in to_run],
                                           jnp.float32), gcfg.b)
            cfg_noeps = dataclasses.replace(cfg, rho=0.0, seed=0,
                                            eps1=1.0, eps2=1.0)
            unit = prepared if prepared is not None else ex.lazy_unit(
                lambda k, r, e1, e2: sim_mod._run_detail_flat_eps(
                    cfg_noeps, k, r, e1, e2, k_pad))
            return ex.dispatch(unit, (keys, rhos, eps1s, eps2s))
        cfg_norho = dataclasses.replace(cfg, rho=0.0, seed=0)
        if gcfg.backend == "bucketed-sharded":
            # the sharded twin pads to a mesh multiple before its own
            # mesh-aware preshard, so it keeps owning both steps
            from dpcorr.parallel import run_detail_flat_sharded

            return run_detail_flat_sharded(cfg_norho, keys, rhos,
                                           mesh=ex.placement.mesh)
        unit = prepared if prepared is not None else ex.lazy_unit(
            lambda k, r: sim_mod._run_detail_flat(cfg_norho, k, r))
        return ex.dispatch(unit, (keys, rhos))

    # Phase 0 — scan every bucket's resume cache up front and, when
    # precompiling (GridConfig.precompile), submit each to-run bucket's
    # AOT compile to a small thread pool. XLA releases the GIL while
    # compiling, so by the time the dispatch loop reaches bucket j its
    # kernel has been building since phase 0 — concurrently with the
    # other buckets' compiles and with earlier buckets' key construction
    # and launches.
    # "auto" backs off on single-core hosts: the overlap needs a second
    # core to run on; without one the pool only adds scheduling overhead
    # (~8% measured — r06_grid_precompile_cpu.json). "on" forces it.
    precompiling = (gcfg.backend == "bucketed"
                    and (gcfg.precompile == "on"
                         or (gcfg.precompile == "auto"
                             and (os.cpu_count() or 1) >= 2)))
    pool = None
    parent_sp = obs_trace.current_span()
    t_scan0 = time.perf_counter()
    buckets = []
    bucket_keys = ["n"] if merged else ["n", "eps1", "eps2"]
    for _, grp in design.groupby(bucket_keys, sort=False):
        rows = list(grp.itertuples(index=False))
        t0 = time.perf_counter()
        # Same fail-loud-per-point semantics as the local backend: a broken
        # bucket is recorded and the remaining buckets still run; one
        # aggregated RuntimeError is raised by run_grid at the end.
        try:
            cfg = gcfg.sim_config(rows[0]._asdict())
            # an ε-merged bucket never fuses: subG is the only merged
            # family and the fused subG kernel is retired (GridConfig)
            fused = None if merged else _fused_bucket_ok(gcfg, cfg)
            paths = {int(r.i): (_design_path(out_dir, int(r.i))
                                if out_dir else None)
                     for r in rows}

            # cfg/rows/paths bound as defaults: the closures ride the
            # bucket records into phases 1 and 2, and the loop variables
            # they would otherwise capture are function-scoped — by then
            # they hold the LAST bucket's values, not this one's
            def mk_stamps(suffix: str, cfg=cfg, rows=rows):
                # ε replaced per row: in merged mode the bucket cfg
                # carries only the FIRST row's ε (a no-op otherwise)
                return {int(r.i): _stamp(dataclasses.replace(
                            cfg, rho=float(r.rho), eps1=float(r.eps1),
                            eps2=float(r.eps2))) + suffix
                        for r in rows}

            def scan_cache(candidates, stamps, paths=paths):
                to_run = []
                for r in candidates:
                    i = int(r.i)
                    cached = _load_cached(paths[i], gcfg.resume, stamps[i])
                    if cached is not None:
                        details[i] = cached
                    else:
                        to_run.append(r)
                return to_run

            if merged:
                # k_pad is part of the dyn stream layout — stamp it so
                # caches from grids with different ε sets never mix
                bucket_k_pad = merged_k_pad(cfg.n, rows)
                merge_tag = "|geom=dyn,kpad=%d" % bucket_k_pad
            else:
                bucket_k_pad = None
            stamps = mk_stamps("|fused=pallas" if fused
                               else merge_tag if merged else "")
            to_run = scan_cache(rows, stamps)
        except Exception as e:
            log.error("bucket (n=%d eps=(%.2f,%.2f), %d points) failed "
                      "at scan: %s",
                      rows[0].n, rows[0].eps1, rows[0].eps2, len(rows), e)
            failures.extend((int(r.i), e) for r in rows
                            if int(r.i) not in details)
            continue
        fut = None
        if precompiling and to_run and not fused:
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = ThreadPoolExecutor(
                    max_workers=min(8, max(2, os.cpu_count() or 1)),
                    thread_name_prefix="dpcorr-grid-compile")
                if ex.observer is None:
                    ex.observer = compile_mod.CompileObserver(tracer=tr)
            fut = pool.submit(_precompile_bucket, ex, cfg,
                              len(to_run) * gcfg.b, merged,
                              bucket_k_pad, parent_sp)
        buckets.append((rows, to_run, stamps, paths, fused, cfg,
                        mk_stamps, scan_cache, bucket_k_pad, fut,
                        time.perf_counter() - t0))

    # Phase 1 — dispatch every bucket without fetching: jit dispatch is
    # asynchronous, so bucket j executes on-device while bucket j+1 is
    # still compiling on the host (dispatch-ahead, VERDICT r1 weak #8);
    # under precompile the compile itself already moved onto the phase-0
    # pool and the dispatch just picks up the executable. Outputs are a
    # few KB of metrics per point, so keeping all buckets in flight
    # costs almost no HBM.
    prof_mod.note_phase("grid.scan", time.perf_counter() - t_scan0,
                        buckets=len(buckets))
    t_disp0 = time.perf_counter()
    pending = []
    try:
        for (rows, to_run, stamps, paths, fused, cfg, mk_stamps,
             scan_cache, bucket_k_pad, fut, scan_s) in buckets:
            t0 = time.perf_counter()
            # one span per bucket compile+launch (parents under grid.run
            # via the thread-local stack; a no-op when tracing is off)
            dsp = tr.start_span("grid.dispatch", n=int(rows[0].n),
                                points=len(rows))
            try:
                raw = None
                if to_run and fused:
                    try:
                        seeds = jnp.concatenate([
                            rng.pallas_seeds(
                                rng.design_key(master, int(r.i)), gcfg.b)
                            for r in to_run])
                        rhos = jnp.repeat(
                            jnp.asarray([r.rho for r in to_run],
                                        jnp.float32),
                            gcfg.b)
                        from dpcorr.ops import pallas_ni

                        args = dict(cfg.dgp_args)
                        raw = pallas_ni.sim_detail_pallas(
                            seeds, rhos, cfg.n, cfg.eps1, cfg.eps2,
                            mu=args.get("mu", (0.0, 0.0)),
                            sigma=args.get("sigma", (1.0, 1.0)),
                            alpha=cfg.alpha, ci_mode=cfg.ci_mode,
                            normalise=cfg.normalise, interpret=False)
                    except Exception as e:
                        # fused is best-effort: a lowering/compile failure
                        # on this bucket's shape degrades to the XLA
                        # kernel (the cache is re-scanned under the XLA
                        # stamps)
                        log.warning(
                            "fused kernel unavailable for bucket (n=%d "
                            "eps=(%.2f,%.2f)): %s -- falling back to XLA",
                            cfg.n, cfg.eps1, cfg.eps2, e)
                        fused, raw = None, None
                        stamps = mk_stamps("")
                        to_run = scan_cache(to_run, stamps)
                if to_run and raw is None:
                    prepared = None
                    if fut is not None:
                        try:
                            prepared = fut.result()
                        except Exception as e:
                            # precompile is an optimization, never a gate:
                            # a worker crash degrades to the inline jit
                            log.warning("bucket precompile (n=%d) failed:"
                                        " %s -- inline jit", cfg.n, e)
                    raw = xla_dispatch(cfg, to_run, k_pad=bucket_k_pad,
                                       prepared=prepared)
            except Exception as e:
                log.error("bucket (n=%d eps=(%.2f,%.2f), %d points) "
                          "failed at dispatch: %s",
                          rows[0].n, rows[0].eps1, rows[0].eps2,
                          len(rows), e)
                failures.extend((int(r.i), e) for r in rows
                                if int(r.i) not in details)
                dsp.set(error=type(e).__name__)
                continue
            else:
                dsp.set(points_run=len(to_run), fused=bool(fused),
                        precompiled=fut is not None)
            finally:
                dsp.end()
            pending.append((rows, to_run, raw, stamps, paths, fused, cfg,
                            mk_stamps, scan_s + time.perf_counter() - t0,
                            fut is not None))
    finally:
        if pool is not None:
            # every submitted future was consumed above; shutdown only
            # reaps worker threads (cancel covers an exceptional exit)
            pool.shutdown(wait=False, cancel_futures=True)

    prof_mod.note_phase("grid.dispatch", time.perf_counter() - t_disp0,
                        buckets=len(pending))
    # Phase 2 — fetch in dispatch order; device-side failures surface here.
    # Per-bucket wall times overlap under dispatch-ahead (a later bucket's
    # fetch_s is near zero because its device work ran during earlier
    # fetches), so throughput is reported only at grid level:
    # ``grid_reps_per_sec``, total reps over the whole two-phase wall clock.
    t_fetch0 = time.perf_counter()
    total_ran = 0
    for (rows, to_run, raw, stamps, paths, fused, cfg, mk_stamps,
         dispatch_s, precompiled) in pending:
        t0 = time.perf_counter()
        fsp = tr.start_span("grid.fetch", n=int(rows[0].n),
                            points=len(rows), points_run=len(to_run))
        try:
            if to_run:
                try:
                    # the plan's one sanctioned host sync (counted into
                    # obs.transfer fetches), then the numpy views
                    raw = ex.fetch(raw)
                    raw = [np.asarray(a)  # dpcorr-lint: ignore[sync-in-loop]
                           for a in raw]
                except Exception as e:
                    if not fused:
                        raise
                    # fused stays best-effort at the fetch barrier too: a
                    # kernel error that only surfaces at np.asarray (device
                    # execution, not lowering) degrades this bucket to the
                    # XLA kernel, mirroring the dispatch-time fallback —
                    # including the re-scan under XLA stamps
                    log.warning(
                        "fused bucket (n=%d eps=(%.2f,%.2f)) failed at "
                        "fetch: %s -- retrying via XLA", cfg.n, cfg.eps1,
                        cfg.eps2, e)
                    fused = None
                    # the dispatch-phase stamp derivation, suffix-free —
                    # NOT an inline re-derivation, which would drop the
                    # per-row ε replacement merged buckets rely on
                    stamps = mk_stamps("")
                    still = []
                    for r in to_run:
                        i = int(r.i)
                        cached = _load_cached(paths[i], gcfg.resume,
                                              stamps[i])
                        if cached is not None:
                            details[i] = cached
                        else:
                            still.append(r)
                    to_run = still
                    # the degraded bucket's own fetch boundary
                    # dpcorr-lint: ignore[sync-in-loop]
                    raw = ([np.asarray(a)
                            for a in ex.fetch(xla_dispatch(cfg, to_run))]
                           if to_run else None)
                for j, r in enumerate(to_run):
                    i = int(r.i)
                    sl = slice(j * gcfg.b, (j + 1) * gcfg.b)
                    detail = {f: a[sl]
                              for f, a in zip(sim_mod.DETAIL_FIELDS, raw,
                                              strict=True)}
                    details[i] = detail
                    if paths[i] is not None:
                        np.savez(paths[i], config_stamp=stamps[i], **detail)
        except Exception as e:
            log.error("bucket (n=%d eps=(%.2f,%.2f), %d points) failed "
                      "at fetch: %s",
                      rows[0].n, rows[0].eps1, rows[0].eps2, len(rows), e)
            failures.extend((int(r.i), e) for r in rows
                            if int(r.i) not in details)
            fsp.set(error=type(e).__name__)
            continue
        finally:
            fsp.end()
        fetch_s = time.perf_counter() - t0
        ran = len(to_run)
        total_ran += ran
        timings.append({
            "n": rows[0].n,
            # a merged bucket spans every ε-pair at this n — per-pair
            # labels would be misleading, so they go NaN and the count
            # says what was merged
            "eps1": np.nan if merged else rows[0].eps1,
            "eps2": np.nan if merged else rows[0].eps2,
            "merged_eps_pairs": (len({(r.eps1, r.eps2) for r in rows})
                                 if merged else 1),
            "points": len(rows), "points_run": ran, "fused": fused,
            "precompiled": precompiled,
            "seconds": dispatch_s + fetch_s,
            "dispatch_s": dispatch_s, "fetch_s": fetch_s,
        })
    prof_mod.note_phase("grid.fetch", time.perf_counter() - t_fetch0,
                        points_run=total_ran)
    wall = (time.perf_counter() - t_fetch0) + sum(
        t[8] for t in pending)  # fetch phase + all dispatch times
    grid_rps = np.nan if not total_ran else total_ran * gcfg.b / wall
    for t in timings:
        t["grid_reps_per_sec"] = grid_rps
    return details, timings, failures


def _assemble_details(design: pd.DataFrame, by_i: dict, b: int) -> pd.DataFrame:
    """Metadata-join per-point detail dicts into the reference's stacked
    replicate frame (vert-cor.R:557-568), in design-row order."""
    details = []
    for row in design.itertuples(index=False):
        frame = pd.DataFrame(by_i[int(row.i)])
        frame.insert(0, "repl", np.arange(1, b + 1))
        frame["n"] = row.n
        frame["rho_true"] = row.rho
        frame["eps1"] = row.eps1
        frame["eps2"] = row.eps2
        details.append(frame)
    return pd.concat(details, ignore_index=True)


def run_grid(gcfg: GridConfig, mesh=None) -> GridResult:
    """Run the whole grid; returns replicate-level and grouped summaries.

    Per-task keys fold the design index into the master key — the moral
    equivalent of the reference's ``seed = 1e6 + i`` (vert-cor.R:531).
    """
    validate_fused(gcfg.fused, gcfg.backend)
    validate_bucket_merge(gcfg.bucket_merge, gcfg.backend, gcfg.use_subg,
                          gcfg.eps_pairs)
    validate_precompile(gcfg.precompile)
    validate_geometry(gcfg.geometry)
    design = gcfg.design_points()
    master = rng.master_key(gcfg.seed)
    out_dir = Path(gcfg.out_dir) if gcfg.out_dir else None
    if out_dir:
        out_dir.mkdir(parents=True, exist_ok=True)

    # the root span of one grid execution: grid.dispatch / grid.fetch /
    # grid.point children parent under it via the thread-local stack
    # (dpcorr.obs.trace; a no-op when no tracer is configured)
    tr = obs_trace.tracer()
    with tr.span("grid.run", backend=gcfg.backend, points=len(design),
                 b=gcfg.b):
        if gcfg.backend in ("bucketed", "bucketed-sharded"):
            by_i, timings, failures = _run_grid_bucketed(
                gcfg, design, master, out_dir, mesh=mesh)
            _raise_if_failed(failures, len(design))
            detail_all = _assemble_details(design, by_i, gcfg.b)
            summ_all = summarize_grid(detail_all)
            if out_dir:
                _persist_tables(out_dir, detail_all, summ_all)
            return GridResult(detail_all, summ_all, pd.DataFrame(timings))

        details, timings, failures = [], [], []
        for row in design.itertuples(index=False):
            i = int(row.i)
            path = _design_path(out_dir, i) if out_dir else None
            t0 = time.perf_counter()
            psp = tr.start_span("grid.point", i=i, n=int(row.n),
                                rho=float(row.rho))
            try:
                cfg = gcfg.sim_config(row._asdict())
                # Cache entries are valid only for the exact SimConfig
                # (and PRNG impl) that produced them; mismatch = miss.
                stamp = _stamp(cfg)
                detail = _load_cached(path, gcfg.resume, stamp)
                cached = detail is not None
                if not cached:
                    res = _run_point(gcfg, cfg, rng.design_key(master, i),
                                     mesh)
                    # per-point fetch boundary (local backend
                    # persists each point before the next dispatches)
                    # dpcorr-lint: ignore[sync-in-loop]
                    detail = {k: np.asarray(v)
                              for k, v in res.detail.items()}
                    if path is not None:
                        np.savez(path, config_stamp=stamp, **detail)
            except Exception as e:  # fail loudly per point (SURVEY.md §5)
                log.error("design point %d (n=%d rho=%.2f eps=(%.2f,%.2f))"
                          " failed: %s",
                          i, row.n, row.rho, row.eps1, row.eps2, e)
                failures.append((i, e))
                psp.set(error=type(e).__name__)
                continue
            else:
                psp.set(cached=cached)
            finally:
                psp.end()
            dt = time.perf_counter() - t0
            timings.append({"i": i, "n": row.n, "rho": row.rho,
                            "eps1": row.eps1, "eps2": row.eps2,
                            "seconds": dt, "cached": cached,
                            "reps_per_sec": (np.nan if cached
                                             else gcfg.b / dt)})

            frame = pd.DataFrame(detail)
            frame.insert(0, "repl", np.arange(1, gcfg.b + 1))
            # metadata join (vert-cor.R:557-565)
            frame["n"] = row.n
            frame["rho_true"] = row.rho
            frame["eps1"] = row.eps1
            frame["eps2"] = row.eps2
            details.append(frame)

        _raise_if_failed(failures, len(design))

        detail_all = pd.concat(details, ignore_index=True)
        summ_all = summarize_grid(detail_all)
        if out_dir:
            _persist_tables(out_dir, detail_all, summ_all)
        return GridResult(detail_all, summ_all, pd.DataFrame(timings))


def _persist_tables(out_dir: Path, detail_all: pd.DataFrame,
                    summ_all: pd.DataFrame) -> None:
    """Persist the merged tables: parquet for the Python world, plus the
    reference's own artifact shape — ``detail_all.rds``, a data.frame R's
    ``readRDS`` consumes directly (``saveRDS(detail_all,
    "sim_detail_all.rds")``, vert-cor.R:569) — so R-side consumers need
    neither reticulate nor parquet bindings."""
    from dpcorr.io.rds_write import write_rds_frame

    detail_all.to_parquet(out_dir / "detail_all.parquet")
    summ_all.to_parquet(out_dir / "summ_all.parquet")
    write_rds_frame(str(out_dir / "detail_all.rds"), detail_all)


def summarize_grid(detail_all: pd.DataFrame) -> pd.DataFrame:
    """Grouped NI/INT summaries by (n, rho_true, eps1, eps2)
    (vert-cor.R:575-597): mse, bias, coverage, ci_len."""
    keys = ["n", "rho_true", "eps1", "eps2"]
    outs = []
    for meth in ("NI", "INT"):
        p = meth.lower()
        g = detail_all.groupby(keys, sort=False)
        summ = pd.DataFrame({
            "mse": g[f"{p}_se2"].mean(),
            "bias": g[f"{p}_hat"].mean() - g["rho_true"].mean(),
            "coverage": g[f"{p}_cover"].mean(),
            "ci_len": g[f"{p}_ci_len"].mean(),
        }).reset_index()
        summ["method"] = meth
        outs.append(summ)
    return pd.concat(outs, ignore_index=True)
