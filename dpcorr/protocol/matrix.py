"""Federation planning: the k×k correlation matrix as pair sessions.

The two-party runtime (protocol.party) answers one cell — the DP
correlation between one X column and one Y column. A deployment holds
many columns across many parties and wants the full k×k matrix. This
module is the *pure scheduling* half of that federation (the runtime
half is protocol.federation): a :class:`FederationPlan` takes N parties
× their column labels and compiles every matrix cell into either a
local computation (both columns at one party) or a round on a **pair
link** — one multiplexed channel per party pair carrying all of that
pair's cells as tagged sub-sessions.

Three properties are decided here, statically, so the runtime never
has to coordinate:

- **Roles.** Columns are globally ordered (party order, then label
  order); the cell (i, j), i < j, runs column i as the protocol's
  ``"x"`` role and column j as ``"y"``. Every column of a federation
  shares one ε, so ``split_roles`` resolves to the x side for every
  family — the lower-indexed party is always the releaser on a link,
  and a link needs exactly one release round-trip per batch of cells.

- **Release reuse.** A column's DP release is a function of its key
  label and values alone (utils.rng.column_root), so every pair that
  needs it reuses the *same bytes* — re-noising a column per pair would
  be both an ε leak and a correlation leak (protocol.scan's cross-pair
  gate). The plan assigns each release **artifact** — ``("x", label)``
  for the wire release, ``("y", label)`` for the finisher's in-finish
  own release — to the single venue that charges it: the first cell
  (in cell order) that uses it. Everything downstream reuses it free.
  Total spend is therefore the column-release optimum
  :meth:`optimal_eps` — for k columns under one ε, ``2·f·ε·(k−1)``
  against the naive per-cell ``f·ε·k·(k−1)`` — strictly less for
  k ≥ 3.

- **Determinism.** Schedules, rounds, artifact assignments and charge
  ids are all pure functions of the public plan, so a party killed
  mid-matrix re-derives the identical schedule on restart and its
  per-link journals resume exactly-once (protocol.journal).

Deliberately jax-free: ``dpcorr federation plan`` and the transcript
scanner run where the estimators can't. The release factor is
re-derived here (like scan.wire_schema) and pinned against
``serve.ledger.release_factor`` by tests/test_federation.py.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from dpcorr.protocol.messages import canonical_encode


def _factor(family: str, normalise: bool) -> float:
    """Jax-free mirror of ``serve.ledger.release_factor`` (the private
    centering double-spend for sign families; pinned by test)."""
    return 2.0 if (family in ("ni_sign", "int_sign") and normalise) else 1.0


def _norm_parties(parties) -> tuple[tuple[str, tuple[str, ...]], ...]:
    if isinstance(parties, dict):
        items = list(parties.items())
    else:
        items = [(name, labels) for name, labels in parties]
    return tuple((str(name), tuple(str(c) for c in labels))
                 for name, labels in items)


@dataclass(frozen=True)
class FederationPlan:
    """The public design point of one k×k federation — every party must
    hold the byte-identical plan (the link handshake pins its hash,
    exactly like the two-party spec hash)."""

    family: str
    n: int
    eps: float
    parties: tuple  # ((party, (label, ...)), ...) — order is public
    alpha: float = 0.05
    normalise: bool = True
    seed: int = 2025
    noise_mode: str = "replay"
    max_cells_per_round: int = 0  # 0: all of a link's cells in one round
    fed: str = ""

    def __post_init__(self):
        object.__setattr__(self, "parties", _norm_parties(self.parties))
        names = [p for p, _ in self.parties]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate party names in {names}")
        labels = [c for _, cols in self.parties for c in cols]
        if len(set(labels)) != len(labels):
            raise ValueError(f"column labels must be globally unique, "
                             f"got {labels}")
        if len(labels) < 2:
            raise ValueError("a federation needs at least 2 columns")
        if not float(self.eps) > 0.0:
            raise ValueError("eps must be positive")
        if self.fed == "":
            object.__setattr__(self, "fed",
                               f"fed-{self.fed_hash()[:12]}")

    # ------------------------------------------------------- identity ----
    def to_public(self) -> dict:
        return {"family": self.family, "n": int(self.n),
                "eps": float(self.eps),
                "parties": [[p, list(cols)] for p, cols in self.parties],
                "alpha": float(self.alpha),
                "normalise": bool(self.normalise),
                "seed": int(self.seed), "noise_mode": self.noise_mode,
                "max_cells_per_round": int(self.max_cells_per_round)}

    def fed_hash(self) -> str:
        return hashlib.sha256(canonical_encode(self.to_public())).hexdigest()

    def trace_id(self) -> str:
        """Deterministic federation-wide trace ID (ISSUE 13): every
        party derives the same 64-bit hex id from the public plan, so
        all k processes — and a crash-resumed rerun of any of them —
        join ONE trace with zero coordination. Same width as the
        tracer's random ids (``secrets.token_hex(8)``)."""
        return self.fed_hash()[:16]

    @classmethod
    def from_public(cls, pub: dict) -> "FederationPlan":
        return cls(family=pub["family"], n=int(pub["n"]),
                   eps=float(pub["eps"]), parties=pub["parties"],
                   alpha=float(pub.get("alpha", 0.05)),
                   normalise=bool(pub.get("normalise", True)),
                   seed=int(pub.get("seed", 2025)),
                   noise_mode=pub.get("noise_mode", "replay"),
                   max_cells_per_round=int(
                       pub.get("max_cells_per_round", 0)))

    # -------------------------------------------------------- columns ----
    def columns(self) -> tuple[tuple[str, str], ...]:
        """Global column order: (owner, label) per column. The order is
        the role rule — cell (i, j) runs i as "x", j as "y"."""
        return tuple((p, c) for p, cols in self.parties for c in cols)

    @property
    def k(self) -> int:
        return len(self.columns())

    def owner(self, i: int) -> str:
        return self.columns()[i][0]

    def label(self, i: int) -> str:
        return self.columns()[i][1]

    def party_index(self, name: str) -> int:
        for idx, (p, _) in enumerate(self.parties):
            if p == name:
                return idx
        raise ValueError(f"unknown party {name!r}")

    def party_labels(self, name: str) -> tuple[str, ...]:
        return dict(self.parties)[name]

    # ---------------------------------------------------------- cells ----
    def cells(self) -> tuple[tuple[int, int], ...]:
        k = self.k
        return tuple((i, j) for i in range(k) for j in range(i + 1, k))

    def cell_venue(self, i: int, j: int):
        """Where cell (i, j) runs: ``("local", P)`` when one party owns
        both columns, else ``("link", P, Q)`` with P the owner of the
        x column — parties are ordered, so the x-column owner is always
        the link's lower party and the link needs one direction of
        release only."""
        p, q = self.owner(i), self.owner(j)
        if p == q:
            return ("local", p)
        return ("link", p, q)

    def local_cells(self, party: str) -> tuple[tuple[int, int], ...]:
        return tuple((i, j) for i, j in self.cells()
                     if self.cell_venue(i, j) == ("local", party))

    # ---------------------------------------------------------- links ----
    def links(self) -> tuple[tuple[str, str], ...]:
        """Party pairs with at least one cross-party cell, each ordered
        (releaser, finisher) = (lower party, higher party)."""
        seen: list[tuple[str, str]] = []
        for i, j in self.cells():
            v = self.cell_venue(i, j)
            if v[0] == "link" and (v[1], v[2]) not in seen:
                seen.append((v[1], v[2]))
        return tuple(seen)

    def party_links(self, name: str) -> tuple[tuple[str, str], ...]:
        return tuple(lk for lk in self.links() if name in lk)

    def link_session(self, p: str, q: str) -> str:
        return f"{self.fed}-{p}-{q}"

    def link_rounds(self, p: str, q: str) -> tuple[tuple, ...]:
        """The link's cells chunked into rounds (each round: one batched
        release message, one batched result message). With
        ``max_cells_per_round == 0`` the whole link is one round."""
        cells = tuple((i, j) for i, j in self.cells()
                      if self.cell_venue(i, j) == ("link", p, q))
        size = self.max_cells_per_round or len(cells)
        if size <= 0:
            return ()
        return tuple(cells[a:a + size] for a in range(0, len(cells), size))

    def round_x_labels(self, p: str, q: str, r: int) -> tuple[str, ...]:
        """Release artifacts one round's envelope carries, in first-use
        order, each exactly once."""
        out: list[str] = []
        for i, _j in self.link_rounds(p, q)[r]:
            if self.label(i) not in out:
                out.append(self.label(i))
        return tuple(out)

    # ------------------------------------------------------ artifacts ----
    def artifact_venues(self) -> dict:
        """``(side, label) -> venue`` charging that artifact: the venue
        of the first cell (in cell order) that uses it. ``side`` is the
        protocol role the column plays — "x" artifacts are the wire
        release, "y" artifacts the finisher's in-finish own release.
        Pure plan arithmetic, so every party (and every restart)
        derives the identical charge assignment."""
        venues: dict = {}
        for i, j in self.cells():
            v = self.cell_venue(i, j)
            venues.setdefault(("x", self.label(i)), (v, (i, j)))
            venues.setdefault(("y", self.label(j)), (v, (i, j)))
        return {art: v for art, (v, _cell) in venues.items()}

    def _round_of(self, p: str, q: str, cell) -> int:
        for r, cells in enumerate(self.link_rounds(p, q)):
            if cell in cells:
                return r
        raise ValueError(f"cell {cell} not on link {p}-{q}")

    def _charged_labels(self, p: str, q: str, r: int,
                        side: str) -> tuple[str, ...]:
        """Labels whose ``side`` artifact this round's gated message
        pays for (release message for "x", result message for "y")."""
        venues: dict = {}
        for i, j in self.cells():
            v = self.cell_venue(i, j)
            venues.setdefault(("x", self.label(i)), (v, (i, j)))
            venues.setdefault(("y", self.label(j)), (v, (i, j)))
        out = []
        for (s, label), (venue, cell) in venues.items():
            if s != side or venue != ("link", p, q):
                continue
            if self._round_of(p, q, cell) == r:
                out.append(label)
        return tuple(out)

    def round_charges(self, p: str, q: str, r: int) -> dict:
        """The two gated messages of one round: who pays what.
        ``release`` is charged by P (new "x" artifacts), ``result`` by
        Q (new "y" artifacts). Reused artifacts appear in the envelope
        but never here — that is the whole optimization."""
        f = _factor(self.family, self.normalise)
        rel = self._charged_labels(p, q, r, "x")
        res = self._charged_labels(p, q, r, "y")
        return {
            "release": {"labels": rel,
                        "charges": ({p: f * self.eps * len(rel)}
                                    if rel else {})},
            "result": {"labels": res,
                       "charges": ({q: f * self.eps * len(res)}
                                   if res else {})},
        }

    def local_charges(self, party: str) -> dict:
        """Artifacts first used by ``party``'s local cells — charged
        once by the owner under a deterministic id, no wire send."""
        f = _factor(self.family, self.normalise)
        arts = tuple(sorted(
            art for art, venue in self.artifact_venues().items()
            if venue == ("local", party)))
        eps = f * self.eps * len(arts)
        return {"artifacts": arts,
                "charges": ({party: eps} if arts else {}),
                "charge_id": f"{self.fed}:{party}:local"}

    # ------------------------------------------------------ ε arithmetic ----
    def optimal_eps(self) -> float:
        """Total ε of the column-release-reuse schedule: each artifact
        charged exactly once. Under one shared ε and a full matrix this
        is ``2·f·ε·(k−1)``."""
        f = _factor(self.family, self.normalise)
        return f * self.eps * len(self.artifact_venues())

    def naive_eps(self) -> float:
        """What per-cell charging would cost (both roles pay per cell,
        like k·(k−1)/2 independent two-party sessions): the baseline
        the benchmark and CI gate against."""
        f = _factor(self.family, self.normalise)
        return 2.0 * f * self.eps * len(self.cells())

    def party_eps(self) -> dict[str, float]:
        """Per-party share of :meth:`optimal_eps` — what each party's
        ledger must show after a clean (or resumed) matrix."""
        f = _factor(self.family, self.normalise)
        out = {p: 0.0 for p, _ in self.parties}
        for (_side, label), _venue in self.artifact_venues().items():
            for p, cols in self.parties:
                if label in cols:
                    out[p] += f * self.eps
        return out

    # ---------------------------------------- two-party equivalence ----
    def cell_spec(self, i: int, j: int):
        """The :class:`~dpcorr.protocol.party.ProtocolSpec` of the
        *independent two-party run* equivalent to cell (i, j): same
        per-column key labels, so the federation matrix is bit-identical
        to k·(k−1)/2 separate sessions (the acceptance contract).
        Imported lazily — planning stays jax-free."""
        from dpcorr.protocol.party import ProtocolSpec

        return ProtocolSpec(
            family=self.family, n=self.n, eps1=self.eps, eps2=self.eps,
            alpha=self.alpha, normalise=self.normalise, seed=self.seed,
            noise_mode=self.noise_mode,
            party_x=self.owner(i), party_y=self.owner(j),
            session=f"{self.fed}-cell-{i}-{j}",
            key_x=self.label(i), key_y=self.label(j))

    def describe(self) -> dict:
        """The ``dpcorr federation plan`` JSON: schedule, venues and the
        ε arithmetic, all derived — nothing here is state."""
        venues = {f"{side}:{label}": list(v if v[0] == "link" else v)
                  for (side, label), v in self.artifact_venues().items()}
        return {
            "fed": self.fed,
            "fed_hash": self.fed_hash(),
            "plan": self.to_public(),
            "k": self.k,
            "cells": [list(c) for c in self.cells()],
            "links": [
                {"pair": [p, q],
                 "session": self.link_session(p, q),
                 "rounds": [[list(c) for c in cells]
                            for cells in self.link_rounds(p, q)]}
                for p, q in self.links()],
            "local": {p: [list(c) for c in self.local_cells(p)]
                      for p, _ in self.parties
                      if self.local_cells(p)},
            "artifact_venues": venues,
            "eps": {"optimal": self.optimal_eps(),
                    "naive_per_cell": self.naive_eps(),
                    "per_party": self.party_eps()},
        }
