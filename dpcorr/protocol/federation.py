"""Federation runtime: N parties computing the k×k matrix (ISSUE 12).

:mod:`~dpcorr.protocol.matrix` decides *what* happens — cells, venues,
rounds, artifact charges — as pure plan arithmetic. This module makes
it happen: one :class:`FederationParty` per real party, holding all of
that party's columns, its single privacy ledger, and one **pair link**
per peer it shares a cell with. A pair link is one
:class:`~dpcorr.protocol.transport.ReliableChannel` carrying *all* of
the pair's cells as a multiplexed session: per round, the lower party
sends one gated ``release`` envelope bundling every column artifact the
round's cells need, and the higher party answers one gated ``result``
after a single batched finish kernel
(:func:`~dpcorr.models.estimators.split_reference.finish_batch`,
``"exact"`` engine) — B cells, two messages, two charges at most.

The budget optimum falls out of the plan: a column's release artifact
is computed once (:meth:`FederationParty.release_artifact` caches the
*encoded* envelope, so every link embeds the identical bytes — which is
also what the cross-pair correlation-leak gate in protocol.scan
verifies) and charged once, at the artifact's first-use venue; rounds
that only reuse artifacts send them with an **empty** charge map
through the same release gate. Total spend is
``FederationPlan.optimal_eps()`` — ``2·f·ε·(k−1)`` for a full matrix —
against the naive per-cell ``f·ε·k·(k−1)``.

Crash safety composes from PR 7 unchanged: every pair link is one
journaled session (:class:`~dpcorr.protocol.journal.SessionJournal`),
local-cell charges carry a deterministic plan-derived ``charge_id``,
and the whole schedule is a pure function of the public plan — so a
party killed anywhere mid-matrix re-derives the identical schedule on
restart, finished links replay from their journals' terminal results,
and the interrupted link resumes exactly-once through the session
re-attach handshake. Chaos points ``federation.pre_release`` /
``federation.pre_finish`` / ``federation.mid_matrix`` mark the
federation-specific crash windows; the shared gate/journal/ledger
windows fire inside the common code paths as before.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from dpcorr import chaos
from dpcorr.obs import from_wire_headers, split_exact, tracer
from dpcorr.obs import recorder as obs_recorder
from dpcorr.obs.metrics import LATENCY_BUCKETS, Registry
from dpcorr.protocol.gate import ReleaseGate
from dpcorr.protocol.journal import SessionJournal
from dpcorr.protocol.matrix import FederationPlan
from dpcorr.protocol.messages import (
    Transcript,
    canonical_encode,
    decode_array,
    encode_array,
)
from dpcorr.protocol.party import (
    ProtocolError,
    ProtocolRefused,
    SessionEndpoint,
)
from dpcorr.protocol.transport import (
    InProcTransport,
    ReconnectingTcpLink,
    ReliableChannel,
    SessionResumeRefused,
    TransportError,
    TransportTimeout,
    tcp_accept,
    tcp_connect,
    tcp_listen,
)
from dpcorr.serve.ledger import BudgetExceededError, PrivacyLedger

#: Same convenience default as runner.DEFAULT_BUDGET (callers that
#: don't bring persistent ledgers are single-shot runs).
DEFAULT_BUDGET = 1e6


def _first_cells(plan: FederationPlan) -> dict:
    """``(side, label) -> first cell`` using the artifact — the cell its
    one-time ε charge is attributed to (matrix.artifact_venues keeps
    the venue; cost attribution needs the cell itself)."""
    first: dict = {}
    for i, j in plan.cells():
        first.setdefault(("x", plan.label(i)), (i, j))
        first.setdefault(("y", plan.label(j)), (i, j))
    return first


@dataclass
class FederationResult:
    """One party's view of a completed matrix: every cell it computed
    or received (local cells plus all cells on its links — cells
    between two *other* parties are not its business to know)."""

    party: str
    fed: str
    cells: dict            # "i,j" -> {"rho_hat", "ci_low", "ci_high"}
    eps: dict              # {"party", "optimal", "naive_per_cell"}
    stats: dict = field(default_factory=dict)
    costs: list = field(default_factory=list)  # per-cell attributions


class _PairLink(SessionEndpoint):
    """One multiplexed pair session — the federation's unit of wire
    traffic, riding the exact journaled/gated endpoint machinery the
    two-party :class:`~dpcorr.protocol.party.Party` uses. The lower
    party (plan order) initiates and releases; the higher party
    verifies the plan hash, finishes each round with one batched
    kernel, and returns the round's results."""

    def __init__(self, owner: "FederationParty", peer: str,
                 channel: ReliableChannel,
                 transcript: Transcript | None = None,
                 journal: SessionJournal | None = None,
                 recv_timeout_s: float = 30.0):
        plan = owner.plan
        lo = plan.party_index(owner.name) < plan.party_index(peer)
        p, q = (owner.name, peer) if lo else (peer, owner.name)
        super().__init__(session=plan.link_session(p, q),
                         spec_hash=plan.fed_hash(), sender=owner.name,
                         channel=channel, ledger=owner.ledger,
                         transcript=transcript,
                         recv_timeout_s=recv_timeout_s, journal=journal)
        self.owner = owner
        self.plan = plan
        self.peer = peer
        self.p, self.q = p, q
        self.initiator = lo
        # re-point the endpoint's gate at an observed one: every charge
        # this link lands (gated send, replay) moves the owner's
        # ε-burn gauge without touching the budget discipline
        self._gate = ReleaseGate(owner.ledger,
                                 on_charge=owner.note_charge)

    # ------------------------------------------------------ handshake ----
    def _handshake(self, first) -> None:
        """Same two frames as the two-party opening, pinning the
        *federation* hash: both ends prove they compiled the identical
        plan (schedule, rounds, charge assignment included) before any
        ε moves. The initiator also names the pair — a link dialed to
        the wrong peer fails here, not mid-round. ``first`` is the
        acceptor's already-received ``hello`` (the span parented on its
        headers was opened in :meth:`run` before this call); the
        initiator passes ``None``."""
        plan = self.plan
        if self.initiator:
            payload = {"fed": plan.to_public(),
                       "fed_hash": plan.fed_hash(),
                       "pair": [self.p, self.q]}
            if self.journal is not None:
                payload["resume_token"] = self.journal.ensure_token()
                self._register_session_info()
            self._send_plain(self._msg("hello", payload))
            self._recv("hello_ack")
            return
        if self.journal is not None:
            token = first.payload.get("resume_token")
            if token:
                self.journal.adopt_token(token)
                self._register_session_info()
        theirs = first.payload.get("fed_hash")
        if theirs != plan.fed_hash() \
                or first.payload.get("pair") != [self.p, self.q]:
            self._send_best_effort(self._msg("error", {
                "kind": "protocol",
                "reason": f"federation plan mismatch: {theirs!r}"}))
            raise ProtocolError(
                f"peer plan hash {theirs!r} != ours "
                f"{plan.fed_hash()!r}")
        self._send_plain(self._msg("hello_ack",
                                   {"fed_hash": plan.fed_hash()}))

    # --------------------------------------------------------- rounds ----
    def _drive_releaser(self) -> list:
        out = []
        link = f"{self.p}-{self.q}"
        for r, cells in enumerate(self.plan.link_rounds(self.p, self.q)):
            t0 = time.perf_counter()
            with tracer().span("federation.round", parent=self._span,
                               link=link, round=r, role="release",
                               cells=len(cells)):
                labels = self.plan.round_x_labels(self.p, self.q, r)
                artifacts = {lab: self.owner.release_artifact(lab)
                             for lab in labels}
                rc = self.plan.round_charges(self.p, self.q,
                                             r)["release"]
                chaos.point("federation.pre_release")
                payload = {"round": r,
                           "cells": [list(c) for c in cells],
                           "artifacts": artifacts,
                           "charged": list(rc["labels"])}
                self._send_gated(self._msg("release", payload),
                                 rc["charges"])
                final = self._recv("result")
                out.extend(self._check_result(final, r, cells))
            self.owner.note_cells(cells, "link")
            self.owner.note_round(link, "release",
                                  time.perf_counter() - t0)
        return out

    def _check_result(self, msg, r: int, cells) -> list:
        pay = msg.payload
        if pay.get("round") != r:
            raise ProtocolError(
                f"result round {pay.get('round')!r} != expected {r}")
        got = pay.get("cells", [])
        if [tuple(c[:2]) for c in got] != [tuple(c) for c in cells]:
            raise ProtocolError(
                f"result cells do not match round {r} of "
                f"link {self.p}-{self.q}")
        return [(int(i), int(j), float(rho), float(lo), float(hi))
                for i, j, rho, lo, hi in got]

    def _refuse(self, reason: str):
        self._send_best_effort(self._msg("error", {
            "kind": "protocol", "reason": reason}))
        raise ProtocolError(reason)

    def _validate_round(self, msg, r: int, cells) -> dict:
        """The finisher's half of the no-raw-columns barrier, per
        artifact: round/cell agreement with the plan, charged-labels
        agreement (a releaser that under- or over-declares its charges
        is refused before any finish), and the family release schema
        on every envelope — exactly Party._validate_release, once per
        label."""
        from dpcorr.models.estimators import split_reference as sr

        plan = self.plan
        pay = msg.payload
        if pay.get("round") != r:
            self._refuse(f"release round {pay.get('round')!r} != "
                         f"expected {r}")
        if [tuple(c) for c in pay.get("cells", [])] \
                != [tuple(c) for c in cells]:
            self._refuse(f"release cells do not match round {r} of the "
                         "plan")
        labels = plan.round_x_labels(self.p, self.q, r)
        arts = pay.get("artifacts")
        if not isinstance(arts, dict) or set(arts) != set(labels):
            self._refuse(
                f"release artifacts {sorted(arts or ())} != plan "
                f"labels {sorted(labels)}")
        want_charged = plan.round_charges(self.p, self.q, r)["release"]
        if tuple(pay.get("charged", ())) != tuple(want_charged["labels"]):
            self._refuse("release charged-labels differ from the plan's "
                         "artifact assignment")
        schema = sr.release_schema(plan.family, plan.n, plan.eps,
                                   plan.eps)
        decoded: dict = {}
        for lab in labels:
            group = arts[lab]
            if not isinstance(group, dict) or set(group) != set(schema):
                self._refuse(f"artifact {lab!r} keys != release schema")
            vals = {}
            for name, want in schema.items():
                env = group[name]
                if not (isinstance(env, dict)
                        and env.get("__array__") == 1):
                    self._refuse(f"artifact {lab!r}[{name!r}] is not an "
                                 "array envelope")
                if env.get("kind") != want["kind"]:
                    self._refuse(
                        f"artifact {lab!r}[{name!r}] kind "
                        f"{env.get('kind')!r} != {want['kind']!r}")
                arr = decode_array(env)
                if tuple(arr.shape) != tuple(want["shape"]) \
                        or str(arr.dtype) != want["dtype"]:
                    self._refuse(
                        f"artifact {lab!r}[{name!r}] is "
                        f"{arr.dtype}{arr.shape}, schema says "
                        f"{want['dtype']}{tuple(want['shape'])}")
                vals[name] = arr
            decoded[lab] = vals
        return decoded

    def _drive_finisher(self) -> list:
        from dpcorr.models.estimators import split_reference as sr

        plan = self.plan
        out = []
        link = f"{self.p}-{self.q}"
        for r, cells in enumerate(plan.link_rounds(self.p, self.q)):
            msg = self._recv("release")
            rt0 = time.perf_counter()
            with tracer().span("federation.round", parent=self._span,
                               link=link, round=r, role="finish",
                               cells=len(cells)) as rsp:
                decoded = self._validate_round(msg, r, cells)
                chaos.point("federation.pre_finish")
                keys = [self.owner.finisher_key(plan.label(j))
                        for _i, j in cells]
                rels = [decoded[plan.label(i)] for i, _j in cells]
                cols = [self.owner.column(plan.label(j))
                        for _i, j in cells]
                t0 = time.perf_counter()
                with tracer().span("federation.finish",
                                   cells=len(cells)):
                    rho, lo, hi = sr.finish_batch(
                        plan.family, keys, rels, cols, plan.eps,
                        plan.eps, plan.alpha, plan.normalise,
                        engine=self.owner.engine)
                finish_s = time.perf_counter() - t0
                result_cells = [
                    [int(i), int(j), float(rho[b]), float(lo[b]),
                     float(hi[b])]
                    for b, (i, j) in enumerate(cells)]
                for i, j in cells:
                    # per-cell completion markers: instantaneous child
                    # spans so the unioned timeline shows exactly when
                    # each matrix cell finished, on which link
                    with tracer().span("federation.cell", parent=rsp,
                                       i=int(i), j=int(j), link=link):
                        pass
                rc = plan.round_charges(self.p, self.q, r)["result"]
                self._send_gated(
                    self._msg("result",
                              {"round": r, "cells": result_cells,
                               "charged": list(rc["labels"])}),
                    rc["charges"])
                self.owner.attribute_round(
                    pair=(self.p, self.q), cells=cells,
                    finish_s=finish_s, n_bytes=len(msg.encode()))
            self.owner.note_cells(cells, "link")
            self.owner.note_round(link, "finish",
                                  time.perf_counter() - rt0)
            out.extend(tuple(c) for c in result_cells)
        return out

    def run(self) -> list:
        """All rounds of this pair session; returns the link's cells as
        ``(i, j, rho, lo, hi)`` tuples. A journaled link that already
        finished returns its terminal result without touching the wire
        or the ledger — the same idempotency level as Party.run.

        Every link of every party joins ONE federation trace: the
        initiator pins the deterministic plan-derived trace id
        (``FederationPlan.trace_id``; a resumed journal's recorded
        trace wins, and is itself that same id for any run of this
        code), the acceptor parents on the hello's wire headers and
        falls back to the same pin when the initiator runs untraced."""
        if self.journal is not None:
            if self.journal.status == "finished" and self.journal.result:
                return [tuple(c) for c in self.journal.result["cells"]]
            try:
                self._attach_journal()
            except SessionResumeRefused as e:
                obs_recorder.trigger(
                    "federation_resume_refused", party=self.sender,
                    peer=self.peer, session=self.session,
                    fed=self.plan.fed, detail=str(e))
                raise
        plan = self.plan
        first = None
        if self.initiator:
            resumed = bool(self.journal is not None
                           and self.journal.trace_id)
            span = tracer().start_span(
                "federation.link",
                trace_id=(self.journal.trace_id if resumed
                          else plan.trace_id()),
                party=self.sender, session=self.session,
                family=plan.family, resumed=resumed)
        else:
            first = self._recv("hello")
            span = tracer().start_span(
                "federation.link",
                parent=from_wire_headers(first.headers),
                trace_id=plan.trace_id(), party=self.sender,
                session=self.session, family=plan.family)
        self._span = span
        if self.journal is not None and span.trace_id:
            self.journal.set_trace(span.trace_id)
        try:
            self._handshake(first)
            cells = (self._drive_releaser() if self.initiator
                     else self._drive_finisher())
            # terminal symmetry with the two-party roles: whichever side
            # received the session's last frame keeps re-acking while
            # loss is possible (transport.drain decides)
            self._linger()
        finally:
            span.end()
            self.transcript.close()
        if self.journal is not None:
            self.journal.set_result({"cells": [list(c) for c in cells]})
            self.journal.finish()
        self.owner.note_link_done(self.p, self.q)
        return cells


class FederationParty:
    """One real party of one federation: its columns, its ledger (one
    gate, shared by every link and the local cells), its pair links.

    ``columns`` maps this party's column labels to raw value arrays —
    they never leave this object except as DP releases through
    ``split_reference``. ``channels`` maps peer name →
    :class:`ReliableChannel`; ``journals``/``transcripts`` likewise,
    all optional. ``engine`` selects the batched finish engine
    (``"exact"`` is the bit-identity contract)."""

    def __init__(self, name: str, plan: FederationPlan, columns,
                 ledger: PrivacyLedger | None,
                 channels: dict | None = None, *,
                 journals: dict | None = None,
                 transcripts: dict | None = None,
                 recv_timeout_s: float = 30.0, engine: str = "exact",
                 registry: Registry | None = None,
                 instance: str | None = None):
        plan.party_index(name)  # unknown party fails loudly here
        self.name = name
        self.plan = plan
        self.ledger = ledger or PrivacyLedger(DEFAULT_BUDGET)
        self.engine = engine
        self.recv_timeout_s = recv_timeout_s
        self.instance = instance
        self.registry = registry if registry is not None else Registry()
        self._init_metrics()
        self._gate = ReleaseGate(self.ledger,
                                 on_charge=self.note_charge)
        self._channels = dict(channels or {})
        self._journals = dict(journals or {})
        self._transcripts = dict(transcripts or {})
        self._columns = {}
        for lab in plan.party_labels(name):
            if lab not in columns:
                raise ValueError(f"party {name!r} is missing its "
                                 f"column {lab!r}")
            col = np.asarray(columns[lab], dtype=np.float32)
            if col.ndim != 1 or col.shape[0] != plan.n:
                raise ValueError(f"column {lab!r} must be shape "
                                 f"({plan.n},), got {col.shape}")
            self._columns[lab] = col
        for p, q in plan.party_links(name):
            peer = q if p == name else p
            if peer not in self._channels:
                raise ValueError(f"party {name!r} has no channel for "
                                 f"its link to {peer!r}")
        self._lock = threading.Lock()
        self._artifacts: dict = {}   # guarded by: _lock
        self._costs: list = []       # guarded by: _lock
        self._done: set = set()      # guarded by: _lock
        self._first = _first_cells(plan)

    # -------------------------------------------------------- metrics ----
    def _init_metrics(self) -> None:
        """The party-process telemetry plane (ISSUE 13): one registry
        backs both the ``--obs-port`` /metrics scrape (FleetCollector-
        compatible: the instance-labelled info gauge is the self-claim
        the fleet merge cross-checks) and /stats. All series carry
        enough labels for the SLO engine's federation objectives
        (round latency, ε-burn vs plan share) to point at them."""
        r = self.registry
        plan = self.plan
        self._m_info = r.gauge(
            "dpcorr_federation_instance_info",
            "federation party identity: constant 1, labelled with the "
            "fleet instance name, party and federation id",
            labelnames=("instance", "party", "fed"))
        if self.instance:
            self._m_info.set(1, instance=str(self.instance),
                             party=self.name, fed=plan.fed)
        self._m_round_latency = r.histogram(
            "dpcorr_federation_round_latency_seconds",
            "wall time of one pair-link round (release->result on the "
            "releaser, recv->result-sent on the finisher)",
            buckets=LATENCY_BUCKETS)
        self._m_rounds = r.counter(
            "dpcorr_federation_rounds_total",
            "pair-link rounds completed", labelnames=("link", "role"))
        self._m_cells = r.counter(
            "dpcorr_federation_cells_completed_total",
            "matrix cells this party finished or received",
            labelnames=("venue",))
        self._m_cache = r.counter(
            "dpcorr_federation_release_cache_total",
            "column release artifact cache outcomes (a hit is the "
            "byte-identical reuse the eps optimum rests on)",
            labelnames=("label", "outcome"))
        self._m_links = r.counter(
            "dpcorr_federation_links_finished_total",
            "pair links run to completion", labelnames=("link",))
        self._m_spent = r.gauge(
            "dpcorr_federation_ledger_spent_eps",
            "eps this party's ledger has spent on its own account",
            labelnames=("ledger",))
        self._m_share = r.gauge(
            "dpcorr_federation_plan_share_eps",
            "this party's plan-derived share of the federation "
            "optimum (constant; burn above it is an SLO violation)",
            labelnames=("ledger",))
        self._m_share.set(plan.party_eps().get(self.name, 0.0),
                          ledger=self.name)
        self.note_charge(None)

    def note_charge(self, charges) -> None:
        """Gate observer: refresh the ε-burn gauge from the ledger
        after any charge leg lands (the gauge reads the ledger, not the
        increment, so refunds and idempotent resume re-charges can
        never drift it)."""
        try:
            self._m_spent.set(self.ledger.spent(self.name),
                              ledger=self.name)
        except Exception:
            pass

    def note_round(self, link: str, role: str, seconds: float) -> None:
        self._m_round_latency.observe(seconds)
        self._m_rounds.inc(link=link, role=role)

    def note_link_done(self, p: str, q: str) -> None:
        self._m_links.inc(link=f"{p}-{q}")

    def note_cells(self, cells, venue: str) -> None:
        with self._lock:
            fresh = [c for c in cells
                     if (int(c[0]), int(c[1])) not in self._done]
            self._done.update((int(c[0]), int(c[1])) for c in fresh)
        if fresh:
            self._m_cells.inc(len(fresh), venue=venue)

    def stats_snapshot(self) -> dict:
        """The /stats document for the party obs endpoint — shaped so
        the fleet console's federation frame and FleetCollector's
        per-instance stats map both read it directly."""
        plan = self.plan
        with self._lock:
            done = len(self._done)
            cached = sorted(self._artifacts)
        spent = self.ledger.spent(self.name)
        return {
            "kind": "federation_party",
            "instance": self.instance,
            "party": self.name,
            "fed": plan.fed,
            "trace_id": plan.trace_id(),
            "family": plan.family,
            "cells_done": done,
            "cells_total": len(plan.cells()),
            "links": [f"{p}-{q}" for p, q in plan.party_links(self.name)],
            "eps": {"spent": spent,
                    "share": plan.party_eps().get(self.name, 0.0),
                    "optimal": plan.optimal_eps(),
                    "naive_per_cell": plan.naive_eps()},
            "artifacts_cached": cached,
        }

    # ----------------------------------------------------------- keys ----
    def _root(self, label: str, side: str):
        from dpcorr.utils import rng

        key = rng.column_root(rng.master_key(self.plan.seed), label)
        return rng.party_root(key, side, self.plan.noise_mode)

    def finisher_key(self, label: str):
        return self._root(label, "y")

    def column(self, label: str):
        return self._columns[label]

    # ------------------------------------------------------ artifacts ----
    def release_artifact(self, label: str) -> dict:
        """The column's encoded release envelope — computed once,
        cached as *bytes-stable wire dicts*, so every link (and every
        round) that embeds this label embeds identical bytes. Re-noising
        per pair would be an ε leak and a correlation leak; the
        cross-pair scan (protocol.scan.scan_federation) enforces the
        byte-identity this cache provides."""
        with self._lock:
            env = self._artifacts.get(label)
            if env is not None:
                self._m_cache.inc(label=label, outcome="hit")
                with tracer().span("federation.release_cache",
                                   label=label, hit=True):
                    pass
                return env
            with tracer().span("federation.release_cache",
                               label=label, hit=False):
                from dpcorr.models.estimators import (
                    split_reference as sr,
                )

                plan = self.plan
                rel = sr.party_release(
                    plan.family, self._root(label, "x"), "x",
                    self._columns[label], plan.eps, plan.eps,
                    plan.normalise)
                kinds = sr.RELEASE_KINDS[plan.family]
                env = {name: encode_array(np.asarray(arr),
                                          kind=kinds[name])
                       for name, arr in rel.items()}
                self._artifacts[label] = env
            self._m_cache.inc(label=label, outcome="build")
            return env

    # ----------------------------------------------------------- cost ----
    def attribute_round(self, pair, cells, finish_s: float,
                        n_bytes: int) -> None:
        """Per-cell cost records for one finished round: the round's
        one kernel time and one release envelope split exactly across
        its cells (obs.split_exact — attributions sum back to the round
        totals), and each cell's ε split into what its round charged
        *new* (artifacts first used by this cell) vs what it reused
        for free — the ledger-facing view of the release-reuse
        optimization."""
        from dpcorr.protocol.matrix import _factor

        plan = self.plan
        unit = _factor(plan.family, plan.normalise) * plan.eps
        times = split_exact(float(finish_s), len(cells))
        sizes = split_exact(int(n_bytes), len(cells))
        recs = []
        for b, (i, j) in enumerate(cells):
            new = sum(
                unit for art in (("x", plan.label(i)),
                                 ("y", plan.label(j)))
                if self._first[art] == (i, j))
            recs.append({"cell": [i, j], "pair": list(pair),
                         "finish_s": times[b], "bytes": sizes[b],
                         "eps_new": new,
                         "eps_reused": 2.0 * unit - new})
        with self._lock:
            self._costs.extend(recs)

    # ---------------------------------------------------- local cells ----
    def _run_local(self) -> list:
        plan = self.plan
        cells = plan.local_cells(self.name)
        if not cells:
            return []
        from dpcorr.models.estimators import split_reference as sr

        lc = plan.local_charges(self.name)
        if lc["charges"]:
            # charge-before-release, same discipline as the wire: the
            # plan-derived charge_id makes a resumed matrix re-run this
            # block without double-spending
            try:
                self._gate.charge_local(lc["charges"],
                                        charge_id=lc["charge_id"])
            except BudgetExceededError as e:
                raise ProtocolRefused(str(e)) from e
        out = []
        for i, j in cells:
            li, lj = plan.label(i), plan.label(j)
            t0 = time.perf_counter()
            with tracer().span("federation.cell",
                               parent=getattr(self, "_matrix_span",
                                              None),
                               i=int(i), j=int(j), venue="local"):
                rho, lo, hi = sr.split_estimate(
                    plan.family, self._root(li, "x"),
                    self.finisher_key(lj), self._columns[li],
                    self._columns[lj], plan.eps, plan.eps,
                    alpha=plan.alpha, normalise=plan.normalise)
            cell_s = time.perf_counter() - t0
            out.append((i, j, float(rho), float(lo), float(hi)))
            self.note_cells([(i, j)], "local")
            unit_new = sum(
                1 for art in (("x", li), ("y", lj))
                if self._first[art] == (i, j))
            from dpcorr.protocol.matrix import _factor

            unit = _factor(plan.family, plan.normalise) * plan.eps
            with self._lock:
                self._costs.append({
                    "cell": [i, j], "pair": [self.name],
                    "finish_s": cell_s, "bytes": 0,
                    "eps_new": unit * unit_new,
                    "eps_reused": unit * (2 - unit_new)})
        return out

    # ------------------------------------------------------------ run ----
    def run(self) -> FederationResult:
        """Local cells, then every pair link concurrently; joins *all*
        link threads before re-raising any link failure, so a simulated
        in-process crash leaves no zombie link thread competing for the
        channels when the restarted party re-attaches."""
        plan = self.plan
        span = tracer().start_span("federation.matrix",
                                   trace_id=plan.trace_id(),
                                   party=self.name, fed=plan.fed,
                                   instance=self.instance or self.name)
        self._matrix_span = span
        results: dict = {}
        try:
            for c in self._run_local():
                results[(c[0], c[1])] = c
            links = []
            for p, q in plan.party_links(self.name):
                peer = q if p == self.name else p
                links.append(_PairLink(
                    self, peer, self._channels[peer],
                    transcript=self._transcripts.get(peer),
                    journal=self._journals.get(peer),
                    recv_timeout_s=self.recv_timeout_s))
            outs: dict[str, list] = {}
            errs: dict[str, BaseException] = {}

            def drive(lk: _PairLink) -> None:
                try:
                    outs[lk.peer] = lk.run()
                except BaseException as e:  # joined + re-raised below
                    errs[lk.peer] = e

            threads = [threading.Thread(target=drive, args=(lk,),
                                        name=f"party-{self.name}")
                       for lk in links]
            for t in threads:
                t.start()
            pending = list(threads)
            try:
                while pending:
                    pending.pop(0).join()
                    chaos.point("federation.mid_matrix")
            finally:
                for t in pending:
                    t.join()
            if errs:
                raise errs[sorted(errs)[0]]
            for lk in links:
                for c in outs[lk.peer]:
                    results[(c[0], c[1])] = c
            stats = {lk.peer: lk._stats() for lk in links}
        except (ProtocolError, ProtocolRefused):
            raise
        except Exception as e:
            obs_recorder.trigger(
                "federation_unhandled", party=self.name, fed=plan.fed,
                error=type(e).__name__, detail=str(e))
            raise
        finally:
            span.end()
        with self._lock:
            costs = list(self._costs)
        return FederationResult(
            party=self.name, fed=plan.fed,
            cells={f"{i},{j}": {"rho_hat": rho, "ci_low": lo,
                                "ci_high": hi}
                   for (i, j), (_i, _j, rho, lo, hi)
                   in sorted(results.items())},
            eps={"party": plan.party_eps().get(self.name, 0.0),
                 "optimal": plan.optimal_eps(),
                 "naive_per_cell": plan.naive_eps()},
            stats=stats, costs=costs)


# ======================================================== drivers ====

def _backoff_max(timeout_s: float) -> float:
    # same cadence scaling as runner._make_parties
    return min(2.0, max(2.0 * timeout_s, 0.1))


def _mk_fault(fault: dict | None, default_seed: int):
    from dpcorr.protocol.runner import _mk_fault as mk

    return mk(fault, default_seed)


def _party_files(plan: FederationPlan, name: str, peer_of: dict,
                 transcript_dir: str | None, journal_dir: str | None):
    transcripts, journals = {}, {}
    for (p, q), peer in peer_of.items():
        sess = plan.link_session(p, q)
        if transcript_dir:
            transcripts[peer] = Transcript(os.path.join(
                transcript_dir, f"{sess}.{name}.jsonl"))
        if journal_dir:
            journals[peer] = SessionJournal(os.path.join(
                journal_dir, f"journal.{name}.{sess}.json"))
    return transcripts, journals


def make_federation_parties(plan: FederationPlan, data, *,
                            ledgers: dict | None = None,
                            endpoints: dict | None = None,
                            fault: dict | None = None,
                            transcript_dir: str | None = None,
                            journal_dir: str | None = None,
                            timeout_s: float = 10.0,
                            max_retries: int = 10,
                            recv_timeout_s: float = 30.0,
                            engine: str = "exact") -> dict:
    """Build every party of an in-process federation over queue-pair
    transports. ``data`` maps column label → values (labels are
    globally unique, so one flat dict covers all parties). Pass
    ``endpoints`` — ``{(p, q): InProcTransport}`` — to reuse the same
    wire across a crash-restart (the chaos tests' pattern: fresh
    parties and channels on the surviving queue pair + the same
    journals); omitted, a fresh transport is made per link."""
    endpoints = ({(p, q): InProcTransport() for p, q in plan.links()}
                 if endpoints is None else endpoints)
    parties = {}
    link_index = {lk: n for n, lk in enumerate(plan.links())}
    for name, labels in plan.parties:
        channels, peer_of = {}, {}
        for p, q in plan.party_links(name):
            pair = endpoints[(p, q)]
            peer = q if p == name else p
            side = pair.a if name == p else pair.b
            # distinct deterministic fault seed per (link, side) so one
            # --fault-seed knob reproduces every endpoint's chaos
            seed = 11 + 2 * link_index[(p, q)] + (0 if name == p else 1)
            channels[peer] = ReliableChannel(
                side, timeout_s=timeout_s, max_retries=max_retries,
                backoff_max_s=_backoff_max(timeout_s),
                fault=_mk_fault(fault, default_seed=seed))
            peer_of[(p, q)] = peer
        transcripts, journals = _party_files(
            plan, name, peer_of, transcript_dir, journal_dir)
        if fault:
            for t in transcripts.values():
                t.meta(fault=dict(fault), fed=plan.fed)
        parties[name] = FederationParty(
            name, plan, {lab: data[lab] for lab in labels},
            (ledgers or {}).get(name), channels, journals=journals,
            transcripts=transcripts, recv_timeout_s=recv_timeout_s,
            engine=engine)
    return parties


def _drive_parties(parties: dict) -> dict:
    """Run every party to completion on its own thread; re-raises the
    first failure (party order) after all joined."""
    results: dict[str, FederationResult] = {}
    errors: dict[str, BaseException] = {}

    def drive(name: str, party: FederationParty) -> None:
        try:
            results[name] = party.run()
        except BaseException as e:  # captured for the joining thread
            errors[name] = e

    threads = [threading.Thread(target=drive, args=(name, p),
                                name=f"party-{name}")
               for name, p in parties.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        for name in parties:
            if name in errors:
                raise errors[name]
    return results


def run_federation_inproc(plan: FederationPlan, data, **kw) -> dict:
    """The whole federation in one process (tests, benchmarks, the
    single-command CLI): every party on a thread, queue-pair wires.
    Returns ``{party: FederationResult}``."""
    return _drive_parties(make_federation_parties(plan, data, **kw))


def run_federation_tcp(plan: FederationPlan, data, *,
                       host: str = "127.0.0.1",
                       ledgers: dict | None = None,
                       fault: dict | None = None,
                       transcript_dir: str | None = None,
                       journal_dir: str | None = None,
                       timeout_s: float = 10.0, max_retries: int = 10,
                       recv_timeout_s: float = 30.0,
                       engine: str = "exact") -> dict:
    """Same drive over real loopback TCP sockets, one per link (the
    full length-prefixed framing path; ``port=0`` ephemeral ports)."""
    links: dict = {}
    servers = []
    for p, q in plan.links():
        srv, bound = tcp_listen(host, 0)
        servers.append(srv)
        got: dict = {}

        def accept(srv=srv, got=got):
            got["q"] = tcp_accept(srv, timeout_s=max(timeout_s, 30.0))

        acceptor = threading.Thread(target=accept, name="fed-accept")
        acceptor.start()
        got["p"] = tcp_connect(host, bound, timeout_s=max(timeout_s,
                                                          30.0))
        acceptor.join()
        links[(p, q)] = got
    link_index = {lk: n for n, lk in enumerate(plan.links())}
    parties = {}
    try:
        for name, labels in plan.parties:
            channels, peer_of = {}, {}
            for p, q in plan.party_links(name):
                peer = q if p == name else p
                side = links[(p, q)]["p" if name == p else "q"]
                seed = 11 + 2 * link_index[(p, q)] \
                    + (0 if name == p else 1)
                channels[peer] = ReliableChannel(
                    side, timeout_s=timeout_s, max_retries=max_retries,
                    backoff_max_s=_backoff_max(timeout_s),
                    fault=_mk_fault(fault, default_seed=seed))
                peer_of[(p, q)] = peer
            transcripts, journals = _party_files(
                plan, name, peer_of, transcript_dir, journal_dir)
            parties[name] = FederationParty(
                name, plan, {lab: data[lab] for lab in labels},
                (ledgers or {}).get(name), channels, journals=journals,
                transcripts=transcripts, recv_timeout_s=recv_timeout_s,
                engine=engine)
        return _drive_parties(parties)
    finally:
        for got in links.values():
            for side in got.values():
                side.close()
        for srv in servers:
            srv.close()


# ============================================ multi-process plumbing ====

class LinkBroker:
    """Demultiplexes inbound pair-link connections on one listening
    socket — the multi-process party advertises a single port, and each
    dialing peer identifies its link with one plaintext ``fed_id``
    frame before any protocol traffic. The broker routes the identified
    link to the waiting per-peer queue; a redial after a peer's crash
    lands the same way, which is exactly what the acceptor-side
    :class:`ReconnectingTcpLink` pops on reconnect."""

    def __init__(self, srv, party: str, expected):
        self.srv = srv
        self.party = party
        self._queues = {peer: queue.Queue() for peer in expected}
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name=f"fed-accept-{party}", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop:
            try:
                link = tcp_accept(self.srv, timeout_s=0.5)
            except TransportTimeout:
                continue
            except OSError:
                return
            try:
                frame = json.loads(link.recv_bytes(timeout_s=5.0))
            except (TransportError, ValueError):
                link.close()
                continue
            q = (self._queues.get(frame.get("party"))
                 if isinstance(frame, dict)
                 and frame.get("kind") == "fed_id" else None)
            if q is None:
                link.close()
                continue
            q.put(link)

    def wait(self, peer: str, timeout_s: float):
        """Block until ``peer`` (re)dials this party's port."""
        try:
            return self._queues[peer].get(timeout=timeout_s)
        except queue.Empty:
            raise TransportTimeout(
                f"peer {peer!r} did not dial within {timeout_s:.3g}s"
            ) from None

    def close(self) -> None:
        self._stop = True
        try:
            self.srv.close()
        except OSError:
            pass


def dial_link(host: str, port: int, party: str, pair,
              timeout_s: float = 5.0):
    """Connect one pair link to a listening peer and identify it: the
    ``fed_id`` frame names the dialing party so the broker routes the
    connection before the protocol handshake starts."""
    link = tcp_connect(host, port, timeout_s=timeout_s)
    link.send_bytes(canonical_encode(
        {"kind": "fed_id", "party": party, "pair": list(pair)}))
    return link


def serve_federation_party(name: str, plan: FederationPlan, columns, *,
                           ledger: PrivacyLedger | None = None,
                           listen: tuple | None = None,
                           peers: dict | None = None,
                           transcript_dir: str | None = None,
                           journal_dir: str | None = None,
                           timeout_s: float = 5.0,
                           max_retries: int = 8,
                           connect_timeout_s: float = 30.0,
                           recv_timeout_s: float = 30.0,
                           engine: str = "exact",
                           on_listening=None,
                           registry: Registry | None = None,
                           instance: str | None = None,
                           on_party=None) -> FederationResult:
    """One real party process of a multi-process federation (the
    ``dpcorr federation party`` CLI body). Topology is plan-derived:
    for each link the *lower* party dials and the higher listens, so a
    party listens iff some lower-indexed peer shares a cell with it
    (``listen`` = (host, port), announced through ``on_listening``)
    and dials every higher-indexed link peer named in ``peers`` =
    ``{peer: (host, port)}``. With ``journal_dir`` every link is
    journaled and its TCP connection redials through peer restarts —
    rerunning this exact invocation after a crash resumes the matrix."""
    my_idx = plan.party_index(name)
    dial_peers, accept_peers, peer_of = {}, [], {}
    for p, q in plan.party_links(name):
        peer = q if p == name else p
        peer_of[(p, q)] = peer
        if plan.party_index(peer) > my_idx:
            dial_peers[peer] = (p, q)
        else:
            accept_peers.append(peer)
    broker = None
    srv = None
    if accept_peers:
        if listen is None:
            raise ValueError(f"party {name!r} is dialed by "
                             f"{accept_peers} and needs listen=(host, "
                             "port)")
        srv, bound = tcp_listen(listen[0], listen[1])
        broker = LinkBroker(srv, name, accept_peers)
        if on_listening is not None:
            on_listening(listen[0], bound)
    channels = {}
    links = []
    try:
        for peer, (p, q) in dial_peers.items():
            if peers is None or peer not in peers:
                raise ValueError(f"party {name!r} must dial {peer!r}; "
                                 "pass peers={...}")
            host, port = peers[peer]
            pair = (p, q)
            if journal_dir:
                jpath = os.path.join(
                    journal_dir,
                    f"journal.{name}.{plan.link_session(p, q)}.json")
                first = (None if os.path.exists(jpath) else dial_link(
                    host, port, name, pair,
                    timeout_s=connect_timeout_s))
                link = ReconnectingTcpLink(
                    lambda h=host, pt=port, pr=pair: dial_link(
                        h, pt, name, pr, timeout_s=5.0),
                    link=first, max_outage_s=connect_timeout_s)
            else:
                link = dial_link(host, port, name, pair,
                                 timeout_s=connect_timeout_s)
            links.append(link)
            channels[peer] = ReliableChannel(
                link, timeout_s=timeout_s, max_retries=max_retries,
                backoff_max_s=_backoff_max(timeout_s))
        for peer in accept_peers:
            pq = next(lk for lk, pr in peer_of.items() if pr == peer)
            if journal_dir:
                jpath = os.path.join(
                    journal_dir,
                    f"journal.{name}.{plan.link_session(*pq)}.json")
                first = (None if os.path.exists(jpath)
                         else broker.wait(peer, connect_timeout_s))
                link = ReconnectingTcpLink(
                    lambda pr=peer: broker.wait(pr, timeout_s=5.0),
                    link=first, max_outage_s=connect_timeout_s)
            else:
                link = broker.wait(peer, connect_timeout_s)
            links.append(link)
            channels[peer] = ReliableChannel(
                link, timeout_s=timeout_s, max_retries=max_retries,
                backoff_max_s=_backoff_max(timeout_s))
        transcripts, journals = _party_files(
            plan, name, peer_of, transcript_dir, journal_dir)
        party = FederationParty(
            name, plan, columns, ledger, channels, journals=journals,
            transcripts=transcripts, recv_timeout_s=recv_timeout_s,
            engine=engine, registry=registry, instance=instance)
        if on_party is not None:
            # the CLI's --obs-port endpoint wires its /stats snapshot
            # to the live party object through this hook
            on_party(party)
        return party.run()
    finally:
        for link in links:
            link.close()
        if broker is not None:
            broker.close()
