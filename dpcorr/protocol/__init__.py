"""Two-party protocol runtime (ISSUE 5): the privacy barrier as a wire.

The paper's deployment model is vertically partitioned — the X-party
and the Y-party each hold one column, and only DP releases may cross
between them — but the monolithic estimators compute with both columns
in one process, so that barrier existed only as prose. This package
makes it an execution mode: two role runtimes, a reliable message
channel, and a release gate that charges the privacy ledger before any
byte leaves the party.

Layering (each module depends only on the ones above it):

- :mod:`messages`  — versioned message schema, canonical deterministic
  serialization, array wire encoding, JSONL transcript log per party.
- :mod:`transport` — :class:`InProcTransport` (queue pair, tests) and
  TCP with length-prefixed framing; :class:`ReliableChannel` adds
  per-message timeout, bounded exponential-backoff retry, sequence
  numbers with idempotent redelivery, and pluggable fault injection.
- :mod:`gate`      — :class:`ReleaseGate`: ledger charge *before* send,
  refusal on budget exhaustion, refund on transport failure.
- :mod:`party`     — the X/Y role runtimes executing the NI and INT
  protocols for all four estimator families as genuine exchanges; each
  party constructs only its own column's releases
  (models.estimators.split_reference) and the finisher combines
  released quantities only.
- :mod:`runner`    — drive both roles in one process (threads over
  in-proc or loopback-TCP channels) for tests, benchmarks and
  ``python -m dpcorr protocol run``.
- :mod:`scan`      — offline transcript auditor: schema enforcement, the
  no-raw-columns proof, and the transcript↔audit-trail ε balance.

Protocol-mode estimates are **bit-identical** to the
``split_reference`` factoring (and, in replay key layout, to the
monolithic estimators) — pinned by tests/test_protocol.py. See
docs/PROTOCOL.md for roles, the message table and failure semantics.
"""

# Exports resolve lazily (PEP 562): the party/runner layer reaches the
# estimators (and therefore jax) at import time, but the scan layer must
# stay importable where jax isn't installed — the auditor runs where the
# estimators can't. An eager star-import here would weld them together.
_EXPORTS = {
    "FederationParty": "federation",
    "FederationResult": "federation",
    "LinkBroker": "federation",
    "dial_link": "federation",
    "make_federation_parties": "federation",
    "run_federation_inproc": "federation",
    "run_federation_tcp": "federation",
    "serve_federation_party": "federation",
    "ReleaseGate": "gate",
    "JournalError": "journal",
    "SessionJournal": "journal",
    "FederationPlan": "matrix",
    "PROTOCOL_VERSION": "messages",
    "Message": "messages",
    "Transcript": "messages",
    "canonical_encode": "messages",
    "decode_array": "messages",
    "encode_array": "messages",
    "read_transcript": "messages",
    "read_transcript_meta": "messages",
    "Party": "party",
    "ProtocolError": "party",
    "ProtocolRefused": "party",
    "ProtocolResult": "party",
    "ProtocolSpec": "party",
    "run_inproc": "runner",
    "run_tcp": "runner",
    "federation_balance": "scan",
    "ledger_balance": "scan",
    "scan_federation": "scan",
    "scan_transcript": "scan",
    "FaultInjector": "transport",
    "InProcTransport": "transport",
    "ReconnectingTcpLink": "transport",
    "ReliableChannel": "transport",
    "TransportError": "transport",
    "TransportTimeout": "transport",
    "tcp_connect": "transport",
    "tcp_listen": "transport",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        submodule = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    value = getattr(
        importlib.import_module(f"dpcorr.protocol.{submodule}"), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
