"""Offline transcript auditor: schema, no-raw-columns, ε balance.

A party's transcript (protocol.messages.Transcript) records the full
wire dict of every frame it sent or received, so the privacy claims of
a finished session are *checkable from the log alone*:

- :func:`scan_transcript` — the structural audit. Every wire object
  must parse as a versioned message from the closed vocabulary; array
  envelopes may appear **only** inside ``release`` payloads and must
  match the family's wire schema (kind, shape, dtype) derived from the
  session's own ``hello`` spec; value-level checks (sign releases take
  values only in {−1, 0, +1}) plus — when the caller supplies the raw
  columns — the no-raw-columns proof: no released array may reproduce a
  raw column (or its sign/clip image) beyond the exact-match rate DP
  noise permits.
- :func:`ledger_balance` — the accounting audit. Every gated send in
  the transcript (``eps > 0``) must match exactly one durable ``charge``
  event in the party's audit trail (same trace, same total ε) and vice
  versa, and replaying the trail must land on the same per-party totals
  — a release that crossed the wire without a durable charge, or a
  charge with no corresponding message, both surface as violations.

Deliberately jax-free (stdlib + numpy): the auditor must run where the
estimators can't, and must not share code paths with the thing it
audits. The wire schema is therefore *re-derived* here from the public
batch-geometry rule — test_protocol.py pins it equal to
``split_reference.release_schema`` so the two can never drift silently.
"""

from __future__ import annotations

import hashlib
import math

from dpcorr.obs.audit import replay
from dpcorr.obs.budget_replay import RESERVED_PREFIXES
from dpcorr.protocol.messages import (
    MSG_TYPES,
    PROTOCOL_VERSION,
    canonical_encode,
    decode_array,
    iter_arrays,
    read_transcript,
)

#: exact-match fraction a continuous-noise release may share with a raw
#: column: Laplace noise makes exact float equality measure-zero, so
#: anything above ~1% of entries means the "release" is raw data.
RAW_MATCH_MAX = 0.01

_SIGN_VALUES = (-1.0, -0.0, 0.0, 1.0)


def wire_schema(family: str, n: int, eps1: float, eps2: float) -> dict:
    """Pure-Python mirror of ``split_reference.release_schema`` (the
    batch-geometry rule ⌈8/(ε₁ε₂)⌉ capped at n; see module docstring
    for why this is re-derived rather than imported)."""
    kinds = {
        "ni_sign": ("batch_means", "noisy_sign_batch_means"),
        "ni_subg": ("batch_means", "noisy_clipped_batch_means"),
        "int_sign": ("flipped_signs", "rr_flipped_signs"),
        "int_subg": ("ldp_values", "ldp_clipped_values"),
    }
    if family not in kinds:
        raise ValueError(f"unknown family {family!r}")
    name, kind = kinds[family]
    if family in ("ni_sign", "ni_subg"):
        m = min(math.ceil(8.0 / (eps1 * eps2)), n)
        shape = (n // m,)
    else:
        shape = (n,)
    return {name: {"kind": kind, "shape": shape, "dtype": "float32"}}


def _violation(out: list, entry_idx: int, rule: str, detail: str) -> None:
    out.append({"entry": entry_idx, "rule": rule, "detail": detail})


def _spec_from_hello(entries: list[dict]) -> dict | None:
    for e in entries:
        w = e.get("wire", {})
        if w.get("msg_type") == "hello":
            return w.get("payload", {}).get("spec")
    return None


def _fed_from_hello(entries: list[dict]) -> dict | None:
    """The federation plan a pair-link transcript opened under (the
    link hello carries the full public plan, like the two-party hello
    carries the public spec)."""
    for e in entries:
        w = e.get("wire", {})
        if w.get("msg_type") == "hello":
            fed = w.get("payload", {}).get("fed")
            if isinstance(fed, dict):
                return fed
    return None


def _check_raw(viol: list, idx: int, rel, raws: dict) -> None:
    """The no-raw-columns proof against supplied raw columns. Shapes
    that cannot hold a column pass trivially; same-shape arrays must
    differ from the raw column (and its sign image) in all but a
    noise-consistent fraction of entries."""
    import numpy as np

    for col_name, raw in raws.items():
        raw = np.asarray(raw, dtype=np.float32)
        if rel.shape != raw.shape:
            continue
        frac = float(np.mean(rel == raw))
        if frac > RAW_MATCH_MAX:
            _violation(viol, idx, "raw-column-on-wire",
                       f"release matches raw {col_name} on "
                       f"{frac:.1%} of entries")
        # a sign image is raw data too: randomized response must have
        # flipped SOMETHING, and batch noise never reproduces it exactly
        if bool(np.array_equal(rel, np.sign(raw))):
            _violation(viol, idx, "raw-column-on-wire",
                       f"release equals sign({col_name}) exactly — "
                       "no randomization applied")


def _check_group(viol: list, idx: int, group, schema: dict, raws: dict,
                 where: str = "") -> None:
    """One release payload group (the whole payload of a two-party
    ``release``, or one labelled artifact of a federation round)
    against the family wire schema — keys, envelope, kind, shape,
    dtype, sign-value range, raw-column proof."""
    import numpy as np

    tag = f"{where}: " if where else ""
    if not isinstance(group, dict) or set(group) != set(schema):
        _violation(viol, idx, "schema-keys",
                   f"{tag}payload keys "
                   f"{sorted(group) if isinstance(group, dict) else group!r}"
                   f" != {sorted(schema)}")
        return
    for name, want in schema.items():
        env = group[name]
        if not (isinstance(env, dict) and env.get("__array__") == 1):
            _violation(viol, idx, "schema-envelope",
                       f"{tag}{name!r} is not an array envelope")
            continue
        if env.get("kind") != want["kind"]:
            _violation(viol, idx, "schema-kind",
                       f"{tag}{name!r} kind {env.get('kind')!r} != "
                       f"{want['kind']!r}")
        rel = decode_array(env)
        if tuple(rel.shape) != want["shape"] \
                or str(rel.dtype) != want["dtype"]:
            _violation(viol, idx, "schema-shape",
                       f"{tag}{name!r} is {rel.dtype}{rel.shape}, schema "
                       f"says {want['dtype']}{want['shape']}")
            continue
        if name == "flipped_signs":
            bad = ~np.isin(rel, np.asarray(_SIGN_VALUES, np.float32))
            if bool(bad.any()):
                _violation(viol, idx, "sign-values",
                           f"{tag}{int(bad.sum())} values outside "
                           "{-1, 0, +1}")
        _check_raw(viol, idx, rel, raws)


def scan_transcript(transcript, spec: dict | None = None,
                    raw_x=None, raw_y=None) -> dict:
    """Audit one party's transcript. ``transcript`` is a path or the
    entry list from :func:`~dpcorr.protocol.messages.read_transcript`;
    ``spec`` overrides the hello-embedded public spec (they are
    cross-checked when both exist). Federation pair-link transcripts
    (hello carries the public *plan*) validate each round's labelled
    artifact groups against the same family schema and flag
    ``"federation": True`` in the report. Returns ``{"ok",
    "violations", "messages", "releases", "gated_eps"}`` — never
    raises on content violations, only on an unreadable transcript."""
    entries = (read_transcript(transcript) if isinstance(transcript, str)
               else list(transcript))
    viol: list[dict] = []
    hello_spec = _spec_from_hello(entries)
    fed = _fed_from_hello(entries)
    if spec is not None and hello_spec is not None and spec != hello_spec:
        _violation(viol, -1, "spec-mismatch",
                   "supplied spec differs from the transcript's hello")
    eff = spec or hello_spec
    if eff is None and fed is not None:
        # a federation pair-link: every column shares the plan's one ε
        eff = {"family": fed["family"], "n": fed["n"],
               "eps1": fed["eps"], "eps2": fed["eps"]}
    schema = (wire_schema(eff["family"], int(eff["n"]),
                          float(eff["eps1"]), float(eff["eps2"]))
              if eff else None)
    raws = {}
    if raw_x is not None:
        raws["x"] = raw_x
    if raw_y is not None:
        raws["y"] = raw_y

    releases = 0
    gated_eps = 0.0
    seen_charge_ids: set = set()
    for idx, entry in enumerate(entries):
        w = entry["wire"]
        if w.get("version") != PROTOCOL_VERSION:
            _violation(viol, idx, "bad-version",
                       f"version {w.get('version')!r}")
            continue
        mtype = w.get("msg_type")
        if mtype not in MSG_TYPES:
            _violation(viol, idx, "unknown-type", f"msg_type {mtype!r}")
            continue
        payload = w.get("payload", {})
        arrays = list(iter_arrays(payload))
        if mtype != "release":
            if arrays:
                _violation(viol, idx, "array-outside-release",
                           f"{len(arrays)} array(s) in a {mtype} message")
            continue
        releases += 1
        if entry.get("dir") == "send":
            # a crash-resumed session may log the same gated send twice
            # (original + journal-replayed line); its charge_id is the
            # collapse key — ε was spent once, count it once
            cid = entry.get("charge_id")
            if cid is None or cid not in seen_charge_ids:
                gated_eps += float(entry.get("eps", 0.0))
                if cid is not None:
                    seen_charge_ids.add(cid)
        if schema is None:
            _violation(viol, idx, "no-spec",
                       "release before any hello spec; cannot validate")
            continue
        if fed is not None:
            # federation round envelope: arrays may appear only inside
            # the labelled artifact groups; each group is one column's
            # release and must satisfy the family schema exactly like a
            # two-party payload
            arts = payload.get("artifacts")
            if not isinstance(arts, dict):
                _violation(viol, idx, "fed-release-shape",
                           "round release carries no artifacts map")
                continue
            outside = list(iter_arrays(
                {k: v for k, v in payload.items() if k != "artifacts"}))
            if outside:
                _violation(viol, idx, "array-outside-artifacts",
                           f"{len(outside)} array(s) outside the "
                           "artifacts map")
            for lab in sorted(arts):
                _check_group(viol, idx, arts[lab], schema, raws,
                             where=f"artifact {lab!r}")
            continue
        _check_group(viol, idx, payload, schema, raws)

    out = {"ok": not viol, "violations": viol,
           "messages": len(entries), "releases": releases,
           "gated_eps": gated_eps}
    if fed is not None:
        out["federation"] = True
    return out


def ledger_balance(transcript, audit_events: list[dict]) -> dict:
    """Match every gated send in the transcript to exactly one durable
    ``charge`` event and vice versa (same trace ID, same total ε), and
    compare per-party replay totals. Refunded charges are excluded from
    the expected set — their release never counted. Returns ``{"ok",
    "unmatched_sends", "unmatched_charges", "spent"}``.

    Crash-resumed sessions balance through the ``charge_id`` lens, the
    audit walked chronologically exactly like the ledger walked it:
    only the first charge under a given id spends (later ones are the
    resumed session's idempotent re-runs — including a ``dedup`` event
    standing in for an original line lost between ledger persist and
    audit append); a refund forgets the id so a genuinely new charge
    may reuse it; transcript send lines sharing a charge_id (an
    original plus its journal-replayed duplicate) collapse to one.

    Reserved directory legs (``user/``, ``global/`` — serve.budget_dir)
    are bookkeeping principals, not wire spend: the transcript's ``eps``
    is party-leg-only by construction, so matching sums only the party
    legs of each event, and events consisting *only* of reserved legs
    (the directory's own per-user trail lines) are accounted by the
    replay but never expected to match a send."""
    entries = (read_transcript(transcript) if isinstance(transcript, str)
               else list(transcript))
    sends = []
    seen_cids: set = set()
    for e in entries:
        if e.get("dir") != "send" or float(e.get("eps", 0.0)) <= 0.0:
            continue
        cid = e.get("charge_id")
        if cid is not None:
            if cid in seen_cids:
                continue
            seen_cids.add(cid)
        sends.append(e)

    # chronological effective-charge set, mirroring the ledger's own
    # idempotency arithmetic (obs.audit._dedup_walk)
    applied: dict = {}     # charge_id -> its first (spending) event
    anon: list = []        # charges without an id (legacy / serve path)
    refunded_tids = set()  # refunds without an id match by trace_id
    for ev in audit_events:
        kind, cid = ev["kind"], ev.get("charge_id")
        if kind == "charge":
            if cid is not None:
                applied.setdefault(cid, ev)
            else:
                anon.append(ev)
        elif kind == "refund":
            if cid is not None:
                applied.pop(cid, None)
            else:
                refunded_tids.add(ev.get("trace_id"))
    def _party_eps(ev: dict) -> float:
        return sum(float(e) for p, e in ev["charges"].items()
                   if not p.startswith(RESERVED_PREFIXES))

    charges = [ev for ev in list(applied.values()) +
               [ev for ev in anon
                if ev.get("trace_id") not in refunded_tids]
               if _party_eps(ev) > 0.0]

    unmatched_sends = []
    pool = list(charges)
    for e in sends:
        eps = float(e.get("eps", 0.0))
        tid = e.get("trace_id")
        cid = e.get("charge_id")
        hit = None
        for ev in pool:
            if cid is not None:
                if ev.get("charge_id") == cid \
                        and abs(_party_eps(ev) - eps) < 1e-9:
                    hit = ev
                    break
            elif ev.get("trace_id") == tid \
                    and abs(_party_eps(ev) - eps) < 1e-9:
                hit = ev
                break
        if hit is None:
            unmatched_sends.append({"seq": e.get("seq"), "eps": eps,
                                    "trace_id": tid, "charge_id": cid})
        else:
            pool.remove(hit)
    unmatched_charges = [{"seq": ev.get("seq"),
                          "eps": _party_eps(ev),
                          "trace_id": ev.get("trace_id"),
                          "charge_id": ev.get("charge_id")}
                         for ev in pool]
    return {
        "ok": not unmatched_sends and not unmatched_charges,
        "unmatched_sends": unmatched_sends,
        "unmatched_charges": unmatched_charges,
        "spent": replay(audit_events),
    }


def scan_federation(transcripts) -> dict:
    """The cross-pair correlation-leak gate over a whole federation's
    pair-link transcripts (every party, every link).

    The federation's budget optimum rests on *reusing* a column's DP
    release across every pair that needs it: re-noising per pair would
    hand a curious observer k−1 independently-noised images of the same
    column (averaging them cancels the noise — a correlation leak the
    per-release ε accounting never sees). The wire-checkable form of
    that contract is **byte identity**: a given column label's release
    envelope must be the *identical bytes* in every transcript it
    appears in. Divergence names the offending pair sessions. The gate
    also refuses double-charging — an artifact whose label appears in
    more than one distinct round's ``charged`` list was paid for twice,
    which is an ε leak even when the bytes agree.

    ``transcripts`` is a list of paths or entry lists. Returns
    ``{"ok", "violations", "labels", "transcripts", "by_label",
    "charged"}`` — the last two are the gate's working evidence
    (per-label encoding variants with sha256 + sessions, and each
    side's charging venues), exported so the ε-provenance builder
    (:mod:`dpcorr.obs.provenance`) can upgrade this pass/fail gate
    into an explorable graph without re-walking the transcripts; the
    ``dpcorr federation scan`` CLI exits 1 on any violation."""
    by_label: dict = {}     # label -> {canonical bytes -> [session...]}
    charged_x: dict = {}    # label -> set of (session, round) charging it
    charged_y: dict = {}
    n = 0
    for t in transcripts:
        entries = (read_transcript(t) if isinstance(t, str) else list(t))
        n += 1
        for e in entries:
            w = e.get("wire", {})
            sess = w.get("session", "?")
            payload = w.get("payload", {})
            mtype = w.get("msg_type")
            if mtype == "release" and isinstance(
                    payload.get("artifacts"), dict):
                for lab, group in payload["artifacts"].items():
                    enc = canonical_encode(group) \
                        if isinstance(group, dict) else repr(group).encode()
                    by_label.setdefault(lab, {}).setdefault(
                        enc, set()).add(sess)
                for lab in payload.get("charged", ()):
                    charged_x.setdefault(lab, set()).add(
                        (sess, payload.get("round")))
            elif mtype == "result":
                for lab in payload.get("charged", ()):
                    charged_y.setdefault(lab, set()).add(
                        (sess, payload.get("round")))
    viol: list[dict] = []
    for lab, variants in sorted(by_label.items()):
        if len(variants) > 1:
            sessions = sorted(s for ss in variants.values() for s in ss)
            _violation(
                viol, -1, "cross-pair-release-divergence",
                f"column {lab!r} released as {len(variants)} distinct "
                f"byte encodings across pair sessions {sessions} — "
                "re-noised releases of one column are subtractable")
    for side, charged in (("x", charged_x), ("y", charged_y)):
        for lab, venues in sorted(charged.items()):
            if len(venues) > 1:
                _violation(
                    viol, -1, "double-charged-artifact",
                    f"({side}, {lab!r}) charged in {len(venues)} rounds "
                    f"{sorted(venues)} — the plan charges each artifact "
                    "exactly once")
    label_detail = {
        lab: [{"sha256": hashlib.sha256(enc).hexdigest(),
               "bytes": len(enc), "sessions": sorted(sessions)}
              for enc, sessions in sorted(
                  variants.items(),
                  key=lambda kv: sorted(kv[1]))]
        for lab, variants in sorted(by_label.items())}
    charged = {side: {lab: sorted(([s, r] for s, r in venues),
                                  key=lambda v: (str(v[0]), str(v[1])))
                      for lab, venues in sorted(ch.items())}
               for side, ch in (("x", charged_x), ("y", charged_y))}
    return {"ok": not viol, "violations": viol,
            "labels": sorted(by_label), "transcripts": n,
            "by_label": label_detail, "charged": charged}


def federation_balance(transcripts, audit_events: list[dict],
                       expected_local_eps: float = 0.0) -> dict:
    """One party's whole-matrix accounting audit: every gated send
    across *all* of its pair-link transcripts matches exactly one
    durable charge (:func:`ledger_balance` over the concatenated
    entries), and the only charges allowed to stand unmatched by any
    send are the party's local-cell charges (their plan-derived
    ``charge_id`` ends in ``":local"`` — local cells spend real ε with
    no wire message to pair it with), whose total must equal
    ``expected_local_eps`` (``FederationPlan.local_charges``)."""
    entries: list = []
    for t in transcripts:
        entries.extend(read_transcript(t) if isinstance(t, str)
                       else list(t))
    bal = ledger_balance(entries, audit_events)
    local, rest = [], []
    for c in bal["unmatched_charges"]:
        cid = str(c.get("charge_id") or "")
        (local if cid.endswith(":local") else rest).append(c)
    local_eps = sum(float(c["eps"]) for c in local)
    ok = (not bal["unmatched_sends"] and not rest
          and abs(local_eps - float(expected_local_eps)) < 1e-9)
    return {"ok": ok, "unmatched_sends": bal["unmatched_sends"],
            "unmatched_charges": rest, "local_eps": local_eps,
            "expected_local_eps": float(expected_local_eps),
            "spent": bal["spent"]}
