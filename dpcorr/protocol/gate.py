"""ReleaseGate: the ledger stands between computation and the wire.

Everything that carries a DP release out of a party goes through
:meth:`ReleaseGate.send_release`, and the ordering is the whole point:

1. ``ledger.charge`` first — all-or-nothing across the named parties,
   durably persisted before it returns (serve.ledger). If the budget is
   exhausted, :class:`~dpcorr.serve.ledger.BudgetExceededError`
   propagates and **no message is sent**: the peer learns nothing
   beyond the abort the party chooses to signal.
2. only then the channel send. If delivery *fails*
   (:class:`~dpcorr.protocol.transport.TransportError` after the retry
   budget), the charge is refunded — the release never reached anyone,
   so the ε was provably not consumed. Note the asymmetry with
   success-side accounting: an ack timeout where the peer actually got
   the frame still counts as failure and refunds, which errs toward
   *over*-refunding only when the peer is also crashing out of the
   protocol (it will not use a release from an aborted session); the
   ledger's own clamp keeps refunds from going negative.

The same charge-before-send / refund-on-refusal discipline the serve
admission path follows is enforced on this module by the budget lint
rule (analysis/rules/budget.py, extended to ``protocol/`` in this PR):
a release send not dominated by a gate charge is a lint error anywhere
in the package.
"""

from __future__ import annotations

from typing import Mapping

from dpcorr import chaos
from dpcorr.protocol.transport import ReliableChannel, TransportError
from dpcorr.serve.ledger import PrivacyLedger


class ReleaseGate:
    """Charges ``ledger`` before any gated send; refunds on transport
    failure. The party runtime holds its ledger only through this gate,
    so every path from estimator output to the wire passes here.

    ``ledger`` may be a plain :class:`PrivacyLedger` or a
    :class:`~dpcorr.serve.budget_dir.CompositeLedger` bound to a user:
    the gate always passes the *party* charges, and the composite
    derives its ``user/`` / ``global/`` legs inside the same
    ``charge``/``refund`` calls — so per-user accounting rides the
    gate's charge-before-send and refund-on-transport-failure
    discipline unchanged, and the receipt's ``eps`` (the transcript
    column) stays party-leg-only by construction.

    ``on_charge`` (optional) is called with the charge mapping after
    every *successful* charge leg — gated send delivered, local charge
    landed, replay charge landed — and never on the refund path. It is
    a telemetry observer (the federation party's ε-burn gauges hang
    here); observer failures are swallowed so metrics can never break
    the budget discipline they watch."""

    def __init__(self, ledger: PrivacyLedger, on_charge=None):
        self.ledger = ledger
        self._on_charge = on_charge

    def _observe(self, charges: Mapping[str, float]) -> None:
        if self._on_charge is None:
            return
        try:
            self._on_charge(dict(charges))
        except Exception:
            pass

    def send_release(self, channel: ReliableChannel, body: dict,
                     charges: Mapping[str, float],
                     trace_id: str | None = None,
                     charge_id: str | None = None,
                     seq: int | None = None) -> dict:
        """Charge, then send; returns the channel receipt augmented
        with the total ε charged (for the transcript's ``eps`` column).

        Raises ``BudgetExceededError`` (nothing sent, nothing spent)
        or ``TransportError`` (charge refunded).

        ``charge_id`` makes the charge leg idempotent (a crash-resumed
        session re-runs this whole sequence; the ledger spends the id
        once) and ``seq`` pins a journal-replayed send to its original
        wire sequence. Both default off, preserving the pre-journal
        call shape — including for channel test doubles that only
        implement ``send(body)``."""
        self.ledger.charge(charges, trace_id=trace_id, charge_id=charge_id)
        chaos.point("gate.post_charge")
        try:
            if seq is None:
                receipt = channel.send(body)
            else:
                receipt = channel.send(body, seq=seq)
        except TransportError:
            self.ledger.refund(charges, trace_id=trace_id,
                               charge_id=charge_id)
            raise
        chaos.point("gate.post_send")
        receipt["eps"] = float(sum(charges.values()))
        self._observe(charges)
        return receipt

    def charge_local(self, charges: Mapping[str, float],
                     trace_id: str | None = None,
                     charge_id: str | None = None) -> float:
        """Charge for releases that never cross a wire: a federation
        party's *local* cells (both columns its own) still run the DP
        split estimator, so the ε is real spend even though there is no
        send to gate. The idempotent ``charge_id`` carries the
        exactly-once contract across crash/resume — a resumed matrix
        re-runs its local cells bit-identically but the ledger spends
        the id once. Returns the total ε charged."""
        self.ledger.charge(charges, trace_id=trace_id,
                           charge_id=charge_id)
        self._observe(charges)
        return float(sum(charges.values()))

    def charge_replayed(self, charges: Mapping[str, float],
                        trace_id: str | None = None,
                        charge_id: str | None = None) -> None:
        """The charge leg alone, for journal-replay slots whose
        delivery is already established (the peer finished and left —
        party.py peer-gone path): the ε must still land exactly once,
        which the idempotent ``charge_id`` guarantees, but there is no
        wire send to pair it with and no failure that could justify a
        refund."""
        self.ledger.charge(charges, trace_id=trace_id,
                           charge_id=charge_id)
        self._observe(charges)
