"""Transports and the reliable channel: framing, retry, dedupe, chaos.

Two links with one contract (``send_bytes``/``recv_bytes`` with a
deadline): :class:`InProcTransport` is a queue pair for tests and the
single-command runner; TCP frames each payload with a 4-byte big-endian
length prefix over a loopback/remote socket (``tcp_listen`` /
``tcp_connect``). Neither link is reliable from the protocol's point of
view — the chaos layer can drop, delay or duplicate any outbound frame
— so reliability lives one layer up:

:class:`ReliableChannel` implements at-least-once delivery with
receiver-side dedupe, which composes to exactly-once *processing*:

- every application message gets a monotonically increasing sequence
  number and is retransmitted on an exponential backoff schedule until
  the matching ack arrives or the retry budget is exhausted
  (:class:`TransportError` — the caller's signal to refund);
- the receiver acks *every* delivery, including duplicates (the ack
  itself may have been the dropped frame), but hands each sequence
  number to the application at most once. Idempotent redelivery is
  therefore a transport property; parties never see duplicates.

Fault injection (:class:`FaultInjector`) sits on the *outbound* edge of
both messages and acks, driven by its own seeded ``random.Random`` —
chaos runs are reproducible and the jax key-tree is untouched (faults
must never perturb estimator noise, that would break the bit-identity
acceptance under fault injection).

Single-owner discipline: a channel is used by one party thread; locks
live in the queue/socket primitives underneath.
"""

from __future__ import annotations

import json
import queue
import random
import socket
import struct
import time


class TransportError(Exception):
    """Delivery gave up: timeout with retry budget exhausted, peer
    closed, or malformed frame. The gate refunds on this."""


class TransportTimeout(TransportError):
    """Nothing arrived within the window — the link itself is (as far
    as we know) healthy. Distinguished from its base class because the
    reconnecting link must NOT tear down a socket over mere idleness:
    only hard failures (reset, EOF, refused) justify a redial."""


class SessionResumeRefused(TransportError):
    """The peer explicitly rejected a session re-attach (session or
    token mismatch). Distinct from silence — an unanswered resume may
    just mean the peer already finished and left, which the party
    runtime tolerates; a refusal is a configuration error and must
    never be downgraded to peer-gone replay."""


class FaultInjector:
    """Deterministic outbound chaos: drop / delay / duplicate.

    ``drop``/``duplicate`` are per-frame probabilities, ``delay_s`` a
    fixed pre-send sleep applied with probability ``delay_rate``
    (default: every frame when ``delay_s > 0``). Uses stdlib
    ``random.Random(seed)``: reproducible, and independent of the jax
    key-tree by construction.
    """

    def __init__(self, drop: float = 0.0, delay_s: float = 0.0,
                 duplicate: float = 0.0, delay_rate: float = 1.0,
                 seed: int = 0):
        for name, p in (("drop", drop), ("duplicate", duplicate),
                        ("delay_rate", delay_rate)):
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {p}")
        self.drop = drop
        self.delay_s = delay_s
        self.duplicate = duplicate
        self.delay_rate = delay_rate
        self._rng = random.Random(seed)
        self.dropped = 0
        self.delayed = 0
        self.duplicated = 0

    def plan(self) -> tuple[int, float]:
        """(copies_to_send, pre_send_delay_s) for one outbound frame.
        0 copies = dropped, 2 = duplicated."""
        copies = 1
        if self.drop and self._rng.random() < self.drop:
            self.dropped += 1
            copies = 0
        elif self.duplicate and self._rng.random() < self.duplicate:
            self.duplicated += 1
            copies = 2
        delay = 0.0
        if self.delay_s and copies and self._rng.random() < self.delay_rate:
            self.delayed += 1
            delay = self.delay_s
        return copies, delay

    def stats(self) -> dict:
        return {"dropped": self.dropped, "delayed": self.delayed,
                "duplicated": self.duplicated}


# ------------------------------------------------------------ in-proc ----
class _QueueLink:
    """One direction-pair endpoint over two queues."""

    def __init__(self, out_q: "queue.Queue[bytes]",
                 in_q: "queue.Queue[bytes]"):
        self._out = out_q
        self._in = in_q

    def send_bytes(self, data: bytes) -> None:
        self._out.put(data)

    def recv_bytes(self, timeout_s: float) -> bytes:
        try:
            return self._in.get(timeout=timeout_s)
        except queue.Empty:
            raise TransportTimeout(
                f"in-proc recv timed out after {timeout_s:.3g}s") from None

    def close(self) -> None:
        pass


class InProcTransport:
    """A connected pair of queue links (``.a`` ↔ ``.b``) for two
    parties in one process — the test/runner transport."""

    def __init__(self):
        qa: queue.Queue[bytes] = queue.Queue()
        qb: queue.Queue[bytes] = queue.Queue()
        self.a = _QueueLink(qa, qb)
        self.b = _QueueLink(qb, qa)


# ---------------------------------------------------------------- tcp ----
_LEN = struct.Struct(">I")
_MAX_FRAME = 64 * 1024 * 1024  # sanity bound; a release is << this


class TcpLink:
    """Length-prefixed framing over one connected socket: 4-byte BE
    payload length then the payload.

    Partial reads are buffered *across calls*: a recv timeout mid-frame
    must keep the bytes already read, or the next call would interpret
    payload bytes as a length prefix and the stream would desynchronize
    permanently — under retransmission-heavy chaos a timeout landing
    mid-frame is the common case, not the corner."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = bytearray()  # partial-frame carry-over between calls
        try:
            self.peer = "%s:%s" % self._sock.getpeername()[:2]
        except OSError:
            self.peer = "<unknown peer>"

    def send_bytes(self, data: bytes) -> None:
        try:
            self._sock.sendall(_LEN.pack(len(data)) + data)
        except OSError as e:
            raise TransportError(
                f"tcp send to {self.peer} failed: {e}") from e

    def _fill(self, need: int, deadline: float) -> None:
        """Grow the buffer to ``need`` bytes; on timeout the buffer
        keeps whatever arrived (frame reassembly resumes next call)."""
        while len(self._buf) < need:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportTimeout(f"tcp recv from {self.peer} timed out")
            self._sock.settimeout(remaining)
            try:
                chunk = self._sock.recv(65536)
            except socket.timeout:
                raise TransportTimeout(
                    f"tcp recv from {self.peer} timed out") from None
            except OSError as e:
                raise TransportError(
                    f"tcp recv from {self.peer} failed: {e}") from e
            if not chunk:
                # EOF mid-frame is a *short read* — the peer died (or
                # reset) partway through a handshake or message, a hard
                # failure, never a timeout
                raise TransportError(
                    f"peer {self.peer} closed connection"
                    + (" mid-frame" if self._buf else ""))
            self._buf.extend(chunk)

    def recv_bytes(self, timeout_s: float) -> bytes:
        deadline = time.monotonic() + timeout_s
        self._fill(_LEN.size, deadline)
        (n,) = _LEN.unpack(self._buf[:_LEN.size])
        if n > _MAX_FRAME:
            raise TransportError(f"frame length {n} exceeds bound")
        self._fill(_LEN.size + n, deadline)
        data = bytes(self._buf[_LEN.size:_LEN.size + n])
        del self._buf[:_LEN.size + n]
        return data

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def tcp_listen(host: str = "127.0.0.1", port: int = 0):
    """Bind a listener; returns ``(server_socket, bound_port)``. Port 0
    picks an ephemeral port — the runner/tests read it back."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(1)
    return srv, srv.getsockname()[1]


def tcp_accept(srv: socket.socket, timeout_s: float = 30.0) -> TcpLink:
    srv.settimeout(timeout_s)
    try:
        sock, _ = srv.accept()
    except socket.timeout:
        raise TransportTimeout(
            f"no peer connected within {timeout_s:.3g}s") from None
    return TcpLink(sock)


def tcp_connect(host: str, port: int, timeout_s: float = 30.0) -> TcpLink:
    """Connect with exponential-backoff retry until ``timeout_s``.

    Retries only the failures that mean "not up *yet*": refused /
    reset / aborted (the listener hasn't bound, or is restarting after
    a crash) and connect timeouts. Anything else — unroutable host,
    permission denied, bad address — fails immediately as a typed
    :class:`TransportError` naming the peer, because no amount of
    waiting fixes it and a silent retry loop would just burn the
    deadline before reporting the same error less clearly."""
    deadline = time.monotonic() + timeout_s
    delay = 0.05
    while True:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            return TcpLink(sock)
        except (ConnectionError, socket.timeout, TimeoutError) as e:
            if time.monotonic() >= deadline:
                raise TransportTimeout(
                    f"could not connect to {host}:{port} within "
                    f"{timeout_s:.3g}s: {e}") from e
            time.sleep(delay)
            delay = min(delay * 2.0, 1.0)
        except OSError as e:
            raise TransportError(
                f"connect to {host}:{port} failed: {e}") from e


class ReconnectingTcpLink:
    """A link that survives its socket: on a *hard* failure (reset,
    EOF, refused) it closes the broken socket and redials, surfacing
    the gap to the :class:`ReliableChannel` as :class:`TransportTimeout`
    — which the channel already treats as "retransmit later". Timeouts
    pass through untouched (an idle peer is not a dead peer).

    ``dial`` is role-appropriate: the connecting side passes a
    ``tcp_connect`` closure, the listening side a ``tcp_accept`` closure
    over its still-open server socket. Each successful redial yields a
    *fresh* :class:`TcpLink`, which deliberately discards any partial
    frame buffered from the dead socket: frames are single ``sendall``
    calls, so a new connection always starts at a frame boundary.

    ``max_outage_s`` bounds how long the link keeps trying before a
    hard :class:`TransportError` escapes (the caller's refund path);
    the outage clock starts at the first failure and resets on any
    successful redial.
    """

    def __init__(self, dial, link: TcpLink | None = None,
                 max_outage_s: float = 30.0,
                 backoff_base_s: float = 0.05):
        self._dial = dial
        self._link = link
        self.max_outage_s = max_outage_s
        self.backoff_base_s = backoff_base_s
        self._outage_since: float | None = None
        self.reconnects = 0

    @property
    def peer(self) -> str:
        return self._link.peer if self._link is not None else "<disconnected>"

    def _mark_down(self, cause: Exception) -> None:
        if self._link is not None:
            self._link.close()
            self._link = None
        now = time.monotonic()
        if self._outage_since is None:
            self._outage_since = now
        if now - self._outage_since > self.max_outage_s:
            raise TransportError(
                f"link down for over {self.max_outage_s:.3g}s "
                f"(last error: {cause})") from cause

    def _ensure(self, deadline: float) -> TcpLink:
        """Redial until connected, ``deadline`` or the outage budget —
        whichever lands first wins."""
        delay = self.backoff_base_s
        while self._link is None:
            now = time.monotonic()
            if self._outage_since is not None \
                    and now - self._outage_since > self.max_outage_s:
                raise TransportError(
                    f"link down for over {self.max_outage_s:.3g}s")
            if now >= deadline:
                raise TransportTimeout("reconnect still pending")
            try:
                self._link = self._dial()
                self.reconnects += 1
                self._outage_since = None
            except TransportError:
                time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
                delay = min(delay * 2.0, 1.0)
        return self._link

    def send_bytes(self, data: bytes) -> None:
        """Best-effort: a frame lost to a dying socket is simply not
        acked, and the channel's retransmit loop re-sends it — exactly
        the at-least-once contract. Only an exhausted outage budget
        escapes."""
        if self._link is None:
            try:
                self._ensure(time.monotonic() + self.backoff_base_s)
            except TransportTimeout:
                return  # still down; the retransmit loop will be back
        try:
            self._link.send_bytes(data)
        except TransportError as e:
            self._mark_down(e)

    def recv_bytes(self, timeout_s: float) -> bytes:
        deadline = time.monotonic() + timeout_s
        while True:
            link = self._ensure(deadline)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportTimeout("recv window exhausted mid-reconnect")
            try:
                return link.recv_bytes(remaining)
            except TransportTimeout:
                raise
            except TransportError as e:
                self._mark_down(e)

    def close(self) -> None:
        if self._link is not None:
            self._link.close()
            self._link = None


# ---------------------------------------------------- reliable channel ----
class ReliableChannel:
    """At-least-once frames + receive dedupe = exactly-once processing.

    ``send`` blocks until the peer acks (retransmitting on exponential
    backoff) and returns a receipt ``{seq, retries, latency_s, bytes}``
    for the transcript; ``recv`` blocks until the next *new* message
    arrives, transparently re-acking duplicates. Frames are
    ``{"kind": "msg"|"ack", "seq": int, "body": ...}`` in the canonical
    encoding, plus the crash-resume pair ``{"kind": "resume", "session",
    "token"}`` / ``{"kind": "resume_ack", "ok"}``. One owner thread per
    channel.

    Crash-resume support (used by the durable session journal):

    - ``on_deliver(seq, body)`` fires for each NEW inbound message
      *before* its ack goes out, so a journaling receiver is durable
      before the sender stops retransmitting — an ack can never outrun
      the journal.
    - ``restore(send_seq, delivered)`` reloads the dedupe state a
      journal preserved; ``send(body, seq=...)`` pins a replayed
      message to its original seq so the peer's dedupe set recognises
      it across the crash.
    - ``resume(session, token)`` is the restarted side's re-attach
      handshake; the surviving side answers from wherever it happens to
      be blocked (send/recv/drain all route frames through one
      dispatcher) after the owning party has set ``session_info``.
    """

    def __init__(self, link, timeout_s: float = 5.0, max_retries: int = 8,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 2.0,
                 fault: FaultInjector | None = None, on_deliver=None):
        self._link = link
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.fault = fault
        self.on_deliver = on_deliver
        self.session_info: dict | None = None  # {"session","token"}
        self._resume_ok: bool | None = None
        self.peer_resumed = False  # we acked a peer's re-attach
        self._send_seq = 0
        self._acked: set[int] = set()       # acks seen (may arrive early)
        self._delivered: set[int] = set()   # peer seqs handed up already
        self._ready: list[dict] = []        # new msgs seen while awaiting ack
        self.sent_msgs = 0
        self.total_retries = 0

    def restore(self, send_seq: int, delivered: set[int]) -> None:
        """Reload journal-preserved channel state after a restart: the
        next auto-assigned outbound seq continues after ``send_seq``,
        and every journaled inbound seq is pre-marked delivered so the
        peer's retransmits are re-acked but never handed up twice."""
        self._send_seq = int(send_seq)
        self._delivered = set(delivered)

    # -- outbound edge (messages AND acks pass through the chaos layer) --
    def _put(self, frame: dict) -> None:
        data = json.dumps(frame, sort_keys=True,
                          separators=(",", ":")).encode("utf-8")
        copies, delay = (self.fault.plan() if self.fault is not None
                         else (1, 0.0))
        if delay:
            time.sleep(delay)
        for _ in range(copies):
            self._link.send_bytes(data)

    def _ack(self, seq: int) -> None:
        self._put({"kind": "ack", "seq": seq})

    def _take(self, timeout_s: float) -> dict:
        data = self._link.recv_bytes(timeout_s)
        try:
            frame = json.loads(data.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise TransportError(f"malformed frame: {e}") from e
        if not isinstance(frame, dict) or "kind" not in frame:
            raise TransportError("malformed frame: missing kind")
        if frame["kind"] in ("msg", "ack") and "seq" not in frame:
            raise TransportError("malformed frame: missing seq")
        return frame

    def _dispatch(self, frame: dict) -> None:
        """Route one inbound frame, whatever loop we happen to be in —
        send, recv, drain and resume all funnel through here so a
        surviving party answers a peer's resume handshake from wherever
        it is blocked. Unknown kinds are ignored (forward compat)."""
        kind = frame["kind"]
        if kind == "ack":
            self._acked.add(int(frame["seq"]))
        elif kind == "msg":
            self._admit(frame)
        elif kind == "resume":
            self._answer_resume(frame)
        elif kind == "resume_ack":
            self._resume_ok = bool(frame.get("ok", False))

    def _answer_resume(self, frame: dict) -> None:
        """Validate a peer's re-attach request against the session the
        owning party registered. No ``session_info`` yet → stay silent
        (the initiator keeps retrying); wrong session/token → explicit
        refusal, the initiator must not replay into the wrong session."""
        info = self.session_info
        if info is None:
            return
        ok = (frame.get("session") == info.get("session")
              and frame.get("token") == info.get("token"))
        if ok:
            # the restarted peer is about to replay its unacked sends;
            # the owning party must linger past its own completion so
            # those replays get re-acked (party._linger keys on this)
            self.peer_resumed = True
        self._put({"kind": "resume_ack", "ok": ok,
                   "session": info.get("session")})

    def _admit(self, frame: dict) -> None:
        """Handle one inbound msg frame: journal NEW messages durably
        (``on_deliver``) *before* the ack goes out — once acked, the
        peer stops retransmitting, so durability must come first — then
        always (re-)ack, since the previous ack may be the frame chaos
        dropped; enqueue the body at most once."""
        seq = int(frame["seq"])
        if seq not in self._delivered:
            if self.on_deliver is not None:
                self.on_deliver(seq, frame.get("body"))
            self._delivered.add(seq)
            self._ready.append({"seq": seq, "body": frame.get("body")})
        self._ack(seq)

    def send(self, body: dict, seq: int | None = None) -> dict:
        """Deliver ``body`` reliably; returns the transcript receipt.
        Raises :class:`TransportError` once ``max_retries``
        retransmissions all miss their ack window.

        ``seq`` pins a replayed message to its journaled sequence
        number (crash resume); new messages leave it unset and take the
        next auto-incremented seq."""
        if seq is None:
            self._send_seq += 1
            seq = self._send_seq
        else:
            self._send_seq = max(self._send_seq, seq)
        frame = {"kind": "msg", "seq": seq, "body": body}
        n_bytes = len(json.dumps(frame, sort_keys=True,
                                 separators=(",", ":")).encode("utf-8"))
        t0 = time.perf_counter()
        for attempt in range(self.max_retries + 1):
            self._put(frame)
            deadline = time.monotonic() + min(
                self.backoff_base_s * (2.0 ** attempt), self.backoff_max_s)
            while True:
                if seq in self._acked:
                    self._acked.discard(seq)
                    self.sent_msgs += 1
                    self.total_retries += attempt
                    return {"seq": seq, "retries": attempt,
                            "latency_s": time.perf_counter() - t0,
                            "bytes": n_bytes}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # retransmit
                try:
                    got = self._take(remaining)
                except TransportTimeout:
                    break  # timeout inside this attempt's window
                self._dispatch(got)  # ack, or peer traffic crossing ours
        raise TransportError(
            f"message seq={seq} unacknowledged after "
            f"{self.max_retries + 1} attempts")

    def resume(self, session: str, token: str,
               timeout_s: float | None = None,
               max_wait_s: float | None = None) -> None:
        """Re-attach a restarted party: retransmit the resume frame
        until the survivor acknowledges (or refuses) it. Runs *before*
        any journal replay — a replayed release must not race the
        peer's recognition of who is talking.

        ``max_wait_s`` bounds the whole exchange rather than each
        attempt: a peer that legitimately finished and exited will
        never answer, and the caller needs a deadline after which it
        can fall back to completing from its journal alone
        (party._attach_journal's peer-gone path)."""
        self._resume_ok = None
        frame = {"kind": "resume", "session": session, "token": token}
        per_attempt = timeout_s if timeout_s is not None else self.timeout_s
        overall = (None if max_wait_s is None
                   else time.monotonic() + max_wait_s)
        for attempt in range(self.max_retries + 1):
            if overall is not None and time.monotonic() >= overall:
                break
            self._put(frame)
            deadline = time.monotonic() + max(
                per_attempt,
                min(self.backoff_base_s * (2.0 ** attempt),
                    self.backoff_max_s))
            if overall is not None:
                deadline = min(deadline, overall)
            while self._resume_ok is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    got = self._take(remaining)
                except TransportTimeout:
                    break
                self._dispatch(got)
            if self._resume_ok is False:
                raise SessionResumeRefused(
                    f"peer refused session resume for {session!r} "
                    "(session/token mismatch)")
            if self._resume_ok:
                return
        raise TransportError(
            f"session resume for {session!r} unanswered "
            + (f"after {max_wait_s:.1f}s" if max_wait_s is not None
               else f"after {self.max_retries + 1} attempts"))

    def recv(self, timeout_s: float | None = None) -> dict:
        """Next new message ``{"seq": int, "body": dict}`` — duplicates
        re-acked and filtered here, stray acks absorbed."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.timeout_s)
        while True:
            if self._ready:
                return self._ready.pop(0)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportTimeout("recv timed out awaiting message")
            got = self._take(remaining)
            self._dispatch(got)

    def drain(self, idle_s: float | None = None,
              max_s: float | None = None) -> None:
        """Linger after the conversation's last inbound message: keep
        re-acking retransmissions until the link stays quiet for
        ``idle_s`` (bounded by ``max_s``). Without this, the party that
        receives the session's final message can exit while its ack is
        still the frame chaos dropped — the peer then retransmits into
        a closed conversation and its send fails spuriously (the
        two-generals tail; a linger window is the standard answer).

        The defaults derive from this channel's own retry config (the
        two ends are configured symmetrically): the idle window must
        exceed the peer's worst inter-retransmit gap — one full ack
        wait plus one maxed backoff — or the drain gives up between two
        of the peer's late-backoff attempts and it strands exactly the
        sends it exists to save; ``max_s`` covers the peer's entire
        retry span so the linger can outlive a worst-case sequence of
        dropped acks."""
        gap = self.timeout_s + self.backoff_max_s
        if idle_s is None:
            idle_s = gap + 0.25
        if max_s is None:
            max_s = (self.max_retries + 1) * gap
        deadline = time.monotonic() + max_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            try:
                got = self._take(min(idle_s, remaining))
            except TransportError:
                return
            self._dispatch(got)

    def close(self) -> None:
        self._link.close()
