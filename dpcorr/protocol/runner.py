"""Drive both protocol roles from one process (tests, benchmarks, CLI).

The genuine deployment is two processes (``python -m dpcorr party``
twice); this module runs the same :class:`~dpcorr.protocol.party.Party`
code on two threads over either transport, which is what the
bit-identity tests, the chaos benchmark and the single-command
``python -m dpcorr protocol run`` use. Each party still gets its *own*
ledger, transcript and channel endpoint — nothing is shared except the
wire — so the in-process mode exercises the identical code paths the
two-process mode does, TCP handshake included.
"""

from __future__ import annotations

import os
import threading

from dpcorr.protocol.messages import Transcript
from dpcorr.protocol.party import Party, ProtocolResult, ProtocolSpec
from dpcorr.protocol.transport import (
    FaultInjector,
    InProcTransport,
    ReliableChannel,
    tcp_accept,
    tcp_connect,
    tcp_listen,
)
from dpcorr.serve.ledger import PrivacyLedger

#: Default per-party budget when the caller doesn't bring a ledger —
#: high enough that single-session runs never refuse by accident, real
#: deployments pass their own persistent ledgers.
DEFAULT_BUDGET = 1e6


def _mk_fault(fault: dict | None, default_seed: int) -> FaultInjector | None:
    """Build one side's injector from a shared fault spec; each side
    gets a distinct stdlib-RNG seed so their chaos is independent. A
    caller-supplied base seed (``fault["seed"]``, the CLI's
    ``--fault-seed``) is folded with the per-side default so one knob
    reproduces *both* sides' fault sequences."""
    if not fault:
        return None
    base = fault.get("seed")
    seed = default_seed if base is None \
        else int(base) * 1000003 + default_seed
    return FaultInjector(drop=fault.get("drop", 0.0),
                         delay_s=fault.get("delay_s", 0.0),
                         duplicate=fault.get("duplicate", 0.0),
                         delay_rate=fault.get("delay_rate", 1.0),
                         seed=seed)


def _transcript(transcript_dir: str | None, spec: ProtocolSpec,
                role: str) -> Transcript:
    if not transcript_dir:
        return Transcript(None)
    return Transcript(os.path.join(
        transcript_dir, f"{spec.session}.{role}.jsonl"))


def _run_pair(party_x: Party, party_y: Party) -> dict:
    """Run both parties to completion on two threads; re-raises the
    first party error (protocol refusals included) after both joined."""
    results: dict[str, ProtocolResult] = {}
    errors: dict[str, BaseException] = {}

    def drive(party: Party) -> None:
        try:
            results[party.role] = party.run()
        except BaseException as e:  # captured for the joining thread
            errors[party.role] = e

    threads = [threading.Thread(target=drive, args=(p,),
                                name=f"party-{p.role}")
               for p in (party_x, party_y)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        role = "x" if "x" in errors else "y"
        raise errors[role]
    return results


def _make_parties(spec: ProtocolSpec, x, y, link_x, link_y,
                  ledger_x, ledger_y, fault, transcript_dir,
                  timeout_s, max_retries) -> tuple[Party, Party]:
    # scale the backoff ceiling with the ack window: short-timeout
    # chaos runs then retransmit (and drain-linger, transport.drain)
    # on a proportionally short cadence instead of parking for the
    # full 2 s default between late attempts
    backoff_max = min(2.0, max(2.0 * timeout_s, 0.1))
    chan_x = ReliableChannel(link_x, timeout_s=timeout_s,
                             max_retries=max_retries,
                             backoff_max_s=backoff_max,
                             fault=_mk_fault(fault, default_seed=11))
    chan_y = ReliableChannel(link_y, timeout_s=timeout_s,
                             max_retries=max_retries,
                             backoff_max_s=backoff_max,
                             fault=_mk_fault(fault, default_seed=23))
    ledger_x = ledger_x or PrivacyLedger(DEFAULT_BUDGET)
    ledger_y = ledger_y or PrivacyLedger(DEFAULT_BUDGET)
    tx = _transcript(transcript_dir, spec, "x")
    ty = _transcript(transcript_dir, spec, "y")
    if fault:
        # reproducibility-from-the-artifact: a chaos failure's fault
        # config (seed included) is in the transcript header itself
        header = {"fault": {k: v for k, v in fault.items()},
                  "session": spec.session}
        tx.meta(**header)
        ty.meta(**header)
    px = Party("x", x, spec, chan_x, ledger_x, transcript=tx)
    py = Party("y", y, spec, chan_y, ledger_y, transcript=ty)
    return px, py


def run_inproc(spec: ProtocolSpec, x, y, *,
               ledger_x: PrivacyLedger | None = None,
               ledger_y: PrivacyLedger | None = None,
               fault: dict | None = None,
               transcript_dir: str | None = None,
               timeout_s: float = 10.0,
               max_retries: int = 10) -> dict:
    """Both roles over the queue-pair transport. Returns
    ``{"x": ProtocolResult, "y": ProtocolResult}``."""
    pair = InProcTransport()
    px, py = _make_parties(spec, x, y, pair.a, pair.b, ledger_x,
                           ledger_y, fault, transcript_dir, timeout_s,
                           max_retries)
    return _run_pair(px, py)


def run_tcp(spec: ProtocolSpec, x, y, *, host: str = "127.0.0.1",
            port: int = 0,
            ledger_x: PrivacyLedger | None = None,
            ledger_y: PrivacyLedger | None = None,
            fault: dict | None = None,
            transcript_dir: str | None = None,
            timeout_s: float = 10.0,
            max_retries: int = 10) -> dict:
    """Both roles over a real loopback TCP socket (length-prefixed
    frames, full handshake). ``port=0`` picks an ephemeral port."""
    srv, bound = tcp_listen(host, port)
    links: dict[str, object] = {}

    def accept() -> None:
        links["y"] = tcp_accept(srv, timeout_s=max(timeout_s, 30.0))

    acceptor = threading.Thread(target=accept, name="tcp-accept")
    acceptor.start()
    links["x"] = tcp_connect(host, bound, timeout_s=max(timeout_s, 30.0))
    acceptor.join()
    srv.close()
    px, py = _make_parties(spec, x, y, links["x"], links["y"], ledger_x,
                           ledger_y, fault, transcript_dir, timeout_s,
                           max_retries)
    try:
        return _run_pair(px, py)
    finally:
        links["x"].close()
        links["y"].close()
