"""PartyX/PartyY role runtimes: the estimator protocols as messages.

One :class:`Party` instance is one side of one protocol session. It
holds exactly one raw column, a reliable channel to the peer, and a
:class:`~dpcorr.protocol.gate.ReleaseGate` wrapping its privacy ledger
— the ledger is reachable *only* through the gate, so there is no code
path from this module to the wire that skips the charge.

Session shape (see docs/PROTOCOL.md for the full table):

1. ``hello`` / ``hello_ack`` — X sends the spec hash (and the public
   spec for operator sanity), Y refuses the session unless the hash
   matches its own spec byte-for-byte. No ε is spent before this pins
   that both sides agree on family, n, ε's, seed and key layout.
2. ``release`` — the releasing role (split_reference.split_roles: the
   x-side for NI, the larger-ε side for INT) computes its column's DP
   release and sends it through the gate (charge → send → refund on
   transport failure).
3. ``result`` — the finishing role validates the payload against the
   family's release schema, combines it with its *own* column's
   contribution (models.estimators.split_reference.finish — spending
   its own ε, also gated), and returns (ρ̂, CI) to the peer.
4. ``error`` — either side aborts (budget refusal, validation failure);
   carries a reason string, never arrays, and is deliberately ungated.

Noise keys come from ``utils.rng.party_root``: ``"replay"`` reproduces
the monolithic stream addresses (bit-identity acceptance), and
``"hardened"`` roots each party in its disjoint ``"protocol/x"`` /
``"protocol/y"`` subtree. Tracing: X opens the session's root span and
its context rides the ``hello`` headers (obs.wire_headers), so Y's
spans — in another process — join the same trace ID.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from dpcorr import chaos
from dpcorr.obs import from_wire_headers, tracer, wire_headers
from dpcorr.obs import recorder as obs_recorder
from dpcorr.protocol.gate import ReleaseGate
from dpcorr.protocol.journal import SessionJournal
from dpcorr.protocol.messages import (
    Message,
    Transcript,
    canonical_encode,
    decode_array,
    encode_array,
)
from dpcorr.protocol.transport import (
    ReliableChannel,
    SessionResumeRefused,
    TransportError,
)
from dpcorr.serve.ledger import (
    BudgetExceededError,
    PrivacyLedger,
    release_factor,
)


class ProtocolError(Exception):
    """Protocol violation: bad spec hash, malformed payload, unexpected
    message type. Not a budget event."""


class ProtocolRefused(Exception):
    """The session aborted on a budget refusal — locally (our ledger
    refused a gated send; nothing was sent) or remotely (the peer sent
    ``error`` with kind ``budget``)."""


@dataclass(frozen=True)
class ProtocolSpec:
    """The public design point both parties must agree on before any ε
    is spent. Everything here is public parameters — the spec hash in
    ``hello`` commits to it without revealing anything private."""

    family: str
    n: int
    eps1: float
    eps2: float
    alpha: float = 0.05
    normalise: bool = True
    seed: int = 2025
    noise_mode: str = "replay"
    party_x: str = "party-x"
    party_y: str = "party-y"
    session: str = ""
    # Optional per-role column key labels (federation): when set, the
    # role roots its noise in utils.rng.column_root(master, label)
    # instead of the bare master key, so different columns of a k×k
    # matrix draw independent noise and a column's release is the same
    # bytes in every pair that reuses it. Empty (the default) keeps the
    # original two-party key layout — and the original spec hash.
    key_x: str = ""
    key_y: str = ""

    def __post_init__(self):
        if self.session == "":
            object.__setattr__(self, "session",
                               f"sess-{self.spec_hash()[:12]}")

    def to_public(self) -> dict:
        pub = {"family": self.family, "n": int(self.n),
               "eps1": float(self.eps1), "eps2": float(self.eps2),
               "alpha": float(self.alpha),
               "normalise": bool(self.normalise),
               "seed": int(self.seed), "noise_mode": self.noise_mode,
               "party_x": self.party_x, "party_y": self.party_y}
        if self.key_x or self.key_y:
            # only present when used: pre-federation specs keep their
            # exact hash (and transcript bytes) across this change
            pub["key_x"] = self.key_x
            pub["key_y"] = self.key_y
        return pub

    def spec_hash(self) -> str:
        return hashlib.sha256(canonical_encode(self.to_public())).hexdigest()

    def party_name(self, role: str) -> str:
        return self.party_x if role == "x" else self.party_y

    def own_eps(self, role: str) -> float:
        return self.eps1 if role == "x" else self.eps2

    def charges_for(self, role: str) -> dict[str, float]:
        """This role's ε spend for its side of the protocol —
        its own ε times the family's release factor (the private
        centering double-spend for sign families, serve.ledger). The
        two roles' charges sum to exactly ``request_charges`` of the
        equivalent serve request, so serving-mode and protocol-mode
        accounting can never drift."""
        f = release_factor(self.family, self.normalise)
        return {self.party_name(role): float(self.own_eps(role)) * f}


@dataclass
class ProtocolResult:
    """One party's view of a completed session."""

    role: str
    session: str
    rho_hat: float
    ci_low: float
    ci_high: float
    trace_id: str | None = None
    stats: dict = field(default_factory=dict)


def _result_floats(rho, lo, hi) -> dict:
    """(ρ̂, CI) as wire floats. float32 → Python float (binary64) is
    exact, and repr round-trips binary64 exactly, so casting back to
    float32 on the far side restores the identical bits — the result
    message never perturbs the estimate."""
    return {"rho_hat": float(rho), "ci_low": float(lo),
            "ci_high": float(hi)}


class SessionEndpoint:
    """One endpoint of one journaled, gated protocol session — the
    plumbing shared by the two-party :class:`Party` and the federation
    pair links (protocol.federation), factored out of ``Party``
    verbatim. Everything session-shaped lives here: transcript
    recording, the journal slot ↔ wire seq discipline, gated and plain
    sends, journal replay on receive, the resume re-attach handshake
    and its peer-gone fallback, and the terminal linger.

    Subclasses provide the three identity facts (``session`` id,
    ``spec_hash`` the handshake pins, ``sender`` — the wire name this
    endpoint signs messages with: the role letter for two-party
    sessions, the party's own name on a federation link) and drive the
    message flow; this class guarantees that however they drive it, ε
    is charged before any release send, refunded only on provable
    non-delivery, and spent exactly once across restarts.
    """

    def __init__(self, *, session: str, spec_hash: str, sender: str,
                 channel: ReliableChannel, ledger: PrivacyLedger,
                 transcript: Transcript | None = None,
                 recv_timeout_s: float = 30.0,
                 journal: SessionJournal | None = None):
        self.session = session
        self.spec_hash = spec_hash
        self.sender = sender
        self.channel = channel
        self._gate = ReleaseGate(ledger)
        self.transcript = transcript or Transcript(None)
        self.recv_timeout_s = recv_timeout_s
        self.journal = journal
        self._span = None
        self._resumed = False
        self._peer_gone = False  # resume went unanswered: peer finished
        self._out_slot = 0   # next outbound journal slot
        self._in_slot = 0    # next inbound journal slot
        self._replay_in = 0  # inbound slots below this replay from journal

    # ------------------------------------------------------- plumbing ----
    def _headers(self) -> dict:
        return wire_headers(self._span.context
                            if self._span is not None else None)

    def _trace_id(self) -> str | None:
        return self._span.trace_id if self._span is not None else None

    def _record(self, direction: str, msg: Message, receipt: dict,
                eps: float = 0.0, charge_id: str | None = None,
                replayed: bool = False) -> None:
        self.transcript.record(
            direction, msg, seq=receipt.get("seq", -1),
            n_bytes=receipt.get("bytes", len(msg.encode())),
            retries=receipt.get("retries", 0),
            latency_s=receipt.get("latency_s", 0.0), eps=eps,
            charge_id=charge_id, replayed=replayed)

    def _journal_outbound(self, msg: Message, charges=None,
                          charge_id=None) -> dict:
        """Claim the next outbound slot and journal the wire dict under
        it — durably, before anything irreversible happens. On a resume
        the slot may already exist, in which case the *journaled* entry
        wins wholesale: replaying recomputed bytes would diverge from
        what the peer may have already acked."""
        slot = self._out_slot
        self._out_slot += 1
        entry = self.journal.outbound_entry(slot)
        if entry is None:
            entry = self.journal.prepare_outbound(
                slot, msg.to_wire(), charges=charges, charge_id=charge_id)
            chaos.point("journal.post_prepare")
        return entry

    def _send_plain(self, msg: Message) -> None:
        """Ungated send — only for messages that carry no DP release
        (hello/hello_ack/error; the lint rule keys on this split)."""
        if self.journal is None:
            receipt = self.channel.send(msg.to_wire())
            self._record("send", msg, receipt)
            return
        entry = self._journal_outbound(msg)
        wire_msg = Message.from_wire(entry["wire"])
        if entry["acked"]:
            # delivered before the crash; keep the transcript complete
            self._record("send", wire_msg, {"seq": entry["seq"]},
                         replayed=True)
            return
        if self._peer_gone:
            # peer completed without us: this frame was necessarily
            # delivered (see _attach_journal) — record, don't resend
            self.journal.mark_acked(entry["slot"])
            self._record("send", wire_msg, {"seq": entry["seq"]},
                         replayed=True)
            return
        receipt = self.channel.send(entry["wire"], seq=entry["seq"])
        self.journal.mark_acked(entry["slot"])
        self._record("send", wire_msg, receipt)

    def _linger(self) -> None:
        """Drain the channel after receiving the session's final
        message — but only when loss is actually possible (fault
        injection active, retransmissions already happened, this is
        a crash-resumed session whose peer may still be retransmitting
        into the gap the restart left, or we just acknowledged a
        *peer's* re-attach and its journal replay is about to arrive):
        a clean queue/TCP link never drops an ack, and the idle window
        would otherwise tax every clean session's latency for
        nothing."""
        if self.channel.fault is not None or self.channel.total_retries \
                or self._resumed or self.channel.peer_resumed:
            self.channel.drain()

    def _send_best_effort(self, msg: Message) -> None:
        """Abort notification: the peer may already be gone (its own
        abort crossed ours, or chaos ate the session) — a delivery
        failure here must not mask the refusal we are about to raise.
        Deliberately unjournaled: aborts are terminal, there is no
        resume that would replay one."""
        try:
            receipt = self.channel.send(msg.to_wire())
            self._record("send", msg, receipt)
        except TransportError:
            pass

    def _send_gated(self, msg: Message, charges) -> None:
        """Charge ``charges``, then send; refund handled inside the
        gate. On refusal, signal the peer with an ungated ``error`` so
        it stops waiting, then raise :class:`ProtocolRefused`.

        Journaled sessions make the whole sequence crash-repeatable:
        the slot (wire + charges + a deterministic charge_id) is
        durable before the charge, the charge is idempotent under that
        id, the send is pinned to the journaled seq (the peer's dedupe
        absorbs a pre-crash delivery), and a slot already marked acked
        skips straight to the transcript — ε spent exactly once no
        matter where in this function the process last died."""
        if self.journal is None:
            try:
                receipt = self._gate.send_release(
                    self.channel, msg.to_wire(), charges,
                    trace_id=self._trace_id())
            except BudgetExceededError as e:
                abort = self._msg("error", {
                    "kind": "budget", "reason": str(e), "party": e.party})
                # dpcorr-lint: ignore[budget-deep-missing-refund] — abort frame is uncharged; send_release already refunded
                self._send_best_effort(abort)
                raise ProtocolRefused(str(e)) from e
            self._record("send", msg, receipt, eps=receipt["eps"])
            return
        cid = f"{self.session}:{self.sender}:out{self._out_slot}"
        entry = self._journal_outbound(msg, charges=charges, charge_id=cid)
        cid = entry["charge_id"]
        wire_msg = Message.from_wire(entry["wire"])
        entry_charges = entry["charges"] or charges
        if entry["acked"]:
            self._record("send", wire_msg, {"seq": entry["seq"]},
                         eps=float(sum(entry_charges.values())),
                         charge_id=cid, replayed=True)
            return
        if self._peer_gone:
            # The peer finished and left before our journal saw this
            # slot acked — but it cannot have completed without the
            # release, so delivery happened at the channel level and
            # only the local bookkeeping is behind. Land the
            # (idempotent) charge, skip the wire, and mark the slot so
            # a further restart replays it identically. Refunding here
            # would double-credit a consumed release.
            self._gate.charge_replayed(entry_charges,
                                       trace_id=self._trace_id(),
                                       charge_id=cid)
            self.journal.mark_acked(entry["slot"])
            self._record("send", wire_msg, {"seq": entry["seq"]},
                         eps=float(sum(entry_charges.values())),
                         charge_id=cid, replayed=True)
            return
        try:
            receipt = self._gate.send_release(
                self.channel, entry["wire"], entry_charges,
                trace_id=self._trace_id(), charge_id=cid,
                seq=entry["seq"])
        except BudgetExceededError as e:
            abort = self._msg("error", {
                "kind": "budget", "reason": str(e), "party": e.party})
            # dpcorr-lint: ignore[budget-deep-missing-refund] — abort frame is uncharged; send_release already refunded
            self._send_best_effort(abort)
            raise ProtocolRefused(str(e)) from e
        self.journal.mark_acked(entry["slot"])
        chaos.point("party.post_gated")
        self._record("send", wire_msg, receipt, eps=receipt["eps"],
                     charge_id=cid)

    def _recv(self, *expect: str) -> Message:
        if self.journal is not None and self._in_slot < self._replay_in:
            # journaled before the crash; the channel pre-marked its seq
            # delivered, so the live link will re-ack but never re-queue
            got = dict(self.journal.inbound_entry(self._in_slot))
            self._in_slot += 1
        else:
            got = self.channel.recv(timeout_s=self.recv_timeout_s)
            self._in_slot += 1
        msg = Message.from_wire(got["body"])
        self._record("recv", msg, {"seq": got["seq"]})
        if msg.session != self.session:
            raise ProtocolError(
                f"session mismatch: peer says {msg.session!r}, "
                f"ours is {self.session!r}")
        if msg.msg_type == "error":
            # terminal inbound: linger so the peer's abort send doesn't
            # fail on a chaos-dropped ack after we raise (transport.drain)
            self._linger()
            kind = msg.payload.get("kind", "protocol")
            reason = msg.payload.get("reason", "peer aborted")
            if kind == "budget":
                raise ProtocolRefused(f"peer refused: {reason}")
            raise ProtocolError(f"peer error: {reason}")
        if msg.msg_type not in expect:
            raise ProtocolError(
                f"expected {expect}, got {msg.msg_type!r}")
        return msg

    def _msg(self, msg_type: str, payload: dict) -> Message:
        return Message(msg_type=msg_type, sender=self.sender,
                       session=self.session, payload=payload,
                       headers=self._headers())

    def _register_session_info(self) -> None:
        """Tell the channel which (session, token) a peer's resume
        handshake must present — the surviving side answers resumes
        from whatever loop it is blocked in."""
        token = self.journal.resume_token if self.journal else None
        if token:
            self.channel.session_info = {"session": self.session,
                                         "token": token}

    def _attach_journal(self) -> None:
        """Bind the journal to this session and reload channel state.

        The resume re-attach handshake runs only when there is evidence
        the *peer* already knows this session (something of ours was
        acked, or something of theirs journaled): before that point the
        peer is still parked in its opening recv and a resume frame
        would go unanswered — the plain journal replay alone is
        sufficient and correct there."""
        j = self.journal
        self._resumed = j.begin(self.session, self.sender, self.spec_hash)
        self._replay_in = len(j.inbound)
        self.channel.on_deliver = j.record_inbound
        self.channel.restore(send_seq=len(j.outbound),
                             delivered=j.delivered_seqs())
        self._register_session_info()
        token = j.resume_token
        peer_knows_us = bool(j.inbound) \
            or any(e["acked"] for e in j.outbound)
        if self._resumed and token and peer_knows_us:
            budget = max(10.0 * self.channel.timeout_s, 5.0)
            try:
                self.channel.resume(self.session, token,
                                    max_wait_s=budget)
            except SessionResumeRefused:
                raise  # wrong session/token — never a peer-gone case
            except TransportError:
                # Unanswered: the peer finished and left. Single-crash
                # soundness: it cannot have completed without every
                # release we journaled — the channel acks a frame only
                # after journaling it, and the peer's final recv could
                # not have returned otherwise — so delivery of our
                # unacked slots already happened and replay can finish
                # from the journal alone (_send_gated/_send_plain skip
                # the wire when this flag is set). A dual-crash that
                # violates the premise fails loudly via recv timeout.
                self._peer_gone = True

    def _stats(self) -> dict:
        ch = self.channel
        out = {"sent_msgs": ch.sent_msgs,
               "total_retries": ch.total_retries}
        if ch.fault is not None:
            out["fault"] = ch.fault.stats()
        return out


class Party(SessionEndpoint):
    """One role ("x" or "y") of one protocol session.

    ``column`` is this party's raw column — it never leaves this object
    except through ``split_reference.party_release``/``finish`` (DP
    releases) and is never serialized. ``ledger`` is wrapped in the
    release gate immediately; the party itself keeps no direct
    reference.

    With ``journal`` (a :class:`SessionJournal`), the session is
    crash-safe: every outbound message is journaled before it is sent
    (outbound slot *k* ↔ wire seq *k+1*), every inbound message is
    journaled before it is acked, the gated charge carries a
    deterministic ``charge_id`` so the ledger spends it once across
    restarts, and a restarted party replays its journal — re-sending
    journaled wire bytes verbatim under their original seqs — until it
    rejoins the live session exactly where it died. Without a journal
    nothing changes, down to the wire bytes (the determinism test
    byte-compares transcripts).
    """

    def __init__(self, role: str, column, spec: ProtocolSpec,
                 channel: ReliableChannel, ledger: PrivacyLedger,
                 transcript: Transcript | None = None,
                 recv_timeout_s: float = 30.0,
                 journal: SessionJournal | None = None):
        if role not in ("x", "y"):
            raise ValueError(f"role must be 'x' or 'y', got {role!r}")
        col = np.asarray(column, dtype=np.float32)
        if col.ndim != 1 or col.shape[0] != spec.n:
            raise ValueError(
                f"column must be shape ({spec.n},), got {col.shape}")
        super().__init__(session=spec.session,
                         spec_hash=spec.spec_hash(), sender=role,
                         channel=channel, ledger=ledger,
                         transcript=transcript,
                         recv_timeout_s=recv_timeout_s, journal=journal)
        self.role = role
        self._column = col
        self.spec = spec

    def _handshake(self) -> None:
        """X proposes (opening the trace root), Y verifies the spec
        hash and parents its root span on the proposal's context —
        from here both processes share one trace ID.

        Journaled sessions thread two extra facts through the same two
        messages: X mints a resume token into the hello (journal-gated,
        so unjournaled sessions keep byte-identical wire traffic), and
        a restarted X pins its root span to the journaled trace ID so
        the resumed half of the session joins the original trace. Y
        needs no special casing — its root span parents on the hello
        headers, which a resume replays verbatim from the journal."""
        if self.role == "x":
            if self.journal is not None and self.journal.trace_id:
                # dpcorr-lint: ignore[span-no-finally] — session root span; ends in close()
                self._span = tracer().start_span(
                    "protocol.session", trace_id=self.journal.trace_id,
                    role=self.role, family=self.spec.family,
                    session=self.spec.session, resumed=True)
            else:
                # dpcorr-lint: ignore[span-no-finally] — session root span; ends in close()
                self._span = tracer().start_span(
                    "protocol.session", role=self.role,
                    family=self.spec.family, session=self.spec.session)
                if self.journal is not None and self._span.trace_id:
                    self.journal.set_trace(self._span.trace_id)
            payload = {"spec": self.spec.to_public(),
                       "spec_hash": self.spec.spec_hash()}
            if self.journal is not None:
                payload["resume_token"] = self.journal.ensure_token()
                self._register_session_info()
            hello = self._msg("hello", payload)
            self._send_plain(hello)
            self._recv("hello_ack")
        else:
            first = self._recv("hello")
            # dpcorr-lint: ignore[span-no-finally] — session root span; ends in close()
            self._span = tracer().start_span(
                "protocol.session", parent=from_wire_headers(first.headers),
                role=self.role, family=self.spec.family,
                session=self.spec.session)
            if self.journal is not None:
                token = first.payload.get("resume_token")
                if token:
                    self.journal.adopt_token(token)
                    self._register_session_info()
                if self._span.trace_id:
                    self.journal.set_trace(self._span.trace_id)
            theirs = first.payload.get("spec_hash")
            if theirs != self.spec.spec_hash():
                refusal = self._msg("error", {
                    "kind": "protocol",
                    "reason": f"spec hash mismatch: {theirs!r}"})
                self._send_best_effort(refusal)
                raise ProtocolError(
                    f"peer spec hash {theirs!r} != ours "
                    f"{self.spec.spec_hash()!r}")
            ack = self._msg("hello_ack",
                            {"spec_hash": self.spec.spec_hash()})
            self._send_plain(ack)

    # ----------------------------------------------------- estimation ----
    def _root_key(self):
        from dpcorr.utils import rng

        key = rng.master_key(self.spec.seed)
        label = self.spec.key_x if self.role == "x" else self.spec.key_y
        if label:
            key = rng.column_root(key, label)
        return rng.party_root(key, self.role, self.spec.noise_mode)

    def _run_releaser(self) -> ProtocolResult:
        from dpcorr.models.estimators import split_reference as sr

        s = self.spec
        with tracer().span("protocol.release", parent=self._span,
                           role=self.role):
            rel = sr.party_release(s.family, self._root_key(), self.role,
                                   self._column, s.eps1, s.eps2,
                                   s.normalise)
            kinds = sr.RELEASE_KINDS[s.family]
            payload = {name: encode_array(np.asarray(arr),
                                          kind=kinds[name])
                       for name, arr in rel.items()}
        outbound = self._msg("release", payload)
        self._send_gated(outbound, self.spec.charges_for(self.role))
        final = self._recv("result")
        # result is the session's last message and we are its receiver:
        # linger so our ack loss doesn't strand the finisher mid-send
        self._linger()
        p = final.payload
        return ProtocolResult(
            role=self.role, session=s.session,
            rho_hat=p["rho_hat"], ci_low=p["ci_low"],
            ci_high=p["ci_high"], trace_id=self._trace_id(),
            stats=self._stats())

    def _validate_release(self, msg: Message) -> dict:
        """Enforce the family's release schema on the inbound payload
        *before* touching values: unexpected keys, missing envelopes,
        wrong kind/shape/dtype are protocol errors. This is the
        receiving half of the no-raw-columns barrier — a payload shaped
        like a raw column cannot reach the finisher."""
        from dpcorr.models.estimators import split_reference as sr

        s = self.spec
        schema = sr.release_schema(s.family, s.n, s.eps1, s.eps2)
        payload = msg.payload
        if set(payload) != set(schema):
            raise ProtocolError(
                f"release payload keys {sorted(payload)} != schema "
                f"{sorted(schema)}")
        out = {}
        for name, want in schema.items():
            env = payload[name]
            if not (isinstance(env, dict) and env.get("__array__") == 1):
                raise ProtocolError(f"release[{name!r}] is not an "
                                    "array envelope")
            if env.get("kind") != want["kind"]:
                raise ProtocolError(
                    f"release[{name!r}] kind {env.get('kind')!r} != "
                    f"{want['kind']!r}")
            arr = decode_array(env)
            if tuple(arr.shape) != tuple(want["shape"]) \
                    or str(arr.dtype) != want["dtype"]:
                raise ProtocolError(
                    f"release[{name!r}] is {arr.dtype}{arr.shape}, "
                    f"schema says {want['dtype']}{tuple(want['shape'])}")
            out[name] = arr
        return out

    def _run_finisher(self) -> ProtocolResult:
        from dpcorr.models.estimators import split_reference as sr

        s = self.spec
        inbound = self._recv("release")
        peer_release = self._validate_release(inbound)
        with tracer().span("protocol.finish", parent=self._span,
                           role=self.role):
            rho, lo, hi = sr.finish(s.family, self._root_key(),
                                    peer_release, self._column, s.eps1,
                                    s.eps2, s.alpha, s.normalise)
        outbound = self._msg("result", _result_floats(rho, lo, hi))
        self._send_gated(outbound, self.spec.charges_for(self.role))
        # our result being acked does NOT mean our ack of the peer's
        # release got through: the releaser absorbs the result (and acks
        # it) from inside its own blocked send, so it can still be
        # retransmitting the release after this send returns. Linger to
        # keep re-acking, or chaos strands the releaser mid-send.
        self._linger()
        return ProtocolResult(
            role=self.role, session=s.session,
            rho_hat=float(rho), ci_low=float(lo), ci_high=float(hi),
            trace_id=self._trace_id(), stats=self._stats())

    def run(self) -> ProtocolResult:
        """Execute this role's side of the session to completion. A
        journaled session that already finished returns its journaled
        result without touching the wire or the ledger — the terminal
        idempotency level."""
        from dpcorr.models.estimators import split_reference as sr

        s = self.spec
        if self.journal is not None:
            if self.journal.status == "finished" and self.journal.result:
                return ProtocolResult(**self.journal.result)
            self._attach_journal()
        # dpcorr-lint: ignore[budget-deep-uncharged-enqueue] — hello/ack frames carry no release, so nothing to charge
        self._handshake()
        chaos.point("party.post_handshake")
        releaser, _ = sr.split_roles(s.family, s.eps1, s.eps2)
        try:
            if self.role == releaser:
                result = self._run_releaser()
            else:
                result = self._run_finisher()
        except (ProtocolError, ProtocolRefused):
            raise  # typed protocol outcomes are expected, not dumped
        except Exception as e:
            # an unhandled session failure triggers a flight-recorder
            # dump (when one is installed — obs.recorder.trigger is a
            # no-op otherwise) so the postmortem has the span chain and
            # recent logs without re-running the session
            obs_recorder.trigger(
                "party_unhandled", role=self.role,
                session=self.spec.session, error=type(e).__name__,
                detail=str(e))
            raise
        finally:
            if self._span is not None:
                self._span.end()
            self.transcript.close()
        if self.journal is not None:
            self.journal.set_result({
                "role": result.role, "session": result.session,
                "rho_hat": result.rho_hat, "ci_low": result.ci_low,
                "ci_high": result.ci_high, "trace_id": result.trace_id,
                "stats": result.stats})
            self.journal.finish()
        return result
