"""Versioned protocol messages, canonical bytes, transcript log.

Everything that crosses the wire is one :class:`Message` serialized by
:func:`canonical_encode` — ``json.dumps`` with sorted keys, no
whitespace, ``allow_nan=False`` — so a given logical message has
exactly one byte representation. That determinism is load-bearing
twice: transcript replay is byte-comparable across runs (the
determinism test diffs serialized payloads, not floats), and the
transcript scanner can reason about payload bytes without a parser
ambiguity. Arrays cross as an explicit tagged envelope
(:func:`encode_array`): dtype + shape + base64 of the raw
little-endian buffer — lossless for float32, so the wire never
perturbs a release bit.

The :class:`Transcript` is each party's own JSONL log of every frame it
sent or received — direction, sequence number, wire size, retries,
latency, the ε charged for gated sends, the trace ID, and the full wire
dict. It is deliberately *complete*: the no-raw-columns audit
(protocol.scan) works on transcripts alone, so anything omitted here
would be invisible to the audit. Jax-free on purpose — the scanner and
``report.protocol_transcript_frame`` import this module under the
jax-free CLI paths.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from dataclasses import asdict, dataclass, field

PROTOCOL_VERSION = 1

#: Closed message vocabulary. ``hello``/``hello_ack`` pin the spec hash
#: (both parties prove they run the same design point before any ε is
#: spent); ``release`` carries the releaser's DP payload; ``result``
#: carries the finisher's (ρ̂, CI) back; ``error`` aborts (budget
#: refusal, validation failure) — it never carries arrays.
MSG_TYPES = ("hello", "hello_ack", "release", "result", "error")


def canonical_encode(obj: dict) -> bytes:
    """The one byte encoding of a wire object: key-sorted, minimal
    separators, NaN/Inf rejected (they would deserialize
    non-canonically and a NaN release is a protocol bug, not data)."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      allow_nan=False).encode("utf-8")


def encode_array(values, kind: str) -> dict:
    """Array → wire envelope. ``kind`` names *what DP release* the
    array is (e.g. ``"noisy_sign_batch_means"``) — the scanner and the
    receiving party validate it against the family's release schema, so
    an array without a declared release kind cannot cross. Accepts
    anything numpy can view as an array; always ships little-endian."""
    import numpy as np

    a = np.asarray(values)
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    return {
        "__array__": 1,
        "kind": str(kind),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "b64": base64.b64encode(np.ascontiguousarray(a).tobytes()).decode(
            "ascii"),
    }


def decode_array(env: dict):
    """Inverse of :func:`encode_array` (numpy array out)."""
    import numpy as np

    if not isinstance(env, dict) or env.get("__array__") != 1:
        raise ValueError("not an array envelope")
    a = np.frombuffer(base64.b64decode(env["b64"]),
                      dtype=np.dtype(env["dtype"]))
    return a.reshape(tuple(env["shape"])).copy()


def iter_arrays(payload):
    """Yield every array envelope in a payload, depth-first — the
    scanner's enumeration (arrays anywhere else than where the schema
    allows are a violation, so enumeration must be exhaustive)."""
    if isinstance(payload, dict):
        if payload.get("__array__") == 1:
            yield payload
            return
        for v in payload.values():
            yield from iter_arrays(v)
    elif isinstance(payload, (list, tuple)):
        for v in payload:
            yield from iter_arrays(v)


@dataclass(frozen=True)
class Message:
    """One protocol message. ``headers`` carries the sender's span
    context (obs.wire_headers) so one trace covers both processes;
    ``payload`` is type-specific (see docs/PROTOCOL.md)."""

    msg_type: str
    sender: str                      # role ("x"|"y") or federation party
    session: str                     # spec-derived session id
    payload: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)
    version: int = PROTOCOL_VERSION

    def __post_init__(self):
        if self.msg_type not in MSG_TYPES:
            raise ValueError(f"unknown msg_type {self.msg_type!r}; "
                             f"expected one of {MSG_TYPES}")
        # two-party sessions use the role letters; federation pair-links
        # (protocol.federation) send under the party's own name
        if not isinstance(self.sender, str) or not self.sender:
            raise ValueError(f"sender must be a non-empty string, "
                             f"got {self.sender!r}")

    def to_wire(self) -> dict:
        return asdict(self)

    @classmethod
    def from_wire(cls, obj: dict) -> "Message":
        if not isinstance(obj, dict):
            raise ValueError("message body must be a JSON object")
        v = obj.get("version")
        if v != PROTOCOL_VERSION:
            raise ValueError(
                f"protocol version mismatch: peer sent {v!r}, "
                f"this runtime speaks {PROTOCOL_VERSION}")
        return cls(msg_type=obj["msg_type"], sender=obj["sender"],
                   session=obj["session"],
                   payload=obj.get("payload", {}),
                   headers=obj.get("headers", {}),
                   version=v)

    def encode(self) -> bytes:
        return canonical_encode(self.to_wire())


class Transcript:
    """Per-party JSONL log of every frame sent/received.

    One line per delivered message: ``{ts, dir, seq, type, bytes,
    retries, latency_s, eps, trace_id, wire}`` where ``wire`` is the
    full wire dict (the scanner audits bytes, not summaries) and
    ``eps`` is the total ε charged for that send (gated sends only,
    else 0). Append-only, line-buffered, lock around the write so the
    runner's two in-process parties can share a process safely.
    """

    def __init__(self, path: str | None):
        self.path = path
        # immutable after construction: the lock-free fast path in
        # record() keys off this, never off the guarded handle
        self.enabled = bool(path)
        self._lock = threading.Lock()
        self._fh = None  # guarded by: _lock
        if path:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    def record(self, direction: str, msg: Message, seq: int,
               n_bytes: int, retries: int = 0, latency_s: float = 0.0,
               eps: float = 0.0, charge_id: str | None = None,
               replayed: bool = False) -> None:
        if not self.enabled:
            return
        entry = {
            "ts": time.time(), "dir": direction, "seq": seq,
            "type": msg.msg_type, "bytes": n_bytes, "retries": retries,
            "latency_s": latency_s, "eps": eps,
            "trace_id": msg.headers.get("trace_id"),
            "wire": msg.to_wire(),
        }
        # resume-only columns stay absent on the normal path so a
        # crash-free transcript is byte-shaped exactly as before
        if charge_id is not None:
            entry["charge_id"] = charge_id
        if replayed:
            entry["replayed"] = True
        line = json.dumps(entry, sort_keys=True)
        with self._lock:
            if self._fh is not None:
                self._fh.write(line + "\n")

    def meta(self, **fields) -> None:
        """Append a non-message header line ``{"ts", "meta": {...}}`` —
        fault seeds, chaos plans, resume markers. Meta lines make every
        chaos run reproducible from the artifact alone; readers of the
        message stream (:func:`read_transcript`) skip them."""
        if not self.enabled or not fields:
            return
        line = json.dumps({"ts": time.time(), "meta": fields},
                          sort_keys=True)
        with self._lock:
            if self._fh is not None:
                self._fh.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_transcript(path: str) -> list[dict]:
    """Load a transcript's *message* lines (meta header lines are
    skipped — they carry no wire traffic); raises ValueError naming the
    first bad line (the audit must fail loudly on a corrupt log, not
    skip lines)."""
    entries = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{i}: bad transcript line: {e}") from e
            if isinstance(obj, dict) and "meta" in obj and "dir" not in obj:
                continue
            if not isinstance(obj, dict) or "dir" not in obj \
                    or "wire" not in obj:
                raise ValueError(f"{path}:{i}: not a transcript entry")
            entries.append(obj)
    return entries


def read_transcript_meta(path: str) -> dict:
    """Merge all meta header lines of a transcript (later lines win on
    key collision). The reproducibility contract: the fault seed and
    chaos plan a run was executed under are recoverable from here."""
    merged: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and isinstance(obj.get("meta"), dict) \
                    and "dir" not in obj:
                merged.update(obj["meta"])
    return merged
