"""Durable per-session journal: everything a restarted party needs.

The protocol runtime (party.py) is a straight-line script — handshake,
one gated release, one result — so its durable state is small and
append-mostly: which session this is, the resume token, every outbound
wire payload with its charge metadata and ack status, every inbound
body in arrival order, and the finished result. The journal persists
that state with the exact discipline the ledger uses (``{path}.tmp.{pid}``
→ ``fsync`` → ``os.replace``), so a crash leaves either the previous
snapshot or the new one — never a torn file.

Two identities do the heavy lifting on resume:

- **slot ↔ seq.** Outbound slot *k* (0-based order of ``send`` calls)
  is always wire seq *k+1*, because *every* outbound protocol message
  is journaled — including the hello. A restarted party pins each
  replayed send to its journaled seq, so the peer's ReliableChannel
  dedupe set recognises retransmits across the crash.
- **journaled wire bytes are replayed verbatim.** A recomputed message
  would differ (trace headers carry fresh span ids); replaying the
  journaled dict byte-for-byte keeps the peer's view identical to an
  uninterrupted run.

stdlib-only on purpose: journals are read by the jax-free chaos driver
and must never pull in the model stack.
"""

from __future__ import annotations

import json
import os
import secrets

from dpcorr.obs.budget_replay import sweep_stale_tmp

_VERSION = 1


class JournalError(ValueError):
    """Journal exists but cannot back this session (corrupt file, or a
    different session/role/spec than the caller is running)."""


def _fresh_state() -> dict:
    return {
        "version": _VERSION,
        "session": None,
        "role": None,
        "spec_hash": None,
        "resume_token": None,
        "trace_id": None,
        "status": "new",          # new -> running -> finished
        "outbound": [],            # [{slot, seq, wire, charges, charge_id, acked}]
        "inbound": [],             # [{seq, body}] in arrival order
        "result": None,
        "meta": {},
    }


class SessionJournal:
    """Crash-safe session state at ``path`` (JSON snapshot).

    Single-threaded by design — party.py drives one session from one
    thread; the journal's only concurrency concern is the *crash*, which
    the tmp+fsync+rename write handles.
    """

    def __init__(self, path: str):
        self.path = str(path)
        # a crash between tmp-write and os.replace strands a
        # ``{path}.tmp.{pid}`` orphan; the dead writer never finishes
        # it, so clear them before loading (same discipline as the
        # ledger snapshot and budget-directory shards)
        sweep_stale_tmp(self.path)
        self._state = self._load()

    # -- persistence -------------------------------------------------

    def _load(self) -> dict:
        if not os.path.exists(self.path):
            return _fresh_state()
        try:
            with open(self.path, encoding="utf-8") as fh:
                state = json.load(fh)
        except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
            quarantine = self.path + ".corrupt"
            os.replace(self.path, quarantine)
            raise JournalError(
                f"session journal {self.path} is corrupt ({e}); moved to "
                f"{quarantine} — delete it to start the session over, or "
                "restore a good snapshot to resume") from e
        if not isinstance(state, dict) or state.get("version") != _VERSION:
            raise JournalError(
                f"session journal {self.path} has unsupported version "
                f"{state.get('version') if isinstance(state, dict) else state!r}"
                f" (want {_VERSION})")
        return state

    def _persist(self) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self._state, fh, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)

    # -- lifecycle ---------------------------------------------------

    def begin(self, session: str, role: str, spec_hash: str) -> bool:
        """Bind the journal to one (session, role, spec). Returns True
        when this is a resume of prior progress, False for a fresh
        session. A journal for a *different* session/role/spec refuses
        loudly — silently mixing two sessions' state could double-spend.
        """
        st = self._state
        if st["status"] == "new" and st["session"] is None:
            st.update(session=session, role=role, spec_hash=spec_hash,
                      status="running")
            self._persist()
            return False
        for key, want in (("session", session), ("role", role),
                          ("spec_hash", spec_hash)):
            if st[key] != want:
                raise JournalError(
                    f"journal {self.path} belongs to {key}={st[key]!r}, "
                    f"not {key}={want!r}; refusing to mix sessions")
        if st["status"] == "new":
            st["status"] = "running"
            self._persist()
        return True

    @property
    def status(self) -> str:
        return self._state["status"]

    @property
    def session(self):
        return self._state["session"]

    @property
    def trace_id(self):
        return self._state["trace_id"]

    def set_trace(self, trace_id: str) -> None:
        if self._state["trace_id"] != trace_id:
            self._state["trace_id"] = trace_id
            self._persist()

    @property
    def resume_token(self):
        return self._state["resume_token"]

    def ensure_token(self) -> str:
        """Mint (once) the session-resume token the peers exchange in
        the hello; stable across restarts so a resumed handshake can
        authenticate as the same session."""
        if self._state["resume_token"] is None:
            self._state["resume_token"] = secrets.token_hex(16)
            self._persist()
        return self._state["resume_token"]

    def adopt_token(self, token: str) -> None:
        """Peer-supplied token (the non-minting side journals it)."""
        if self._state["resume_token"] != token:
            self._state["resume_token"] = token
            self._persist()

    # -- outbound ----------------------------------------------------

    @property
    def outbound(self) -> list:
        return self._state["outbound"]

    def outbound_entry(self, slot: int):
        out = self._state["outbound"]
        return out[slot] if slot < len(out) else None

    def prepare_outbound(self, slot: int, wire: dict, charges=None,
                         charge_id=None) -> dict:
        """Journal outbound slot ``slot`` before anything irreversible
        (charge, send) happens. Idempotent: re-preparing an existing
        slot returns the journaled entry untouched — the journaled wire
        wins over a recomputed one."""
        out = self._state["outbound"]
        if slot < len(out):
            return out[slot]
        if slot != len(out):
            raise JournalError(
                f"outbound slots must be journaled in order; have "
                f"{len(out)}, got slot {slot}")
        entry = {"slot": slot, "seq": slot + 1, "wire": wire,
                 "charges": charges, "charge_id": charge_id,
                 "acked": False}
        out.append(entry)
        self._persist()
        return entry

    def mark_acked(self, slot: int) -> None:
        entry = self._state["outbound"][slot]
        if not entry["acked"]:
            entry["acked"] = True
            self._persist()

    # -- inbound -----------------------------------------------------

    @property
    def inbound(self) -> list:
        return self._state["inbound"]

    def inbound_entry(self, slot: int):
        ib = self._state["inbound"]
        return ib[slot] if slot < len(ib) else None

    def record_inbound(self, seq: int, body: dict) -> None:
        """ReliableChannel ``on_deliver`` hook: journal each NEW inbound
        message durably *before* the channel acks it, so an ack can
        never outrun durability (ack-then-crash would lose the message
        forever — the peer stops retransmitting acked seqs)."""
        ib = self._state["inbound"]
        if any(e["seq"] == seq for e in ib):
            return
        ib.append({"seq": seq, "body": body})
        self._persist()

    def delivered_seqs(self) -> set:
        return {e["seq"] for e in self._state["inbound"]}

    # -- result ------------------------------------------------------

    @property
    def result(self):
        return self._state["result"]

    def set_result(self, result: dict) -> None:
        self._state["result"] = result
        self._persist()

    def finish(self) -> None:
        if self._state["status"] != "finished":
            self._state["status"] = "finished"
            self._persist()

    # -- metadata ----------------------------------------------------

    @property
    def meta(self) -> dict:
        return self._state["meta"]

    def set_meta(self, **fields) -> None:
        self._state["meta"].update(fields)
        self._persist()
