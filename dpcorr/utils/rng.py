"""Deterministic RNG key-tree.

The reference relies on R's global Mersenne-Twister stream with a seeding
discipline (MASTER_SEED=2025 at vert-cor.R:16-17; ``set.seed(seed)`` at the
top of every ``run_sim_one``, vert-cor.R:364; per-grid-task seeds ``1e6+i``,
vert-cor.R:531; HRS sweep seeds ``10+37·rep+1000·eps_idx``,
real-data-sims.R:416). R streams cannot be reproduced bitwise in JAX; per
SURVEY.md §5 the acceptance criterion is *statistical* (coverage to 1e-3) and
this module provides the replacement determinism contract: a counter-based
(threefry) key-tree

    master(seed) → design point (fold_in i) → replication (fold_in b)
                 → named substream (fold_in crc32(name))

so every noise draw in the framework has a stable, collision-resistant
address and runs are bit-reproducible *within* the framework on a given
backend.
"""

from __future__ import annotations

import os
import zlib

import jax
import jax.numpy as jnp

# Same master seed as the reference (vert-cor.R:16).
MASTER_SEED: int = 2025


def master_key(seed: int = MASTER_SEED, impl: str | None = None) -> jax.Array:
    """Root of the key-tree. Replaces ``set.seed(MASTER_SEED)``.

    ``impl`` selects the PRNG implementation for the whole tree below this
    root (everything downstream is impl-generic ``fold_in``): the default
    ``threefry2x32`` is the bit-reproducibility contract; ``"rbg"`` maps to
    the TPU hardware generator and is substantially cheaper in
    PRNG-dominated kernels (the bench's ``xla_rbg`` path), at the cost of
    weaker stream-independence guarantees — acceptance for it is
    statistical, like everything else (SURVEY.md §5 RNG). The
    ``DPCORR_PRNG`` env var sets a default for the whole process.
    """
    impl = impl or os.environ.get("DPCORR_PRNG") or None
    return jax.random.key(seed, impl=impl)


def impl_tag() -> str:
    """The process-default PRNG impl, for result-cache stamps: results from
    different implementations are different numbers and must never be mixed
    by a resume (grid.py stamps npz files with this)."""
    return os.environ.get("DPCORR_PRNG") or "threefry2x32"


def design_key(key: jax.Array, design_index: int | jax.Array) -> jax.Array:
    """Key for one design point. Replaces per-task ``seed = 1e6 + i``
    (vert-cor.R:531, ver-cor-subG.R:287)."""
    return jax.random.fold_in(key, design_index)


def chunk_key(key: jax.Array, chunk_index: int | jax.Array) -> jax.Array:
    """Key for one streaming n-chunk (streaming.py rematerialization):
    the fold-on-index rung of the tree for data-parallel indices below a
    named stream. Same derivation as :func:`design_key` — kept as its
    own entry so call sites say which axis they fold over, and so the
    key-tree discipline stays checkable (`dpcorr lint` rng-raw-api
    forbids raw ``fold_in`` outside this module)."""
    return jax.random.fold_in(key, chunk_index)


def rep_keys(key: jax.Array, n_reps: int) -> jax.Array:
    """Vector of per-replication keys, shape ``(n_reps,)``.

    Replaces ``set.seed(seed)`` + sequential stream inside the reference's
    B-loop (vert-cor.R:364, 392). ``vmap``-ing a kernel over this axis is the
    TPU equivalent of the replication loop.
    """
    return jax.vmap(lambda b: jax.random.fold_in(key, b))(jnp.arange(n_reps))


def rep_keys_slice(key: jax.Array, start, n_reps: int) -> jax.Array:
    """Contiguous slice ``[start, start + n_reps)`` of the
    :func:`rep_keys` stream, without materializing the full vector.

    This is the per-shard keygen of the mesh rep pipeline
    (``dpcorr.plan`` / ``sim.RepBlockPipeline``): shard *s* derives its
    block-local keys at the **global** replication addresses
    ``start = s * reps_per_shard``, so sharded and single-device runs
    fold identical ``(key, index)`` pairs and every per-rep output stays
    bit-identical. ``start`` may be traced (e.g. built from
    ``lax.axis_index`` inside ``shard_map``)."""
    return jax.vmap(lambda b: jax.random.fold_in(key, b))(
        start + jnp.arange(n_reps))


def pallas_seeds(key: jax.Array, n_reps: int) -> jax.Array:
    """Per-replication (n_reps, 2) int32 seed words for the on-chip
    (Pallas) hardware PRNG, derived from the key-tree so fused-kernel runs
    keep the same determinism contract (master seed → design point → this
    array). Two words give a 2⁶⁴ seed space — a single-word draw would hit
    birthday duplicates at campaign scale (≈256 expected colliding pairs
    among 2²⁰ draws from 2³¹), silently repeating replications. The
    kernel's counter PRNG is a different stream family from threefry —
    results are reproducible but not bit-comparable to the XLA path
    (grid.py stamps fused results separately)."""
    return jax.random.randint(stream(key, "pallas/seeds"), (n_reps, 2),
                              jnp.iinfo(jnp.int32).min,
                              jnp.iinfo(jnp.int32).max, dtype=jnp.int32)


def key_aval(n: int | None = None) -> jax.ShapeDtypeStruct:
    """Abstract value of a typed PRNG key vector under the process
    default impl (``DPCORR_PRNG``) — what AOT compilation lowers
    against (utils.compile) without materializing concrete keys. ``n``
    is the leading axis; None means a scalar key."""
    shape = () if n is None else (int(n),)
    k = jax.eval_shape(
        lambda: jax.random.key(0, impl=os.environ.get("DPCORR_PRNG")
                               or None))
    return jax.ShapeDtypeStruct(shape, k.dtype)


def key_data_aval(n: int | None = None) -> jax.ShapeDtypeStruct:
    """Abstract value of the raw uint32 key *data* for :func:`key_aval`
    — the serializable stand-in ``jax.export`` programs take, because
    typed key avals cannot cross its serialization boundary (see
    utils.compile module docstring)."""
    return jax.eval_shape(jax.random.key_data, key_aval(n))


def key_data(keys: jax.Array) -> jax.Array:
    """Typed keys → raw uint32 words (the export-boundary encoding)."""
    return jax.random.key_data(keys)


def keys_from_data(data: jax.Array, impl: str | None = None) -> jax.Array:
    """Raw uint32 words → typed keys; inverse of :func:`key_data`.
    ``impl`` defaults to the process impl (:func:`impl_tag`), so a
    deserialized kernel rebuilds exactly the keys the live path uses —
    mixing impls would silently change every stream."""
    return jax.random.wrap_key_data(data, impl=impl or impl_tag())


def party_root(key: jax.Array, role: str, mode: str = "replay") -> jax.Array:
    """Root key for one protocol party (``dpcorr.protocol``).

    ``"replay"`` (default) hands the party the session key unchanged, so
    every named stream it draws keeps its monolithic address — the
    two-party run is bit-identical to the single-process estimator under
    the same master seed (the protocol acceptance contract, ISSUE 5).

    ``"hardened"`` roots the party in its own disjoint named subtree
    (``"protocol/x"`` / ``"protocol/y"``): statistically equivalent
    draws that are no longer bit-comparable to the monolithic path, and
    — when each party derives ``key`` from a genuinely secret seed — not
    reconstructable (hence not subtractable) by the peer. This is the
    deployment layout; replay is the simulation/testing layout.
    """
    if role not in ("x", "y"):
        raise ValueError(f"role must be 'x' or 'y', got {role!r}")
    if mode == "replay":
        return key
    if mode == "hardened":
        return stream(key, f"protocol/{role}")
    raise ValueError(f"unknown noise mode {mode!r}; "
                     "expected 'replay' or 'hardened'")


def column_root(key: jax.Array, label: str) -> jax.Array:
    """Root key for one federated column (``dpcorr.protocol.matrix``).

    A k×k federation runs one protocol session per column pair; if every
    session reused the session key directly, two different columns would
    draw their noise from the *same* named streams — the same Laplace
    vector added to two different releases is subtractable, a privacy
    bug. Each column therefore gets its own named subtree keyed by its
    public label, so (a) a column's release is a function of (label,
    column) alone — byte-identical wherever it is reused, the federation
    reuse contract — and (b) noise across distinct columns is
    independent by key-tree construction. Composes with
    :func:`party_root`: the pair session applies its role/noise-mode
    layout *below* the column root.
    """
    if not label:
        raise ValueError("column label must be non-empty")
    return stream(key, f"protocol/col/{label}")


def stream(key: jax.Array, name: str) -> jax.Array:
    """Named substream: stable across code movement, unlike split() order.

    Each independent noise source in a kernel (e.g. the X-side batch noise vs
    the Y-side batch noise vs the randomized-response flips) pulls its own
    named stream so adding a new source never perturbs existing ones.
    """
    return jax.random.fold_in(key, zlib.crc32(name.encode()) & 0x7FFFFFFF)
