"""Utilities: RNG key-tree, configuration, profiling, checkpointing."""
