"""Per-host batch-geometry autotuner for the replication hot path.

The bench's (chunk_size × block_reps) shape was a hand-flipped constant
(``WORKER_SHAPE``), and the one time it was re-tuned by hand (2048 →
8192 between r03 and r04) coincided with the headline silently halving.
This module replaces the constant with a measured choice:

- :func:`autotune` probe-times a small ladder of (chunk, block) shapes
  at bench/grid start — chunk first at a fixed probe block, then block
  at the winning chunk — and returns the fastest,
- the winner is persisted per ``(device_kind, family, n, dtype)`` in a
  JSON cache (``~/.cache/dpcorr/geometry.json``; ``DPCORR_GEOMETRY_CACHE``
  overrides, ``=0`` disables), so steady-state runs skip the probe,
- ``DPCORR_BENCH_CHUNK`` / ``DPCORR_BENCH_BLOCK_REPS`` pin the shape
  outright (``source="pinned"``) — the tuning-run escape hatch the old
  env overrides already provided.

Bit-identity constraint (measured, r08): replication results are
bitwise identical across every vmap chunk width **≥ 2** for all four
estimator families, but width **1** lowers differently and produces
different bits. The ladder therefore floors at chunk 2 — an autotuned
geometry can never move a result by even one ulp — and
:func:`chunk_floor` is exported for the tail-split in
``sim.chunked_vmap``, which pads width-1 tails up to 2 for the same
reason.

The ``dtype`` cache axis reuses the f32/f64 geometry-band detector
(``estimators.common.f32_geometry_band``): an ε set inside the
~1e-6 band compiles a *different* batch design (adjacent m) than the
static rule, so its tuned shape must not be shared with the off-band
kernel of the same nominal dtype — :func:`dtype_tag` folds the band
verdict into the cache key.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time

log = logging.getLogger("dpcorr.geometry")

#: minimum bit-safe vmap chunk width (see module docstring)
CHUNK_FLOOR = 2

#: probe ladders per device kind: (chunk candidates, block candidates).
#: CPU candidates bracket the measured r08 sweep (chunk 2-4 optimal at
#: n=10⁴ — small widths keep one rep's sample tables inside L2; blocks
#: amortize dispatch). TPU candidates bracket the r02 block-scaling
#: sweep (wide chunks, 2¹⁷-2¹⁹ blocks amortize ~0.2 s/fetch of tunnel
#: latency). Probing is cheap on CPU (a few blocks); on TPU the probe
#: block is already the smaller candidate.
LADDERS: dict[str, tuple[tuple[int, ...], tuple[int, ...]]] = {
    "cpu": ((2, 4, 16, 64), (2048, 4096, 8192)),
    "tpu": ((4096, 16384), (1 << 17, 1 << 19)),
}


@dataclasses.dataclass(frozen=True)
class Geometry:
    """One chosen replication-block shape and where it came from:
    ``autotune`` (probed now), ``cache`` (probed by an earlier run on
    this host), ``pinned`` (env override), ``default`` (ladder fallback
    when probing is impossible)."""

    chunk_size: int
    block_reps: int
    source: str
    reps_per_sec: float | None = None

    def as_detail(self) -> dict:
        """The bench-JSON ``detail.geometry`` stamp."""
        d = {"chunk_size": self.chunk_size, "block_reps": self.block_reps,
             "source": self.source}
        if self.reps_per_sec is not None:
            d["probe_reps_per_sec"] = round(self.reps_per_sec, 1)
        return d


def chunk_floor(width: int) -> int:
    """Clamp a requested vmap width to the bit-safe floor."""
    return max(CHUNK_FLOOR, int(width))


def dtype_tag(dtype: str = "f32", eps_pairs=None, n: int | None = None,
              ) -> str:
    """Cache-key dtype component, band-split via the shared detector
    (``common.f32_geometry_band``) so in-band ε sets never share a
    tuned shape with the off-band kernel (different batch design ⇒
    different program ⇒ different optimum)."""
    if eps_pairs:
        from dpcorr.models.estimators.common import f32_geometry_band

        if f32_geometry_band(eps_pairs, n=n):
            return f"{dtype}-band"
    return dtype


def cache_path() -> str | None:
    """Resolved persistent-cache path, or None when disabled."""
    raw = os.environ.get("DPCORR_GEOMETRY_CACHE")
    if raw is not None:
        if raw.strip().lower() in ("0", "off", "none", ""):
            return None
        return raw
    return os.path.join(os.path.expanduser("~"), ".cache", "dpcorr",
                        "geometry.json")


def _cache_key(device_kind: str, family: str, n: int, dtype: str,
               device_count: int = 1, mesh_shape=None) -> str:
    """Cache key; multi-device runs get a fifth ``dev=`` axis (count
    plus mesh shape) so a shape tuned for a 4-way sharded pipeline is
    never served to the 1-device path or vice versa. Single-device keys
    keep the historical 4-part form — old caches stay valid."""
    key = f"{device_kind}|{family}|n={int(n)}|{dtype}"
    if int(device_count or 1) > 1:
        key += f"|dev={int(device_count)}"
        if mesh_shape:
            key += "".join(f":{a}={int(s)}"
                           for a, s in sorted(dict(mesh_shape).items()))
    return key


def _load(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            state = json.load(f)
        return state if isinstance(state, dict) else {}
    except (OSError, ValueError):
        return {}


def load_strict(path: str) -> dict:
    """Like :func:`_load` but corrupt/unreadable raises — the CLI's
    contract (``dpcorr obs geometry`` exits 1 on a corrupt cache where
    the hot path deliberately shrugs and re-probes)."""
    with open(path, encoding="utf-8") as f:
        state = json.load(f)
    if not isinstance(state, dict):
        raise ValueError(f"{path}: geometry cache is not a JSON object")
    return state


def entries(state: dict, *, now: float | None = None) -> list[dict]:
    """Decompose a cache dict into display rows for the CLI: the
    ``device_kind|family|n=N|dtype`` key split back into its axes, plus
    ``age_s`` staleness from ``captured_utc`` (None when unstamped).
    Malformed keys/values become ``note``-carrying rows, never a crash.
    """
    now = time.time() if now is None else now
    rows: list[dict] = []
    for key in sorted(state):
        val = state[key]
        row: dict = {"key": key}
        parts = key.split("|")
        if len(parts) in (4, 5) and parts[2].startswith("n="):
            row.update(device_kind=parts[0], family=parts[1],
                       n=parts[2][2:], dtype=parts[3])
            if len(parts) == 5:
                if parts[4].startswith("dev="):
                    row["devices"] = parts[4][4:]
                else:
                    row["note"] = "unrecognized key shape"
        else:
            row["note"] = "unrecognized key shape"
        if isinstance(val, dict):
            row["chunk_size"] = val.get("chunk_size")
            row["block_reps"] = val.get("block_reps")
            row["reps_per_sec"] = val.get("reps_per_sec")
            cap = val.get("captured_utc")
            row["captured_utc"] = cap
            row["age_s"] = None
            if isinstance(cap, str) and cap:
                try:
                    import calendar

                    row["age_s"] = max(0.0, now - calendar.timegm(
                        time.strptime(cap, "%Y-%m-%dT%H:%M:%SZ")))
                except ValueError:
                    row["note"] = "unparseable captured_utc"
        else:
            row["note"] = "entry is not an object"
        rows.append(row)
    return rows


def _store(path: str, key: str, geo: Geometry) -> None:
    state = _load(path)
    state[key] = {"chunk_size": geo.chunk_size,
                  "block_reps": geo.block_reps,
                  "reps_per_sec": geo.reps_per_sec,
                  "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime())}
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(state, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:  # a read-only home must not fail the bench
        log.warning("geometry cache write to %s failed: %s", path, e)


#: in-process memo: one probe per (device_kind, family, n, dtype) per
#: process even when the persistent cache is disabled
_MEMO: dict[str, Geometry] = {}


def _pinned() -> Geometry | None:
    chunk = os.environ.get("DPCORR_BENCH_CHUNK")
    block = os.environ.get("DPCORR_BENCH_BLOCK_REPS")
    if chunk is None and block is None:
        return None
    # a half-pin inherits the other axis from the device default ladder
    # at resolve time — callers pass the resolved Geometry to as_detail
    return Geometry(chunk_size=chunk_floor(int(chunk)) if chunk else 0,
                    block_reps=int(block) if block else 0,
                    source="pinned")


def resolve_pinned(geo: Geometry, device_kind: str) -> Geometry:
    """Fill a half-pinned geometry's zero axes from the ladder default."""
    chunks, blocks = LADDERS.get(device_kind, LADDERS["cpu"])
    return dataclasses.replace(
        geo,
        chunk_size=geo.chunk_size or chunks[-1],
        block_reps=geo.block_reps or blocks[-1])


def lookup(family: str, n: int, *, device_kind: str = "cpu",
           dtype: str = "f32", eps_pairs=None, env_pin: bool = True,
           device_count: int = 1, mesh_shape=None) -> Geometry | None:
    """Read-only geometry resolution (no probing): env pin → in-process
    memo → persistent cache. The grid's ``geometry="auto"`` path —
    probing inside a resumable grid would burn replications and jitter
    its timings, so the grid only *reads* what a bench/autotune run on
    this host already measured. Returns None on a cold host; the caller
    keeps its configured shape. ``env_pin=False`` skips the env-pin rung
    entirely — the bench's CPU fallback uses it because
    ``DPCORR_BENCH_CHUNK``/``DPCORR_BENCH_BLOCK_REPS`` tune the TPU
    paths and a TPU-sized pin inherited by the fallback would blow its
    kill timeout (bench.py ``_worker_shape``)."""
    pinned = _pinned() if env_pin else None
    if pinned is not None:
        return resolve_pinned(pinned, device_kind)
    key = _cache_key(device_kind, family, n,
                     dtype_tag(dtype, eps_pairs, n),
                     device_count, mesh_shape)
    geo = _MEMO.get(key)
    if geo is not None:
        return geo
    path = cache_path()
    if path:
        hit = _load(path).get(key)
        if hit:
            geo = Geometry(chunk_size=chunk_floor(hit["chunk_size"]),
                           block_reps=int(hit["block_reps"]),
                           source="cache",
                           reps_per_sec=hit.get("reps_per_sec"))
            _MEMO[key] = geo
            return geo
    return None


def autotune(family: str, n: int, make_runner, *,
             device_kind: str = "cpu", dtype: str = "f32",
             eps_pairs=None, ladder=None, probe_reps: int | None = None,
             clock=time.perf_counter, use_cache: bool = True,
             force: bool = False, env_pin: bool = True,
             device_count: int = 1, mesh_shape=None) -> Geometry:
    """Choose (chunk_size, block_reps) for one replication workload.

    ``make_runner(chunk, block)`` must return a zero-arg callable that
    runs ONE block of ``block`` replications synchronously (compile
    excluded by the warm call the tuner makes first). ``clock`` is
    injectable so the determinism test can script the timings; the
    probe protocol itself is deterministic given the clock: chunk is
    chosen first at the smallest block candidate, then block at the
    winning chunk, ties broken toward the earlier ladder entry.

    Resolution order: env pin → in-process memo → persistent cache →
    probe (winner persisted). ``force=True`` skips memo+cache reads
    (re-probe), never the env pin — an operator's pin outranks tuning.
    ``env_pin=False`` removes the env-pin rung (see :func:`lookup`).
    """
    pinned = _pinned() if env_pin else None
    if pinned is not None:
        return resolve_pinned(pinned, device_kind)

    tag = dtype_tag(dtype, eps_pairs, n)
    key = _cache_key(device_kind, family, n, tag, device_count,
                     mesh_shape)
    if not force:
        geo = _MEMO.get(key)
        if geo is not None:
            return geo
        path = cache_path() if use_cache else None
        if path:
            hit = _load(path).get(key)
            if hit:
                geo = Geometry(chunk_size=chunk_floor(hit["chunk_size"]),
                               block_reps=int(hit["block_reps"]),
                               source="cache",
                               reps_per_sec=hit.get("reps_per_sec"))
                _MEMO[key] = geo
                return geo

    chunks, blocks = ladder or LADDERS.get(device_kind, LADDERS["cpu"])
    chunks = tuple(chunk_floor(c) for c in chunks)
    probe_block = probe_reps or blocks[0]

    def timed(chunk: int, block: int) -> float:
        run = make_runner(chunk, block)
        run()  # warm: compile + first dispatch excluded
        t0 = clock()
        run()
        return max(clock() - t0, 1e-9)

    try:
        best_chunk = min(chunks, key=lambda c: timed(c, probe_block))
        per_rep = {b: timed(best_chunk, b) / b for b in blocks}
        best_block = min(blocks, key=lambda b: per_rep[b])
        geo = Geometry(chunk_size=best_chunk, block_reps=best_block,
                       source="autotune",
                       reps_per_sec=1.0 / per_rep[best_block])
    except Exception as e:  # probing must never kill the measurement
        log.warning("geometry autotune failed (%s: %s); using ladder "
                    "default", type(e).__name__, e)
        geo = Geometry(chunk_size=chunks[-1], block_reps=blocks[-1],
                       source="default")

    _MEMO[key] = geo
    if use_cache and geo.source == "autotune":
        path = cache_path()
        if path:
            _store(path, key, geo)
    return geo
