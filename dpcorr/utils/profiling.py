"""Tracing / profiling (SURVEY.md §5: absent in the reference — the only
perf note there is a comment "~ minutes not hours", vert-cor.R:501).

Two tools:

- :func:`trace`: context manager around ``jax.profiler`` writing a
  TensorBoard/Perfetto trace directory for kernel-level inspection;
- :class:`Throughput`: wall-clock replications/sec counter — the
  BASELINE.json metric (reps/sec/chip) — with a context-manager API used by
  ``bench.py`` and the grid driver's timing table.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time

import jax


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/dpcorr_trace"):
    """Capture a device trace: ``with trace("dir"): run_kernels()``.

    View with TensorBoard's profile plugin or Perfetto. Traces include XLA
    op names so fusion decisions and collective overlap are visible.
    """
    from dpcorr.obs import trace as obs_trace

    jax.profiler.start_trace(log_dir)
    # mirror the capture window into the obs span log so a profiler dump
    # can be lined up against the span timeline it overlaps
    sp = obs_trace.tracer().start_span("profiler.trace", log_dir=log_dir)
    try:
        yield log_dir
    finally:
        sp.end()
        jax.profiler.stop_trace()


@dataclasses.dataclass
class Throughput:
    """reps/sec counter.

    >>> tp = Throughput(n_devices=len(jax.devices()))
    >>> with tp.measure():
    ...     out = run_block(...)   # must block (fetch) before exiting
    >>> tp.add(n_reps)
    >>> tp.reps_per_sec_chip
    """

    n_devices: int = 1
    reps: int = 0
    seconds: float = 0.0
    _t0: float | None = None

    @contextlib.contextmanager
    def measure(self):
        t0 = time.perf_counter()
        yield
        self.seconds += time.perf_counter() - t0

    def add(self, n_reps: int) -> None:
        self.reps += int(n_reps)

    @property
    def reps_per_sec(self) -> float:
        return self.reps / self.seconds if self.seconds > 0 else float("nan")

    @property
    def reps_per_sec_chip(self) -> float:
        return self.reps_per_sec / max(self.n_devices, 1)

    def utilization(self, flops_per_rep: float, bytes_per_rep: float,
                    platform: str | None = None) -> dict:
        """%-of-peak view of the measured throughput: combine a per-rep
        work model (from ``roofline.analytic_rep_model`` or
        ``roofline.xla_cost``) with reps/sec/chip against the platform's
        chip ceilings. See docs/PERFORMANCE.md "MFU / roofline"."""
        from dpcorr.utils.roofline import peaks_for, summarize

        if platform is None:
            platform = jax.devices()[0].platform
        return summarize(self.reps_per_sec_chip, flops_per_rep,
                         bytes_per_rep, peaks_for(platform))
