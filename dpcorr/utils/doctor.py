"""Environment health report: ``python -m dpcorr doctor``.

The reference has no operational tooling at all (SURVEY.md §5: failure
detection "absent" — a dead mclapply task yields a silent NULL slot,
vert-cor.R:534-554). This framework's TPU runtime, by contrast, lives
behind a tunnel with known failure modes (docs/STATUS_r04.md wedge
forensics), and the difference between "chip busy", "tunnel endpoint
dead" and "a stray process holds the exclusive TPU client" decides what
an operator should do next. ``doctor`` runs the whole diagnosis in one
command and prints either a human table or one JSON line.

Checks (each sub-second except the opt-in device probe; note the
interpreter itself may take seconds to start where a site hook preloads
JAX — the checks below never import it):

- **relay**: TCP-connect the tunnel relay's local listen ports. All
  refused ⇒ the client-side endpoint is gone and no amount of waiting
  inside this session brings the chip back (only an infra redial does).
- **strays**: ``bench.py --worker`` processes reparented to init — each
  holds the exclusive TPU client forever and masquerades as a wedged
  tunnel. ``--sweep`` kills them (same rule bench.py applies).
- **compile-cache**: persistent XLA cache dir (entries / bytes) — a warm
  cache turns a 20-40 s first compile into seconds.
- **queue**: marker state of the unattended validation queue, if its
  state dir exists (ok / fail / wedge counts per step).
- **probe** (``--probe`` only): the authoritative device check — init
  JAX in a subprocess with a hard timeout and report platform + device.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import subprocess
import sys

#: The tunnel relay's local listen ports (an infra-owned stdio
#: multiplexer; see docs/STATUS_r04.md). Checking a subset is enough:
#: the relay binds all or none of them. These are THIS deployment's
#: observed ports, not a protocol constant — another image's relay (or a
#: re-provisioned tunnel) may bind elsewhere, so the list is overridable
#: via ``DPCORR_RELAY_PORTS`` (comma-separated) without editing the
#: package.
RELAY_PORTS = (8082, 8083, 8087)


def relay_ports() -> tuple[int, ...]:
    """The relay port list in effect: ``DPCORR_RELAY_PORTS`` (comma-
    separated ints) if set and parseable, else the baked-in default.
    An unparseable override falls back to the default rather than
    raising — doctor is a diagnostic tool and must not crash on a typo
    — but the rendered report always shows which ports were checked,
    so the fallback is auditable."""
    env = os.environ.get("DPCORR_RELAY_PORTS", "").strip()
    if env:
        try:
            ports = tuple(int(tok) for tok in env.split(",") if tok.strip())
            if ports:
                return ports
        except ValueError:
            pass
    return RELAY_PORTS

DEFAULT_CACHE = os.path.expanduser("~/.cache/dpcorr/xla")


def default_queue_dir() -> str:
    """Same resolution rule as tpu_r05_queue.sh / harvest_r05.sh
    (``OUT=${TPU_R05_IN:-/tmp/tpu_r05}``) so doctor reads the markers
    the queue actually wrote. Falls back to the r04 dir when no r05
    state exists yet (e.g. triaging right after a reboot that predates
    the r05 queue's first launch)."""
    env = os.environ.get("TPU_R05_IN")
    if env:
        return env
    if os.path.isdir("/tmp/tpu_r05"):
        return "/tmp/tpu_r05"
    # an explicitly-set TPU_R04_IN is honored unconditionally, exactly
    # like TPU_R05_IN above — the operator pointed at it, report on it
    # even if it doesn't exist yet; only the *default* legacy dir must
    # prove itself with an isdir check
    legacy = os.environ.get("TPU_R04_IN")
    if legacy:
        return legacy
    return "/tmp/tpu_r04" if os.path.isdir("/tmp/tpu_r04") else "/tmp/tpu_r05"


def check_relay(ports=None, timeout=2.0) -> dict:
    """True if any relay port accepts a TCP connection."""
    if ports is None:
        ports = relay_ports()
    open_ports = []
    for p in ports:
        s = socket.socket()
        s.settimeout(timeout)
        try:
            s.connect(("127.0.0.1", p))
            open_ports.append(p)
        except OSError:
            pass
        finally:
            s.close()
    return {"alive": bool(open_ports), "open_ports": open_ports,
            "checked": list(ports)}


def find_stray_workers() -> list[dict]:
    """``bench.py --worker`` processes whose parent is init (ppid 1).

    Every live orchestrator keeps a live parent, so ppid==1 means the
    orchestrator died (SIGKILL class) and the worker now holds the
    exclusive TPU client with nothing left to reap it. This is the
    CANONICAL Python implementation of the stranded-client rule —
    ``bench.py:_sweep_stranded_clients`` delegates here.
    ``benchmarks/tpu_r05_queue.sh::sweep_strays`` approximates it in
    shell with ``pgrep -f "bench\\.py --worker"`` — an *adjacent-token*
    match, narrower than this rule, but exact for the only spawn form
    that exists (``<python> bench.py --worker <kind>``).
    """
    strays = []
    for pid_dir in glob.glob("/proc/[0-9]*"):
        try:
            pid = int(os.path.basename(pid_dir))
            with open(os.path.join(pid_dir, "cmdline"), "rb") as f:
                argv = [a for a in f.read().split(b"\0") if a]
            # a real worker invocation is `<python> .../bench.py --worker
            # <kind> ...` — at least 3 args; the endswith anchor keeps us
            # off driver shells that merely mention bench.py in a string
            if (len(argv) < 3 or b"--worker" not in argv
                    or not any(a.endswith(b"bench.py") for a in argv)):
                continue
            with open(os.path.join(pid_dir, "stat")) as f:
                ppid = int(f.read().rsplit(")", 1)[1].split()[1])
            if ppid == 1 and pid != os.getpid():
                strays.append({"pid": pid, "cmdline": b" ".join(argv)
                               .decode(errors="replace").strip()})
        except (OSError, ValueError, IndexError):
            continue  # raced a process exit or unreadable /proc entry
    return strays


def sweep_strays(strays: list[dict]) -> list[int]:
    swept = []
    for s in strays:
        try:
            os.kill(s["pid"], 9)
            swept.append(s["pid"])
        except OSError:
            pass
    return swept


def parse_cache_env() -> tuple[str | None, bool]:
    """Canonical parse of DPCORR_COMPILE_CACHE: ``(dir, disabled)`` where
    ``dir`` is the explicit directory (None if unset or disabled) and
    ``disabled`` is True only for the explicit 0/off/none tokens. The
    two consumers apply different defaults to the unset case — bench.py
    defaults the cache ON at DEFAULT_CACHE, the dpcorr CLI stays cold
    unless the var is set (README "benchmarks" note) — so resolution
    is per-consumer: ``resolve_cache_dir``."""
    env = os.environ.get("DPCORR_COMPILE_CACHE", "")
    disabled = bool(env) and env.lower() in ("0", "off", "none")
    return (env if env and not disabled else None), disabled


def resolve_cache_dir(consumer: str = "bench") -> str | None:
    """The cache dir a given consumer would actually use (None = cold)."""
    if consumer not in ("bench", "cli"):
        # a typo'd consumer silently running cold would cost minutes of
        # avoidable compile per unattended run — fail loudly instead
        raise ValueError(f"unknown cache consumer {consumer!r}")
    env_dir, disabled = parse_cache_env()
    if disabled:
        return None
    if consumer == "bench":
        return env_dir or DEFAULT_CACHE
    return env_dir  # dpcorr CLI: opt-in only


def check_compile_cache(path: str | None = None) -> dict:
    """State of bench.py's persistent XLA cache (``path``: bench
    semantics — default ON). ``cli_path`` records what the opt-in
    dpcorr CLI would use (None = cold), so the report can't suggest a
    warm cache to a `python -m dpcorr grid` run that won't see one."""
    cli_path = resolve_cache_dir("cli")
    if path is None:
        path = resolve_cache_dir("bench")
    if path is None:
        return {"path": None, "present": False, "disabled": True,
                "cli_path": cli_path}
    if not os.path.isdir(path):
        return {"path": path, "present": False, "cli_path": cli_path}
    entries = bytes_total = 0
    for root, _dirs, files in os.walk(path):
        for fn in files:
            entries += 1
            try:
                bytes_total += os.path.getsize(os.path.join(root, fn))
            except OSError:
                pass
    return {"path": path, "present": True, "entries": entries,
            "mb": round(bytes_total / 1e6, 1),
            "cli_path": cli_path}


def check_queue(state_dir: str | None = None) -> dict:
    if state_dir is None:
        state_dir = default_queue_dir()
    if not os.path.isdir(state_dir):
        return {"state_dir": state_dir, "present": False}
    out: dict = {"state_dir": state_dir, "present": True,
                 "ok": [], "fail": [], "wedges": {}}
    for f in sorted(os.listdir(state_dir)):
        stem, dot, kind = f.rpartition(".")
        if not dot:
            continue
        if kind == "ok":
            out["ok"].append(stem)
        elif kind == "fail":
            out["fail"].append(stem)
        elif kind == "wedges":
            try:
                with open(os.path.join(state_dir, f)) as fh:
                    out["wedges"][stem] = int(fh.read().strip())
            except (OSError, ValueError):
                pass
    return out


def probe_device(timeout_s: float = 150.0) -> dict:
    """Authoritative device check in a throwaway process GROUP (JAX init
    can hang on a wedged tunnel — never run it in-process, and reap the
    whole group on every exit path: a leaked descendant holding the
    capture pipe would both block us past the timeout and keep the
    exclusive TPU tunnel handle — the same contract as
    ``bench.py:_health_probe``)."""
    import signal

    code = ("import jax, json; d = jax.devices()[0]; "
            "print(json.dumps({'platform': d.platform, "
            "'device': str(d)}))")
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, start_new_session=True)
    try:
        out, err = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"timeout after {timeout_s:.0f}s"}
    finally:
        try:  # reap the whole group whether we timed out or not
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        if p.poll() is None:
            p.wait()
    if p.returncode != 0:
        return {"ok": False, "error": (err or "")[-300:]}
    try:
        return {"ok": True, **json.loads(out.strip().splitlines()[-1])}
    except (ValueError, IndexError):
        return {"ok": False, "error": f"unparseable: {out[-200:]!r}"}


def diagnose(probe: bool = False, sweep: bool = False,
             cache_dir: str | None = None,
             queue_dir: str | None = None) -> dict:
    strays = find_stray_workers()
    report = {
        "relay": check_relay(),
        "stray_workers": strays,
        "compile_cache": check_compile_cache(cache_dir),
        "queue": check_queue(queue_dir),
    }
    remaining = list(strays)
    if sweep:
        # key always present when --sweep was requested: a stable JSON
        # schema for scripts (`jq .swept` must not go null on the
        # healthy path)
        report["swept"] = sweep_strays(strays) if strays else []
        remaining = [s for s in strays
                     if s["pid"] not in set(report["swept"])]
    if probe:
        if not report["relay"]["alive"]:
            # against a dead endpoint the jax probe can only hang to its
            # 150 s timeout (same short-circuit tpu_r05_queue.sh::probe
            # applies); if the relay port list ever goes stale, the
            # rendered report still shows exactly which ports were
            # checked, so the skip is auditable
            report["device_probe"] = {
                "ok": False, "skipped": "relay endpoint down"}
        elif remaining:
            # a surviving stray HOLDS the exclusive TPU client — the
            # probe would hang its full timeout against it by definition
            report["device_probe"] = {
                "ok": False,
                "skipped": "stray client holds the TPU client "
                           "(sweep first)"}
        else:
            report["device_probe"] = probe_device()
    # one-word triage verdict, the thing an operator actually wants.
    # A stray that survived --sweep (EPERM, other owner) still holds the
    # TPU client — that must dominate the verdict, not read as "ok".
    # UNLESS the relay endpoint is also dead: then re-probing after a
    # sweep is futile (the probe would be skipped as "relay endpoint
    # down" anyway), so the endpoint condition dominates and the strays
    # become a secondary note — the operator sweeps locally AND waits
    # for the infra redial, in that order.
    if remaining and not report["relay"]["alive"]:
        report["verdict"] = (
            "tunnel-endpoint-dead+stray-client (sweep strays, but the "
            "chip needs an infra redial either way; CPU work only)")
    elif remaining:
        report["verdict"] = ("stray-client (run --sweep, then re-probe)"
                             if not sweep else
                             "stray-client-unkillable (sweep could not "
                             "remove pids %s)" % [s["pid"]
                                                  for s in remaining])
    elif not report["relay"]["alive"]:
        report["verdict"] = ("tunnel-endpoint-dead (heals only on infra "
                             "redial; CPU work only)")
    elif probe and not report.get("device_probe", {}).get("ok"):
        report["verdict"] = "relay-up-but-device-probe-failed (wedged chip?)"
    else:
        report["verdict"] = "ok" if probe else "ok (relay up; --probe to confirm device)"
    return report


def render_text(report: dict) -> str:
    lines = []
    r = report["relay"]
    lines.append(f"relay endpoint : {'UP  (ports ' + str(r['open_ports']) + ')' if r['alive'] else 'DOWN (all of ' + str(r['checked']) + ' refused)'}")
    s = report["stray_workers"]
    lines.append(f"stray clients  : {len(s)}" + (
        " -> " + ", ".join(str(x["pid"]) for x in s) if s else ""))
    if "swept" in report:
        lines.append(f"swept          : {report['swept']}")
    c = report["compile_cache"]
    cli = (f"dpcorr CLI: {c['cli_path']}" if c.get("cli_path")
           else "dpcorr CLI: cold (opt-in)")
    lines.append("compile cache  : " + (
        "disabled (DPCORR_COMPILE_CACHE)" if c.get("disabled")
        else f"bench: {c['entries']} entries, {c['mb']} MB at {c['path']}"
        if c.get("present") else f"bench: absent ({c['path']})") +
        f"; {cli}")
    q = report["queue"]
    if q.get("present"):
        lines.append(f"queue markers  : ok={len(q['ok'])} fail={len(q['fail'])}"
                     + (f" wedges={q['wedges']}" if q["wedges"] else ""))
    else:
        lines.append(f"queue markers  : none ({q['state_dir']})")
    if "device_probe" in report:
        p = report["device_probe"]
        lines.append("device probe   : " + (
            f"ok — {p['device']} ({p['platform']})" if p.get("ok")
            else f"skipped — {p['skipped']}" if "skipped" in p
            else f"FAILED — {p.get('error', '?')}"))
    lines.append(f"verdict        : {report['verdict']}")
    return "\n".join(lines)
