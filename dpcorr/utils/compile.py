"""Compile-ahead layer: single-flight AOT compilation + export reuse.

XLA compilation is pure latency with zero statistical value — for the
paper's workloads it lands at the worst moments: the first flush of
each serve kernel signature and the head of every grid bucket. This
module is the one place both consumers (serve.kernels, grid phase-0
precompile) get compilation *off* the request path:

- :class:`SingleFlight` — per-key deduplication of concurrent builds.
  The first caller for a key becomes the *leader* and runs the build;
  callers arriving while it is inflight wait on the same result instead
  of compiling again (the ``KernelCache.get`` race this fixes had the
  second thread's compile silently overwrite the first's). Distinct
  keys build concurrently: XLA releases the GIL during compilation, so
  a thread pool over signatures gets real parallelism.
- :func:`aot_compile` — explicit ahead-of-time ``jit(...).lower(avals)
  .compile()``. The returned executable is called with the *dynamic*
  arguments only (static argnums are baked in at lowering). Every
  compile is measured into the obs registry (``dpcorr_compile_seconds``
  histogram, ``dpcorr_compile_inflight`` gauge) and wrapped in a
  ``kernel.compile`` span carrying the signature, so a slow p99 is
  attributable to the compile that caused it. Lowering failure degrades
  to the plain jitted callable (``ok=False``) — AOT is an optimization,
  never a correctness gate.
- :func:`save_exported` / :func:`load_exported` — version-gated
  ``jax.export`` serialization of compiled programs, so a restarted
  server skips even the persistent-cache retrace. Caveat the serve
  consumer owns: ``jax.export`` cannot serialize typed PRNG-key avals
  (``KeyError: key<fry>`` on this jax), so exported programs must take
  raw key *data* (``rng.key_data_aval``) and wrap it back inside
  (``rng.keys_from_data``) — verified bit-identical round trip.

The AOT artifact is the same ``exact``/``vector`` kernel the lazy path
would have jit-compiled — identical HLO, so outputs stay bit-identical
to the pre-AOT serving/grid paths (pinned by tests/test_compile.py).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time

from dpcorr.obs import trace as obs_trace
from dpcorr.obs.metrics import Registry, default_registry

log = logging.getLogger("dpcorr.compile")

#: Compile-time buckets (seconds): kernels range from ~50 ms trivial CPU
#: programs to minutes-long Mosaic/TPU compiles through the tunnel —
#: wider than the serving-latency buckets on both ends.
COMPILE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)

#: Why a compile happened (``dpcorr_compile_recompile_total{cause}``):
#: ``new-signature`` — first time this signature was seen;
#: ``cache-evict``  — the signature was compiled before but its entry
#: was LRU-evicted (warm boots re-paying this are capacity problems);
#: ``jit-fallback`` — AOT lowering failed and the lazy jit path will
#: compile on first call instead.
RECOMPILE_CAUSES = ("new-signature", "cache-evict", "jit-fallback")


def signature_key(signature) -> tuple:
    """Hashable identity of a compile signature dict (sorted items)."""
    return tuple(sorted((str(k), str(v))
                        for k, v in (signature or {}).items()))


class _Flight:
    """One inflight build: the leader publishes ``value``/``error`` then
    sets ``done``; followers wait on it."""

    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error = None


class SingleFlight:
    """Per-key build deduplication (Go's ``singleflight`` shape).

    ``do(key, build)`` returns ``(value, leader)``: exactly one caller
    per concurrently-missed key runs ``build`` (leader=True); the rest
    block until it finishes and share the result. A build that raises
    propagates the exception to the leader *and* every waiter, and the
    key is cleared so the next call retries fresh. The leader publishes
    its result *before* the flight is removed, so a caller can install
    the value into its own cache inside ``build`` without a window
    where a third thread re-builds.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict[object, _Flight] = {}  # guarded by: _lock

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def do(self, key, build):
        with self._lock:
            fl = self._inflight.get(key)
            leader = fl is None
            if leader:
                fl = _Flight()
                self._inflight[key] = fl
        if not leader:
            fl.done.wait()
            if fl.error is not None:
                raise fl.error
            return fl.value, False
        try:
            fl.value = build()
        except BaseException as e:
            fl.error = e
            raise
        finally:
            # publish-then-clear: value/error are set before the flight
            # leaves the map and the event releases the waiters
            with self._lock:
                self._inflight.pop(key, None)
            fl.done.set()
        return fl.value, True


class CompileObserver:
    """The obs wiring one consumer's compiles report through: a
    histogram of compile seconds, an inflight gauge, a per-result
    counter, and ``kernel.compile`` spans. Serve passes its per-server
    registry (so /metrics and /stats see the series); grid uses the
    process defaults."""

    def __init__(self, registry: Registry | None = None,
                 tracer: obs_trace.Tracer | None = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self._tracer = tracer
        self.seconds = self.registry.histogram(
            "dpcorr_compile_seconds",
            "Wall seconds per kernel compilation (AOT lower+compile)",
            buckets=COMPILE_BUCKETS)
        self.inflight = self.registry.gauge(
            "dpcorr_compile_inflight",
            "Kernel compilations currently running")
        self.results = self.registry.counter(
            "dpcorr_compile_total",
            "Kernel compilations by outcome",
            labelnames=("result",))
        self.recompiles = self.registry.counter(
            "dpcorr_compile_recompile_total",
            "Kernel compilations by cause",
            labelnames=("cause",))
        self._cause_lock = threading.Lock()
        self._seen: set = set()     # guarded by: _cause_lock
        self._evicted: set = set()  # guarded by: _cause_lock

    def note_evicted(self, key) -> None:
        """A consumer cache dropped this signature's entry — the next
        compile for it is a recompile caused by eviction, not novelty."""
        with self._cause_lock:
            self._evicted.add(key)

    def classify(self, key, ok: bool) -> str:
        """Attribute one compile to a RECOMPILE_CAUSES cause and count
        it. Called by :func:`aot_compile` after the outcome is known."""
        with self._cause_lock:
            if not ok:
                cause = "jit-fallback"
            elif key in self._evicted or key in self._seen:
                cause = "cache-evict"
            else:
                cause = "new-signature"
            self._seen.add(key)
            self._evicted.discard(key)
        self.recompiles.inc(cause=cause)
        return cause

    def tracer(self) -> obs_trace.Tracer:
        # resolved per call, not at construction: the process tracer can
        # be configured after a long-lived observer is built
        return self._tracer if self._tracer is not None \
            else obs_trace.tracer()


def aot_compile(jitted, lower_args, *, lower_kwargs=None, signature=None,
                observer: CompileObserver | None = None, parent=None):
    """AOT-compile ``jitted`` at ``lower_args`` (the full argument list
    as the jitted callable takes it — static args included, as concrete
    values; dynamic args may be ``jax.ShapeDtypeStruct`` avals).
    ``lower_kwargs`` are keyword arguments forwarded to ``lower`` for
    programs with keyword statics (the roofline cost probe).

    Returns ``(fn, aot_ok)``. On success ``fn`` is the compiled
    executable, called with the *dynamic* args only and strict about
    shapes (TypeError on mismatch — callers keep the jitted fallback
    for off-signature shapes). On lowering/compile failure ``fn`` is
    ``jitted`` itself and ``aot_ok`` is False: the caller keeps working,
    just lazily compiled.

    ``signature`` (a flat dict) labels the ``kernel.compile`` span;
    ``parent`` pins the span's parent for pool threads whose implicit
    (thread-local) span stack is empty.
    """
    obs = observer if observer is not None else CompileObserver()
    attrs = dict(signature or {})
    obs.inflight.inc()
    t0 = time.perf_counter()
    try:
        with obs.tracer().span("kernel.compile", parent=parent,
                               **attrs) as sp:
            try:
                fn = jitted.lower(*lower_args,
                                  **(lower_kwargs or {})).compile()
                ok = True
            except Exception as e:
                log.warning("AOT compile failed for %s: %s -- falling "
                            "back to lazy jit", attrs or "<kernel>", e)
                fn, ok = jitted, False
            cause = obs.classify(signature_key(signature), ok)
            sp.set(aot=ok, cause=cause)
    finally:
        dt = time.perf_counter() - t0
        obs.inflight.dec()
    obs.seconds.observe(dt)
    obs.results.inc(result="aot" if ok else "jit-fallback")
    if ok:
        # Compile-time introspection (ISSUE 15): cost/memory analysis,
        # HLO fingerprint and op histogram into the process store so
        # `dpcorr obs hlo diff` can compare persisted dumps. Never a
        # compile-path failure mode.
        try:
            from dpcorr.obs import hlo as obs_hlo

            obs_hlo.default_store().record(signature, fn,
                                           seconds=dt, cause=cause)
        except Exception:  # noqa: BLE001 — introspection is best-effort
            pass
    return fn, ok


# ------------------------------------------------------- shardings ----
def host_sharding(device=None):
    """The explicit placement the donated rep-block pipeline pins every
    operand and result to (``sim.RepBlockPipeline``): pass a device (or
    nothing for the process default) and get a sharding suitable for
    ``in_shardings``/``out_shardings``/``jnp.zeros(device=...)``.

    Degenerate on the 1-device CPU box — but keeping it *explicit* is
    what lets chained blocks alias donated buffers with no reshard copy
    in between, and the same call sites accept a ``NamedSharding`` when
    a mesh exists (:func:`mesh_shardings`), so the CPU pipeline and the
    TPU pipeline are one code path."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    if isinstance(dev, jax.sharding.Sharding):
        return dev
    return jax.sharding.SingleDeviceSharding(dev)


def mesh_shardings(mesh, axis: str = "rep"):
    """``(sharded, replicated)`` NamedSharding pair for a 1-axis mesh —
    the explicit in/out shardings the parallel backend's shard_map
    kernels declare (``parallel.backend``) so the flat replication axis
    arrives pre-sharded (jit inserts no resharding copy) and scalars
    stay replicated."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return NamedSharding(mesh, P(axis)), NamedSharding(mesh, P())


# ------------------------------------------------------- jax.export ----
def export_supported() -> bool:
    """Version gate for the serialization path: ``jax.export`` only
    (the older experimental module had an incompatible format)."""
    try:
        from jax import export as jax_export
    except Exception:  # pragma: no cover - depends on jax version
        return False
    return (hasattr(jax_export, "export")
            and hasattr(jax_export, "deserialize"))


def signature_digest(*parts) -> str:
    """Stable filename stem for one exported kernel signature. The jax
    version is folded in — serialized programs are not portable across
    jax upgrades, and a stale artifact must miss, not deserialize into
    wrong semantics."""
    import jax

    blob = "|".join(str(p) for p in parts) + f"|jax={jax.__version__}"
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def export_path(root: str, digest: str) -> str:
    return os.path.join(root, f"{digest}.jaxexp")


def save_exported(path: str, jitted, lower_args) -> bool:
    """Serialize ``jitted`` exported at ``lower_args`` to ``path``
    (atomic tmp+rename — a crashed writer leaves no torn artifact).
    Returns False (never raises) when export/serialize is unsupported
    for this program — e.g. typed PRNG-key avals; see module docstring
    for the key-data wrapper contract."""
    if not export_supported():
        return False
    try:
        from jax import export as jax_export

        blob = jax_export.export(jitted)(*lower_args).serialize()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return True
    except Exception as e:
        log.warning("jax.export serialization to %s failed: %s", path, e)
        return False


def load_exported(path: str):
    """Deserialize an exported kernel; returns its ``.call`` (a
    traceable callable) or None on any failure — a corrupt or
    version-mismatched artifact degrades to a fresh compile."""
    if not export_supported():
        return None
    try:
        from jax import export as jax_export

        with open(path, "rb") as f:
            blob = f.read()
        return jax_export.deserialize(blob).call
    except FileNotFoundError:
        return None
    except Exception as e:
        log.warning("stale/corrupt exported kernel %s ignored: %s",
                    path, e)
        return None
