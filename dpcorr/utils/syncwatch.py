"""Runtime lock-witness sanitizer (``DPCORR_SYNCWATCH=1``).

The static lock model (``dpcorr lint --deep``, analysis/callgraph.py)
predicts which lock-order edges the repo can traverse. This module is
the empirical other half: an opt-in wrapper around ``threading.Lock``
/ ``threading.RLock`` that records the acquisition-order graph a live
process *actually* walks, detects order inversions and held-across-
fsync windows as they happen, and dumps a witness artifact on exit —
including chaos kills (``chaos.on_crash``; ``os._exit`` skips atexit).
``dpcorr lint --witness DIR`` (analysis/witness.py) then diffs the
observed graph against the static prediction: an observed edge the
model did not predict fails CI, and an observed cycle aborts the
smoke.

Scope: only locks *created from dpcorr source files* are wrapped (the
factory checks its caller's frame), so stdlib and third-party locks —
``concurrent.futures`` internals, logging, the ``threading.Condition``
a bare ``Condition()`` allocates for itself — pass through untouched.
A lock's identity is its creation site ``relpath:lineno``: every
instance born at one site shares an id, which is exactly the static
model's granularity. jax-free by construction: stdlib only, safe to
enable in the lint container.

Cost when disabled: zero (nothing is patched). Cost when enabled: one
dict lookup + list append per acquisition — fine for smokes, not meant
for benchmark runs.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading

#: where witness artifacts land unless DPCORR_SYNCWATCH_DIR says else.
DEFAULT_DIR = ".dpcorr-syncwatch"

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_fsync = os.fsync

# package root ("<...>/dpcorr") — creator frames under it get wrapped
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_DIR = os.path.dirname(_PKG_DIR)

_enabled = False
_meta = _real_lock()          # guards everything below (a REAL lock:
_edges: dict = {}             # the sanitizer must not watch itself)
_locks: dict = {}             # site -> kind
_inversions: list = []
_fsync_under_lock: dict = {}
_threads_seen: set = set()
_tls = threading.local()


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _creation_site() -> str | None:
    """``relpath:lineno`` of the frame creating the lock, when that
    frame lives in a dpcorr source file; None otherwise."""
    try:
        f = sys._getframe(2)
    except ValueError:
        return None
    # a relative sys.path entry (`sys.path.insert(0, '.')`) leaves
    # co_filename relative or un-normalized ("<cwd>/./pkg/mod.py");
    # anchor and normalize it the way import resolved it
    fn = os.path.abspath(f.f_code.co_filename)
    if not fn.startswith(_PKG_DIR + os.sep):
        return None
    rel = os.path.relpath(fn, _REPO_DIR).replace(os.sep, "/")
    return f"{rel}:{f.f_lineno}"


class _WatchedLock:
    """Wraps one real lock; records order edges on acquisition. API
    surface matches what ``with``, ``threading.Condition`` and direct
    acquire/release callers use."""

    __slots__ = ("_real", "site", "kind")

    def __init__(self, real, site: str, kind: str):
        self._real = real
        self.site = site
        self.kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def _record_acquire(self) -> None:
        stack = _held()
        reentrant = self.site in stack
        if not reentrant and stack:
            acquiring = self.site
            with _meta:
                _threads_seen.add(threading.current_thread().name)
                for held_site in set(stack):
                    if held_site == acquiring:
                        continue
                    edge = (held_site, acquiring)
                    if edge not in _edges:
                        _edges[edge] = threading.current_thread().name
                        if (acquiring, held_site) in _edges:
                            inv = {"held": held_site,
                                   "acquiring": acquiring,
                                   "thread":
                                       threading.current_thread().name}
                            _inversions.append(inv)
                            print(f"dpcorr syncwatch: lock-order "
                                  f"inversion: {held_site} -> "
                                  f"{acquiring} (reverse edge already "
                                  f"observed)", file=sys.stderr)
        elif stack:
            with _meta:
                _threads_seen.add(threading.current_thread().name)
        stack.append(self.site)

    def release(self) -> None:
        stack = _held()
        # remove the most recent entry for this site (reentrant locks
        # push once per level, so counts stay balanced)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.site:
                del stack[i]
                break
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _at_fork_reinit(self) -> None:  # pragma: no cover - fork only
        self._real._at_fork_reinit()
        _tls.stack = []


def _make_factory(real_factory, kind: str):
    def factory():
        real = real_factory()
        site = _creation_site()
        if site is None:
            return real
        with _meta:
            _locks.setdefault(site, kind)
        return _WatchedLock(real, site, kind)
    return factory


def _watched_fsync(fd):
    stack = _held()
    if stack:
        with _meta:
            for site in set(stack):
                _fsync_under_lock[site] = \
                    _fsync_under_lock.get(site, 0) + 1
    return _real_fsync(fd)


def snapshot() -> dict:
    """The witness artifact as a dict (also what gets dumped)."""
    with _meta:
        return {
            "pid": os.getpid(),
            "locks": {site: {"kind": kind}
                      for site, kind in sorted(_locks.items())},
            "edges": sorted([a, b] for (a, b) in _edges),
            "edge_threads": {f"{a} -> {b}": t
                             for (a, b), t in sorted(_edges.items())},
            "inversions": list(_inversions),
            "fsync_under_lock": dict(sorted(
                _fsync_under_lock.items())),
            "threads": sorted(_threads_seen),
        }


def dump(directory: str | None = None) -> str:
    """Write the witness artifact for this process; returns the path.
    Registered both with atexit and ``chaos.on_crash`` so a planned
    kill (``os._exit``) still leaves its witness behind."""
    directory = directory or os.environ.get("DPCORR_SYNCWATCH_DIR",
                                            DEFAULT_DIR)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"witness-{os.getpid()}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(snapshot(), fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def enable() -> None:
    """Patch the lock factories and ``os.fsync``. Idempotent; called
    from ``dpcorr/__init__`` when ``DPCORR_SYNCWATCH=1`` so the patch
    lands before any dpcorr module creates a lock."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    threading.Lock = _make_factory(_real_lock, "lock")
    threading.RLock = _make_factory(_real_rlock, "rlock")
    os.fsync = _watched_fsync
    atexit.register(dump)
    from dpcorr import chaos
    chaos.on_crash(lambda point: dump())


def disable() -> None:
    """Undo :func:`enable` (tests). Locks already created stay
    wrapped; recording state is reset."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    os.fsync = _real_fsync
    try:
        atexit.unregister(dump)
    except Exception:  # pragma: no cover
        pass
    with _meta:
        _edges.clear()
        _locks.clear()
        _inversions.clear()
        _fsync_under_lock.clear()
        _threads_seen.clear()
