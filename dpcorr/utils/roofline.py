"""Roofline / hardware-utilization accounting for the bench kernels.

The reference publishes no performance model at all (SURVEY.md §6); the
BASELINE metric is MC replications/sec/chip. This module turns a measured
reps/sec into *%-of-peak* numbers so the throughput can be judged against
what the chip could possibly do:

- **Work model**: per-replication FLOPs and HBM bytes, two ways —
  (a) XLA's own cost analysis of the compiled headline kernel
  (``Compiled.cost_analysis()``; the compiler's count of the program it
  actually emitted, post-fusion), and (b) an analytic hand count of the
  math (:func:`analytic_rep_model`) with reference citations, as a sanity
  bound on (a).
- **Peaks**: per-chip ceilings for the units this workload can use. The
  MC simulation has no large matmuls — its FLOPs are elementwise PRNG,
  transforms, and reductions, i.e. **VPU** work (the MXU ceiling is
  listed only to show how far this workload class sits from it), and its
  memory traffic is the per-rep (n, 2) sample table streaming through HBM
  when XLA materializes it between fusions.

The classification (VPU-bound vs HBM-bound) falls out of the achieved
fractions; ``benchmarks/roofline.py`` runs the measurement and writes the
JSON artifact PERFORMANCE.md cites.
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class ChipPeaks:
    """Per-chip ceilings in SI units (FLOP/s, B/s)."""

    name: str
    mxu_bf16_flops: float  #: systolic-array peak (bf16 inputs, f32 acc)
    vpu_f32_flops: float   #: elementwise f32 peak (the relevant one here)
    hbm_bytes: float       #: HBM streaming bandwidth
    note: str = ""


#: TPU v5 lite (v5e) — the chip behind this image's tunnel. MXU/HBM are
#: the public figures (197 bf16 TFLOP/s, 819 GB/s; jax-ml.github.io/
#: scaling-book rooflines chapter). The VPU peak is an *estimate* from the
#: architecture: 8 sublanes x 128 lanes x 4 ALUs x ~0.94 GHz ~= 3.9e12
#: f32 FLOP/s — labeled as such in every artifact that uses it.
TPU_V5E = ChipPeaks(
    name="tpu-v5e",
    mxu_bf16_flops=1.97e14,
    vpu_f32_flops=3.9e12,
    hbm_bytes=8.19e11,
    note="MXU/HBM public; VPU estimated 8x128 lanes x 4 ALUs x 0.94 GHz",
)

#: Honest CPU stand-in so the script degrades meaningfully off-TPU: one
#: modern x86 core ~ 1e11 f32 FLOP/s (AVX-512 FMA at ~3 GHz), ~2e10 B/s
#: effective per-core stream bandwidth. Order-of-magnitude only.
CPU_CORE = ChipPeaks(
    name="cpu-core",
    mxu_bf16_flops=1e11,
    vpu_f32_flops=1e11,
    hbm_bytes=2e10,
    note="order-of-magnitude single-core estimate",
)


def peaks_for(platform: str) -> ChipPeaks:
    return TPU_V5E if platform in ("tpu", "axon") else CPU_CORE


def xla_cost(jitted_fn, *args, **static) -> dict:
    """FLOPs / bytes-accessed of the compiled program, per XLA.

    ``Compiled.cost_analysis()`` returns the compiler's properties dict
    (key spellings vary across versions: ``flops``, ``bytes accessed``).
    Returns ``{"flops": float, "bytes": float}``; zero values mean the
    entry is absent on this backend (e.g. an opaque custom call — Pallas
    kernels are invisible to this analysis; use the analytic model there)
    or that AOT lowering failed. Compilation goes through the one
    compile path (``utils.compile.aot_compile`` — on success the
    returned executable *is* the ``Compiled``), so even the cost probe
    never grows a private ``.lower().compile()`` site.
    """
    from dpcorr.utils import compile as compile_mod

    compiled, ok = compile_mod.aot_compile(
        jitted_fn, args, lower_kwargs=static,
        signature={"kernel": "roofline.xla_cost"})
    if not ok:
        return {"flops": 0.0, "bytes": 0.0}
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax: one dict per program
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed",
                                  ca.get("bytes_accessed", 0.0)))}


def analytic_rep_model(n: int, eps1: float, eps2: float) -> dict:
    """Hand count of one bench replication (FLOPs and minimal HBM bytes).

    One rep of the north-star workload (bench.py: vert-cor.R:392-419 at
    n=10k) does, per sample unless noted:

    - **PRNG**: 2 uniforms (threefry-2x32: ~24 rounds of ~3 int-ops on
      2 words ≈ 150 ops per 2x32-bit block ⇒ ~75/word) + key derivation
      amortized; counted as integer "FLOPs" since they occupy the same
      VPU issue slots.
    - **generate** (models/dgp.py:29-35, closed-form 2x2 Cholesky of
      MASS::mvrnorm vert-cor.R:72): 2 normals via Box-Muller (log, sqrt,
      sincos ~ 30 flops) + 3 flops combine.
    - **standardize** (ops/standardize.py, priv_standardize
      vert-cor.R:322-348): clip (2), two moment-sum passes fused to one
      (2), center-only subtract (1) x 2 vars ~= 10.
    - **sign-batch estimate** (ni_sign.py:41-48, vert-cor.R:118-156):
      sign (1), batch-mean add (1) x 2 vars; per-batch Laplace noise +
      products are O(k) << n.
    - **CI** (vert-cor.R:233-254): O(k) — negligible.

    HBM floor: XLA materializes the (n, 2) f32 sample table between the
    generate and estimate fusions (write + read = 16 B/sample); everything
    else lives in registers/VMEM.
    """
    per_sample = (2 * 75) + 30 + 3 + 10 + 2 + 2  # ~197
    flops = per_sample * n
    m = min(max(math.ceil(8.0 / (eps1 * eps2)), 1), n)
    k = max(n // m, 1)
    return {
        "flops_per_rep": float(flops),
        "bytes_per_rep_floor": float(2 * n * 4 * 2),  # write+read (n,2) f32
        "per_sample_flops": per_sample,
        "batch_geometry": {"m": m, "k": k},
    }


def summarize(reps_per_sec: float, flops_per_rep: float,
              bytes_per_rep: float, peaks: ChipPeaks) -> dict:
    """Achieved rates and %-of-peak; classify the binding resource."""
    fl = reps_per_sec * flops_per_rep
    by = reps_per_sec * bytes_per_rep
    frac_vpu = fl / peaks.vpu_f32_flops
    frac_hbm = by / peaks.hbm_bytes
    return {
        "reps_per_sec": reps_per_sec,
        "achieved_flops_per_sec": fl,
        "achieved_bytes_per_sec": by,
        "pct_of_vpu_peak": round(100 * frac_vpu, 1),
        "pct_of_mxu_bf16_peak": round(100 * fl / peaks.mxu_bf16_flops, 2),
        "pct_of_hbm_peak": round(100 * frac_hbm, 1),
        "bound": ("vpu" if frac_vpu >= frac_hbm else "hbm"),
        "peaks": dataclasses.asdict(peaks),
    }
